"""Device lane: the single-threaded dispatch stage of the serving
pipeline, with identical-dispatch coalescing.

The whole table executes as ONE vmapped XLA program, so the chip is a
single serialized execution lane — unlike the reference's per-segment
operator trees, there is nothing to gain from launching kernels from
many threads, and every millisecond a scheduler worker spends on host
planning or finalize while *holding* the device is a millisecond the
chip idles.  The server query path is therefore a three-stage pipeline:

  PREP      (QueryScheduler worker pool): prune -> stage lookup ->
            StaticPlan -> QueryInputs -> H2D uploads
  DISPATCH  (this module, one thread): kernel launches only.  Launches
            are asynchronous — jax returns device buffers before the
            program finishes, so the lane keeps the device queue fed
            while earlier queries are still executing/finalizing.
  FINALIZE  (back on the worker that submitted): the first D2H read
            (``np.asarray`` on the packed output buffer) blocks until
            the program completes, then partials build host-side.

COALESCING: waiters whose (StaticPlan, staged-table identity,
query-inputs digest) match a dispatch that is queued, launching, or
still EXECUTING on device attach to it instead of enqueueing their own
— the one set of output buffers fans out to every waiter, so N
concurrent dashboard-style repeats of the same query cost ONE kernel
launch.  Identical key implies identical device inputs implies
identical outputs, and each waiter still runs its own FINALIZE, so
results stay independent per query.  The window ends the moment the
program's outputs are ready (``jax.Array.is_ready``): past that point
handing out the buffers would be result caching, which this
deliberately is not — a query arriving after the outputs exist always
re-dispatches.

BATCHING: coalescing only merges *identical* dispatches; the
micro-batching tier merges *similar* ones.  Dispatches that share a
batch key (same StaticPlan — the literal-bucketed device program, so
``a>5`` and ``a>999`` share it — same staged-table token, same
query-input signature) and carry a ``BatchSpec`` are collected at
dequeue time into ONE vmapped launch: the staged columns are read once
while every member's literals ride a stacked batch axis
(``kernel.make_packed_batched_table_kernel``), and each member's
FINALIZE slices its own row out of the one packed fetch — payloads stay
byte-identical to unbatched execution.  The batch window is adaptive:
an idle lane launches immediately (batching must never add latency when
the device is free), while demonstrated same-shape demand (>= 2 members
already queued — the lane-depth signal PR 7's admission plane feeds)
holds the window open up to ``PINOT_TPU_BATCH_WINDOW_MS`` for more
arrivals, filling to ``PINOT_TPU_BATCH_MAX``.

DEADLINES: each waiter carries the broker-propagated monotonic deadline
(server/scheduler.py semantics).  A waiter whose deadline expired while
its dispatch sat in the lane queue — or while its batch was forming —
is shed with the existing ``QueryAbandonedError`` before any device
work happens on its behalf, without poisoning batchmates; a dispatch
all of whose waiters expired is dropped without launching.

SUPERVISION: the lane is the server's single point of device contact,
so it is also where device faults are contained.  Every launch
exception is classified into a typed ``DeviceExecutionError``
(retryable transient vs deterministic poison) before it reaches a
waiter, and a watchdog thread detects an in-flight launch stalled past
``stall_timeout_s``: the wedged lane thread is abandoned (generation
bump — when its launch finally returns it discards the result and
exits), the stalled dispatch's waiters get a ``stalled`` error (the
executor fails them over to the host path), and a fresh lane thread is
spawned that re-drives everything still queued.  One bad kernel launch
never takes down serving.

Counters (surfaced via the server status/metrics snapshot):
lane depth gauge, dispatch/coalesce-hit/shed meters, device-failure /
restart / stale-completion counters, and the ``phase.laneDispatch``
timer for time spent inside launches.
"""
from __future__ import annotations

import atexit
import os
import threading
import time
import weakref
from collections import deque
from typing import Any, Callable, Deque, Dict, Hashable, List, Optional

from pinot_tpu.engine import compilecache
from pinot_tpu.server.scheduler import QueryAbandonedError

# completed dispatches kept open (still coalescible) at once; beyond
# this the oldest close early — a bound on pinned output buffers, not
# a correctness knob
_MAX_OPEN = 32


def batch_max() -> int:
    """Upper bound on batch members per launch (PINOT_TPU_BATCH_MAX,
    default 16; <= 1 disables the micro-batching tier)."""
    try:
        return int(os.environ.get("PINOT_TPU_BATCH_MAX", "16"))
    except ValueError:
        return 16


def batch_window_s() -> float:
    """Bounded batch-formation window in seconds
    (PINOT_TPU_BATCH_WINDOW_MS, default 2.0 ms; 0 disables the wait —
    only already-queued peers batch)."""
    try:
        return float(os.environ.get("PINOT_TPU_BATCH_WINDOW_MS", "2.0")) / 1000.0
    except ValueError:
        return 0.002
# poll period for closing open dispatches while the queue is idle; the
# check is a non-blocking is_ready() per open dispatch
_SWEEP_S = 0.005

# every lane ever constructed, for the test-suite thread-leak check
# (tests/conftest.py): a CLOSED lane must not keep threads alive
_all_lanes: "weakref.WeakSet[DeviceLane]" = weakref.WeakSet()

# Zero-overhead contract counter for the occupancy plane (the PR 4
# SPAN_ALLOCATIONS analog): incremented ONLY when an OccupancySampler
# records a sample.  The lane's own busy/depth accounting is plain
# float accumulation on state transitions — with no sampler running, a
# launch allocates nothing occupancy-related, and the tests hold this
# counter at zero to prove it.
OCCUPANCY_ALLOCATIONS = 0

# Interpreter-shutdown fence for the cost-analysis helper threads: a
# daemon thread mid-XLA-trace while the runtime's C++ statics destruct
# can abort the whole process (std::terminate), so at exit we stop
# spawning new analyses and drain the in-flight ones (bounded join —
# an analysis is a trace, not a compile, so this is fast).
_shutting_down = False
_cost_threads_lock = threading.Lock()
_cost_threads: List[threading.Thread] = []


def _drain_cost_analysis_threads() -> None:
    global _shutting_down
    _shutting_down = True
    with _cost_threads_lock:
        pending = [t for t in _cost_threads if t.is_alive()]
        _cost_threads.clear()
    deadline = time.monotonic() + 10.0
    for t in pending:
        t.join(timeout=max(0.0, deadline - time.monotonic()))


atexit.register(_drain_cost_analysis_threads)


class DeviceExecutionError(RuntimeError):
    """Typed device-dispatch failure — the lane-supervision contract.

    ``retryable=True``: transient (transfer hiccup, device busy) — one
    more device attempt is worth it.  ``retryable=False``: poison — the
    failure is deterministic for this (plan, inputs) shape (trace-time
    type error, compile failure, injected poison), so the executor
    quarantines the plan and serves via the host path.  ``stalled``
    marks watchdog-detected wedges (never device-retried: the retry
    would wedge the fresh lane thread for another full timeout).
    ``resource_exhausted`` marks device allocation failures — a
    DISTINCT heal class (engine/residency.py): retrying into the same
    full HBM would fail identically, so the executor demotes the
    coldest residents first, and never poisons the plan (the plan is
    healthy; the device was just full)."""

    def __init__(
        self,
        message: str,
        retryable: bool,
        cause: Optional[BaseException] = None,
        stalled: bool = False,
        resource_exhausted: bool = False,
    ) -> None:
        super().__init__(message)
        self.retryable = retryable
        self.cause = cause
        self.stalled = stalled
        self.resource_exhausted = resource_exhausted


# substrings that mark a launch failure as transient: PJRT/XLA status
# codes for resource pressure and transport trouble, plus tunnel-layer
# connection wording.  Anything else (TypeError from tracing, lowering
# and shape errors, INVALID_ARGUMENT…) is deterministic for the plan —
# poison, not worth a device retry.
_RETRYABLE_MARKERS = (
    "resource_exhausted",
    "unavailable",
    "aborted",
    "data_loss",
    "cancelled",
    "deadline_exceeded",
    "connection",
    "transfer",
    "tunnel",
)

# substrings marking the failure as ALLOCATION pressure (PJRT's
# RESOURCE_EXHAUSTED status and XLA's allocator wording): retryable,
# but only after the residency manager has made room — see
# DeviceExecutionError.resource_exhausted above.
_OOM_MARKERS = (
    "resource_exhausted",
    "out of memory",
    "out-of-memory",
)


def classify_device_error(exc: BaseException) -> DeviceExecutionError:
    """Wrap a raw launch exception in the typed error (idempotent)."""
    if isinstance(exc, DeviceExecutionError):
        return exc
    text = f"{type(exc).__name__}: {exc}"
    low = text.lower()
    oom = any(marker in low for marker in _OOM_MARKERS)
    retryable = oom or any(marker in low for marker in _RETRYABLE_MARKERS)
    return DeviceExecutionError(
        text, retryable=retryable, cause=exc, resource_exhausted=oom
    )


def plan_digest(plan: Any) -> str:
    """Stable (within a process) digest of a StaticPlan — the handle the
    device fault injector and the executor's poison quarantine share.
    StaticPlan is a frozen dataclass, so repr is deterministic."""
    import hashlib

    return hashlib.blake2b(repr(plan).encode(), digest_size=8).hexdigest()


def leaked_lane_threads(grace_s: float = 2.0) -> List[threading.Thread]:
    """Threads still alive on CLOSED lanes — the post-test leak check
    guarding the watchdog-restart path (a restart must never leak one
    wedged thread per wedge once the wedge resolves and the lane is
    closed).  Open lanes (module-scoped fixtures) are exempt."""
    suspects: List[threading.Thread] = []
    for lane in list(_all_lanes):
        if not lane._closed:
            continue
        suspects.extend(t for t in lane._threads if t.is_alive())
    deadline = time.monotonic() + grace_s
    leaked = []
    for t in suspects:
        t.join(timeout=max(0.0, deadline - time.monotonic()))
        if t.is_alive():
            leaked.append(t)
    return leaked


def outputs_pending(value: Any) -> bool:
    """True while any jax-array leaf of a launch's return value has not
    finished computing — the coalescibility window for a launch that
    already returned.  Values with no device arrays report False (no
    retention)."""
    import jax

    for leaf in jax.tree_util.tree_leaves(value):
        is_ready = getattr(leaf, "is_ready", None)
        if is_ready is not None:
            try:
                if not is_ready():
                    return True
            except Exception:
                return False
    return False


class LaneClosedError(RuntimeError):
    """Submit after close(), or queued work drained by close()."""


class LaneTicket:
    """One waiter's slot: the submitting worker blocks on ``result`` and
    resumes FINALIZE when the lane delivers outputs (or an error).
    ``coalesced`` marks a ticket that attached to an identical in-flight
    dispatch instead of enqueueing its own (trace/metrics attribution);
    ``batch_size`` is the member count of the batched launch this
    ticket's dispatch rode (1 = unbatched)."""

    __slots__ = ("deadline", "coalesced", "batch_size", "_event", "_value", "_error")

    def __init__(self, deadline: Optional[float]) -> None:
        self.deadline = deadline
        self.coalesced = False
        self.batch_size = 1
        self._event = threading.Event()
        self._value: Any = None
        self._error: Optional[BaseException] = None

    def _deliver(self, value: Any = None, error: Optional[BaseException] = None) -> None:
        self._value = value
        self._error = error
        self._event.set()

    def result(self, deadline: Optional[float] = None) -> Any:
        """Block until the dispatch delivers; honors the query deadline
        (raises the builtin ``TimeoutError`` like ``QueryScheduler.run``
        so the instance's timeout reply path handles both stages)."""
        timeout = None
        if deadline is not None:
            timeout = max(0.0, deadline - time.monotonic())
        if not self._event.wait(timeout):
            raise TimeoutError("device lane result exceeded query deadline")
        if self._error is not None:
            raise self._error
        return self._value


class BatchSpec:
    """One dispatch's micro-batching contract (executor-built).

    ``key``: hashable batch-equivalence key — dispatches with equal keys
    stack into one launch.  The executor keys on (StaticPlan,
    staged-table token, query-input signature): one device program, one
    resident table, structurally identical input pytrees.
    ``inputs``: this query's HOST numpy query-input pytree (the
    pre-upload form — batched members upload ONCE, stacked).
    ``launch_batched``: callable(list of member input pytrees) ->
    ``(fetch, handle)`` launching the vmapped batched kernel; ``fetch``
    returns the whole batch's host outputs in one packed D2H.
    ``max_members``: per-plan cap below the lane-wide PINOT_TPU_BATCH_MAX
    (the executor bounds it so batch x rows stays under the per-dispatch
    row budget — batching must not blow HBM at compile time)."""

    __slots__ = ("key", "inputs", "launch_batched", "max_members")

    def __init__(
        self,
        key: Hashable,
        inputs: Any,
        launch_batched: Callable[[List[Any]], Any],
        max_members: int = 0,
    ) -> None:
        self.key = key
        self.inputs = inputs
        self.launch_batched = launch_batched
        self.max_members = max_members


class _BatchFetch:
    """Shared FINALIZE handle for one batched launch: the FIRST member
    to need outputs performs the ONE packed D2H fetch (counted once —
    the PR 10 transfer-accounting contract); every member then slices
    its leading-axis row from the cached host pytree.  Thread-safe:
    members finalize concurrently on their own scheduler workers."""

    def __init__(self, fetch: Callable, size: int) -> None:
        self._fetch = fetch
        self._lock = threading.Lock()
        self._outs: Any = None
        self._error: Optional[BaseException] = None
        self.size = size

    def _resolve(self, handle) -> Any:
        with self._lock:
            if self._error is not None:
                raise self._error
            if self._outs is None:
                try:
                    self._outs = self._fetch(handle, count_transfer=True)
                except BaseException as e:
                    self._error = e
                    raise
            return self._outs

    def member(self, index: int) -> Callable:
        def fetch_member(handle, count_transfer: bool = True) -> Any:
            # count_transfer is ignored by design: the one physical D2H
            # is counted inside _resolve exactly once per batch
            outs = self._resolve(handle)
            from pinot_tpu.engine.packing import slice_batched_outputs

            return slice_batched_outputs(outs, index)

        return fetch_member


class _Dispatch:
    __slots__ = (
        "key", "launch", "pending", "waiters", "completed", "value",
        "error", "plan_digest", "cost_provider", "batch", "batch_size",
    )

    def __init__(
        self,
        key: Hashable,
        launch: Callable[[], Any],
        pending: Callable[[Any], bool],
        plan_digest: Optional[str] = None,
        cost_provider: Optional[Callable[[], Optional[dict]]] = None,
        batch: Optional[BatchSpec] = None,
    ) -> None:
        self.key = key
        self.launch = launch
        self.pending = pending
        self.plan_digest = plan_digest
        self.cost_provider = cost_provider
        self.batch = batch
        self.batch_size = 1  # members of the batched launch this rode
        self.waiters: List[LaneTicket] = []
        self.completed = False
        self.value: Any = None
        self.error: Optional[BaseException] = None


class DeviceLane:
    """Single-threaded asynchronous kernel-launch queue with
    identical-dispatch coalescing and watchdog supervision (see module
    docstring).

    ``stall_timeout_s`` arms the watchdog (default from
    ``PINOT_TPU_LANE_STALL_S``, 120s — above the worst observed cold
    compile; <= 0 disables it).
    ``fault_injector`` is an optional ``common.faults``
    ``DeviceFaultInjector`` consulted before every launch."""

    def __init__(
        self,
        metrics=None,
        stall_timeout_s: Optional[float] = None,
        fault_injector=None,
        index: Optional[int] = None,
    ) -> None:
        # lane-group membership (engine/mesh.py): ``index`` set means
        # this lane is one of several driving distinct chip groups —
        # its gauges move to the per-lane ``lane.<i>.*`` namespace and
        # its meters mark BOTH the aggregate lane.* series (marks sum
        # naturally across lanes) and the per-lane twin.  None (the
        # default, and every single-lane server) keeps the exact
        # pre-mesh metric names.
        self.index = index
        self.metrics = metrics
        if stall_timeout_s is None:
            # default well ABOVE the worst observed first-call compile
            # over a tunneled chip (~25s cold, PARITY.md): a watchdog
            # that fires during a legitimate cold compile would poison
            # a healthy plan
            stall_timeout_s = float(os.environ.get("PINOT_TPU_LANE_STALL_S", "120"))
        self.stall_timeout_s = stall_timeout_s
        self.fault_injector = fault_injector
        # persistent compile cache (engine/compilecache.py): point jax's
        # on-disk cache under PINOT_TPU_COMPILE_CACHE_DIR, isolated per
        # backend/topology fingerprint.  Disabled (None) keeps the exact
        # pre-r16 cold/warm behavior; the call is idempotent, so every
        # lane of a group paying it is free.
        self.persistent_cache_dir = compilecache.configure_jax_cache()
        # micro-batching tier config (module docstring): resolved once
        # at construction so a long-lived lane is immune to env churn
        self.batch_max = batch_max()
        self.batch_window_s = batch_window_s()
        self.batch_launches = 0
        self.batched_queries = 0
        self.batch_window_full = 0
        self.batch_window_timeout = 0
        self._cv = threading.Condition()
        self._queue: Deque[_Dispatch] = deque()
        self._by_key: Dict[Hashable, _Dispatch] = {}
        self._open: Deque[_Dispatch] = deque()  # launched, program still running
        self._thread: Optional[threading.Thread] = None
        self._watchdog: Optional[threading.Thread] = None
        # spawned threads still of interest to the leak check; dead
        # entries are pruned at each registration so repeated profile
        # captures / cost-analysis spawns don't grow this without bound
        self._threads: List[threading.Thread] = []
        self._threads_lock = threading.Lock()
        # restart fencing: a wedged thread that finally returns compares
        # its spawn-time generation against this and, when stale, drops
        # its result and exits without touching lane state
        self._generation = 0
        # (leader dispatch, started_at, members tuple) while a launch
        # (possibly batched) is in flight
        self._inflight: Optional[tuple] = None
        self._closed = False
        self.dispatch_count = 0
        self.coalesce_hits = 0
        self.shed_count = 0
        self.device_failure_count = 0
        self.restart_count = 0
        self.stale_completions = 0
        # compile timeline (workload introspection): per device-plan
        # digest, the FIRST launch's wall ms — on a cold jit cache that
        # launch pays trace + XLA compile (the ~25s cold figure PARITY.md
        # cites on a tunneled chip), so firstCallMs IS the measured
        # compile cost; later launches of the same digest are warm.
        # Read by EXPLAIN (cold/warm verdict + measured ms) and exposed
        # as compile.* metrics + lane.stats()["compiledPlans"].
        # Entries also accumulate per-digest launch timers
        # (launchMsTotal) and, once the async analysis lands, the
        # static XLA cost analysis ("costAnalysis": {flops,
        # bytesAccessed, ...}) — the roofline numerator.
        self._compile: Dict[str, Dict[str, float]] = {}
        # -- occupancy accounting (utilization plane) ----------------
        # Plain float accumulation at state transitions — NO per-launch
        # allocations (OCCUPANCY_ALLOCATIONS contract above).  busy =
        # wall seconds inside launch calls; depth-seconds integrates
        # queue depth over time.  Windowed readers (gauges, status,
        # sampler) each diff against their own last checkpoint.
        self._busy_s = 0.0
        self._busy_since: Optional[float] = None
        self._depth_s = 0.0
        self._depth_mark = time.monotonic()
        self._created_at = self._depth_mark
        self._occ_reads: Dict[str, tuple] = {}  # reader key -> (t, busy, depth_s, last_result)
        if metrics is not None:
            # pre-register the lane series (depth/inflight gauges,
            # dispatch/coalesce/shed/restart meters) so /metrics shows
            # them at zero before the first device query
            for name in ("lane.dispatches", "lane.coalesced", "lane.shed",
                         "lane.deviceFailures", "lane.restarts",
                         "compile.cold", "compile.warm",
                         "compile.persistentHit", "compile.persistentMiss",
                         "compile.prewarmed",
                         "compile.costAnalyses",
                         "compile.costAnalysisUnavailable",
                         "batch.launches", "batch.queries",
                         "batch.windowClosedFull",
                         "batch.windowClosedTimeout",
                         "batch.windowClosedIdle"):
                metrics.meter(name)
            metrics.timer("compile.firstCallMs")
            if self.index is None:
                metrics.gauge("lane.depth").set(0)
                metrics.gauge("lane.open").set(0)
                metrics.gauge("lane.inflight").set(0)
            else:
                # per-lane twins (lane.<i>.*): the group registers the
                # aggregate gauges as set_fn rollups over every lane
                for suffix in ("dispatches", "coalesced", "shed",
                               "deviceFailures", "restarts"):
                    metrics.meter(f"lane.{self.index}.{suffix}")
                metrics.gauge(f"lane.{self.index}.depth").set(0)
                metrics.gauge(f"lane.{self.index}.open").set(0)
                metrics.gauge(f"lane.{self.index}.inflight").set(0)
        _all_lanes.add(self)

    def _lane_mark(self, suffix: str, n: int = 1) -> None:
        """Mark the aggregate lane.<suffix> meter and, on a lane-group
        member, its per-lane twin lane.<index>.<suffix>."""
        if self.metrics is None:
            return
        self.metrics.meter(f"lane.{suffix}").mark(n)
        if self.index is not None:
            self.metrics.meter(f"lane.{self.index}.{suffix}").mark(n)

    # -- producer side -------------------------------------------------
    def submit(
        self,
        key: Hashable,
        launch: Callable[[], Any],
        deadline: Optional[float] = None,
        pending: Callable[[Any], bool] = outputs_pending,
        plan_digest: Optional[str] = None,
        cost_provider: Optional[Callable[[], Optional[dict]]] = None,
        batch: Optional[BatchSpec] = None,
    ) -> LaneTicket:
        """Enqueue a kernel launch, or coalesce onto an identical one
        that is queued, launching, or still executing on device.
        Returns immediately; the caller blocks on ``ticket.result`` when
        FINALIZE actually needs the outputs.

        ``cost_provider`` (optional, utilization plane): a zero-arg
        callable returning the plan's static XLA cost analysis (or
        None).  Invoked ONCE per plan digest on an async helper thread
        after the digest's first successful launch — never on the lane
        thread, so a slow analysis cannot stall serving.

        ``batch`` (optional, micro-batching tier): a ``BatchSpec``
        marking this dispatch stackable with same-key peers into one
        vmapped launch.  Identical dispatches still coalesce FIRST (one
        member, many waiters); batching merges *distinct* members."""
        ticket = LaneTicket(deadline)
        with self._cv:
            if self._closed:
                raise LaneClosedError("device lane is closed")
            d = self._by_key.get(key)
            if d is not None and d.completed:
                # launched already: shareable only while the program is
                # still executing (never serve finished outputs anew)
                still = d.error is None and self._still_pending(d)
                if still:
                    self._hit()
                    ticket.coalesced = True
                    # a still-pending BATCHED member hands out its
                    # member slice — the late waiter rode that batch
                    # too, so it must report the same batch size
                    ticket.batch_size = d.batch_size
                    ticket._deliver(value=d.value)
                    return ticket
                self._close_open(d)
                d = None
            if d is not None:
                d.waiters.append(ticket)
                ticket.coalesced = True
                self._hit()
            else:
                d = _Dispatch(key, launch, pending, plan_digest, cost_provider, batch)
                d.waiters.append(ticket)
                self._by_key[key] = d
                self._depth_tick_locked()
                self._queue.append(d)
                self._set_depth()
                # notify_all: the WATCHDOG also sleeps on this condition
                # — a single notify could wake it instead of the lane
                # thread and strand the queued dispatch
                self._cv.notify_all()
            if self._thread is None:
                # lazy start: instances that never run a device query
                # (host-path tables, unit tests) cost no thread
                self._spawn_lane_locked()
                if self.stall_timeout_s and self.stall_timeout_s > 0:
                    self._spawn_watchdog_locked()
        return ticket

    @property
    def depth(self) -> int:
        return len(self._queue)

    def stats(self) -> Dict[str, int]:
        return {
            "depth": len(self._queue),
            "open": len(self._open),
            "dispatches": self.dispatch_count,
            "coalesceHits": self.coalesce_hits,
            "shed": self.shed_count,
            "deviceFailures": self.device_failure_count,
            "restarts": self.restart_count,
            "staleCompletions": self.stale_completions,
            "compiledPlans": len(self._compile),
            # micro-batching tier: batched launches, the queries they
            # carried (occupancy = batchedQueries / batchLaunches), and
            # how the formation windows closed
            "batchLaunches": self.batch_launches,
            "batchedQueries": self.batched_queries,
            "batchWindowFull": self.batch_window_full,
            "batchWindowTimeout": self.batch_window_timeout,
        }

    def compile_info(self, digest: Optional[str]) -> Optional[Dict[str, float]]:
        """Compile-timeline entry for a device-plan digest: None when
        the digest has never launched here (a query would compile cold),
        else {firstCallMs, firstAt, launches, launchMsTotal[,
        costAnalysis]}.  ``costAnalysis`` is absent while the async
        analysis is still running, a dict once it landed, and None when
        the backend reported nothing (the explicit "unavailable")."""
        if digest is None:
            return None
        with self._cv:
            entry = self._compile.get(digest)
            return dict(entry) if entry is not None else None

    def record_prewarmed(self, digest: Optional[str], compile_ms: float) -> bool:
        """Register a background-prewarmed plan digest in the compile
        timeline WITHOUT touching the serving-path meters.  The prewarm
        worker (server/prewarm.py) calls this after an AOT
        ``lower().compile()`` of the phantom kernel: the executable now
        sits in the in-process jit cache (and the on-disk cache when
        enabled), so the digest's first serving launch runs warm.
        Counts on ``compile.prewarmed`` only — never compile.cold or
        firstCallMs (accounting honesty), and never near the stall
        watchdog (the compile ran off-lane).  No-op when the digest
        already launched or prewarmed here."""
        if digest is None:
            return False
        with self._cv:
            if digest in self._compile:
                return False
            if len(self._compile) > 4096:
                victim = min(
                    self._compile, key=lambda k: self._compile[k]["firstAt"]
                )
                self._compile.pop(victim, None)
            self._compile[digest] = {
                # firstCallMs here is the MEASURED prewarm compile wall
                # ms — the cost the serving path did NOT pay
                "firstCallMs": round(compile_ms, 3),
                "firstAt": round(time.time(), 3),
                "launches": 0,
                "launchMsTotal": 0.0,
                "via": "prewarmed",
            }
        if self.metrics is not None:
            self.metrics.meter("compile.prewarmed").mark()
        if self.persistent_cache_dir is not None:
            compilecache.record_plan(digest)
        return True

    # -- occupancy (utilization plane) --------------------------------
    def _depth_tick_locked(self, now: Optional[float] = None) -> None:
        """Integrate queue depth over time (lock held, called BEFORE
        every queue mutation): pure float accumulation, no
        allocations."""
        if now is None:
            now = time.monotonic()
        self._depth_s += len(self._queue) * (now - self._depth_mark)
        self._depth_mark = now

    def occupancy_read(
        self, key: str = "default", min_interval_s: float = 0.0
    ) -> Dict[str, float]:
        """Windowed occupancy read: busy-fraction and time-weighted
        average queue depth since THIS reader's previous call (first
        call windows from lane construction).  Distinct readers (the
        device.util gauges, status(), a sampler) pass distinct keys so
        their windows never clobber each other; ``min_interval_s``
        returns the cached last result for rapid re-reads (two gauges
        sharing one key read one consistent window).  Idle lanes read
        0.0 — there is no decay to wait out."""
        now = time.monotonic()
        with self._cv:
            prev = self._occ_reads.get(key)
            if (
                prev is not None
                and min_interval_s > 0
                and now - prev[0] < min_interval_s
            ):
                return dict(prev[3])
            busy = self._busy_s
            if self._busy_since is not None:
                # count the in-flight launch's elapsed time as busy so a
                # long cold compile doesn't read as an idle device
                busy += max(0.0, now - self._busy_since)
            self._depth_tick_locked(now)
            depth_s = self._depth_s
            if prev is None:
                prev_t, prev_busy, prev_depth = self._created_at, 0.0, 0.0
            else:
                prev_t, prev_busy, prev_depth = prev[0], prev[1], prev[2]
            dt = max(now - prev_t, 1e-9)
            result = {
                "windowS": round(dt, 6),
                "busyFraction": round(
                    min(max((busy - prev_busy) / dt, 0.0), 1.0), 6
                ),
                "avgQueueDepth": round(max((depth_s - prev_depth) / dt, 0.0), 6),
                "depth": len(self._queue),
                "inflight": 1 if self._busy_since is not None else 0,
            }
            if len(self._occ_reads) > 32 and key not in self._occ_reads:
                # bounded reader registry: evict the least-recently-read
                # checkpoint only — clearing everything would reset every
                # established reader's window to lane construction
                oldest = min(self._occ_reads.items(), key=lambda kv: kv[1][0])[0]
                del self._occ_reads[oldest]
            self._occ_reads[key] = (now, busy, depth_s, result)
        return dict(result)

    def close(self) -> None:
        """Idempotent: stop accepting submits, fail queued waiters, and
        let the lane + watchdog threads exit after any in-flight
        launch."""
        with self._cv:
            if self._closed:
                return
            self._closed = True
            drained = list(self._queue)
            self._depth_tick_locked()
            self._queue.clear()
            self._open.clear()
            self._by_key.clear()
            for d in drained:
                d.completed = True
            self._cv.notify_all()
        err = LaneClosedError("device lane closed while queued")
        for d in drained:
            for w in d.waiters:
                w._deliver(error=err)

    # -- internals -----------------------------------------------------
    def _track_thread(self, t: threading.Thread) -> None:
        """Register a spawned thread for the leak check.  Builds a new
        list (atomic reference swap) so concurrent leak-check readers
        never see a half-pruned list; the dedicated lock keeps two
        registrations (lane spawn under _cv, sampler start under its
        own lock) from losing one another's entry."""
        with self._threads_lock:
            alive = [x for x in self._threads if x.is_alive()]
            alive.append(t)
            self._threads = alive

    def _spawn_lane_locked(self) -> None:
        t = threading.Thread(
            target=self._run,
            args=(self._generation,),
            name=f"device-lane-g{self._generation}",
            daemon=True,
        )
        self._thread = t
        self._track_thread(t)
        t.start()

    def _spawn_cost_analysis_locked(self, digest: str, provider) -> None:
        """One short-lived helper thread per cold plan digest: runs the
        static XLA cost analysis off the serving path and stores the
        result (or the explicit None = "unavailable") into the compile
        registry.  Registered in the leak-check list like every lane
        thread, and in the module drain list so interpreter shutdown
        joins any still-tracing analysis before XLA statics destruct."""
        if _shutting_down:
            return
        t = threading.Thread(
            target=self._run_cost_analysis,
            args=(digest, provider),
            name=f"lane-cost-analysis-{digest[:8]}",
            daemon=True,
        )
        self._track_thread(t)
        with _cost_threads_lock:
            _cost_threads[:] = [x for x in _cost_threads if x.is_alive()]
            _cost_threads.append(t)
        t.start()

    def _run_cost_analysis(self, digest: str, provider) -> None:
        if _shutting_down:
            return
        try:
            analysis = provider()
        except Exception:
            analysis = None
        if analysis is not None and not isinstance(analysis, dict):
            analysis = None
        with self._cv:
            entry = self._compile.get(digest)
            if entry is not None:
                entry["costAnalysis"] = analysis
        if self.metrics is not None:
            name = (
                "compile.costAnalyses"
                if analysis is not None
                else "compile.costAnalysisUnavailable"
            )
            self.metrics.meter(name).mark()

    def _spawn_watchdog_locked(self) -> None:
        if self._watchdog is not None:
            return
        w = threading.Thread(
            target=self._watch, name="device-lane-watchdog", daemon=True
        )
        self._watchdog = w
        self._track_thread(w)
        w.start()

    def _watch(self) -> None:
        """Watchdog: restart the lane when the in-flight launch stalls
        past ``stall_timeout_s`` — abandon the wedged thread (generation
        bump), fail the stalled dispatch's waiters with a typed stall
        error, and respawn a lane thread that re-drives the queue.

        Sleeps under the lane condition variable, waking exactly at the
        in-flight dispatch's stall deadline (or a coarse idle poll) —
        no free-running high-frequency timer, and ``close()``'s
        notify_all wakes it immediately for a prompt exit."""
        idle_poll = max(0.05, self.stall_timeout_s / 4.0)
        while True:
            victims: List[LaneTicket] = []
            err: Optional[DeviceExecutionError] = None
            with self._cv:
                if self._closed:
                    return
                infl = self._inflight
                now = time.monotonic()
                if infl is None:
                    self._cv.wait(timeout=idle_poll)
                elif now - infl[1] <= self.stall_timeout_s:
                    self._cv.wait(
                        timeout=infl[1] + self.stall_timeout_s - now + 0.005
                    )
                else:
                    # a batched launch wedges as a unit: every member's
                    # waiters get the stall verdict (the executor fails
                    # each one over to the host path independently)
                    members = infl[2]
                    self._inflight = None
                    if self._busy_since is not None:
                        # bank the wedged launch's window as busy time;
                        # the abandoned thread sees itself stale later
                        # and leaves the accounting alone
                        self._busy_s += max(0.0, now - self._busy_since)
                        self._busy_since = None
                    self._generation += 1
                    self.restart_count += 1
                    self.device_failure_count += 1
                    err = DeviceExecutionError(
                        f"device dispatch stalled > {self.stall_timeout_s:.3f}s; "
                        "lane restarted",
                        retryable=False,
                        stalled=True,
                    )
                    victims = []
                    for d in members:
                        d.completed = True
                        if self._by_key.get(d.key) is d:
                            self._by_key.pop(d.key)
                        victims.extend(d.waiters)
                        d.waiters = []
                        d.error = err
                    self._spawn_lane_locked()
            if victims:
                self._lane_mark("restarts")
                self._lane_mark("deviceFailures")
                for w in victims:
                    w._deliver(error=err)

    def _hit(self) -> None:
        self.coalesce_hits += 1
        self._lane_mark("coalesced")

    def _set_depth(self) -> None:
        if self.metrics is not None:
            if self.index is None:
                self.metrics.gauge("lane.depth").set(len(self._queue))
                self.metrics.gauge("lane.open").set(len(self._open))
            else:
                self.metrics.gauge(f"lane.{self.index}.depth").set(len(self._queue))
                self.metrics.gauge(f"lane.{self.index}.open").set(len(self._open))

    def _set_inflight(self, n: int) -> None:
        if self.metrics is not None:
            if self.index is None:
                self.metrics.gauge("lane.inflight").set(n)
            else:
                self.metrics.gauge(f"lane.{self.index}.inflight").set(n)

    def _still_pending(self, d: _Dispatch) -> bool:
        if d.pending is None:
            return False
        try:
            return bool(d.pending(d.value))
        except Exception:
            return False

    def _close_open(self, d: _Dispatch) -> None:
        """Drop a completed dispatch from the coalescible set (lock
        held)."""
        if self._by_key.get(d.key) is d:
            self._by_key.pop(d.key, None)
        try:
            self._open.remove(d)
        except ValueError:
            pass

    def _sweep_open_locked(self) -> None:
        for d in list(self._open):
            if d.error is not None or not self._still_pending(d):
                self._close_open(d)
        while len(self._open) > _MAX_OPEN:
            self._close_open(self._open[0])

    # -- micro-batching formation (lock held) --------------------------
    def _gather_peers_locked(self, spec: BatchSpec, members: List[_Dispatch], cap: int) -> None:
        """Pull queued dispatches whose batch key equals ``spec.key``
        into ``members`` (up to ``cap``).  Coalescing already folded
        identical dispatches together, so every peer here is a DISTINCT
        (literals/inputs) instance of the same device program over the
        same staged table."""
        if len(members) >= cap:
            return
        taken = []
        for peer in self._queue:
            if len(members) + len(taken) >= cap:
                break
            pb = peer.batch
            if pb is not None and pb.key == spec.key:
                taken.append(peer)
        if not taken:
            return
        self._depth_tick_locked()
        for peer in taken:
            self._queue.remove(peer)
            members.append(peer)
        self._set_depth()

    def _form_batch_locked(self, d: _Dispatch, members: List[_Dispatch], gen: int) -> str:
        """Adaptive batch window (module docstring).  Gathers queued
        same-key peers immediately; an idle lane (no same-shape demand:
        fewer than 2 members) closes at once so batching never adds
        latency to a quiet server, while demonstrated demand holds the
        window open up to ``batch_window_s`` and fills to the cap.
        Returns the close reason ("full" | "timeout" | "idle")."""
        spec = d.batch
        cap = self.batch_max
        if spec.max_members:
            cap = max(1, min(cap, spec.max_members))
        self._gather_peers_locked(spec, members, cap)
        if len(members) >= cap:
            return "full"
        if len(members) < 2 or self.batch_window_s <= 0:
            return "idle"
        deadline_w = time.monotonic() + self.batch_window_s
        while (
            len(members) < cap
            and not self._closed
            and gen == self._generation
        ):
            remaining = deadline_w - time.monotonic()
            if remaining <= 0:
                return "timeout"
            # cv.wait releases the lock: submits keep landing and the
            # next gather sweep picks up fresh same-key arrivals
            self._cv.wait(remaining)
            self._gather_peers_locked(spec, members, cap)
        return "full" if len(members) >= cap else "timeout"

    def _run(self, gen: int) -> None:
        while True:
            with self._cv:
                if gen != self._generation:
                    return  # restarted away while we held no work
                self._sweep_open_locked()
                while not self._queue and not self._closed and gen == self._generation:
                    if self._open:
                        # finite wait: open dispatches must close (and
                        # release their buffers) soon after the device
                        # finishes even when no new work arrives
                        self._cv.wait(timeout=_SWEEP_S)
                        self._sweep_open_locked()
                    else:
                        self._cv.wait()
                if gen != self._generation:
                    return
                if self._closed and not self._queue:
                    return
                self._depth_tick_locked()
                d = self._queue.popleft()
                self._set_depth()
                # micro-batching: gather same-key peers (and, under
                # demonstrated demand, hold the bounded window open for
                # more) BEFORE the deadline sweep, so members expiring
                # during formation shed too
                members = [d]
                window_close = None
                if d.batch is not None and self.batch_max > 1:
                    window_close = self._form_batch_locked(d, members, gen)
                if self._closed or gen != self._generation:
                    # closed/restarted mid-formation: our members left
                    # the queue, so close()'s drain missed them — fail
                    # their waiters here
                    victims: List[LaneTicket] = []
                    closing_err: BaseException = LaneClosedError(
                        "device lane closed while batch was forming"
                    )
                    for m in members:
                        m.completed = True
                        m.error = closing_err
                        if self._by_key.get(m.key) is m:
                            self._by_key.pop(m.key)
                        victims.extend(m.waiters)
                        m.waiters = []
                    for w in victims:
                        w._deliver(error=closing_err)
                    return
                # deadline shed at lane-dequeue time, mirroring the
                # scheduler's dequeue check: the broker already failed
                # over or timed out, so device work for this waiter
                # would only delay queries that can still make it.  A
                # member expiring out of a forming batch sheds alone —
                # its batchmates launch unaffected.
                now = time.monotonic()
                dead = []
                live_members = []
                for m in members:
                    lv = [w for w in m.waiters if w.deadline is None or now < w.deadline]
                    dd = [w for w in m.waiters if w.deadline is not None and now >= w.deadline]
                    dead.extend(dd)
                    m.waiters = lv
                    if lv:
                        live_members.append(m)
                    else:
                        m.completed = True
                        if self._by_key.get(m.key) is m:
                            self._by_key.pop(m.key)
                members = live_members
                if members:
                    # watchdog window opens BEFORE the launch call: a
                    # wedge inside the fault injector or the launch
                    # itself both count as in-flight stalls; a batched
                    # launch is ONE in-flight unit (all members stall
                    # or complete together)
                    self._inflight = (members[0], now, tuple(members))
                    self._busy_since = now  # occupancy: device busy
            if dead:
                self.shed_count += len(dead)
                self._lane_mark("shed", len(dead))
                err = QueryAbandonedError(
                    "deadline expired while queued in device lane; "
                    "broker already gave up"
                )
                for w in dead:
                    w._deliver(error=err)
            if not members:
                continue
            d = members[0]
            batched = len(members) > 1
            # launch OUTSIDE the lock: first-call compiles can take
            # seconds and coalescing submits must not block behind them
            t0 = time.perf_counter()
            self._set_inflight(1)
            error: Optional[BaseException] = None
            value: Any = None
            member_values: List[Any] = []
            try:
                inj = self.fault_injector
                if inj is not None:
                    # one physical launch: the injector sees it once
                    # (members share the plan digest by construction)
                    inj.on_launch(d.plan_digest, d.key)
                if batched:
                    fetch_b, handle_b = d.batch.launch_batched(
                        [m.batch.inputs for m in members]
                    )
                    shared = _BatchFetch(fetch_b, len(members))
                    member_values = [
                        (shared.member(i), handle_b) for i in range(len(members))
                    ]
                    value = member_values[0]
                else:
                    value = d.launch()
            except Exception as e:  # typed delivery, lane stays alive
                error = classify_device_error(e)
            except BaseException as e:  # deliver raw, keep the lane alive:
                # a dead lane thread would strand every waiter and (with
                # self._thread non-None) never respawn
                error = e
            finally:
                self._set_inflight(0)
            launch_ms = (time.perf_counter() - t0) * 1000
            cold = False
            via = "cold"
            if (
                error is None
                and d.plan_digest is not None
                and self.persistent_cache_dir is not None
                and d.plan_digest not in self._compile
            ):
                # classify a first launch BEFORE taking the lane lock —
                # the plan-ledger lookup is disk I/O.  The unlocked
                # membership pre-check can only cost a spurious stat;
                # the authoritative entry check happens under _cv below.
                if compilecache.known_plan(d.plan_digest):
                    # the on-disk XLA cache served the binary: fast
                    # launch, and NOT a serving-path cold compile
                    via = "persistent"
            with self._cv:
                stale = gen != self._generation
                if not stale and self._busy_since is not None:
                    # occupancy: launch window closed.  Stale threads
                    # must not touch this — after a watchdog restart
                    # _busy_since belongs to the fresh lane thread (the
                    # watchdog already banked the wedged window).
                    self._busy_s += max(0.0, time.monotonic() - self._busy_since)
                    self._busy_since = None
                if not stale and self._inflight is not None and self._inflight[0] is d:
                    self._inflight = None
                if stale:
                    # the watchdog already failed our waiters and moved
                    # the lane on; delivering now would hand out a result
                    # nobody waits for (or double-deliver an error)
                    self.stale_completions += 1
                    return
                self.dispatch_count += 1
                if batched:
                    self.batch_launches += 1
                    self.batched_queries += len(members)
                    if window_close == "full":
                        self.batch_window_full += 1
                    elif window_close == "timeout":
                        self.batch_window_timeout += 1
                if error is None and d.plan_digest is not None:
                    # compile timeline: first successful launch of this
                    # digest measured cold (trace + XLA compile included)
                    entry = self._compile.get(d.plan_digest)
                    if entry is None:
                        cold = True
                        if len(self._compile) > 4096:
                            # bounded registry: evict the OLDEST entry
                            # only — a full clear would re-record every
                            # still-jit-cached plan as "cold" with a
                            # warm-speed firstCallMs, corrupting the
                            # compile series this registry exists for
                            victim = min(
                                self._compile, key=lambda k: self._compile[k]["firstAt"]
                            )
                            self._compile.pop(victim, None)
                        self._compile[d.plan_digest] = {
                            "firstCallMs": round(launch_ms, 3),
                            "firstAt": round(time.time(), 3),
                            "launches": 1,
                            "launchMsTotal": round(launch_ms, 3),
                            # how the first launch got its executable:
                            # "cold" (paid the XLA compile here),
                            # "persistent" (on-disk cache restored it),
                            # or "prewarmed" via record_prewarmed()
                            "via": via,
                        }
                        if d.cost_provider is not None:
                            # static cost analysis, once per digest, on
                            # a helper thread — never the lane thread
                            self._spawn_cost_analysis_locked(
                                d.plan_digest, d.cost_provider
                            )
                    else:
                        entry["launches"] += 1
                        entry["launchMsTotal"] = round(
                            entry.get("launchMsTotal", 0.0) + launch_ms, 3
                        )
                if error is not None:
                    self.device_failure_count += 1
                deliveries = []
                for i, m in enumerate(members):
                    m.completed = True
                    m.error = error
                    m.batch_size = len(members)
                    m.value = (
                        None
                        if error is not None
                        else (member_values[i] if batched else value)
                    )
                    waiters = list(m.waiters)
                    m.waiters = []
                    deliveries.append((m.value, waiters))
                    if error is None and not self._closed and self._still_pending(m):
                        # program still executing: keep coalescible
                        self._open.append(m)
                    elif self._by_key.get(m.key) is m:
                        self._by_key.pop(m.key)
                self._sweep_open_locked()
            if self.metrics is not None:
                self._lane_mark("dispatches")
                if batched:
                    self.metrics.meter("batch.launches").mark()
                    self.metrics.meter("batch.queries").mark(len(members))
                    self.metrics.meter(
                        {
                            "full": "batch.windowClosedFull",
                            "timeout": "batch.windowClosedTimeout",
                        }.get(window_close, "batch.windowClosedIdle")
                    ).mark()
                if error is not None:
                    self._lane_mark("deviceFailures")
                elif d.plan_digest is not None:
                    if cold:
                        # accounting honesty (r16): only a launch that
                        # actually PAID the XLA compile on the serving
                        # path counts cold — a persistent-cache restore
                        # is its own meter, and firstCallMs keeps
                        # measuring compile cost, not restore cost
                        if via == "persistent":
                            self.metrics.meter("compile.persistentHit").mark()
                        else:
                            self.metrics.meter("compile.cold").mark()
                            self.metrics.timer("compile.firstCallMs").update(
                                launch_ms
                            )
                            if self.persistent_cache_dir is not None:
                                self.metrics.meter("compile.persistentMiss").mark()
                    else:
                        self.metrics.meter("compile.warm").mark()
                self.metrics.timer("phase.laneDispatch").update(launch_ms)
            if cold and via == "cold" and self.persistent_cache_dir is not None:
                # the compile just wrote an XLA cache entry; ledger it so
                # the NEXT process classifies this digest as persistent
                compilecache.record_plan(d.plan_digest)
            n_members = len(members)
            for mvalue, waiters in deliveries:
                for w in waiters:
                    w.batch_size = n_members
                    w._deliver(value=mvalue, error=error)


class LaneSelection:
    """One query's lane routing verdict: which lane executes it and
    which chip group (engine/mesh.py) that lane drives."""

    __slots__ = ("index", "lane", "group")

    def __init__(self, index: int, lane: DeviceLane, group) -> None:
        self.index = index
        self.lane = lane
        self.group = group


class LaneGroup:
    """One DeviceLane per chip group (engine/mesh.py MeshTopology) —
    the pod-scale generalization of the single serving lane.

    Lane selection is SHAPE-HASHED: a query routes by its literal-
    erased plan-shape digest (engine/plandigest.py), so every instance
    of a shape lands on the same lane and identical-dispatch coalescing
    keeps working exactly as on a single lane, while distinct shapes
    spread across the groups.  Deadline shedding, watchdog supervision,
    and poison classification are all per-lane (unchanged DeviceLane
    semantics): one wedged or poisoned lane heals via the host path
    while the other lanes keep serving their shapes.

    A single-group topology builds ONE lane with ``index=None`` — the
    byte-identical pre-mesh configuration (same metric names, same
    stats shape)."""

    def __init__(
        self,
        topology,
        metrics=None,
        stall_timeout_s: Optional[float] = None,
        fault_injector=None,
    ) -> None:
        self.topology = topology
        groups = list(topology.groups)
        n = len(groups)
        self.lanes: List[DeviceLane] = [
            DeviceLane(
                metrics=metrics,
                stall_timeout_s=stall_timeout_s,
                fault_injector=fault_injector,
                index=None if n == 1 else g.index,
            )
            for g in groups
        ]
        if metrics is not None and n > 1:
            # aggregate gauges become rollups over the group (per-lane
            # twins live at lane.<i>.*); meters need nothing — every
            # lane marks the shared aggregate series
            lanes = self.lanes
            metrics.gauge("lane.depth").set_fn(
                lambda: sum(l.depth for l in lanes)
            )
            metrics.gauge("lane.open").set_fn(
                lambda: sum(len(l._open) for l in lanes)
            )
            metrics.gauge("lane.inflight").set_fn(
                lambda: sum(1 for l in lanes if l._busy_since is not None)
            )

    @property
    def size(self) -> int:
        return len(self.lanes)

    @property
    def primary(self) -> DeviceLane:
        return self.lanes[0]

    @property
    def restart_count(self) -> int:
        return sum(l.restart_count for l in self.lanes)

    def lane_index(self, shape_key) -> int:
        """Stable shape -> lane hash (blake2b, not the per-process-
        randomized builtin hash: the routing must be reproducible
        across runs for committed bench artifacts to be comparable)."""
        if len(self.lanes) == 1:
            return 0
        import hashlib

        h = hashlib.blake2b(str(shape_key).encode(), digest_size=8).digest()
        return int.from_bytes(h, "little") % len(self.lanes)

    def select(self, shape_key) -> LaneSelection:
        i = self.lane_index(shape_key)
        return LaneSelection(i, self.lanes[i], self.topology.groups[i])

    def compile_info(self, digest: Optional[str]) -> Optional[Dict[str, float]]:
        """Compile-timeline entry across the group (a digest only ever
        launches on its shape-hashed lane, so at most one lane knows
        it)."""
        for lane in self.lanes:
            ci = lane.compile_info(digest)
            if ci is not None:
                return ci
        return None

    def stats(self) -> Dict[str, Any]:
        """Single lane: the lane's stats verbatim (pre-mesh shape).
        Group: summed rollup plus the per-lane list — the fleet-rollup
        totals are computed FROM the per-lane snapshots, so they equal
        the sum of lane snapshots by construction."""
        if len(self.lanes) == 1:
            return self.lanes[0].stats()
        per_lane = [l.stats() for l in self.lanes]
        rollup: Dict[str, Any] = {
            k: sum(s[k] for s in per_lane) for k in per_lane[0]
        }
        rollup["lanes"] = per_lane
        return rollup

    def occupancy_read(
        self, key: str = "default", min_interval_s: float = 0.0
    ) -> Dict[str, Any]:
        """Windowed occupancy across the group.  Single lane: verbatim
        lane read.  Group: per-lane reads under ``lanes`` plus a rollup
        whose summable fields equal the sum of the lane snapshots
        (busyFraction sums to "busy lanes" in [0, size] — the fleet
        busy measure; depth/inflight/avgQueueDepth sum likewise)."""
        if len(self.lanes) == 1:
            return self.lanes[0].occupancy_read(key, min_interval_s)
        reads = [l.occupancy_read(key, min_interval_s) for l in self.lanes]
        return {
            "windowS": max(r["windowS"] for r in reads),
            "busyFraction": round(sum(r["busyFraction"] for r in reads), 6),
            "avgQueueDepth": round(sum(r["avgQueueDepth"] for r in reads), 6),
            "depth": sum(r["depth"] for r in reads),
            "inflight": sum(r["inflight"] for r in reads),
            "lanes": reads,
        }

    def close(self) -> None:
        for lane in self.lanes:
            lane.close()


class OccupancySampler:
    """Periodic lane-occupancy sampler: a small thread recording
    (wall ts, busy-fraction, avg queue depth, instantaneous depth)
    samples into a bounded ring — the queue-depth-over-time series
    behind ``status()["device"]`` and the profiling workflow.

    STRICTLY opt-in: nothing starts it by default, and while it is not
    running the lane's launch path performs no occupancy-related
    allocations at all (the ``OCCUPANCY_ALLOCATIONS`` contract — the
    lane's own accounting is plain float accumulation).  ``start()`` /
    ``stop()`` are idempotent; the thread registers with its lane's
    leak-check list so the conftest thread-leak guard holds the
    lifecycle honest, and it exits on its own when the lane closes."""

    def __init__(self, lane: DeviceLane, interval_s: float = 0.25,
                 capacity: int = 240) -> None:
        self.lane = lane
        self.interval_s = max(0.02, float(interval_s))
        self._ring: Deque[tuple] = deque(maxlen=max(8, capacity))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._key = f"sampler-{id(self):x}"
        self.samples_taken = 0

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive() and not self._stop.is_set()

    def start(self) -> None:
        with self._lock:
            if self.running or self.lane._closed:
                return
            prev = self._thread
            if prev is not None and prev.is_alive():
                # a stop() set the event but hasn't finished joining:
                # finish the join HERE before re-arming, else the old
                # thread could miss the cleared event and sample forever
                # alongside the new one
                self._stop.set()
                prev.join(timeout=2)
                if prev.is_alive():
                    return  # refuse to double-start; retry after it exits
            self._stop = threading.Event()  # fresh event per thread
            t = threading.Thread(
                target=self._run,
                args=(self._stop,),
                name="lane-occupancy-sampler",
                daemon=True,
            )
            self._thread = t
            self.lane._track_thread(t)
            t.start()

    def stop(self) -> None:
        with self._lock:
            self._stop.set()
            t = self._thread
        if t is not None:
            t.join(timeout=2)
        # drop this sampler's reader checkpoint so repeated sampler
        # lifecycles on a long-lived lane don't walk the registry cap
        with self.lane._cv:
            self.lane._occ_reads.pop(self._key, None)

    def _run(self, stop: threading.Event) -> None:
        global OCCUPANCY_ALLOCATIONS
        while not stop.wait(self.interval_s):
            if self.lane._closed:
                return
            occ = self.lane.occupancy_read(self._key)
            OCCUPANCY_ALLOCATIONS += 1
            self.samples_taken += 1
            self._ring.append(
                (
                    round(time.time(), 3),
                    occ["busyFraction"],
                    occ["avgQueueDepth"],
                    occ["depth"],
                )
            )

    def snapshot(self, last: int = 60) -> Dict[str, Any]:
        samples = list(self._ring)[-max(1, last):]
        return {
            "running": self.running,
            "intervalS": self.interval_s,
            "samplesTaken": self.samples_taken,
            "samples": [
                {
                    "ts": s[0],
                    "busyFraction": s[1],
                    "avgQueueDepth": s[2],
                    "depth": s[3],
                }
                for s in samples
            ],
        }
