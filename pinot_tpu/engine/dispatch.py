"""Device lane: the single-threaded dispatch stage of the serving
pipeline, with identical-dispatch coalescing.

The whole table executes as ONE vmapped XLA program, so the chip is a
single serialized execution lane — unlike the reference's per-segment
operator trees, there is nothing to gain from launching kernels from
many threads, and every millisecond a scheduler worker spends on host
planning or finalize while *holding* the device is a millisecond the
chip idles.  The server query path is therefore a three-stage pipeline:

  PREP      (QueryScheduler worker pool): prune -> stage lookup ->
            StaticPlan -> QueryInputs -> H2D uploads
  DISPATCH  (this module, one thread): kernel launches only.  Launches
            are asynchronous — jax returns device buffers before the
            program finishes, so the lane keeps the device queue fed
            while earlier queries are still executing/finalizing.
  FINALIZE  (back on the worker that submitted): the first D2H read
            (``np.asarray`` on the packed output buffer) blocks until
            the program completes, then partials build host-side.

COALESCING: waiters whose (StaticPlan, staged-table identity,
query-inputs digest) match a dispatch that is queued, launching, or
still EXECUTING on device attach to it instead of enqueueing their own
— the one set of output buffers fans out to every waiter, so N
concurrent dashboard-style repeats of the same query cost ONE kernel
launch.  Identical key implies identical device inputs implies
identical outputs, and each waiter still runs its own FINALIZE, so
results stay independent per query.  The window ends the moment the
program's outputs are ready (``jax.Array.is_ready``): past that point
handing out the buffers would be result caching, which this
deliberately is not — a query arriving after the outputs exist always
re-dispatches.

DEADLINES: each waiter carries the broker-propagated monotonic deadline
(server/scheduler.py semantics).  A waiter whose deadline expired while
its dispatch sat in the lane queue is shed with the existing
``QueryAbandonedError`` before any device work happens on its behalf;
a dispatch all of whose waiters expired is dropped without launching.

Counters (surfaced via the server status/metrics snapshot):
lane depth gauge, dispatch/coalesce-hit/shed meters, and the
``phase.laneDispatch`` timer for time spent inside launches.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, Hashable, List, Optional

from pinot_tpu.server.scheduler import QueryAbandonedError

# completed dispatches kept open (still coalescible) at once; beyond
# this the oldest close early — a bound on pinned output buffers, not
# a correctness knob
_MAX_OPEN = 32
# poll period for closing open dispatches while the queue is idle; the
# check is a non-blocking is_ready() per open dispatch
_SWEEP_S = 0.005


def outputs_pending(value: Any) -> bool:
    """True while any jax-array leaf of a launch's return value has not
    finished computing — the coalescibility window for a launch that
    already returned.  Values with no device arrays report False (no
    retention)."""
    import jax

    for leaf in jax.tree_util.tree_leaves(value):
        is_ready = getattr(leaf, "is_ready", None)
        if is_ready is not None:
            try:
                if not is_ready():
                    return True
            except Exception:
                return False
    return False


class LaneClosedError(RuntimeError):
    """Submit after close(), or queued work drained by close()."""


class LaneTicket:
    """One waiter's slot: the submitting worker blocks on ``result`` and
    resumes FINALIZE when the lane delivers outputs (or an error)."""

    __slots__ = ("deadline", "_event", "_value", "_error")

    def __init__(self, deadline: Optional[float]) -> None:
        self.deadline = deadline
        self._event = threading.Event()
        self._value: Any = None
        self._error: Optional[BaseException] = None

    def _deliver(self, value: Any = None, error: Optional[BaseException] = None) -> None:
        self._value = value
        self._error = error
        self._event.set()

    def result(self, deadline: Optional[float] = None) -> Any:
        """Block until the dispatch delivers; honors the query deadline
        (raises the builtin ``TimeoutError`` like ``QueryScheduler.run``
        so the instance's timeout reply path handles both stages)."""
        timeout = None
        if deadline is not None:
            timeout = max(0.0, deadline - time.monotonic())
        if not self._event.wait(timeout):
            raise TimeoutError("device lane result exceeded query deadline")
        if self._error is not None:
            raise self._error
        return self._value


class _Dispatch:
    __slots__ = ("key", "launch", "pending", "waiters", "completed", "value", "error")

    def __init__(
        self,
        key: Hashable,
        launch: Callable[[], Any],
        pending: Callable[[Any], bool],
    ) -> None:
        self.key = key
        self.launch = launch
        self.pending = pending
        self.waiters: List[LaneTicket] = []
        self.completed = False
        self.value: Any = None
        self.error: Optional[BaseException] = None


class DeviceLane:
    """Single-threaded asynchronous kernel-launch queue with
    identical-dispatch coalescing (see module docstring)."""

    def __init__(self, metrics=None) -> None:
        self.metrics = metrics
        self._cv = threading.Condition()
        self._queue: Deque[_Dispatch] = deque()
        self._by_key: Dict[Hashable, _Dispatch] = {}
        self._open: Deque[_Dispatch] = deque()  # launched, program still running
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self.dispatch_count = 0
        self.coalesce_hits = 0
        self.shed_count = 0

    # -- producer side -------------------------------------------------
    def submit(
        self,
        key: Hashable,
        launch: Callable[[], Any],
        deadline: Optional[float] = None,
        pending: Callable[[Any], bool] = outputs_pending,
    ) -> LaneTicket:
        """Enqueue a kernel launch, or coalesce onto an identical one
        that is queued, launching, or still executing on device.
        Returns immediately; the caller blocks on ``ticket.result`` when
        FINALIZE actually needs the outputs."""
        ticket = LaneTicket(deadline)
        with self._cv:
            if self._closed:
                raise LaneClosedError("device lane is closed")
            d = self._by_key.get(key)
            if d is not None and d.completed:
                # launched already: shareable only while the program is
                # still executing (never serve finished outputs anew)
                still = d.error is None and self._still_pending(d)
                if still:
                    self._hit()
                    ticket._deliver(value=d.value)
                    return ticket
                self._close_open(d)
                d = None
            if d is not None:
                d.waiters.append(ticket)
                self._hit()
            else:
                d = _Dispatch(key, launch, pending)
                d.waiters.append(ticket)
                self._by_key[key] = d
                self._queue.append(d)
                self._set_depth()
                self._cv.notify()
            if self._thread is None:
                # lazy start: instances that never run a device query
                # (host-path tables, unit tests) cost no thread
                self._thread = threading.Thread(
                    target=self._run, name="device-lane", daemon=True
                )
                self._thread.start()
        return ticket

    @property
    def depth(self) -> int:
        return len(self._queue)

    def stats(self) -> Dict[str, int]:
        return {
            "depth": len(self._queue),
            "open": len(self._open),
            "dispatches": self.dispatch_count,
            "coalesceHits": self.coalesce_hits,
            "shed": self.shed_count,
        }

    def close(self) -> None:
        """Idempotent: stop accepting submits, fail queued waiters, and
        let the lane thread exit after any in-flight launch."""
        with self._cv:
            if self._closed:
                return
            self._closed = True
            drained = list(self._queue)
            self._queue.clear()
            self._open.clear()
            self._by_key.clear()
            for d in drained:
                d.completed = True
            self._cv.notify_all()
        err = LaneClosedError("device lane closed while queued")
        for d in drained:
            for w in d.waiters:
                w._deliver(error=err)

    # -- internals -----------------------------------------------------
    def _hit(self) -> None:
        self.coalesce_hits += 1
        if self.metrics is not None:
            self.metrics.meter("lane.coalesced").mark()

    def _set_depth(self) -> None:
        if self.metrics is not None:
            self.metrics.gauge("lane.depth").set(len(self._queue))

    def _still_pending(self, d: _Dispatch) -> bool:
        if d.pending is None:
            return False
        try:
            return bool(d.pending(d.value))
        except Exception:
            return False

    def _close_open(self, d: _Dispatch) -> None:
        """Drop a completed dispatch from the coalescible set (lock
        held)."""
        if self._by_key.get(d.key) is d:
            self._by_key.pop(d.key, None)
        try:
            self._open.remove(d)
        except ValueError:
            pass

    def _sweep_open_locked(self) -> None:
        for d in list(self._open):
            if d.error is not None or not self._still_pending(d):
                self._close_open(d)
        while len(self._open) > _MAX_OPEN:
            self._close_open(self._open[0])

    def _run(self) -> None:
        while True:
            with self._cv:
                self._sweep_open_locked()
                while not self._queue and not self._closed:
                    if self._open:
                        # finite wait: open dispatches must close (and
                        # release their buffers) soon after the device
                        # finishes even when no new work arrives
                        self._cv.wait(timeout=_SWEEP_S)
                        self._sweep_open_locked()
                    else:
                        self._cv.wait()
                if self._closed and not self._queue:
                    return
                d = self._queue.popleft()
                self._set_depth()
                # deadline shed at lane-dequeue time, mirroring the
                # scheduler's dequeue check: the broker already failed
                # over or timed out, so device work for this waiter
                # would only delay queries that can still make it
                now = time.monotonic()
                live = [w for w in d.waiters if w.deadline is None or now < w.deadline]
                dead = [w for w in d.waiters if w.deadline is not None and now >= w.deadline]
                d.waiters = live
                if not live:
                    d.completed = True
                    self._by_key.pop(d.key, None)
            if dead:
                self.shed_count += len(dead)
                if self.metrics is not None:
                    self.metrics.meter("lane.shed").mark(len(dead))
                err = QueryAbandonedError(
                    "deadline expired while queued in device lane; "
                    "broker already gave up"
                )
                for w in dead:
                    w._deliver(error=err)
            if not live:
                continue
            # launch OUTSIDE the lock: first-call compiles can take
            # seconds and coalescing submits must not block behind them
            t0 = time.perf_counter()
            error: Optional[BaseException] = None
            value: Any = None
            try:
                value = d.launch()
            except BaseException as e:  # deliver to waiters, keep lane alive
                error = e
            self.dispatch_count += 1
            if self.metrics is not None:
                self.metrics.meter("lane.dispatches").mark()
                self.metrics.timer("phase.laneDispatch").update(
                    (time.perf_counter() - t0) * 1000
                )
            with self._cv:
                d.completed = True
                d.value, d.error = value, error
                waiters = list(d.waiters)
                d.waiters = []
                if error is None and not self._closed and self._still_pending(d):
                    # program still executing: keep coalescible
                    self._open.append(d)
                    self._sweep_open_locked()
                else:
                    self._by_key.pop(d.key, None)
            for w in waiters:
                w._deliver(value=value, error=error)
