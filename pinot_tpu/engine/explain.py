"""EXPLAIN: the serving-tier decision records, computed WITHOUT serving.

``build_explain_node`` walks the exact decision order the executor
applies (``executor.execute`` -> ``_execute_engine``) — prune verdicts,
star-tree routing, the postings/scan operator choice, planner
host-forcing, poison quarantine, and the zone-map/full-scan split — and
returns a JSON-safe per-server plan node instead of results.

The device-path decisions (StaticPlan shape, its digest, the zone-map
candidate fraction) normally require a staged table; EXPLAIN must never
stage (a cold EXPLAIN of a 1B-row table must not trigger a multi-GB H2D
transfer) and never launch kernels.  ``_phantom_staged`` therefore
builds a metadata-only ``StagedTable`` twin: the same n_pad/card_pad
bucketing, per-segment cards, and role-array PRESENCE (zero-length
sentinels) that real staging would produce — ``build_static_plan`` and
``build_query_inputs`` read only those, so the phantom yields the
IDENTICAL ``StaticPlan`` (hence the identical plan digest and poison
key) the executor would compile, with zero device bytes moved.

The safety contract (tier-1 guarded): plain EXPLAIN performs zero lane
submissions and marks zero cost meters.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from pinot_tpu.common.request import BrokerRequest
from pinot_tpu.engine import config
from pinot_tpu.engine.context import get_table_context
from pinot_tpu.engine.device import LEDGER, StagedColumn, StagedTable
from pinot_tpu.engine.dispatch import plan_digest
from pinot_tpu.engine.invindex_path import index_path_decision
from pinot_tpu.engine.plan import (
    build_query_inputs,
    build_static_plan,
    plan_forced_host,
)
from pinot_tpu.engine.plandigest import plan_shape_digest, plan_shape_summary
from pinot_tpu.engine.pruner import prune_explain
from pinot_tpu.segment.immutable import ImmutableSegment

# serving-tier name (as it appears in per-segment records) -> cost-
# vector count key, derived from the ONE mapping in engine/results.py
# so EXPLAIN ANALYZE's estimated-vs-actual comparison lines up
# key-for-key with the cost vector ("fullScan" -> "segmentsFullScan")
from pinot_tpu.engine.results import SEGMENT_TIER_NAMES

TIER_COST_KEYS = {name: key for key, name in SEGMENT_TIER_NAMES.items()}


def _json_safe(v: Any) -> Any:
    """numpy scalars/arrays -> plain Python, recursively (the plan node
    rides the tagged wire codec, which knows no numpy)."""
    if isinstance(v, dict):
        return {str(k): _json_safe(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_json_safe(x) for x in v]
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, np.ndarray):
        return [_json_safe(x) for x in v.tolist()]
    if isinstance(v, (np.bool_,)):
        return bool(v)
    return v


_SENTINEL = np.zeros(0, dtype=np.int8)


def _phantom_staged(
    segments: Sequence[ImmutableSegment],
    column_names: Sequence[str],
    raw_cols: Sequence[str],
    gfwd_cols: Sequence[str],
    hll_cols: Sequence[str],
    pad_segments_to: int = 0,
) -> StagedTable:
    """Metadata-only StagedTable twin (module docstring): identical
    shape bucketing + role presence, zero device arrays.  MUST mirror
    ``device.stage_segments``'s metadata computation exactly — the
    resulting StaticPlan (and therefore its digest and poison key) has
    to match what a real execution would build."""
    S = max(len(segments), pad_segments_to)
    n_pad = config.pad_docs(max(seg.num_docs for seg in segments))
    st = StagedTable(
        segment_names=tuple(s.segment_name for s in segments),
        num_segments=S,
        n_pad=n_pad,
        num_docs=tuple(s.num_docs for s in segments) + (0,) * (S - len(segments)),
        num_docs_arr=np.asarray(
            [s.num_docs for s in segments] + [0] * (S - len(segments)),
            dtype=np.int32,
        ),
    )
    for name in sorted(set(column_names)):
        cols = [seg.column(name) for seg in segments]
        meta0 = cols[0].metadata
        cards = tuple(c.dictionary.cardinality for c in cols)
        card_pad = config.pad_card(max(cards))
        sc = StagedColumn(
            name=name,
            stored_type=meta0.data_type.stored_type,
            single_value=meta0.single_value,
            card_pad=card_pad,
            mv_pad=0,
            cards=cards,
        )
        if meta0.single_value:
            # role-array PRESENCE must match stage_segments' conditions:
            # the planner reads only `is not None`
            if name in raw_cols and sc.is_numeric:
                sc.raw = _SENTINEL
            if name in gfwd_cols:
                sc.gfwd = _SENTINEL
            if name in hll_cols:
                sc.hll_rho = _SENTINEL
                sc.hll_bucket = _SENTINEL
        else:
            mv_pad = max(1, max(c.metadata.max_num_multi_values for c in cols))
            sc.mv_pad = config.pad_card(mv_pad)
            if name in raw_cols and sc.is_numeric:
                sc.mv_raw = _SENTINEL
        st.columns[name] = sc
    return st


def _estimate_scan_bytes(
    segments: Sequence[ImmutableSegment], columns: Sequence[str], fraction: float
) -> int:
    """Static byte estimate for a device scan: per-column forward-index
    bytes at the staged integer width, scaled by the zone-map candidate
    fraction (1.0 for a full scan) — the same shape the actual cost
    vector reports."""
    total = 0
    for seg in segments:
        for name in columns:
            col = seg.columns.get(name)
            if col is None:
                continue
            meta = col.metadata
            itemsize = np.dtype(
                config.index_dtype(config.pad_card(max(meta.cardinality, 1)))
            ).itemsize
            rows = seg.num_docs
            if meta.single_value:
                total += rows * itemsize
            else:
                total += rows * max(1, meta.max_num_multi_values) * itemsize
    return int(total * min(max(fraction, 0.0), 1.0))


def _staged_snapshot(table: str, segment_names: Sequence[str]) -> Dict[str, Any]:
    """What of this query's segments is ALREADY resident in HBM, read
    off the PR 6 staging ledger (never stages anything new).  Entries
    must match on BOTH table and segment names: segment names are only
    unique within a table, so name intersection alone would attribute
    another table's staged bytes to this query."""
    from pinot_tpu.engine.plandigest import _raw_table

    wanted = set(segment_names)
    raw = _raw_table(table)
    bytes_total = 0
    columns: set = set()
    entries = 0
    for e in LEDGER.snapshot()["entries"]:
        etable = e.get("table") or ""
        # ledger tables come from segment metadata (physical names);
        # an empty one (metadata without table_name) can only match on
        # segments
        if etable and _raw_table(etable) != raw:
            continue
        if not wanted.intersection(e.get("segments") or ()):
            continue
        entries += 1
        bytes_total += int(e.get("bytes") or 0)
        columns.update((e.get("columns") or {}).keys())
    # per-segment residency tier (engine/residency.py): which of this
    # query's segments sit hot (HBM), warm (host snapshot), cold (disk
    # spool) — anything the manager has never seen is "unstaged".
    # Matching mirrors the ledger rules above: physical table names,
    # empty falls back to segment-name membership.
    from pinot_tpu.engine.residency import RESIDENCY

    tiers = RESIDENCY.segment_tiers(raw, segment_names, raw_match=True)
    residency = {s: tiers.get(s, "unstaged") for s in segment_names}
    return {
        "hbmBytes": bytes_total,
        "stagedTables": entries,
        "columns": sorted(columns),
        "residency": residency,
    }


def build_explain_node(
    executor,
    segments: Sequence[ImmutableSegment],
    request: BrokerRequest,
    table: str,
    server_name: str,
    plan_stats=None,
    result_cache=None,
) -> Dict[str, Any]:
    """One server's EXPLAIN plan node (module docstring).  ``executor``
    supplies the decision helpers AND the live poison-quarantine state;
    ``plan_stats`` (utils/planstats.py) supplies historical estimates;
    ``result_cache`` (engine/rescache.py) answers the device node's
    cacheHit probe without marking hit/miss meters."""
    total_docs = sum(s.num_docs for s in segments)
    records: List[Dict[str, Any]] = []
    tier_counts: Dict[str, int] = {}

    def record(seg: ImmutableSegment, tier: str, reason: str, **extra) -> None:
        tier_counts[TIER_COST_KEYS[tier]] = tier_counts.get(TIER_COST_KEYS[tier], 0) + 1
        records.append(
            dict({"segment": seg.segment_name, "tier": tier, "reason": reason}, **extra)
        )

    verdicts = prune_explain(segments, request)
    live = [seg for seg, reason in verdicts if reason is None]
    for seg, reason in verdicts:
        if reason is not None:
            record(seg, "pruned", reason)

    device_info: Optional[Dict[str, Any]] = None
    est_bytes = 0
    normal: List[ImmutableSegment] = []
    if live:
        from pinot_tpu.startree.operator import is_fit_for_star_tree

        star = [s for s in live if is_fit_for_star_tree(request, s)]
        normal = [s for s in live if s not in star]
        for seg in star:
            record(
                seg,
                "starTree",
                "conjunctive-EQ dims + aggregations covered by the "
                "segment's star-tree cube",
            )

    if normal:
        needed = set(request.referenced_columns())
        sel_columns: Optional[List[str]] = None
        if request.is_selection:
            sel_columns = executor._resolve_selection_columns(request, normal[0])
            needed.update(sel_columns)
        # chip-group routing mirrors the executor EXACTLY: the phantom
        # must pad the segment axis for the mesh of the lane this shape
        # would execute on, or the StaticPlan digest would diverge from
        # real sharded execution
        selection = None
        if getattr(executor, "lanes", None) is not None:
            selection = executor.lane_selection(request)
        exec_mesh = (
            selection.group.mesh if selection is not None else executor.mesh
        )
        pad_to = 0
        if exec_mesh is not None:
            n = int(exec_mesh.devices.size)
            pad_to = -(-len(normal) // n) * n
        needed -= executor._docrange_only_columns(request, normal, sel_columns)
        ctx = get_table_context(normal)

        decision, state = index_path_decision(request, normal, ctx, total_docs)
        bsi_decision, bsi_state = (None, None)
        if state is None and exec_mesh is None:
            # same tier order as the executor: bit-sliced engages only
            # after postings declines, and only off-mesh
            from pinot_tpu.engine.bitsliced import bitsliced_decision

            bsi_decision, bsi_state = bitsliced_decision(
                request, normal, ctx, total_docs
            )
        if state is not None:
            est_bytes = int(decision.get("estMatches", 0)) * (
                decision.get("residuals", 0) + 1
            ) * 8
            for seg in normal:
                record(
                    seg, "postings", decision["reason"],
                    drivingColumn=decision.get("column"),
                )
        elif bsi_state is not None:
            _spec, _leaves, _aggs, planes_total, _fp = bsi_state
            est_bytes = (total_docs * planes_total) // 8
            for seg in normal:
                record(
                    seg,
                    "bitsliced",
                    bsi_decision["reason"],
                    planes=bsi_decision.get("planes"),
                    planeCounts=bsi_decision.get("planeCounts"),
                    fusedAggs=bsi_decision.get("fusedAggs"),
                )
            # the bit-sliced kernel is a lane-registered device plan
            # like any scan: its digest must match what the real
            # execution hands the lane (try_bitsliced_path), so the
            # compile timeline and poison lookups stay digest-exact
            pdigest = plan_digest(("bsi", _spec))
            lane = (
                selection.lane
                if selection is not None
                else getattr(executor, "lane", None)
            )
            compile_entry = (
                lane.compile_info(pdigest) if lane is not None else None
            )
            if compile_entry is not None:
                cstate = (
                    "warm"
                    if compile_entry.get("launches", 0) > 0
                    else compile_entry.get("via", "warm")
                )
                compile_info = {"state": cstate, **compile_entry}
                if "costAnalysis" not in compile_entry:
                    compile_info["costAnalysis"] = "pending"
                elif compile_entry["costAnalysis"] is None:
                    compile_info["costAnalysis"] = "unavailable"
            else:
                from pinot_tpu.engine import compilecache

                cstate = (
                    "persistent"
                    if compilecache.enabled() and compilecache.known_plan(pdigest)
                    else "cold"
                )
                compile_info = {"state": cstate, "costAnalysis": "unavailable"}
            lanes_obj = getattr(executor, "lanes", None)
            n_lanes = lanes_obj.size if lanes_obj is not None else 1
            device_info = {
                "planDigest": pdigest,
                "compile": compile_info,
                "quarantined": False,
                "mesh": {
                    "shape": f"{n_lanes}x1",
                    "lanes": n_lanes,
                    "laneIndex": selection.index if selection is not None else 0,
                    "shardAxis": None,
                    "collective": None,
                },
            }
        elif plan_forced_host(request, ctx):
            est_bytes = _estimate_scan_bytes(normal, sorted(needed), 1.0)
            for seg in normal:
                record(
                    seg,
                    "host",
                    "planner forces host before staging (group capacity "
                    "or guaranteed sort-pair overflow)",
                )
        else:
            raw_cols, gfwd_cols, hll_cols = executor._role_columns(
                request, normal, ctx
            )
            phantom = _phantom_staged(
                normal,
                list(needed) + list(request.referenced_columns()),
                raw_cols, gfwd_cols, hll_cols,
                pad_segments_to=pad_to,
            )
            scratch: Dict[Any, Any] = {}
            plan = build_static_plan(request, ctx, phantom, scratch=scratch)
            if not plan.on_device:
                est_bytes = _estimate_scan_bytes(normal, sorted(needed), 1.0)
                for seg in normal:
                    record(
                        seg,
                        "host",
                        "StaticPlan is device-ineligible (group capacity, "
                        "MV expansion, or pair-overflow guard)",
                    )
            else:
                pdigest = plan_digest(plan)
                poison = executor.poisoned_entry((pdigest, phantom.segment_names))
                lane = (
                    selection.lane
                    if selection is not None
                    else getattr(executor, "lane", None)
                )
                compile_entry = (
                    lane.compile_info(pdigest) if lane is not None else None
                )
                if compile_entry is not None:
                    # launched here -> warm; a prewarmed/persistent
                    # entry that has NOT served yet reports how its
                    # executable arrived (the r16 warm-start states)
                    state = (
                        "warm"
                        if compile_entry.get("launches", 0) > 0
                        else compile_entry.get("via", "warm")
                    )
                    compile_info = {"state": state, **compile_entry}
                    # static cost-analysis tri-state (utilization
                    # plane): a dict once the async analysis landed,
                    # explicit "unavailable" when the backend reported
                    # nothing, "pending" while it is still running
                    if "costAnalysis" not in compile_entry:
                        compile_info["costAnalysis"] = "pending"
                    elif compile_entry["costAnalysis"] is None:
                        compile_info["costAnalysis"] = "unavailable"
                else:
                    # never launched here: no analysis exists yet.  The
                    # plan ledger can still prove the on-disk cache
                    # holds the binary — the first launch would restore,
                    # not compile
                    from pinot_tpu.engine import compilecache

                    state = (
                        "persistent"
                        if compilecache.enabled()
                        and compilecache.known_plan(pdigest)
                        else "cold"
                    )
                    compile_info = {"state": state, "costAnalysis": "unavailable"}
                # mesh decision record: which chip-group lane executes
                # this shape, the mesh it shards over, and the XLA
                # collectives the cross-chip merge lowers to (the
                # single-chip fallback reports shardAxis/collective
                # None — the per-segment combine is fused in-program)
                from pinot_tpu.engine.mesh import SEGMENT_AXIS, collective_names

                lanes_obj = getattr(executor, "lanes", None)
                n_lanes = lanes_obj.size if lanes_obj is not None else 1
                group_size = (
                    selection.group.size
                    if selection is not None
                    else (int(exec_mesh.devices.size) if exec_mesh is not None else 1)
                )
                mesh_info = {
                    "shape": f"{n_lanes}x{group_size}",
                    "lanes": n_lanes,
                    "laneIndex": selection.index if selection is not None else 0,
                    "shardAxis": SEGMENT_AXIS if exec_mesh is not None else None,
                    "collective": (
                        collective_names(plan) if exec_mesh is not None else None
                    ),
                }
                device_info = {
                    "planDigest": pdigest,
                    "compile": compile_info,
                    "quarantined": poison is not None,
                    "mesh": mesh_info,
                }
                if poison is not None:
                    # HONESTY: the device plan is quarantined, so this
                    # query will ACTUALLY serve from the host path — the
                    # explain must say so, not report the device tier
                    est_bytes = _estimate_scan_bytes(normal, sorted(needed), 1.0)
                    for seg in normal:
                        record(
                            seg,
                            "host",
                            "device plan quarantined (poisoned): "
                            f"{poison['reason']} — serving via host "
                            f"fallback for {poison['ttlRemainingS']}s more",
                        )
                else:
                    q_np = build_query_inputs(
                        request, plan, ctx, phantom, scratch=scratch
                    )
                    block_ids, scanned_rows = executor._block_skip_ids(
                        plan, q_np, normal, phantom
                    )
                    from pinot_tpu.engine.kernel import chunk_rows_limit

                    _limit = chunk_rows_limit()
                    if (
                        block_ids is not None
                        and _limit
                        and phantom.num_segments * phantom.n_pad > _limit
                    ):
                        block_ids = None  # mirrors the executor's guard
                    if block_ids is not None and scanned_rows is not None:
                        frac = (
                            min(1.0, scanned_rows / phantom.total_docs)
                            if phantom.total_docs
                            else 1.0
                        )
                        est_bytes = _estimate_scan_bytes(
                            normal, sorted(needed), frac
                        )
                        for seg in normal:
                            record(
                                seg,
                                "zonemap",
                                "zone-map block pruning engages: candidate "
                                f"fraction {frac:.4f} of the table",
                                candidateFraction=round(frac, 4),
                            )
                    else:
                        est_bytes = _estimate_scan_bytes(normal, sorted(needed), 1.0)
                        for seg in normal:
                            record(
                                seg,
                                "fullScan",
                                "no selective tier applies: full vmapped "
                                "device scan",
                            )
                    # batching decision record (lane micro-batching
                    # tier): whether this shape's dispatches would
                    # stack with same-plan peers, the window/cap that
                    # governs formation, and whether the result cache
                    # holds this exact query's answer RIGHT NOW.
                    # Mirrors the executor's eligibility exactly: the
                    # plain packed single-device kernel only.
                    rows_total = phantom.num_segments * phantom.n_pad
                    cap = 0
                    if lane is not None and getattr(lane, "batch_max", 0) > 1:
                        cap = lane.batch_max
                        if _limit:
                            cap = min(cap, max(1, _limit // max(rows_total, 1)))
                    batchable = (
                        exec_mesh is None
                        and block_ids is None
                        and cap > 1
                        and (not _limit or rows_total <= _limit)
                    )
                    device_info["batching"] = {
                        "batched": batchable,
                        "batchMax": cap,
                        "windowMs": (
                            round(lane.batch_window_s * 1000, 3)
                            if lane is not None
                            else 0.0
                        ),
                        "cacheHit": (
                            result_cache.contains(request, segments, table)
                            if result_cache is not None
                            else False
                        ),
                    }

    digest = plan_shape_digest(request)
    estimated: Dict[str, Any] = {
        "source": "static",
        "bytesScanned": int(est_bytes),
    }
    estimated.update({k: v for k, v in tier_counts.items()})
    if plan_stats is not None:
        hist = plan_stats.estimate(digest)
        if hist is not None:
            estimated = dict(hist)
            estimated["source"] = "history"

    node: Dict[str, Any] = {
        "server": server_name,
        "table": table,
        "planDigest": digest,
        "summary": plan_shape_summary(request),
        "numSegments": len(segments),
        "totalDocs": int(total_docs),
        "tierCounts": tier_counts,
        "segments": records,
        "staged": _staged_snapshot(table, [s.segment_name for s in segments]),
        "estimatedCost": estimated,
        "generatedAtMs": round(time.time() * 1000, 3),
    }
    if device_info is not None:
        node["device"] = device_info
    return _json_safe(node)


# ---------------------------------------------------------------------------
# Prewarm compile specs (r16 warm-start plane): the phantom machinery
# above, driven one step further — instead of *reporting* the StaticPlan
# a query would compile, hand back an AOT-lowerable (kernel, avals) pair
# so the prewarm worker (server/prewarm.py) can pay the XLA compile off
# the serving path.  Still zero real staging: segment arrays enter the
# lowering as ShapeDtypeStructs that mirror ``device.stage_segments``'s
# shapes/dtypes exactly (including the skip-base elisions), so the
# compiled executable — and the persistent-cache entry it writes — is
# the one the first serving launch of this shape will ask for.
# ---------------------------------------------------------------------------


def _phantom_segment_avals(
    phantom: StagedTable, needed, ctx, skip_base
) -> Dict[str, Any]:
    """ShapeDtypeStruct twin of ``device.segment_arrays(staged, needed)``
    for a phantom staged table: same keys, same shapes, same dtypes as
    real staging would upload — no device bytes."""
    import jax

    S, n_pad = phantom.num_segments, phantom.n_pad
    fdt = np.dtype(config.np_float_dtype())
    avals: Dict[str, Any] = {}
    has_rows = False
    for name in needed:
        col = phantom.columns.get(name)
        if col is None:
            continue
        idt = np.dtype(config.index_dtype(col.card_pad))
        sb = name in skip_base and col.single_value
        if col.single_value:
            if not sb:
                avals[f"{name}.fwd"] = jax.ShapeDtypeStruct((S, n_pad), idt)
                has_rows = True
        else:
            avals[f"{name}.mv"] = jax.ShapeDtypeStruct((S, n_pad, col.mv_pad), idt)
            avals[f"{name}.mvc"] = jax.ShapeDtypeStruct(
                (S, n_pad), np.dtype(config.count_dtype(col.mv_pad))
            )
            has_rows = True
        if col.is_numeric and not sb:
            avals[f"{name}.dict"] = jax.ShapeDtypeStruct((S, col.card_pad), fdt)
        if col.raw is not None:
            avals[f"{name}.raw"] = jax.ShapeDtypeStruct((S, n_pad), fdt)
            has_rows = True
        if col.gfwd is not None:
            gdt = np.dtype(
                config.index_dtype(
                    config.pad_card(ctx.column(name).global_cardinality)
                )
            )
            avals[f"{name}.gfwd"] = jax.ShapeDtypeStruct((S, n_pad), gdt)
            has_rows = True
        if col.hll_bucket is not None:
            avals[f"{name}.hllb"] = jax.ShapeDtypeStruct((S, n_pad), np.dtype(np.uint8))
            avals[f"{name}.hllr"] = jax.ShapeDtypeStruct((S, n_pad), np.dtype(np.uint8))
            has_rows = True
        if col.mv_raw is not None:
            avals[f"{name}.mvraw"] = jax.ShapeDtypeStruct((S, n_pad, col.mv_pad), fdt)
            has_rows = True
    if has_rows:
        avals["num_docs"] = jax.ShapeDtypeStruct((S,), np.dtype(np.int32))
    else:
        avals["valid"] = jax.ShapeDtypeStruct((S, n_pad), np.dtype(np.bool_))
    return avals


def build_prewarm_spec(
    executor,
    segments: Sequence[ImmutableSegment],
    request: BrokerRequest,
) -> Optional[Dict[str, Any]]:
    """AOT prewarm spec for one query shape, or None when the shape has
    nothing lowerable to prewarm.

    Walks the EXACT executor decision order (as ``build_explain_node``
    does) and returns ``{"planDigest", "lane", "compile"}`` where
    ``compile()`` pays the XLA compile of the kernel the first serving
    launch would otherwise pay cold.  None is a *skip*, not a failure:

    - host/postings/star-tree-only shapes compile no device kernel;
    - mesh-sharded shapes need device-placed lowering (not supported —
      sharded servers fall back to persistent-cache classification);
    - chunked dispatch sequences are many programs, not one lowering;
    - shapes already in the lane's compile timeline are warm already.
    """
    verdicts = prune_explain(segments, request)
    live = [seg for seg, reason in verdicts if reason is None]
    if not live:
        return None
    from pinot_tpu.startree.operator import is_fit_for_star_tree

    normal = [s for s in live if not is_fit_for_star_tree(request, s)]
    if not normal:
        return None
    total_docs = sum(s.num_docs for s in segments)
    needed = set(request.referenced_columns())
    sel_columns: Optional[List[str]] = None
    if request.is_selection:
        sel_columns = executor._resolve_selection_columns(request, normal[0])
        needed.update(sel_columns)
    selection = None
    if getattr(executor, "lanes", None) is not None:
        selection = executor.lane_selection(request)
    exec_mesh = selection.group.mesh if selection is not None else executor.mesh
    if exec_mesh is not None:
        return None
    lane = selection.lane if selection is not None else getattr(executor, "lane", None)
    if lane is None:
        return None
    needed -= executor._docrange_only_columns(request, normal, sel_columns)
    ctx = get_table_context(normal)
    decision, state = index_path_decision(request, normal, ctx, total_docs)
    if state is not None or plan_forced_host(request, ctx):
        return None
    from pinot_tpu.engine.bitsliced import bitsliced_decision

    if bitsliced_decision(request, normal, ctx, total_docs)[1] is not None:
        # the bit-sliced tier compiles its own (tiny) kernel per spec,
        # not the standard StaticPlan kernel this prewarm would pay for
        return None
    raw_cols, gfwd_cols, hll_cols = executor._role_columns(request, normal, ctx)
    phantom = _phantom_staged(
        normal,
        list(needed) + list(request.referenced_columns()),
        raw_cols, gfwd_cols, hll_cols,
    )
    scratch: Dict[Any, Any] = {}
    plan = build_static_plan(request, ctx, phantom, scratch=scratch)
    if not plan.on_device:
        return None
    pdigest = plan_digest(plan)
    if lane.compile_info(pdigest) is not None:
        return None  # already cold/warm/prewarmed here: nothing to pay
    q_np = build_query_inputs(request, plan, ctx, phantom, scratch=scratch)
    block_ids, _scanned = executor._block_skip_ids(plan, q_np, normal, phantom)
    from pinot_tpu.engine.kernel import (
        chunk_rows_limit,
        make_packed_block_table_kernel,
        make_packed_table_kernel,
        plan_chunkable,
    )

    _limit = chunk_rows_limit()
    rows_total = phantom.num_segments * phantom.n_pad
    if block_ids is not None and _limit and rows_total > _limit:
        block_ids = None  # mirrors the executor's guard
    if block_ids is None and _limit and rows_total > _limit and plan_chunkable(plan):
        return None  # chunked dispatch sequence: not one lowerable program
    skip_base = executor._skip_base_columns(
        request, normal, raw_cols, gfwd_cols, hll_cols
    )
    seg_avals = _phantom_segment_avals(phantom, needed, ctx, skip_base)
    if block_ids is not None:
        from pinot_tpu.engine.zonemap import zone_block_rows

        import jax

        kernel = make_packed_block_table_kernel(plan, zone_block_rows())
        ids = np.asarray(block_ids)
        lower_args = (seg_avals, q_np, jax.ShapeDtypeStruct(ids.shape, ids.dtype))
    else:
        # the factories are lru_cached per plan: this is the SAME
        # callable the serving launch will call, so an in-process AOT
        # compile also seeds the persistent cache entry serving reads
        kernel = make_packed_table_kernel(plan)
        lower_args = (seg_avals, q_np)

    def compile_now() -> None:
        kernel.lower(*lower_args).compile()

    return {"planDigest": pdigest, "lane": lane, "compile": compile_now}
