"""Kernel builder: StaticPlan -> jit-compiled query kernel.

The reference executes a virtual-call operator tree per segment in
10k-doc blocks (``AggregationGroupByOperator.java:74-96``,
``MProjectionOperator.java``).  Here the whole per-segment pipeline —
filter mask -> projection gather -> aggregate / group-by scatter —
is ONE traced XLA program over the full (padded) column arrays:

  mask      = boolean combine of match-table gathers       (filter ops)
  values    = dict_vals[fwd]                                (projection)
  scalars   = masked reductions                             (aggregation)
  group-by  = scatter-add/min/max into dense [capacity]
              holders keyed by global-id mixed-radix keys   (group-by)

The kernel is written for ONE segment and lifted with ``jax.vmap`` over
the stacked segment axis — the TPU replacement for MCombineOperator's
thread pools; cross-segment merge is an elementwise reduction over that
axis (and a `psum` across chips in ``pinot_tpu.parallel``).

Everything is static-shaped: padding rows are masked by ``valid``,
invalid scatter entries are routed to index=capacity and dropped
(XLA scatter mode 'drop').
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pinot_tpu.engine import config
from pinot_tpu.engine.plan import MV_ANY, MV_NONE, SV, StaticAgg, StaticPlan

BIG = jnp.inf

# Group-by scatter-adds lower poorly on TPU (serialized scatter); for
# small key spaces a chunked one-hot matmul rides the MXU instead:
#   acc[K] += w[chunk] @ onehot(keys[chunk], K)
# Enabled on non-CPU backends (or forced via env for tests).
import os as _os

MATMUL_GROUP_CAP = int(_os.environ.get("PINOT_TPU_MATMUL_GROUP_CAP", str(512)))
# 2^18-row chunks: the on-chip sweep (r4_chunk_sweep) measured 14% off
# the Q1 kernel vs 2^15 (fewer, fatter scan steps); flat beyond 2^18
_MATMUL_CHUNK = int(_os.environ.get("PINOT_TPU_MATMUL_CHUNK", str(1 << 18)))
# dense presence/hist holders ride the FACTORED contraction
# (_value_state_counts) with a combined (group, valueId) key while
# capacity * gcard_pad stays under this; the r5 on-chip sweep
# (tools/probe_hll_sweep.py) measured 0.8ns/row at K=2^14 and
# 3.4ns/row at K=2^18 — still 3.6x ahead of the serialized scatter —
# so the r4 cap of 2^16 lifts to 2^18
_MATMUL_VALUE_CAP = int(_os.environ.get("PINOT_TPU_MATMUL_VALUE_CAP", str(1 << 18)))
# grouped HLL: contraction FLOPs grow with capacity*16384, crossing the
# sort-lowering cost (~4.2ns/row) near capacity ~16 on v5e
_MATMUL_HLL_CAP = int(_os.environ.get("PINOT_TPU_MATMUL_HLL_CAP", str(1 << 18)))
# grouped HLL beyond the matmul gate lowers to ONE packed int32 sort +
# searchsorted run-max extraction (bit-identical to scatter-max,
# tools/probe_hll_e2e.py: 565ms vs 1665ms at 134M rows, cap 1024) while
# (capacity * HLL_M * 64) fits int32; beyond that the flat scatter runs
_HLL_SORT_CAP = int(_os.environ.get("PINOT_TPU_HLL_SORT_CAP", str(1 << 16)))


def _use_matmul_groupby() -> bool:
    import os

    force = os.environ.get("PINOT_TPU_GROUPBY_MATMUL")
    if force is not None:
        return force == "1"
    return jax.default_backend() != "cpu"


def _grouped_hll_path(capacity: int) -> str:
    """Which lowering a dense grouped-HLL agg takes — consulted by BOTH
    the kernel builder (_group_state) and the reduce-spec builder
    (_state_reduce); they must agree or the reduce misreads the state.

    'matmul': (group, bucket, rho) occupancy contraction on the MXU.
    'sort':   packed int32 keys, sort + run-max extraction in the reduce.
    'scatter': flat serialized scatter-max (packed key would overflow).
    """
    K = capacity * config.HLL_M * 64
    if _use_matmul_groupby() and K <= _MATMUL_HLL_CAP:
        return "matmul"
    if capacity <= _HLL_SORT_CAP:
        return "sort"
    return "scatter"


def _segment_add_matmul_multi(flat_idx, W, capacity: int):
    """Sum m weight columns into capacity buckets with ONE chunked
    one-hot contraction: [m, chunk] @ [chunk, K] per scan step.

    The one-hot block is built once per chunk for EVERY aggregation —
    per-agg scans would rebuild (and re-stream) it once per agg, which
    dominated the Q1 kernel's HBM traffic.  Out-of-range indices
    (== capacity) one-hot to a zero row and drop."""
    fdt = config.float_dtype()
    m, n = W.shape
    chunk = min(_MATMUL_CHUNK, n)
    pad = (-n) % chunk
    if pad:
        flat_idx = jnp.concatenate([flat_idx, jnp.full(pad, capacity, flat_idx.dtype)])
        W = jnp.concatenate([W, jnp.zeros((m, pad), W.dtype)], axis=1)
    nb = flat_idx.shape[0] // chunk

    def body(acc, b):
        start = b * chunk
        i_c = jax.lax.dynamic_slice_in_dim(flat_idx, start, chunk)
        w_c = jax.lax.dynamic_slice_in_dim(W, start, chunk, axis=1).astype(fdt)
        onehot = jax.nn.one_hot(i_c, capacity, dtype=fdt)  # [chunk, K]
        return acc + w_c @ onehot, None

    acc, _ = jax.lax.scan(
        body, jnp.zeros((m, capacity), dtype=fdt), jnp.arange(nb)
    )
    return acc


# block size for the factored contraction: the r5 on-chip sweep found
# batched-dot cost flat from 2^15 to 2^18 blocks; smaller blocks keep
# the per-block [K1, 128] partials cheap to tree-sum
_FACTORED_CHUNK = int(_os.environ.get("PINOT_TPU_FACTORED_CHUNK", str(1 << 15)))


_PALLAS_HIST_BLOCK = 2048


def _value_state_counts_pallas(flat_idx, K: int):
    """Pallas variant of the factored occupancy contraction: the two
    thin one-hots are GENERATED in VMEM per block and contracted into a
    VMEM-resident [K1, 128] accumulator, so HBM traffic is the index
    stream alone (the XLA form streams both generated one-hots through
    HBM, ~512 B/row at K=2^14).  Gated by PINOT_TPU_VALUE_STATE_PALLAS
    pending the on-chip A/B (microbench hll_lowerings); semantics are
    identical to _value_state_counts."""
    from jax.experimental import pallas as pl

    fdt = jnp.float32
    n = flat_idx.shape[0]
    if n == 0:
        # grid (0,) would never run the i==0 init — return exact zeros
        # like the XLA variant
        return jnp.zeros(K, dtype=config.float_dtype())
    blk = _PALLAS_HIST_BLOCK
    pad = (-n) % blk
    if pad:
        flat_idx = jnp.concatenate([flat_idx, jnp.full(pad, K, flat_idx.dtype)])
    nb = flat_idx.shape[0] // blk
    K1 = -(-K // 128)
    blocks = flat_idx.reshape(nb, blk)

    def kernel(idx_ref, out_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _init():
            out_ref[...] = jnp.zeros_like(out_ref)

        idx = idx_ref[0, :]  # [blk] int32
        hi_iota = jax.lax.broadcasted_iota(jnp.int32, (blk, K1), 1)
        lo_iota = jax.lax.broadcasted_iota(jnp.int32, (blk, 128), 1)
        hi = ((idx[:, None] // 128) == hi_iota).astype(jnp.bfloat16)
        lo = ((idx[:, None] % 128) == lo_iota).astype(jnp.bfloat16)
        out_ref[...] += jax.lax.dot_general(
            hi, lo, (((0,), (0,)), ((), ())), preferred_element_type=fdt
        )

    out = pl.pallas_call(
        kernel,
        grid=(nb,),
        in_specs=[pl.BlockSpec((1, blk), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((K1, 128), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((K1, 128), fdt),
        # the sequential-grid accumulator idiom (i==0 init + +=) is
        # only safe where grid steps run in order — i.e. compiled TPU;
        # everywhere else run the interpreter
        interpret=jax.default_backend() != "tpu",
    )(blocks)
    return out.reshape(-1)[:K].astype(config.float_dtype())


def _use_pallas_value_state() -> bool:
    from pinot_tpu.engine.pallas_kernels import PALLAS_AVAILABLE

    return PALLAS_AVAILABLE and _os.environ.get("PINOT_TPU_VALUE_STATE_PALLAS") == "1"


def _value_state_counts(flat_idx, K: int):
    """Gated dispatch: the Pallas histogram when enabled and available,
    else the XLA factored contraction."""
    if _use_pallas_value_state():
        return _value_state_counts_pallas(flat_idx, K)
    return _value_state_counts_xla(flat_idx, K)


def _value_state_counts_xla(flat_idx, K: int):
    """Occupancy counts over a combined value-state key space of size K
    with a FACTORED one-hot contraction: split the key into (hi, lo)
    radix-128 digits and contract two THIN one-hots as a real
    [K1, block] @ [block, 128] matmul per block — full MXU tiles instead
    of the M=1 degenerate matmul of the scan contraction (the r4 shape
    that measured 31.5ns/row; this form measures 0.8ns/row at K=2^14,
    tools/probe_hll_sweep.py).

    Weights must be binary and FOLDED into the index: invalid entries
    carry ``flat_idx == K`` and one-hot to a dropped row.  bf16 one-hots
    are exact (values 0/1) and the f32 accumulate is exact for counts
    below 2^24 per cell per segment.  Returns float counts [K].
    """
    fdt = config.float_dtype()
    onehot_dt = jnp.bfloat16 if jax.default_backend() != "cpu" else fdt
    n = flat_idx.shape[0]
    chunk = min(_FACTORED_CHUNK, max(128, n))
    pad = (-n) % chunk
    if pad:
        flat_idx = jnp.concatenate(
            [flat_idx, jnp.full(pad, K, flat_idx.dtype)]
        )
    nb = flat_idx.shape[0] // chunk
    K1 = -(-K // 128)  # sentinel K lands in the padded tail, sliced off
    blocks = flat_idx.reshape(nb, chunk)
    hi = jax.nn.one_hot(blocks // 128, K1, dtype=onehot_dt)
    lo = jax.nn.one_hot(blocks % 128, 128, dtype=onehot_dt)
    out = jax.lax.dot_general(
        hi, lo, (((1,), (1,)), ((0,), (0,))), preferred_element_type=fdt
    )
    return jnp.sum(out, axis=0).reshape(-1)[:K]




def _row_shaped(key: str) -> bool:
    return key.endswith((".fwd", ".raw", ".gfwd", ".mv", ".hllb", ".hllr", ".mvraw"))


def _valid_mask(seg: Dict[str, Any]) -> jnp.ndarray:
    """Doc-validity mask: ``iota < num_docs`` (free register compare)
    rather than a stored bool column (an HBM byte per row).  Falls back
    to a materialized ``valid`` array when no row-shaped column exists
    to take the row count from."""
    if "num_docs" in seg:
        for k, v in seg.items():
            if _row_shaped(k):
                n = v.shape[0]
                return jax.lax.iota(jnp.int32, n) < seg["num_docs"]
    return seg["valid"]


def _mv_valid(seg: Dict[str, Any], column: str) -> jnp.ndarray:
    """MV entry-validity mask from per-doc counts: iota < mvc."""
    mv = seg[f"{column}.mv"]
    counts = seg[f"{column}.mvc"]
    iota = jax.lax.broadcasted_iota(jnp.int32, mv.shape, mv.ndim - 1)
    return iota < counts[..., None]


def _doc_ids(seg: Dict[str, Any]) -> jnp.ndarray:
    """Row ids for doc-range predicates: the original doc ids when rows
    were block-gathered (zone-map path), else a plain iota."""
    if "rowid" in seg:
        return seg["rowid"]
    for k, v in seg.items():
        if _row_shaped(k):
            return jax.lax.iota(jnp.int32, v.shape[0])
    return jax.lax.iota(jnp.int32, seg["valid"].shape[0])


def _leaf_mask(plan: StaticPlan, i: int, seg: Dict[str, Any], q: Dict[str, Any]) -> jnp.ndarray:
    leaf = plan.leaves[i]
    kind = leaf.eval_kind
    if kind == "docrange":
        # sorted column: contiguous doc interval, no column read
        lo, hi = q["bounds"][i][0], q["bounds"][i][1]
        ids = _doc_ids(seg)
        return (ids >= lo) & (ids < hi)

    def ids_match(ids):
        """Per-dictId predicate truth, by the leaf's static eval kind.
        interval/points are pure vector compares (dictIds are
        order-preserving); table is the bool[card] gather fallback."""
        if kind == "interval":
            lo, hi = q["bounds"][i][0], q["bounds"][i][1]
            return (ids >= lo) & (ids < hi)
        if kind in ("points", "points_none"):
            pts = q["pts"][i]  # [k_pad], -1 padded
            hit = jnp.any(ids[..., None] == pts, axis=-1)
            return ~hit if (kind == "points_none" and leaf.mode == SV) else hit
        if kind == "runs":
            # interval union: [k_pad, 2] dictId ranges (SV complements
            # baked in, like the table kind); empty runs match nothing
            rr = q["runs"][i]
            return jnp.any(
                (ids[..., None] >= rr[:, 0]) & (ids[..., None] < rr[:, 1]), axis=-1
            )
        return q["match"][i][ids]

    if leaf.mode == SV:
        return ids_match(seg[f"{leaf.column}.fwd"])  # [n]
    mv = seg[f"{leaf.column}.mv"]  # [n, mv]
    mvv = _mv_valid(seg, leaf.column)
    hit = jnp.any(ids_match(mv) & mvv, axis=-1)
    if leaf.mode == MV_ANY:
        return hit
    return ~hit  # MV_NONE


def _eval_tree(plan: StaticPlan, node: tuple, seg, q) -> jnp.ndarray:
    kind = node[0]
    if kind == "leaf":
        return _leaf_mask(plan, node[1], seg, q)
    masks = [_eval_tree(plan, c, seg, q) for c in node[1]]
    out = masks[0]
    for m in masks[1:]:
        out = (out & m) if kind == "and" else (out | m)
    return out


def _row_values(agg: StaticAgg, seg, mask):
    """Per-row (or per-entry) numeric values + entry mask for an agg column."""
    fdt = config.float_dtype()
    if agg.is_mv:
        mvv = _mv_valid(seg, agg.column) & mask[:, None]
        mvr = seg.get(f"{agg.column}.mvraw")
        if mvr is not None:
            return mvr, mvv  # staged decoded values, no gather
        mv = seg[f"{agg.column}.mv"]
        vals = seg[f"{agg.column}.dict"][mv]
        return vals, mvv
    if agg.use_raw:
        return seg[f"{agg.column}.raw"], mask  # streamed, no gather
    fwd = seg[f"{agg.column}.fwd"]
    vals = seg[f"{agg.column}.dict"][fwd]
    return vals, mask


def _agg_state(agg: StaticAgg, i: int, seg, q, mask) -> Any:
    """Per-segment partial state for one aggregation (no group-by)."""
    fdt = config.float_dtype()
    base = agg.base
    if base == "count":
        if agg.is_mv:
            mvv = _mv_valid(seg, agg.column) & mask[:, None]
            return jnp.sum(mvv, dtype=fdt)
        return jnp.sum(mask, dtype=fdt)

    if agg.kind == "scalar" or agg.kind == "pair":
        vals, m = _row_values(agg, seg, mask)
        if base == "sum":
            return jnp.sum(jnp.where(m, vals, 0), dtype=fdt)
        if base == "min":
            return jnp.min(jnp.where(m, vals, BIG))
        if base == "max":
            return jnp.max(jnp.where(m, vals, -BIG))
        if base == "avg":
            return (
                jnp.sum(jnp.where(m, vals, 0), dtype=fdt),
                jnp.sum(m, dtype=fdt),
            )
        if base == "minmaxrange":
            return (
                jnp.min(jnp.where(m, vals, BIG)),
                jnp.max(jnp.where(m, vals, -BIG)),
            )

    aux = q["agg_aux"][i]
    if agg.kind in ("presence", "hist"):
        # one (entry mask, global valueId) extraction serves all three
        # storage strategies below
        remap = aux["remap"]
        if agg.is_mv:
            mv = seg[f"{agg.column}.mv"]
            m = (_mv_valid(seg, agg.column) & mask[:, None]).reshape(-1)
            gids = remap[mv].reshape(-1)
        else:
            m = mask
            gids = _value_gids(agg, seg, remap)
        if agg.sort_pairs:
            # emit (0, valueId) pairs; the sort reduce dedups (presence)
            # and carries run starts for occurrence counts (hist)
            sent = _PAIR_SENTINEL
            return (
                jnp.where(m, 0, sent).astype(jnp.int32),
                jnp.where(m, gids.astype(jnp.int32), sent),
            )
        K = agg.gcard_pad
        if _use_matmul_groupby() and K <= _MATMUL_VALUE_CAP:
            combined = jnp.where(m, gids.astype(jnp.int32), K).astype(jnp.int32)
            flat = _value_state_counts(combined, K)
            if agg.kind == "presence":
                return (flat > 0).astype(jnp.int32)
            return flat
        if agg.kind == "presence":
            presence = jnp.zeros(K, dtype=jnp.int32)
            return presence.at[gids].max(m.astype(jnp.int32), mode="drop")
        hist = jnp.zeros(K, dtype=fdt)
        return hist.at[gids].add(m.astype(fdt), mode="drop")

    if agg.kind == "hll":
        bucket, rho = aux["bucket"], aux["rho"]
        if agg.is_mv:
            mv = seg[f"{agg.column}.mv"]
            m = (_mv_valid(seg, agg.column) & mask[:, None]).reshape(-1)
            b_rows = bucket[mv].reshape(-1)
            r_rows = rho[mv].reshape(-1)
        else:
            m = mask
            b_rows, r_rows = _hll_rows(agg, seg, bucket, rho)
        K = config.HLL_M * 64  # rho < 64 always (64-bit hash)
        if _use_matmul_groupby() and K <= _MATMUL_VALUE_CAP:
            # register max via a (bucket, rho) occupancy contraction on
            # the MXU + argmax-by-iota — replaces the serialized
            # scatter-max
            combined = jnp.where(
                m, b_rows.astype(jnp.int32) * 64 + r_rows.astype(jnp.int32), K
            ).astype(jnp.int32)
            counts = _value_state_counts(combined, K).reshape(config.HLL_M, 64)
            rho_iota = jax.lax.broadcasted_iota(jnp.int32, (config.HLL_M, 64), 1)
            return jnp.max(jnp.where(counts > 0, rho_iota, 0), axis=1)
        regs = jnp.zeros(config.HLL_M, dtype=jnp.uint8)
        return regs.at[b_rows.astype(jnp.int32)].max(
            jnp.where(m, r_rows, 0).astype(jnp.uint8), mode="drop"
        )

    raise AssertionError(agg)


def _group_keys(plan: StaticPlan, seg, q, mask):
    """Mixed-radix global group keys.

    Returns (keys [n, E], kvalid [n, E]) where E is the static MV
    expansion factor (1 if all group columns are single-value).
    """
    gb = plan.group_by
    kdt = config.key_dtype()
    n = mask.shape[0]
    keys = jnp.zeros((n, 1), dtype=kdt)
    kvalid = mask[:, None]
    for col, is_mv, gcard, remap, use_g in zip(
        gb.columns, gb.col_is_mv, gb.gcards, q["group_remap"], gb.use_gfwd
    ):
        if not is_mv:
            if use_g:
                g = seg[f"{col}.gfwd"].astype(kdt)  # [n], staged global ids
            else:
                g = remap[seg[f"{col}.fwd"]].astype(kdt)  # [n]
            keys = keys * gcard + g[:, None]
        else:
            mv = seg[f"{col}.mv"]
            mvv = _mv_valid(seg, col)
            g = remap[mv].astype(kdt)  # [n, mv]
            E = keys.shape[1]
            keys = (keys[:, :, None] * gcard + g[:, None, :]).reshape(n, -1)
            kvalid = (kvalid[:, :, None] & mvv[:, None, :]).reshape(n, -1)
    return keys, kvalid


def _group_add_weights(agg: StaticAgg, seg, mask, kvalid):
    """Flattened per-entry weight columns for the sum-shaped group aggs
    (count / sum / avg) — the batchable operands of the fused one-hot
    contraction.  None for aggs needing other combining ops (min/max/
    presence/hist/hll), which keep their own scatter paths."""
    if agg.base not in ("count", "sum", "avg"):
        return None
    if agg.base != "count" and agg.kind not in ("scalar", "pair"):
        return None
    fdt = config.float_dtype()
    shape = kvalid.shape

    def per_entry(row_scalar):
        return jnp.broadcast_to(row_scalar[:, None], shape).reshape(-1)

    if agg.base == "count":
        if agg.is_mv:
            mvv = _mv_valid(seg, agg.column)
            return (per_entry(jnp.sum(mvv, axis=-1).astype(fdt)),)
        return (jnp.ones(shape, dtype=fdt).reshape(-1),)
    vals, m = _row_values(agg, seg, mask)
    if agg.is_mv:
        row_sum = jnp.sum(jnp.where(m, vals, 0), axis=-1)
        row_cnt = jnp.sum(m, axis=-1).astype(fdt)
    else:
        row_sum = vals
        row_cnt = jnp.ones_like(vals, dtype=fdt)
    if agg.base == "sum":
        return (per_entry(row_sum),)
    return (per_entry(row_sum), per_entry(row_cnt))


def _group_state(agg: StaticAgg, i: int, seg, q, mask, keys, kvalid, capacity) -> Any:
    fdt = config.float_dtype()
    base = agg.base
    idx = jnp.where(kvalid, keys, capacity)  # invalid -> dropped
    flat_idx = idx.reshape(-1)
    fvalid = kvalid.reshape(-1)

    def per_entry(row_scalar):
        """Broadcast a per-row scalar across the expansion axis, flattened."""
        return jnp.broadcast_to(row_scalar[:, None], idx.shape).reshape(-1)

    def group_add(weights):
        # count/sum/avg reach here only on the scatter branch — on the
        # matmul branch the fused multi-column contraction handles them
        # (make_single_segment_kernel)
        w = jnp.where(fvalid, weights, 0)
        return jnp.zeros(capacity, dtype=fdt).at[flat_idx].add(w, mode="drop")

    if base == "count":
        if agg.is_mv:
            mvv = _mv_valid(seg, agg.column)
            row_counts = jnp.sum(mvv, axis=-1).astype(fdt)
            w = per_entry(row_counts)
        else:
            w = jnp.ones_like(flat_idx, dtype=fdt)
        return group_add(w)

    if agg.kind in ("scalar", "pair"):
        vals, m = _row_values(agg, seg, mask)
        if agg.is_mv:
            row_sum = jnp.sum(jnp.where(m, vals, 0), axis=-1)
            row_cnt = jnp.sum(m, axis=-1).astype(fdt)
            row_min = jnp.min(jnp.where(m, vals, BIG), axis=-1)
            row_max = jnp.max(jnp.where(m, vals, -BIG), axis=-1)
        else:
            row_sum = vals
            row_cnt = jnp.ones_like(vals, dtype=fdt)
            row_min = vals
            row_max = vals

        def scatter_add(row_vals):
            return group_add(per_entry(row_vals))

        def scatter_min(row_vals):
            return jnp.full(capacity, BIG, dtype=fdt).at[flat_idx].min(
                jnp.where(fvalid, per_entry(row_vals), BIG), mode="drop"
            )

        def scatter_max(row_vals):
            return jnp.full(capacity, -BIG, dtype=fdt).at[flat_idx].max(
                jnp.where(fvalid, per_entry(row_vals), -BIG), mode="drop"
            )

        if base == "sum":
            return scatter_add(row_sum)
        if base == "min":
            return scatter_min(row_min)
        if base == "max":
            return scatter_max(row_max)
        if base == "avg":
            return (scatter_add(row_sum), scatter_add(row_cnt))
        if base == "minmaxrange":
            return (scatter_min(row_min), scatter_max(row_max))

    aux = q["agg_aux"][i]
    if agg.kind in ("presence", "hist"):
        remap = aux["remap"]
        if agg.is_mv:
            mv = seg[f"{agg.column}.mv"]
            mvv = _mv_valid(seg, agg.column)
            gids = remap[mv]  # [n, mv]
            E = idx.shape[1]
            pair_k = jnp.broadcast_to(idx[:, :, None], idx.shape + gids.shape[-1:]).reshape(-1)
            pair_g = jnp.broadcast_to(gids[:, None, :], (gids.shape[0], E, gids.shape[-1])).reshape(-1)
            pair_v = (kvalid[:, :, None] & mvv[:, None, :]).reshape(-1)
        else:
            gids = _value_gids(agg, seg, remap)  # [n] global value ids
            pair_k = flat_idx
            pair_g = per_entry(gids)
            pair_v = fvalid
        if agg.sort_pairs:
            # high-cardinality exact distinct: emit (group slot, valueId)
            # pairs; the cross-segment reduce sort-dedups them
            # (apply_reduce "distinct_pairs") — no [capacity, gcard_pad]
            # state ever materializes
            sent = _PAIR_SENTINEL
            return (
                jnp.where(pair_v, pair_k.astype(jnp.int32), sent),
                jnp.where(pair_v, pair_g.astype(jnp.int32), sent),
            )
        K = capacity * agg.gcard_pad
        if _use_matmul_groupby() and K <= _MATMUL_VALUE_CAP:
            # combined (group, valueId) key through the one-hot MXU
            # contraction: ~0.7ns/row at K=2^16 vs the serialized 2-D
            # scatter's ~12.5ns/element
            combined = jnp.where(
                pair_v, pair_k.astype(jnp.int32) * agg.gcard_pad + pair_g, K
            ).astype(jnp.int32)
            flat = _value_state_counts(combined, K)
            grid = flat.reshape(capacity, agg.gcard_pad)
            if agg.kind == "presence":
                return (grid > 0).astype(jnp.int32)
            return grid
        if agg.kind == "presence":
            holder = jnp.zeros((capacity, agg.gcard_pad), dtype=jnp.int32)
            return holder.at[pair_k, pair_g].max(pair_v.astype(jnp.int32), mode="drop")
        holder = jnp.zeros((capacity, agg.gcard_pad), dtype=fdt)
        return holder.at[pair_k, pair_g].add(pair_v.astype(fdt), mode="drop")

    if agg.kind == "hll":
        bucket, rho = aux["bucket"], aux["rho"]
        if agg.is_mv:
            mv = seg[f"{agg.column}.mv"]
            mvv = _mv_valid(seg, agg.column)
            b = bucket[mv]
            r = rho[mv]
            E = idx.shape[1]
            pair_k = jnp.broadcast_to(idx[:, :, None], idx.shape + b.shape[-1:]).reshape(-1)
            pair_b = jnp.broadcast_to(b[:, None, :], (b.shape[0], E, b.shape[-1])).reshape(-1)
            pair_r = jnp.broadcast_to(r[:, None, :], (r.shape[0], E, r.shape[-1])).reshape(-1)
            pair_v = (kvalid[:, :, None] & mvv[:, None, :]).reshape(-1)
        else:
            b_rows, r_rows = _hll_rows(agg, seg, bucket, rho)
            pair_k = flat_idx
            pair_b = per_entry(b_rows)
            pair_r = per_entry(r_rows)
            pair_v = fvalid
        if agg.sort_pairs:
            # big group spaces: (slot, bucket*64+rho) pairs through the
            # generic sort-dedup reduce; finalize max-reduces rho per
            # (slot, bucket) into registers
            sent = _PAIR_SENTINEL
            gid = pair_b.astype(jnp.int32) * 64 + pair_r.astype(jnp.int32)
            return (
                jnp.where(pair_v, pair_k.astype(jnp.int32), sent),
                jnp.where(pair_v, gid, sent),
            )
        path = _grouped_hll_path(capacity)
        K = capacity * config.HLL_M * 64
        if path == "matmul":
            # small group spaces: (group, bucket, rho) occupancy on the
            # MXU + argmax-by-iota, like the scalar HLL path
            combined = jnp.where(
                pair_v,
                (
                    pair_k.astype(jnp.int32) * config.HLL_M
                    + pair_b.astype(jnp.int32)
                )
                * 64
                + pair_r.astype(jnp.int32),
                K,
            ).astype(jnp.int32)
            counts = _value_state_counts(combined, K).reshape(
                capacity, config.HLL_M, 64
            )
            rho_iota = jax.lax.broadcasted_iota(
                jnp.int32, (capacity, config.HLL_M, 64), 2
            )
            return jnp.max(jnp.where(counts > 0, rho_iota, 0), axis=2)
        if path == "sort":
            # mid/large group spaces: pack (group, bucket, rho) into ONE
            # int32 per entry (4 B/row — the leanest HBM footprint of
            # the three paths) and let the cross-segment reduce sort the
            # packed keys and run-max-extract registers (bit-identical
            # to scatter-max; 3x faster on v5e, tools/probe_hll_e2e.py)
            packed = jnp.where(
                pair_v,
                ((pair_k * config.HLL_M + pair_b.astype(jnp.int32)) << 6)
                | pair_r.astype(jnp.int32),
                _PAIR_SENTINEL,
            )
            return packed
        # huge capacities (> _HLL_SORT_CAP: packed key overflows int32):
        # one FLAT scatter index instead of (k, b) pairs — a single fused
        # index plus uint8 values keeps per-row temporaries at 5 B/row
        flat = jnp.where(
            pair_v,
            pair_k * config.HLL_M + pair_b.astype(jnp.int32),
            capacity * config.HLL_M,
        )
        holder = jnp.zeros(capacity * config.HLL_M, dtype=jnp.uint8)
        regs = holder.at[flat].max(pair_r.astype(jnp.uint8), mode="drop")
        return regs.reshape(capacity, config.HLL_M)

    raise AssertionError(agg)


def make_single_segment_kernel(plan: StaticPlan) -> Callable:
    def kernel(seg: Dict[str, Any], q: Dict[str, Any]) -> Dict[str, Any]:
        valid = _valid_mask(seg)
        if plan.filter_tree is not None:
            mask = _eval_tree(plan, plan.filter_tree, seg, q) & valid
        else:
            mask = valid
        out: Dict[str, Any] = {
            "num_docs": jnp.sum(mask, dtype=config.float_dtype())
        }

        if plan.group_by is not None:
            keys, kvalid = _group_keys(plan, seg, q, mask)
            cap = plan.group_by.capacity
            flat_idx = jnp.where(kvalid, keys, cap).reshape(-1)
            fvalid = kvalid.reshape(-1)
            fdt = config.float_dtype()
            if cap <= MATMUL_GROUP_CAP and _use_matmul_groupby():
                # ONE fused one-hot contraction (MXU) covers occupancy
                # AND every sum-shaped agg: a single pass over rows with
                # one one-hot per chunk, instead of a scan per agg —
                # the per-agg version re-streamed the one-hot blocks
                # and dominated the kernel's HBM traffic
                cols = [fvalid.astype(fdt)]
                slots: Dict[int, List[int]] = {}
                for i, agg in enumerate(plan.aggs):
                    if agg.base == "count" and not agg.is_mv:
                        # count weights == the occupancy column exactly
                        slots[i] = [0]
                        continue
                    w = _group_add_weights(agg, seg, mask, kvalid)
                    if w is None:
                        continue
                    slots[i] = []
                    for vec in w:
                        slots[i].append(len(cols))
                        cols.append(jnp.where(fvalid, vec, 0))
                states = _segment_add_matmul_multi(flat_idx, jnp.stack(cols), cap)
                out["gb_presence"] = (states[0] > 0).astype(jnp.int32)
                for i, agg in enumerate(plan.aggs):
                    if i in slots:
                        rows = [states[j] for j in slots[i]]
                        out[f"gb_{i}"] = rows[0] if len(rows) == 1 else tuple(rows)
                    else:
                        out[f"gb_{i}"] = _group_state(
                            agg, i, seg, q, mask, keys, kvalid, cap
                        )
            else:
                out["gb_presence"] = (
                    jnp.zeros(cap, dtype=jnp.int32)
                    .at[flat_idx]
                    .max(fvalid.astype(jnp.int32), mode="drop")
                )
                for i, agg in enumerate(plan.aggs):
                    out[f"gb_{i}"] = _group_state(
                        agg, i, seg, q, mask, keys, kvalid, cap
                    )
        else:
            for i, agg in enumerate(plan.aggs):
                out[f"agg_{i}"] = _agg_state(agg, i, seg, q, mask)

        if plan.selection is not None:
            out.update(_selection_outputs(plan, seg, q, mask))
        return out

    return kernel


def _sort_ordinals(sel, seg, q, dtype):
    """Per sort column: global ordinal of each doc's value, ascending
    order (descending columns flipped). MV columns order by first value
    (oracle semantics)."""
    for col, asc, gcard, remap, use_g in zip(
        sel.sort_columns,
        sel.sort_ascending,
        sel.sort_gcards,
        q["sel_remap"],
        sel.use_gfwd,
    ):
        if use_g:
            g = seg[f"{col}.gfwd"].astype(dtype)
        else:
            scol = seg.get(f"{col}.fwd")
            if scol is None:
                scol = seg[f"{col}.mv"][:, 0]
            g = remap[scol].astype(dtype)
        if not asc:
            g = (gcard - 1) - g
        yield g, gcard


def _selection_outputs(plan: StaticPlan, seg, q, mask) -> Dict[str, Any]:
    sel = plan.selection
    n = mask.shape[0]
    kdt = config.key_dtype()
    if not sel.sort_columns:
        # first-k matching docIds, in doc order
        score = jnp.where(mask, jnp.arange(n, dtype=kdt), n)
    elif not sel.packed:
        # Wide key space: radix product overflows the key dtype, so sort
        # lexicographically with one int32 operand per sort column instead
        # of packing (XLA sorts multi-operand natively; reference handles
        # this with its heap comparator, SelectionOperatorService.java:66).
        keys = [jnp.logical_not(mask).astype(jnp.int32)]  # matches first
        keys.extend(g for g, _ in _sort_ordinals(sel, seg, q, jnp.int32))
        keys.append(jnp.arange(n, dtype=jnp.int32))  # doc-order tie-break
        sorted_ops = jax.lax.sort(tuple(keys), num_keys=len(keys))
        idx = sorted_ops[-1][: sel.k]
        return {"sel_docids": idx, "sel_valid": mask[idx]}
    else:
        key = jnp.zeros(n, dtype=kdt)
        for g, gcard in _sort_ordinals(sel, seg, q, kdt):
            key = key * gcard + g
        score = jnp.where(mask, key, jnp.iinfo(kdt).max)
    neg = -score
    _, idx = jax.lax.top_k(neg, sel.k)  # k smallest scores
    sel_valid = mask[idx]
    return {"sel_docids": idx.astype(jnp.int32), "sel_valid": sel_valid}


# ---------------------------------------------------------------------------
# Cross-segment merge spec + compiled table kernel
# ---------------------------------------------------------------------------


def output_reducers(plan: StaticPlan) -> Dict[str, str]:
    """Reduce op over the segment axis per output key.

    'none' outputs stay per-segment (selection candidates).
    These same ops become `psum`/`pmax`-style collectives across chips.
    """
    red: Dict[str, str] = {"num_docs": "sum"}
    if plan.group_by is not None:
        red["gb_presence"] = "max"
        for i, agg in enumerate(plan.aggs):
            red[f"gb_{i}"] = _state_reduce(agg, plan.group_by.capacity)
    else:
        for i, agg in enumerate(plan.aggs):
            red[f"agg_{i}"] = _state_reduce(agg)
    if plan.selection is not None:
        red["sel_docids"] = "none"
        red["sel_valid"] = "none"
    return red


def _state_reduce(agg: StaticAgg, capacity: int = 0) -> str:
    base = agg.base
    if base in ("count", "sum"):
        return "sum"
    if base == "min":
        return "min"
    if base == "max":
        return "max"
    if base == "avg":
        return "sum_pair"
    if base == "minmaxrange":
        return "minmax_pair"
    if agg.kind == "presence":
        return "distinct_pairs" if agg.sort_pairs else "max"
    if agg.kind == "hist":
        return "distinct_pairs" if agg.sort_pairs else "sum"
    if agg.kind == "hll":
        if agg.sort_pairs:
            return "distinct_pairs"
        if capacity and _grouped_hll_path(capacity) == "sort":
            # packed-key states: the reduce itself sorts and extracts
            # registers — the capacity rides in the op tag
            return f"hll_sort:{capacity}"
        return "max"
    raise AssertionError(agg)


# int32 sentinel marking invalid (masked) pairs; sorts past every real
# (slot, gid) pair since slots < MAX_GROUP_CAPACITY and gids < 2^31-1
_PAIR_SENTINEL = np.iinfo(np.int32).max


def _hll_rows(agg: StaticAgg, seg, bucket, rho):
    """Per-row (register index, rank) for an SV HLL agg: prefer the
    host-staged uint8 streams over on-device table gathers.  Returned
    in their NATIVE dtype (uint8 streams) — consumers cast only where
    the op needs it, because a blanket int32 cast materializes 4 B/row
    temporaries that dominate HBM at 1B rows."""
    hb = seg.get(f"{agg.column}.hllb")
    if hb is not None:
        return hb, seg[f"{agg.column}.hllr"]
    fwd = seg[f"{agg.column}.fwd"]
    return bucket[fwd], rho[fwd]


def _value_gids(agg: StaticAgg, seg, remap):
    """Per-row GLOBAL value ids for an SV presence/hist agg: prefer
    the host-staged global-id stream (``.gfwd``, executor._role_columns)
    over an on-device remap-table gather — device gathers serialize on
    TPU at any cardinality (MICROBENCH_TPU.json)."""
    gf = seg.get(f"{agg.column}.gfwd")
    if gf is not None:
        return gf
    return remap[seg[f"{agg.column}.fwd"]]


def _reduce_distinct_pairs(value):
    """Global sort-dedup of (group slot, valueId) pairs across all
    segments — the exact distinct/histogram merge without per-pair
    state.

    1. lexicographic sort of the flattened pairs (two int32 keys — no
       int64 needed, so it runs with x64 disabled on TPU),
    2. run-boundary mask = the unique pairs; sentinels excluded,
    3. stable compaction sort (unique-first, position carried as
       payload) into a DISTINCT_PAIR_CAP buffer.

    Returns (slots[CAP], gids[CAP], starts[CAP], n_unique, total_valid):
    ``starts`` are each run's first position in the sorted order, so
    per-pair OCCURRENCE counts fall out as diff(starts) on host —
    distinctcount ignores them, exact percentile histograms need them.
    Host falls back when n_unique overflows the buffer.
    """
    s = value[0].reshape(-1)
    g = value[1].reshape(-1)
    s, g = jax.lax.sort((s, g), num_keys=2)
    first = jnp.concatenate(
        [jnp.ones((1,), bool), (s[1:] != s[:-1]) | (g[1:] != g[:-1])]
    )
    uniq = first & (s != _PAIR_SENTINEL)
    n_unique = jnp.sum(uniq).astype(jnp.int32)
    total_valid = jnp.sum(s != _PAIR_SENTINEL).astype(jnp.int32)
    rank = jnp.where(uniq, 0, 1).astype(jnp.int32)
    pos = jax.lax.iota(jnp.int32, s.shape[0])
    _, s2, g2, p2 = jax.lax.sort((rank, s, g, pos), num_keys=1, is_stable=True)
    k = min(config.DISTINCT_PAIR_CAP, int(s2.shape[0]))
    return (s2[:k], g2[:k], p2[:k], n_unique, total_valid)


def counts_from_starts(starts, n, total):
    """Recover per-pair occurrence counts from a compacted 5-tuple's
    run starts ON DEVICE (the host does this with np.diff): entry i's
    count = starts[i+1] - starts[i], last valid entry = total - start."""
    k = starts.shape[0]
    iota = jax.lax.iota(jnp.int32, k)
    nxt = jnp.concatenate([starts[1:], starts[-1:]])
    nxt = jnp.where(iota == n - 1, total, nxt)
    return jnp.where(iota < n, nxt - starts, 0)


def merge_pair_buffers(slots, gids, counts):
    """Merge gathered per-chip compacted (slot, gid, count) buffers into
    one 5-tuple with the same contract as _reduce_distinct_pairs.

    The exclusive cumsum of counts in merged-sorted order plays the
    'starts' role: diff of consecutive unique entries' excl-cumsum is
    exactly the summed count of the run (each (slot, gid) appears at
    most once per chip)."""
    s = slots.reshape(-1).astype(jnp.int32)
    g = gids.reshape(-1).astype(jnp.int32)
    c = counts.reshape(-1).astype(jnp.int32)
    s, g, c = jax.lax.sort((s, g, c), num_keys=2)
    first = jnp.concatenate(
        [jnp.ones((1,), bool), (s[1:] != s[:-1]) | (g[1:] != g[:-1])]
    )
    uniq = first & (s != _PAIR_SENTINEL)
    n_unique = jnp.sum(uniq).astype(jnp.int32)
    total_valid = jnp.sum(jnp.where(s != _PAIR_SENTINEL, c, 0)).astype(jnp.int32)
    excl = jnp.cumsum(c) - c
    rank = jnp.where(uniq, 0, 1).astype(jnp.int32)
    _, s2, g2, e2 = jax.lax.sort((rank, s, g, excl), num_keys=1, is_stable=True)
    k = min(config.DISTINCT_PAIR_CAP, int(s2.shape[0]))
    return (s2[:k], g2[:k], e2[:k], n_unique, total_valid)


def _reduce_hll_sort(value, capacity: int):
    """Dense grouped-HLL registers from packed (group, bucket, rho)
    int32 keys across all segments — ONE single-operand device sort
    plus a searchsorted run-max extraction (bit-identical to the
    scatter-max lowering: rho rides the low 6 bits, so the largest
    packed key within a (group, bucket) cell prefix carries the cell's
    max rho).  Replaces the serialized scatter for the north-star
    high-cardinality HLL group-by (3x on v5e, tools/probe_hll_e2e.py).
    """
    s = jax.lax.sort(value.reshape(-1))
    ncells = capacity * config.HLL_M
    # the last packed key below (cell+1)<<6 is the cell's max-rho entry
    bounds = (jnp.arange(ncells, dtype=jnp.int32) + 1) << 6
    pos = jnp.searchsorted(s, bounds) - 1
    v = s[jnp.maximum(pos, 0)]
    cell_ids = jnp.arange(ncells, dtype=jnp.int32)
    regs = jnp.where((pos >= 0) & ((v >> 6) == cell_ids), v & 63, 0)
    return regs.reshape(capacity, config.HLL_M).astype(jnp.uint8)


def apply_reduce(op: str, value: Any):
    if op.startswith("hll_sort:"):
        return _reduce_hll_sort(value, int(op.split(":", 1)[1]))
    if op == "sum":
        return jnp.sum(value, axis=0)
    if op == "min":
        return jnp.min(value, axis=0)
    if op == "max":
        return jnp.max(value, axis=0)
    if op == "sum_pair":
        return (jnp.sum(value[0], axis=0), jnp.sum(value[1], axis=0))
    if op == "minmax_pair":
        return (jnp.min(value[0], axis=0), jnp.max(value[1], axis=0))
    if op == "distinct_pairs":
        return _reduce_distinct_pairs(value)
    if op == "none":
        return value
    raise ValueError(op)


def _row_key(key: str) -> bool:
    return key.endswith((".fwd", ".raw", ".gfwd", ".mv", ".mvc", ".hllb", ".hllr", ".mvraw"))


def _gather_blocks(seg: Dict[str, Any], ids: jnp.ndarray, block: int):
    """Gather candidate row blocks out of one segment's staged arrays.

    ids: int32 [nb_pad], -1 = padding.  Row-shaped arrays [n_pad, ...]
    come back as [nb_pad*block, ...]; a ``valid`` mask and the original
    doc ids (``rowid``) are derived so the single-segment kernel runs
    unchanged on the gathered view.
    """
    safe = jnp.maximum(ids, 0)
    out: Dict[str, Any] = {}
    for k, v in seg.items():
        if k == "num_docs" or k == "valid" or not _row_key(k):
            if k not in ("num_docs", "valid"):
                out[k] = v
            continue
        nb_tot = v.shape[0] // block
        vb = v.reshape((nb_tot, block) + v.shape[1:])
        out[k] = vb[safe].reshape((ids.shape[0] * block,) + v.shape[1:])
    offs = jax.lax.broadcasted_iota(jnp.int32, (ids.shape[0], block), 1)
    rowid = (safe[:, None] * block + offs).reshape(-1)
    live = jnp.broadcast_to((ids >= 0)[:, None], (ids.shape[0], block)).reshape(-1)
    if "num_docs" in seg:
        valid = live & (rowid < seg["num_docs"])
    else:
        vb = seg["valid"].reshape(-1, block)
        valid = live & vb[safe].reshape(-1)
    out["valid"] = valid
    out["rowid"] = rowid  # original doc ids (docrange leaves, selection)
    return out, rowid


def make_single_segment_block_kernel(plan: StaticPlan, block: int) -> Callable:
    """Single-segment kernel over a gathered subset of row blocks —
    the zone-map skipping path (engine/zonemap.py): work is
    O(candidate blocks), not O(n)."""
    single = make_single_segment_kernel(plan)

    def kernel(seg: Dict[str, Any], q: Dict[str, Any], ids: jnp.ndarray):
        gseg, rowid = _gather_blocks(seg, ids, block)
        out = single(gseg, q)
        if "sel_docids" in out:
            out["sel_docids"] = rowid[out["sel_docids"]]
        return out

    return kernel


@functools.lru_cache(maxsize=256)
def make_block_table_kernel(plan: StaticPlan, block: int) -> Callable:
    """vmapped + jitted block-skipping variant of make_table_kernel;
    extra input: block ids int32 [S, nb_pad] (-1 padded)."""
    single = make_single_segment_block_kernel(plan, block)
    reducers = output_reducers(plan)

    def table_fn(segs, q, ids):
        outs = jax.vmap(single)(segs, q, ids)
        return {k: apply_reduce(reducers[k], v) for k, v in outs.items()}

    return jax.jit(table_fn)


@functools.lru_cache(maxsize=256)
def make_table_kernel(plan: StaticPlan) -> Callable:
    """vmap the single-segment kernel over the stacked segment axis and
    merge; jitted once per (plan, shape signature).

    The lru_cache is what makes jit's own executable cache effective:
    returning a fresh jit wrapper per query would retrace and recompile
    the same plan on every call.
    """
    single = make_single_segment_kernel(plan)
    reducers = output_reducers(plan)

    def table_fn(segs: Dict[str, Any], q: Dict[str, Any]) -> Dict[str, Any]:
        outs = jax.vmap(single)(segs, q)
        return {k: apply_reduce(reducers[k], v) for k, v in outs.items()}

    return jax.jit(table_fn)


# Per-row kernel temporaries scale with S * n_pad: beyond ~2^28 rows the
# int32 intermediates alone reach several GB and a 1B-row table blows
# the 16 GB HBM at compile time.  Chunking the segment axis bounds the
# working set; chunk outputs (already segment-reduced) combine with the
# same elementwise ops the in-kernel reduce uses.  Env-overridable
# (PINOT_TPU_CHUNK_ROWS = max rows per dispatch; 0 disables).
_ELEMENTWISE_REDUCERS = ("sum", "min", "max", "sum_pair", "minmax_pair")


def chunk_rows_limit() -> int:
    import os

    try:
        return int(os.environ.get("PINOT_TPU_CHUNK_ROWS", str(1 << 28)))
    except ValueError:
        return 1 << 28


def plan_chunkable(plan: StaticPlan) -> bool:
    """Chunk-combinable: every output reduces elementwise, or (hll_sort)
    reduces to dense registers that merge elementwise across chunks.
    The distinct_pairs sort-dedup buffers and per-segment selection
    outputs need their full segment axis in one program."""
    return all(
        op in _ELEMENTWISE_REDUCERS or op.startswith("hll_sort:")
        for op in output_reducers(plan).values()
    )


def combine_reduced(op: str, a, b):
    if op.startswith("hll_sort:"):
        return jnp.maximum(a, b)  # chunk-reduced register states
    if op == "sum":
        return a + b
    if op == "max":
        return jnp.maximum(a, b)
    if op == "min":
        return jnp.minimum(a, b)
    if op == "sum_pair":
        return (a[0] + b[0], a[1] + b[1])
    if op == "minmax_pair":
        return (jnp.minimum(a[0], b[0]), jnp.maximum(a[1], b[1]))
    raise ValueError(op)


def make_chunked_table_kernel(plan: StaticPlan, num_segments: int, n_pad: int) -> Callable:
    """The table kernel, dispatched over segment-axis chunks when the
    table exceeds the per-dispatch row budget.  Falls back to the plain
    kernel when chunking is off, unnecessary, or the plan isn't
    chunk-combinable."""
    # the resolved limit is part of the cache key: a kernel built under
    # one PINOT_TPU_CHUNK_ROWS value must not be reused after it changes
    return _chunked_table_kernel(plan, num_segments, n_pad, chunk_rows_limit())


def _pick_chunk(num_segments: int, n_pad: int, limit: int, granularity: int = 1) -> int:
    """Segments per dispatch under the row budget, in multiples of
    ``granularity`` (the mesh device count on sharded paths).  Prefers
    a divisor of num_segments (every dispatch then shares one compiled
    shape) but never shrinks below half the budget chasing one — a
    remainder-shaped trailing chunk costing one extra compile is
    cheaper than collapsing to tiny dispatches on prime counts."""
    chunk = max(1, limit // max(n_pad, 1)) if limit else num_segments
    chunk = max(granularity, (chunk // granularity) * granularity)
    divisor = chunk
    while divisor > max(granularity, chunk // 2) and (
        num_segments % divisor or divisor % granularity
    ):
        divisor -= granularity
    if (
        divisor >= max(granularity, chunk // 2)
        and num_segments % divisor == 0
        and divisor % granularity == 0
    ):
        chunk = divisor
    return chunk


def _chunked_run(table: Callable, reducers: Dict[str, str], num_segments: int, chunk: int) -> Callable:
    from pinot_tpu.engine.packing import make_packed_kernel

    # the combined outputs still fetch via ONE packed D2H transfer —
    # per-leaf fetches pay a tunnel RTT each (engine/packing.py)
    pack = make_packed_kernel(lambda o: o)

    def sliced(tree, s, e):
        return jax.tree_util.tree_map(lambda x: x[s:e], tree)

    def dispatch(segs: Dict[str, Any], q: Dict[str, Any]):
        outs = None
        for s in range(0, num_segments, chunk):
            e = min(s + chunk, num_segments)
            o = table(sliced(segs, s, e), sliced(q, s, e))
            outs = (
                o
                if outs is None
                else {k: combine_reduced(reducers[k], outs[k], o[k]) for k in o}
            )
        return pack.dispatch(outs)

    def run(segs: Dict[str, Any], q: Dict[str, Any]) -> Dict[str, Any]:
        return pack.fetch(dispatch(segs, q))

    # device-lane pipeline halves (engine/dispatch.py): launch the chunk
    # sequence without blocking, fetch later from the FINALIZE worker
    run.dispatch = dispatch
    run.fetch = pack.fetch
    return run


@functools.lru_cache(maxsize=64)
def _chunked_table_kernel(
    plan: StaticPlan, num_segments: int, n_pad: int, limit: int
) -> Callable:
    chunk = _pick_chunk(num_segments, n_pad, limit)
    if not limit or num_segments <= chunk or not plan_chunkable(plan):
        return make_table_kernel(plan)
    return _chunked_run(make_table_kernel(plan), output_reducers(plan), num_segments, chunk)


def make_chunked_sharded_kernel(plan: StaticPlan, mesh, num_segments: int, n_pad: int):
    """Mesh analog of ``make_chunked_table_kernel``: chunks the GLOBAL
    segment axis in device-count multiples when the per-device row
    share exceeds the dispatch budget, so pod-scale tables hit the same
    capacity path the single chip does.  Returns the plain packed
    sharded kernel when chunking is off or unnecessary."""
    from pinot_tpu.engine.packing import make_packed_kernel
    from pinot_tpu.parallel.multichip import make_sharded_table_kernel

    limit = chunk_rows_limit()
    n_dev = int(mesh.devices.size)
    chunk = (
        _pick_chunk(num_segments, n_pad, limit * n_dev, granularity=n_dev)
        if limit
        else num_segments
    )
    if not limit or num_segments <= chunk or not plan_chunkable(plan):
        return make_packed_kernel(make_sharded_table_kernel(plan, mesh))
    return _chunked_run(
        make_sharded_table_kernel(plan, mesh),
        output_reducers(plan),
        num_segments,
        chunk,
    )

@functools.lru_cache(maxsize=256)
def make_packed_table_kernel(plan: StaticPlan) -> Callable:
    """make_table_kernel + single-transfer output fetch: returns HOST
    numpy outputs via one packed D2H transfer (engine/packing.py) —
    the serving path's kernel (per-leaf fetches pay one tunnel RTT
    each; the bench's async dispatch keeps using the raw kernel)."""
    from pinot_tpu.engine.packing import make_packed_kernel

    return make_packed_kernel(make_table_kernel(plan))


@functools.lru_cache(maxsize=256)
def make_packed_block_table_kernel(plan: StaticPlan, block: int) -> Callable:
    from pinot_tpu.engine.packing import make_packed_kernel

    return make_packed_kernel(make_block_table_kernel(plan, block))


@functools.lru_cache(maxsize=128)
def make_packed_batched_table_kernel(plan: StaticPlan) -> Callable:
    """Cross-query batched variant of the packed table kernel (the
    lane micro-batching tier, engine/dispatch.py): ONE launch evaluates
    B same-plan queries over the SAME staged segment arrays, with each
    query's literals/inputs stacked along a new leading batch axis.

    This is the PIMDAL amortization move for serving: the memory-bound
    column scan is read ONCE per launch while B operator instances
    consume it, so same-shape queries that differ only in literals
    (``a>5`` vs ``a>999`` — one StaticPlan, different query inputs)
    stop paying B full passes over the resident columns.

    vmap is applied OUTSIDE the per-table function with
    ``in_axes=(None, 0)``: segment arrays broadcast (never copied per
    batch member), query-input leaves carry the batch axis, and every
    output leaf gains a leading ``[B]`` axis the executor slices per
    member at FINALIZE.  Per-member reductions happen along the same
    axes as the unbatched kernel, so member b's outputs are the same
    computation the unbatched launch would have produced — the
    byte-identity differential in tests/test_batching.py holds the two
    together.  Outputs fetch via the standard single packed D2H
    transfer, counted once per batched launch."""
    single = make_single_segment_kernel(plan)
    reducers = output_reducers(plan)

    def table_fn(segs: Dict[str, Any], q: Dict[str, Any]) -> Dict[str, Any]:
        outs = jax.vmap(single)(segs, q)
        return {k: apply_reduce(reducers[k], v) for k, v in outs.items()}

    from pinot_tpu.engine.packing import make_packed_kernel

    return make_packed_kernel(jax.vmap(table_fn, in_axes=(None, 0)))


# ---------------------------------------------------------------------------
# Bit-sliced (BSI) filter/aggregate tier (engine/bitsliced.py): the
# bulk-bitwise formulation.  A predicate over a W-plane bit-sliced
# column evaluates in O(W) wide bitwise passes over n/32 packed uint32
# words; COUNT/SUM/MIN/MAX fuse into the SAME pass via popcounts and a
# bit-serial candidate descent, so a qualifying mid-selectivity
# aggregation never materializes row indices at all.
#
# The kernel spec is a plain hashable tuple (no StaticPlan — the tier
# has its own, much smaller, plan space):
#   (leaves, tree, sums, extremes)
#   leaves   = ((kind, col, width, k_pad), ...)   kind in
#              {"interval", "points", "points_none"}
#   tree     = ("leaf", i) | ("and"|"or", child, ...)
#   sums     = ((col, value_width), ...)          value-offset planes
#   extremes = ((col, width, is_max), ...)        dictId planes
# Inputs: segs = {"nd": int32 [S],
#                 "p:<col>": uint32 [S, W, nw], "v:<col>": uint32 [S, Wv, nw]}
#         q    = {"bounds:<i>": int32 [S, 2], "pts:<i>": int32 [S, k_pad]}
# Outputs (per segment — host finalize owns the cross-segment merge so
# it can apply per-segment vmin offsets and dictionary lookups):
#   "count": int32 [S]; "psum:<col>": int32 [S, Wv]; "ext:<col>": int32 [S]
# ---------------------------------------------------------------------------

_U32_FULL = np.uint32(0xFFFFFFFF)


def _bsi_valid_words(num_docs, n_words: int):
    """uint32 [n_words] validity mask from the segment's doc count:
    word j keeps bits for rows j*32 .. j*32+31 below num_docs."""
    j = jax.lax.iota(jnp.int32, n_words)
    bits = jnp.clip(num_docs - j * 32, 0, 32)
    base = (
        jnp.uint32(1) << jnp.clip(bits, 0, 31).astype(jnp.uint32)
    ) - jnp.uint32(1)
    return jnp.where(bits >= 32, jnp.uint32(_U32_FULL), base)


def _bsi_ge(planes, t, width: int):
    """Bitmap of rows whose value >= t (runtime int32 scalar) — the
    bit-serial MSB->LSB descent: ``gt`` accumulates rows already proven
    greater, ``eq`` tracks rows still matching t's prefix."""
    gt = jnp.zeros_like(planes[0])
    eq = jnp.full_like(planes[0], _U32_FULL)
    for b in range(width - 1, -1, -1):
        tb = ((t >> b) & 1).astype(jnp.uint32)
        tmask = jnp.uint32(0) - tb  # 0x0 or 0xFFFFFFFF
        gt = gt | (eq & planes[b] & ~tmask)
        eq = eq & ~(planes[b] ^ tmask)
    ge = gt | eq
    if width < 31:
        # t at/above 2^W would otherwise truncate to GE(t mod 2^W)
        ge = jnp.where(t >= (1 << width), jnp.zeros_like(ge), ge)
    return ge


def _bsi_points(planes, pts, width: int):
    """Bitmap of rows whose value is in ``pts`` (int32 [k], -1 padded) —
    per-point XNOR descent, OR-reduced over the point axis."""
    eq = jnp.full((pts.shape[0], planes.shape[1]), _U32_FULL, dtype=jnp.uint32)
    for b in range(width):
        pb = ((pts >> b) & 1).astype(jnp.uint32)[:, None]
        eq = eq & ~(planes[b][None, :] ^ (jnp.uint32(0) - pb))
    # -1 padding under the arithmetic shift above is all-ones and would
    # alias dictId 2^W - 1: mask padded (and any out-of-width) points
    ok = pts >= 0
    if width < 31:
        ok = ok & (pts < (1 << width))
    eq = jnp.where(ok[:, None], eq, jnp.zeros_like(eq))
    return jax.lax.reduce(eq, np.uint32(0), jax.lax.bitwise_or, (0,))


def _bsi_extreme(planes, bitmap, width: int, is_max: bool):
    """Bit-serial candidate descent: the extreme dictId among bitmap
    rows (garbage when the bitmap is empty — callers mask on count)."""
    cand = bitmap
    out = jnp.int32(0)
    for b in range(width - 1, -1, -1):
        t = (cand & planes[b]) if is_max else (cand & ~planes[b])
        any_t = jnp.any(t != 0)
        cand = jnp.where(any_t, t, cand)
        taken = any_t if is_max else ~any_t
        out = out | (taken.astype(jnp.int32) << b)
    return out


def _bsi_eval_tree(node, bms):
    if node[0] == "leaf":
        return bms[node[1]]
    acc = _bsi_eval_tree(node[1], bms)
    for child in node[2:]:
        m = _bsi_eval_tree(child, bms)
        acc = (acc & m) if node[0] == "and" else (acc | m)
    return acc


def make_single_segment_bitsliced_kernel(spec) -> Callable:
    leaves, tree, sums, extremes = spec

    def single(seg: Dict[str, Any], q: Dict[str, Any]) -> Dict[str, Any]:
        bms = []
        n_words = None
        for i, (kind, col, width, k_pad) in enumerate(leaves):
            planes = seg[f"p:{col}"]
            n_words = planes.shape[-1]
            if kind == "interval":
                lo, hi = q[f"bounds:{i}"][0], q[f"bounds:{i}"][1]
                bm = _bsi_ge(planes, lo, width) & ~_bsi_ge(planes, hi, width)
            else:
                bm = _bsi_points(planes, q[f"pts:{i}"], width)
                if kind == "points_none":
                    bm = ~bm  # complement; padding cleared by vw below
            bms.append(bm)
        vw = _bsi_valid_words(seg["nd"], n_words)
        bitmap = _bsi_eval_tree(tree, bms) & vw
        pop = jax.lax.population_count
        outs: Dict[str, Any] = {
            "count": jnp.sum(pop(bitmap)).astype(jnp.int32)
        }
        for col, vwidth in sums:
            outs[f"psum:{col}"] = (
                jnp.sum(pop(seg[f"v:{col}"] & bitmap[None, :]), axis=1)
                .astype(jnp.int32)
            )
        for col, width, is_max in extremes:
            outs[f"ext:{'mx' if is_max else 'mn'}:{col}"] = _bsi_extreme(
                seg[f"p:{col}"], bitmap, width, is_max
            )
        return outs

    return single


@functools.lru_cache(maxsize=256)
def make_packed_bitsliced_kernel(spec) -> Callable:
    """vmapped + jitted + packed-fetch bit-sliced tier kernel — same
    caching/dispatch idiom as make_packed_table_kernel (the lru_cache
    is what makes jit's executable cache effective)."""
    single = make_single_segment_bitsliced_kernel(spec)

    def table_fn(segs: Dict[str, Any], q: Dict[str, Any]) -> Dict[str, Any]:
        return jax.vmap(single)(segs, q)

    from pinot_tpu.engine.packing import make_packed_kernel

    return make_packed_kernel(jax.jit(table_fn))


@functools.lru_cache(maxsize=128)
def make_packed_batched_bitsliced_kernel(spec) -> Callable:
    """Cross-query batched bit-sliced kernel — the BSI tier joining the
    lane micro-batching plane (make_packed_batched_table_kernel's exact
    shape, applied to the plane kernels): ONE launch evaluates B
    same-spec queries over the SAME resident bit-planes, each member's
    per-leaf ``bounds:<i>``/``pts:<i>`` arrays stacked along a new
    leading batch axis.

    The plane arrays broadcast (``in_axes=(None, 0)`` — never copied
    per member), so B distinct range/IN literals over one bit-sliced
    column cost one O(W) bitwise pass instead of B.  Every output leaf
    gains a leading [B] axis the lane slices per member; member b's
    outputs are the computation the solo launch would have produced
    (tests/test_bitsliced.py holds the two together byte-identically)."""
    single = make_single_segment_bitsliced_kernel(spec)

    def table_fn(segs: Dict[str, Any], q: Dict[str, Any]) -> Dict[str, Any]:
        return jax.vmap(single)(segs, q)

    from pinot_tpu.engine.packing import make_packed_kernel

    return make_packed_kernel(jax.jit(jax.vmap(table_fn, in_axes=(None, 0))))


# ---------------------------------------------------------------------------
# Device hash join (engine/join.py JoinPlan -> one jitted program)
# ---------------------------------------------------------------------------


def _join_hash(k, cap: int):
    """Knuth multiplicative hash of int32 key ids, masked to the pow2
    open-addressing capacity."""
    h = (k.astype(jnp.uint32) * jnp.uint32(2654435761)) >> jnp.uint32(8)
    return (h & jnp.uint32(cap - 1)).astype(jnp.int32)


@functools.lru_cache(maxsize=128)
def make_join_kernel(jplan) -> Callable:
    """Build+probe hash-join program for one ``engine/join.py``
    JoinPlan: int32 open-addressing over padded lanes.

    BUILD: unique build keys insert in parallel-claim rounds — each
    unplaced lane proposes slot ``(hash + r) & (cap-1)``; lanes whose
    proposed slot is empty scatter-min their lane index to claim it,
    winners write (key, lane) into the table, everyone else advances
    ``r``.  Keys are unique (the host packing pre-aggregated per key)
    and the table is <= half full, so every lane lands within ``cap``
    rounds; ``join_ok`` reports the invariant so the executor can heal
    to the host join instead of serving a wrong answer if it ever
    breaks.

    PROBE: every probe lane walks its probe sequence until key match
    (join hit: the build lane index) or empty slot (no match), all
    lanes in lockstep under one while_loop.

    AGGREGATE: matched lanes gather the build side's per-key
    pre-reductions (cnt/sum/min/max) and combine with their own value
    columns — a probe row matching a duplicated build key contributes
    ``cnt`` joined rows, so SUM weights by cnt and COUNT sums cnt,
    which is exactly the inner-join multiplicity.  Group mode scatters
    into dense ``[n_groups]`` holders keyed by the mixed-radix
    (probe-group, build-group) id."""
    cap = jplan.cap

    def kern(inputs: Dict[str, Any]) -> Dict[str, Any]:
        bk = inputs["bk"]
        bc = inputs["bc"]
        U = bk.shape[0]

        # -- build phase: parallel-claim insertion --------------------
        bh = _join_hash(bk, cap)
        lane_ids = jnp.arange(U, dtype=jnp.int32)
        table_key = jnp.full((cap,), -1, dtype=jnp.int32)
        table_row = jnp.zeros((cap,), dtype=jnp.int32)
        placed = bk < 0  # padded lanes never insert

        def build_cond(state):
            _tk, _tr, placed_, r = state
            return jnp.logical_and(jnp.any(~placed_), r < 2 * cap)

        def build_body(state):
            tk, tr, placed_, r = state
            slot = (bh + r) & (cap - 1)
            attempt = jnp.logical_and(~placed_, tk[slot] == -1)
            # claim: lowest lane index wins each contested empty slot
            claim_slot = jnp.where(attempt, slot, cap)
            claims = jnp.full((cap,), U, dtype=jnp.int32)
            claims = claims.at[claim_slot].min(lane_ids, mode="drop")
            won = jnp.logical_and(attempt, claims[slot] == lane_ids)
            win_slot = jnp.where(won, slot, cap)
            tk = tk.at[win_slot].set(bk, mode="drop")
            tr = tr.at[win_slot].set(lane_ids, mode="drop")
            return tk, tr, jnp.logical_or(placed_, won), r + 1

        table_key, table_row, placed, _r = jax.lax.while_loop(
            build_cond, build_body, (table_key, table_row, placed, jnp.int32(0))
        )
        join_ok = jnp.all(placed)

        # -- probe phase: lockstep linear probing ---------------------
        pk = inputs["pk"]
        N = pk.shape[0]
        ph = _join_hash(pk, cap)
        midx0 = jnp.full((N,), -1, dtype=jnp.int32)
        done0 = pk < 0  # padded lanes: no match

        def probe_cond(state):
            done, _m, off = state
            return jnp.logical_and(jnp.any(~done), off <= cap)

        def probe_body(state):
            done, midx, off = state
            slot = (ph + off) & (cap - 1)
            at = table_key[slot]
            found = jnp.logical_and(~done, at == pk)
            empty = jnp.logical_and(~done, at == -1)
            midx = jnp.where(found, table_row[slot], midx)
            return jnp.logical_or(done, jnp.logical_or(found, empty)), midx, off + 1

        _done, midx, _off = jax.lax.while_loop(
            probe_cond, probe_body, (done0, midx0, jnp.int32(0))
        )

        matched = midx >= 0
        safe = jnp.maximum(midx, 0)
        fdt = config.float_dtype()
        cnt = jnp.where(matched, bc[safe], 0).astype(jnp.int32)
        cntf = cnt.astype(fdt)
        outs: Dict[str, Any] = {
            "num_docs": jnp.sum(cnt.astype(jnp.int64))
            if jax.config.jax_enable_x64
            else jnp.sum(cnt),
            "join_ok": join_ok,
        }

        pv = inputs["pv"]
        bs = inputs["bs"]
        bmn = inputs["bmn"]
        bmx = inputs["bmx"]
        inf = jnp.asarray(jnp.inf, dtype=fdt)

        def probe_vals(idx):
            return pv[idx]

        if jplan.n_groups:
            G = jplan.n_groups
            gid = inputs["pg"] * jnp.int32(jplan.bg_space) + inputs["bg"][safe]
            gslot = jnp.where(matched, gid, G)  # drop unmatched lanes
            gcnt = jnp.zeros((G,), jnp.int32).at[gslot].add(cnt, mode="drop")
            outs["gb_cnt"] = gcnt
            for i, (kind, side, idx) in enumerate(jplan.aggs):
                if kind == "count":
                    outs[f"gb_{i}"] = gcnt
                    continue
                if side == "p":
                    v = probe_vals(idx)
                    vsum = v * cntf
                    vmin = v
                    vmax = v
                else:
                    vsum = bs[idx][safe]
                    vmin = bmn[idx][safe]
                    vmax = bmx[idx][safe]

                def _sum():
                    return jnp.zeros((G,), fdt).at[gslot].add(
                        jnp.where(matched, vsum, 0.0), mode="drop"
                    )

                def _min():
                    return jnp.full((G,), inf).at[gslot].min(
                        jnp.where(matched, vmin, inf), mode="drop"
                    )

                def _max():
                    return jnp.full((G,), -inf).at[gslot].max(
                        jnp.where(matched, vmax, -inf), mode="drop"
                    )

                if kind == "sum":
                    outs[f"gb_{i}"] = _sum()
                elif kind == "avg":
                    outs[f"gb_{i}"] = (_sum(), gcnt)
                elif kind == "min":
                    outs[f"gb_{i}"] = _min()
                elif kind == "max":
                    outs[f"gb_{i}"] = _max()
                else:  # minmaxrange
                    outs[f"gb_{i}"] = (_min(), _max())
            return outs

        total_cnt = jnp.sum(cnt)
        for i, (kind, side, idx) in enumerate(jplan.aggs):
            if kind == "count":
                outs[f"agg_{i}"] = total_cnt
                continue
            if side == "p":
                v = probe_vals(idx)
                ssum = jnp.sum(jnp.where(matched, v * cntf, 0.0))
                smin = jnp.min(jnp.where(jnp.logical_and(matched, cnt > 0), v, inf))
                smax = jnp.max(
                    jnp.where(jnp.logical_and(matched, cnt > 0), v, -inf)
                )
            else:
                ssum = jnp.sum(jnp.where(matched, bs[idx][safe], 0.0))
                smin = jnp.min(jnp.where(matched, bmn[idx][safe], inf))
                smax = jnp.max(jnp.where(matched, bmx[idx][safe], -inf))
            if kind == "sum":
                outs[f"agg_{i}"] = ssum
            elif kind == "avg":
                outs[f"agg_{i}"] = (ssum, total_cnt)
            elif kind == "min":
                outs[f"agg_{i}"] = smin
            elif kind == "max":
                outs[f"agg_{i}"] = smax
            else:
                outs[f"agg_{i}"] = (smin, smax)
        return outs

    from pinot_tpu.engine.packing import make_packed_kernel

    return make_packed_kernel(kern)
