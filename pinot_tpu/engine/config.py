"""Engine-wide dtype and sizing policy.

On CPU test runs x64 is enabled and aggregation runs in float64,
reproducing the reference's Java ``double`` semantics exactly; on TPU the
default is float32/bfloat16-friendly shapes (sums use pairwise tree
reduction inside XLA, which keeps error small at 100M+ rows).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# Padding buckets: shapes are padded up so the jit cache stays small
# (the reference's analog is its fixed 10k/5k block sizes,
# DocIdSetPlanNode.java:33).
DOC_PAD_MULTIPLE = 1024
MIN_CARD_PAD = 8

# Group-by dense-holder cap (reference caps ARRAY_BASED key space at 1M,
# DefaultGroupKeyGenerator.java): beyond this the host hash path runs.
MAX_GROUP_CAPACITY = 1 << 20

# distinctcount / percentile dense state cap (global dictionary size).
MAX_VALUE_STATE = 1 << 22

# sort-dedup distinct path (StaticAgg.sort_pairs): device output buffer
# for compacted unique (group, valueId) pairs.  Overflow (more unique
# pairs than this) falls back to the host path at runtime — at that
# cardinality the exact-distinct result itself is bigger than any
# sensible response payload.
DISTINCT_PAIR_CAP = 1 << 22

HLL_LOG2M = 8  # HllConstants.java DEFAULT_LOG2M
HLL_M = 1 << HLL_LOG2M


def x64_enabled() -> bool:
    return bool(jax.config.jax_enable_x64)


def float_dtype():
    return jnp.float64 if x64_enabled() else jnp.float32


def np_float_dtype():
    return np.float64 if x64_enabled() else np.float32


def key_dtype():
    return jnp.int64 if x64_enabled() else jnp.int32


def max_key_space() -> int:
    return 2**62 if x64_enabled() else 2**30


def pad_docs(n: int) -> int:
    """Round doc count up to the padding bucket (pow2 beyond one block)."""
    if n <= DOC_PAD_MULTIPLE:
        m = 8
        while m < n:
            m *= 2
        return m
    blocks = -(-n // DOC_PAD_MULTIPLE)
    # round block count to next power of two to bound jit-cache size
    b = 1
    while b < blocks:
        b *= 2
    return b * DOC_PAD_MULTIPLE


def pad_card(c: int) -> int:
    m = MIN_CARD_PAD
    while m < c:
        m *= 2
    return m


def pad_value_card(c: int) -> int:
    """Value-state holder padding: QUARTER-pow2 buckets (2048, 2560,
    3072, 3584, 4096, 5120, ...).  The dense presence/hist/HLL
    contraction cost is LINEAR in the padded cardinality, so pow2's
    up-to-2x overshoot is real MXU work (the r4 bench shape padded
    2526 -> 4096, a 1.6x tax on the hot HLL group-by); quarter steps
    cap the overshoot at 25% while keeping the jit cache bucketed."""
    base = MIN_CARD_PAD
    while base * 2 <= c:
        base *= 2
    if base >= c:
        return base
    step = max(base // 4, MIN_CARD_PAD)
    return base + -(-(c - base) // step) * step


# ---------------------------------------------------------------------------
# HBM staging widths.  The query kernels are memory-bound (SURVEY §6:
# rows/s ~ HBM bytes/row), so forward indexes stage at the narrowest
# integer dtype that holds the dictId range — the analog of the
# reference's bit-packed fwd index (FixedBitSingleValueReader.java:25),
# except the "unpack" is a free in-register upcast on TPU.
# ---------------------------------------------------------------------------

# Agg-input feed policy: columns with cardinality above raw_card_min()
# stage a dictionary-decoded float raw array for aggregation reads; at
# or below it, the kernel gathers dict_vals[fwd].
#
# Measured on a real v5e chip (2026-07-30, tools/microbench.py
# `gather_vs_raw`): XLA lowers the per-row dict gather to a serialized
# loop — ~12.5 ns/element, 159x slower than streaming a raw float32
# array (1257 ms vs 7.9 ms for TPC-H Q1 over 33.5M rows; raw-feed hits
# 4.25 B rows/s vs the 295 GB/s stream roofline).  So on accelerators
# the threshold defaults to 0: ALWAYS stage raw feeds — the 4x HBM
# bytes/row are far cheaper than any gather.  On CPU (tests) vector
# gathers are cheap and narrow staging halves memory, so the old
# threshold stands.  Env-overridable for A/B (PINOT_TPU_RAW_CARD_MIN).
import os as _os

_raw_card_min: int | None = None


def raw_card_min() -> int:
    """Lazy so importing config never initializes a jax backend (tests
    must force the CPU mesh before first backend init)."""
    global _raw_card_min
    env = _os.environ.get("PINOT_TPU_RAW_CARD_MIN")
    if env is not None:
        return int(env)
    if _raw_card_min is None:
        import jax

        _raw_card_min = (1 << 15) if jax.default_backend() == "cpu" else 0
    return _raw_card_min


_qinput_budget: int | None = None


def qinput_cache_budget_bytes() -> int:
    """HBM byte budget for the device-resident query-input cache
    (executor._to_device_inputs).  Sized so serving many distinct query
    shapes over high-cardinality tables cannot pin unbounded HBM: the
    v5e chip has 16 GB; segments + workspace dominate, so the input
    cache defaults to 1 GiB.  Env-overridable
    (PINOT_TPU_QINPUT_CACHE_BYTES); 0 disables caching entirely.
    Parsed once — this sits on the query hot path, and a junk env value
    must degrade to the default, not fail every query at serve time."""
    global _qinput_budget
    if _qinput_budget is None:
        try:
            _qinput_budget = int(_os.environ.get("PINOT_TPU_QINPUT_CACHE_BYTES", 1 << 30))
        except ValueError:
            _qinput_budget = 1 << 30
    return _qinput_budget


def index_dtype(max_exclusive: int):
    """np dtype for dictId arrays indexing tables of max_exclusive rows.

    Unsigned, and sized so the table length itself is representable
    (jax index normalization materializes the axis size as a constant
    of the index dtype)."""
    if max_exclusive <= 255:
        return np.uint8
    if max_exclusive <= 65535:
        return np.uint16
    return np.int32


# count arrays (values <= bound) share the same width ladder
count_dtype = index_dtype
