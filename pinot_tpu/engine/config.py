"""Engine-wide dtype and sizing policy.

On CPU test runs x64 is enabled and aggregation runs in float64,
reproducing the reference's Java ``double`` semantics exactly; on TPU the
default is float32/bfloat16-friendly shapes (sums use pairwise tree
reduction inside XLA, which keeps error small at 100M+ rows).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# Padding buckets: shapes are padded up so the jit cache stays small
# (the reference's analog is its fixed 10k/5k block sizes,
# DocIdSetPlanNode.java:33).
DOC_PAD_MULTIPLE = 1024
MIN_CARD_PAD = 8

# Group-by dense-holder cap (reference caps ARRAY_BASED key space at 1M,
# DefaultGroupKeyGenerator.java): beyond this the host hash path runs.
MAX_GROUP_CAPACITY = 1 << 20

# distinctcount / percentile dense state cap (global dictionary size).
MAX_VALUE_STATE = 1 << 22

HLL_LOG2M = 8  # HllConstants.java DEFAULT_LOG2M
HLL_M = 1 << HLL_LOG2M


def x64_enabled() -> bool:
    return bool(jax.config.jax_enable_x64)


def float_dtype():
    return jnp.float64 if x64_enabled() else jnp.float32


def np_float_dtype():
    return np.float64 if x64_enabled() else np.float32


def key_dtype():
    return jnp.int64 if x64_enabled() else jnp.int32


def max_key_space() -> int:
    return 2**62 if x64_enabled() else 2**30


def pad_docs(n: int) -> int:
    """Round doc count up to the padding bucket (pow2 beyond one block)."""
    if n <= DOC_PAD_MULTIPLE:
        m = 8
        while m < n:
            m *= 2
        return m
    blocks = -(-n // DOC_PAD_MULTIPLE)
    # round block count to next power of two to bound jit-cache size
    b = 1
    while b < blocks:
        b *= 2
    return b * DOC_PAD_MULTIPLE


def pad_card(c: int) -> int:
    m = MIN_CARD_PAD
    while m < c:
        m *= 2
    return m
