"""Ingest-aware per-server result cache.

A result cache on a realtime datastore is only safe if it can PROVE a
cached answer is as fresh as a re-execution.  Two fences provide that
proof, and both must hold:

1. **Key fence (correctness):** entries key on
   ``(plan-shape digest, literal digest, ((segment, staging token), …))``
   — the full semantic query identity (engine/plandigest.py: shape +
   literals) times the exact *resident data generation* it ran over.
   Staging tokens are process-unique per segment instance
   (segment/immutable.py): a consuming MutableSegment mints a NEW
   snapshot (new token) the moment its watermark advances, and a
   reloaded/replaced immutable copy gets a new token too.  A lookup for
   fresher data therefore computes a DIFFERENT key and can never match
   a stale entry — serving a stale realtime answer is structurally
   impossible, not merely unlikely.

2. **Offset fence (eagerness):** the key fence alone would leave dead
   entries pinned until LRU pressure.  The LLC consumers
   (realtime/llc.py + the networked RemoteConsumer) call
   ``on_offset_advance`` whenever a partition's consume/commit offset
   moves, and segment add/remove calls ``invalidate_table`` — stale
   entries are dropped the moment the data that produced them is
   superseded (``rescache.staleEvictions``), so memory tracks the live
   working set and the hit-rate meters stay honest.

Entries are stored PICKLED: a hit deserializes a fresh
``IntermediateResult`` (no shared mutable state with past or future
readers, and the payload a hit produces is byte-identical to what the
stored execution produced) and replaces its cost vector with
``{"rescacheHits": 1}`` — a cache hit marks ZERO device/host work by
construction, which the acceptance test asserts.

Opt-in: ``PINOT_TPU_RESULT_CACHE=1`` (default off, matching the
reference ecosystem's posture — e.g. ClickHouse's query cache —
because a result cache changes observable execution counts even when
payloads are identical).  ``PINOT_TPU_RESCACHE_N`` /
``PINOT_TPU_RESCACHE_BYTES`` bound the LRU.
"""
from __future__ import annotations

import os
import pickle
import threading
from collections import OrderedDict
from typing import Any, Dict, Hashable, Optional, Sequence, Tuple

from pinot_tpu.engine.plandigest import (
    _raw_table,
    plan_literal_digest,
    plan_shape_digest,
)


def cache_enabled() -> bool:
    return os.environ.get("PINOT_TPU_RESULT_CACHE", "0") not in ("0", "", "false")


def _max_entries() -> int:
    try:
        return int(os.environ.get("PINOT_TPU_RESCACHE_N", "512"))
    except ValueError:
        return 512


def _max_bytes() -> int:
    try:
        return int(os.environ.get("PINOT_TPU_RESCACHE_BYTES", str(64 << 20)))
    except ValueError:
        return 64 << 20


class ResultCache:
    """Bounded LRU of pickled ``IntermediateResult`` payloads (module
    docstring).  Thread-safe: the scheduler worker pool reads and
    writes concurrently; LLC consumer threads invalidate."""

    def __init__(
        self,
        metrics=None,
        enabled: Optional[bool] = None,
        max_entries: Optional[int] = None,
        max_bytes: Optional[int] = None,
    ) -> None:
        self.enabled = cache_enabled() if enabled is None else bool(enabled)
        self.max_entries = _max_entries() if max_entries is None else int(max_entries)
        self.max_bytes = _max_bytes() if max_bytes is None else int(max_bytes)
        self.metrics = metrics
        self._lock = threading.Lock()
        # key -> (payload bytes, raw table attribution tuple, nbytes)
        self._entries: "OrderedDict[Hashable, Tuple[bytes, str, int]]" = OrderedDict()
        self._bytes = 0
        if metrics is not None:
            # pre-registered at construction (scrape gap != "no cache")
            for m in (
                "rescache.hits",
                "rescache.misses",
                "rescache.puts",
                "rescache.invalidations",
                "rescache.staleEvictions",
            ):
                metrics.meter(m)
            metrics.gauge("rescache.entries").set_fn(self.entry_count)
            metrics.gauge("rescache.bytes").set_fn(lambda: self._bytes)
            metrics.gauge("rescache.enabled").set(1 if self.enabled else 0)

    # -- keying --------------------------------------------------------
    @staticmethod
    def key_for(request, views: Sequence[Any], table: str) -> Optional[Hashable]:
        """Cache key for a parsed request over the exact segment views
        being served, or None when the query is uncacheable (EXPLAIN
        modes).  Traced requests ARE cacheable — the tail sampler
        (PR 11) arms tracing on every query, so excluding them would
        disable the cache outright; stored entries carry no trace (put
        strips it) and a hit records a ``rescacheHit`` event on the
        live span tree instead.  The view tuple is sorted by name so
        routing order can't fork one logical cover into several keys."""
        if request.explain is not None:
            return None
        try:
            fence = tuple(
                sorted(
                    (v.segment_name, int(v.staging_token)) for v in views
                )
            )
        except (AttributeError, TypeError):
            return None  # a view without token identity is uncacheable
        return (
            _raw_table(table),
            plan_shape_digest(request),
            plan_literal_digest(request),
            fence,
        )

    @staticmethod
    def key_for_join(
        request,
        probe_views: Sequence[Any],
        build_views: Sequence[Any],
        probe_table: str,
        build_table: str,
    ) -> Optional[Hashable]:
        """Cache key for a COLOCATED join execution: the semantic query
        identity times BOTH sides' exact resident data generations.  An
        ingest advance / segment change on EITHER table mints new
        staging tokens, so a stale joined answer is structurally
        unreachable; the entry is attributed to both raw tables so
        either side's eager invalidation drops it (ISSUE 14 guard).
        Broadcast/shuffle executions are never cached server-side —
        their build payloads are broker-shipped per query."""
        if request.explain is not None:
            return None
        try:
            fence_p = tuple(
                sorted((v.segment_name, int(v.staging_token)) for v in probe_views)
            )
            fence_b = tuple(
                sorted((v.segment_name, int(v.staging_token)) for v in build_views)
            )
        except (AttributeError, TypeError):
            return None
        return (
            (_raw_table(probe_table), _raw_table(build_table)),
            plan_shape_digest(request),
            plan_literal_digest(request),
            fence_p,
            fence_b,
        )

    # -- read/write ----------------------------------------------------
    def _mark(self, name: str, n: int = 1) -> None:
        if self.metrics is not None and n:
            self.metrics.meter(name).mark(n)

    def get(self, key: Hashable):
        """A fresh ``IntermediateResult`` clone for the key, or None.
        The clone's cost vector is exactly ``{"rescacheHits": 1}`` —
        zero device work, one attributed cache hit."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
        if entry is None:
            self._mark("rescache.misses")
            return None
        result = pickle.loads(entry[0])
        result.cost = {"rescacheHits": 1}
        self._mark("rescache.hits")
        return result

    def put(self, key: Hashable, result) -> None:
        """Store a successful execution's result.  Callers must only
        pass complete, exception-free results (no partial covers) —
        cached partial answers would replay an outage after it healed.
        The stored copy carries NO trace: replaying one query's span
        tree under another's requestId would be a lie."""
        saved_trace = result.trace
        try:
            result.trace = {}
            payload = pickle.dumps(result, protocol=pickle.HIGHEST_PROTOCOL)
        except Exception:
            return  # an unpicklable result is simply not cacheable
        finally:
            result.trace = saved_trace
        nbytes = len(payload)
        if nbytes > max(1, self.max_bytes) // 4:
            return  # one oversized answer must not churn the whole LRU
        raw = key[0] if isinstance(key, tuple) and key else ""
        # entries attribute to one raw table (scans) or several (joins:
        # the key's first element is a tuple of both sides)
        raw = tuple(raw) if isinstance(raw, tuple) else (str(raw),)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[2]
            self._entries[key] = (payload, raw, nbytes)
            self._bytes += nbytes
            while self._entries and (
                self._bytes > self.max_bytes or len(self._entries) > self.max_entries
            ):
                _, (_, _, old_bytes) = self._entries.popitem(last=False)
                self._bytes -= old_bytes
        self._mark("rescache.puts")

    # -- invalidation (the offset fence) -------------------------------
    def invalidate_table(self, table: str, reason: str = "segments") -> int:
        """Drop every entry for ``table`` (raw name; physical
        ``_OFFLINE``/``_REALTIME`` suffixes stripped).  Returns the
        number of entries evicted.  ``reason`` is attribution only —
        "offset" marks LLC watermark advancement, "segments" a segment
        set change."""
        raw = _raw_table(table)
        dropped = 0
        with self._lock:
            victims = [k for k, e in self._entries.items() if raw in e[1]]
            for k in victims:
                _, _, nbytes = self._entries.pop(k)
                self._bytes -= nbytes
                dropped += 1
        if dropped or reason == "offset":
            self._mark("rescache.invalidations")
        self._mark("rescache.staleEvictions", dropped)
        return dropped

    def on_offset_advance(self, table: str, partition: int, offset: int) -> int:
        """LLC watermark hook: a partition's consume/commit offset
        advanced, so every cached answer over this table's previous
        watermark is superseded.  (The key fence already makes those
        entries unreachable — this drops them eagerly.)"""
        return self.invalidate_table(table, reason="offset")

    def contains(self, request, views: Sequence[Any], table: str) -> bool:
        """EXPLAIN probe: would this exact query over these exact views
        hit right now?  Ignores the request's explain mode (the probe
        asks about the EXECUTABLE twin) and marks no hit/miss meters —
        EXPLAIN must never skew the hit-rate series."""
        if not self.enabled:
            return False
        try:
            fence = tuple(
                sorted((v.segment_name, int(v.staging_token)) for v in views)
            )
        except (AttributeError, TypeError):
            return False
        key = (
            _raw_table(table),
            plan_shape_digest(request),
            plan_literal_digest(request),
            fence,
        )
        with self._lock:
            return key in self._entries

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    # -- observability -------------------------------------------------
    def entry_count(self) -> int:
        with self._lock:
            return len(self._entries)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            entries = len(self._entries)
            nbytes = self._bytes
        out = {
            "enabled": self.enabled,
            "entries": entries,
            "bytes": nbytes,
            "maxEntries": self.max_entries,
            "maxBytes": self.max_bytes,
        }
        if self.metrics is not None:
            for short, name in (
                ("hits", "rescache.hits"),
                ("misses", "rescache.misses"),
                ("puts", "rescache.puts"),
                ("invalidations", "rescache.invalidations"),
                ("staleEvictions", "rescache.staleEvictions"),
            ):
                out[short] = self.metrics.meter(name).count
            denom = out["hits"] + out["misses"]
            out["hitRate"] = round(out["hits"] / denom, 4) if denom else 0.0
        return out
