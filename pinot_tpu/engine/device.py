"""Device staging: immutable segments -> HBM-resident stacked arrays.

The analog of the reference's mmap staging (``PinotDataBuffer.java:45``)
plus the load path (``Loaders.java:40``): column data becomes jax device
arrays, ready for the jit'd query kernels.

Layout (S = number of segments stacked on the leading axis — the
parallelism axis that replaces MCombineOperator's thread pools and is
sharded over the chip mesh in ``pinot_tpu.parallel``):

  fwd        int32 [S, n_pad]            SV dictId forward index
  mv         int32 [S, n_pad, mv_pad]    MV dictIds (padded)
  mv_valid   bool  [S, n_pad, mv_pad]    MV entry validity
  dict_vals  float [S, card_pad]         numeric dictionary values
  valid      bool  [S, n_pad]            doc validity (padding rows False)

All shapes are bucketed (pow2 padding, ``config.pad_docs/pad_card``) so
the jit cache stays bounded; padding docs carry dictId 0 and valid=False,
and every kernel masks with ``valid``.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pinot_tpu.common.schema import DataType
from pinot_tpu.engine import config
from pinot_tpu.segment.immutable import ImmutableSegment


@dataclass
class StagedColumn:
    name: str
    stored_type: DataType
    single_value: bool
    card_pad: int
    mv_pad: int
    cards: Tuple[int, ...]  # per-segment true cardinality
    fwd: Optional[jnp.ndarray] = None
    mv: Optional[jnp.ndarray] = None
    mv_valid: Optional[jnp.ndarray] = None
    dict_vals: Optional[jnp.ndarray] = None
    # optional role-specific arrays (big-dictionary gathers are slow on
    # TPU, so these trade HBM for streaming access):
    raw: Optional[jnp.ndarray] = None  # float [S, n_pad] dictionary-decoded values
    gfwd: Optional[jnp.ndarray] = None  # int32 [S, n_pad] global-dictId fwd

    @property
    def is_numeric(self) -> bool:
        return self.stored_type != DataType.STRING


@dataclass
class StagedTable:
    """A set of segments staged into device memory, stacked on axis 0."""

    segment_names: Tuple[str, ...]
    num_segments: int
    n_pad: int
    num_docs: Tuple[int, ...]
    valid: jnp.ndarray  # bool [S, n_pad]
    columns: Dict[str, StagedColumn] = field(default_factory=dict)

    def column(self, name: str) -> StagedColumn:
        return self.columns[name]

    @property
    def total_docs(self) -> int:
        return int(sum(self.num_docs))


def stage_segments(
    segments: Sequence[ImmutableSegment],
    column_names: Sequence[str],
    device=None,
    pad_segments_to: int = 0,
    raw_columns: Sequence[str] = (),
    gfwd_columns: Sequence[str] = (),
    ctx=None,
) -> StagedTable:
    """Stack + pad + transfer the given columns of the segments.

    ``pad_segments_to`` rounds the segment axis up with all-invalid
    dummy segments so it divides the mesh's device count (multi-chip
    ``shard_map`` needs an evenly shardable leading axis).

    ``raw_columns`` (numeric SV) additionally stage dictionary-decoded
    value arrays; ``gfwd_columns`` (SV, requires ``ctx``) stage
    global-dictId forward arrays. Both are host-side numpy gathers done
    once at staging so query kernels stream instead of gathering.
    """
    S = max(len(segments), pad_segments_to)
    n_pad = config.pad_docs(max(seg.num_docs for seg in segments))

    put = (lambda x: jax.device_put(x, device)) if device is not None else jnp.asarray

    valid_np = np.zeros((S, n_pad), dtype=bool)
    for i, seg in enumerate(segments):
        valid_np[i, : seg.num_docs] = True

    staged = StagedTable(
        segment_names=tuple(s.segment_name for s in segments),
        num_segments=S,
        n_pad=n_pad,
        num_docs=tuple(s.num_docs for s in segments) + (0,) * (S - len(segments)),
        valid=put(valid_np),
    )

    fdt = config.np_float_dtype()
    for name in column_names:
        cols = [seg.column(name) for seg in segments]
        meta0 = cols[0].metadata
        cards = tuple(c.dictionary.cardinality for c in cols)
        card_pad = config.pad_card(max(cards))
        sc = StagedColumn(
            name=name,
            stored_type=meta0.data_type.stored_type,
            single_value=meta0.single_value,
            card_pad=card_pad,
            mv_pad=0,
            cards=cards,
        )
        if meta0.single_value:
            fwd = np.zeros((S, n_pad), dtype=np.int32)
            for i, c in enumerate(cols):
                fwd[i, : c.fwd.size] = c.fwd
            sc.fwd = put(fwd)
            if name in raw_columns and sc.is_numeric:
                raw = np.zeros((S, n_pad), dtype=fdt)
                for i, c in enumerate(cols):
                    vals = np.asarray(c.dictionary.values, dtype=fdt)
                    raw[i, : c.fwd.size] = vals[c.fwd]
                sc.raw = put(raw)
            if name in gfwd_columns and ctx is not None:
                gf = np.zeros((S, n_pad), dtype=np.int32)
                remaps = ctx.column(name).remaps
                for i, c in enumerate(cols):
                    gf[i, : c.fwd.size] = remaps[i][c.fwd]
                sc.gfwd = put(gf)
        else:
            mv_pad = max(1, max(c.metadata.max_num_multi_values for c in cols))
            mv_pad = config.pad_card(mv_pad)  # pow2 bucket
            mv = np.zeros((S, n_pad, mv_pad), dtype=np.int32)
            mvv = np.zeros((S, n_pad, mv_pad), dtype=bool)
            for i, c in enumerate(cols):
                offs = c.mv_offsets
                counts = np.diff(offs)
                n = counts.size
                # scatter CSR into padded matrix
                row_idx = np.repeat(np.arange(n), counts)
                col_idx = np.concatenate([np.arange(k) for k in counts]) if n else np.zeros(0, int)
                mv[i, row_idx, col_idx] = c.mv_values
                mvv[i, row_idx, col_idx] = True
            sc.mv_pad = mv_pad
            sc.mv = put(mv)
            sc.mv_valid = put(mvv)
        if sc.is_numeric:
            dv = np.zeros((S, card_pad), dtype=fdt)
            for i, c in enumerate(cols):
                dv[i, : cards[i]] = np.asarray(c.dictionary.values, dtype=fdt)
            sc.dict_vals = put(dv)
        staged.columns[name] = sc
    return staged


# ---------------------------------------------------------------------------
# Staging cache: segments are immutable, so staging is reusable per
# (segment set, column set) — the HBM-residency analog of the reference
# keeping segments mmap'd between queries.
# ---------------------------------------------------------------------------

_stage_cache: Dict[Tuple, StagedTable] = {}


def get_staged(
    segments: Sequence[ImmutableSegment],
    column_names: Sequence[str],
    pad_segments_to: int = 0,
    raw_columns: Sequence[str] = (),
    gfwd_columns: Sequence[str] = (),
    ctx=None,
) -> StagedTable:
    """Cached staging. The cache key covers only the base arrays; role
    arrays (raw/gfwd) are attached to the cached StagedTable on demand,
    so queries differing only in roles share one HBM copy of the base
    columns."""
    key = (
        tuple(f"{s.segment_name}:{s.metadata.crc}" for s in segments),
        tuple(sorted(column_names)),
        pad_segments_to,
    )
    st = _stage_cache.get(key)
    if st is None:
        st = stage_segments(
            segments,
            sorted(column_names),
            pad_segments_to=pad_segments_to,
            raw_columns=raw_columns,
            gfwd_columns=gfwd_columns,
            ctx=ctx,
        )
        if len(_stage_cache) > 32:
            _stage_cache.clear()
        _stage_cache[key] = st
    else:
        _augment_staged(st, segments, raw_columns, gfwd_columns, ctx)
    return st


def _augment_staged(
    st: StagedTable,
    segments: Sequence[ImmutableSegment],
    raw_columns: Sequence[str],
    gfwd_columns: Sequence[str],
    ctx,
) -> None:
    """Attach missing role arrays to an already-staged table."""
    fdt = config.np_float_dtype()
    S, n_pad = st.num_segments, st.n_pad
    for name in raw_columns:
        sc = st.columns.get(name)
        if sc is None or sc.raw is not None or not sc.is_numeric or not sc.single_value:
            continue
        raw = np.zeros((S, n_pad), dtype=fdt)
        for i, seg in enumerate(segments):
            c = seg.column(name)
            vals = np.asarray(c.dictionary.values, dtype=fdt)
            raw[i, : c.fwd.size] = vals[c.fwd]
        sc.raw = jnp.asarray(raw)
    for name in gfwd_columns:
        sc = st.columns.get(name)
        if sc is None or sc.gfwd is not None or not sc.single_value or ctx is None:
            continue
        gf = np.zeros((S, n_pad), dtype=np.int32)
        remaps = ctx.column(name).remaps
        for i, seg in enumerate(segments):
            c = seg.column(name)
            gf[i, : c.fwd.size] = remaps[i][c.fwd]
        sc.gfwd = jnp.asarray(gf)


def clear_staging_cache() -> None:
    _stage_cache.clear()
