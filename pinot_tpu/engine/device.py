"""Device staging: immutable segments -> HBM-resident stacked arrays.

The analog of the reference's mmap staging (``PinotDataBuffer.java:45``)
plus the load path (``Loaders.java:40``): column data becomes jax device
arrays, ready for the jit'd query kernels.

Layout (S = number of segments stacked on the leading axis — the
parallelism axis that replaces MCombineOperator's thread pools and is
sharded over the chip mesh in ``pinot_tpu.parallel``):

  fwd        int32 [S, n_pad]            SV dictId forward index
  mv         int32 [S, n_pad, mv_pad]    MV dictIds (padded)
  mv_valid   bool  [S, n_pad, mv_pad]    MV entry validity
  dict_vals  float [S, card_pad]         numeric dictionary values
  valid      bool  [S, n_pad]            doc validity (padding rows False)

All shapes are bucketed (pow2 padding, ``config.pad_docs/pad_card``) so
the jit cache stays bounded; padding docs carry dictId 0 and valid=False,
and every kernel masks with ``valid``.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pinot_tpu.common.schema import DataType
from pinot_tpu.engine import config
from pinot_tpu.segment.immutable import ImmutableSegment


@dataclass
class StagedColumn:
    name: str
    stored_type: DataType
    single_value: bool
    card_pad: int
    mv_pad: int
    cards: Tuple[int, ...]  # per-segment true cardinality
    fwd: Optional[jnp.ndarray] = None
    mv: Optional[jnp.ndarray] = None
    mv_valid: Optional[jnp.ndarray] = None
    dict_vals: Optional[jnp.ndarray] = None

    @property
    def is_numeric(self) -> bool:
        return self.stored_type != DataType.STRING


@dataclass
class StagedTable:
    """A set of segments staged into device memory, stacked on axis 0."""

    segment_names: Tuple[str, ...]
    num_segments: int
    n_pad: int
    num_docs: Tuple[int, ...]
    valid: jnp.ndarray  # bool [S, n_pad]
    columns: Dict[str, StagedColumn] = field(default_factory=dict)

    def column(self, name: str) -> StagedColumn:
        return self.columns[name]

    @property
    def total_docs(self) -> int:
        return int(sum(self.num_docs))


def stage_segments(
    segments: Sequence[ImmutableSegment],
    column_names: Sequence[str],
    device=None,
    pad_segments_to: int = 0,
) -> StagedTable:
    """Stack + pad + transfer the given columns of the segments.

    ``pad_segments_to`` rounds the segment axis up with all-invalid
    dummy segments so it divides the mesh's device count (multi-chip
    ``shard_map`` needs an evenly shardable leading axis).
    """
    S = max(len(segments), pad_segments_to)
    n_pad = config.pad_docs(max(seg.num_docs for seg in segments))

    put = (lambda x: jax.device_put(x, device)) if device is not None else jnp.asarray

    valid_np = np.zeros((S, n_pad), dtype=bool)
    for i, seg in enumerate(segments):
        valid_np[i, : seg.num_docs] = True

    staged = StagedTable(
        segment_names=tuple(s.segment_name for s in segments),
        num_segments=S,
        n_pad=n_pad,
        num_docs=tuple(s.num_docs for s in segments) + (0,) * (S - len(segments)),
        valid=put(valid_np),
    )

    fdt = config.np_float_dtype()
    for name in column_names:
        cols = [seg.column(name) for seg in segments]
        meta0 = cols[0].metadata
        cards = tuple(c.dictionary.cardinality for c in cols)
        card_pad = config.pad_card(max(cards))
        sc = StagedColumn(
            name=name,
            stored_type=meta0.data_type.stored_type,
            single_value=meta0.single_value,
            card_pad=card_pad,
            mv_pad=0,
            cards=cards,
        )
        if meta0.single_value:
            fwd = np.zeros((S, n_pad), dtype=np.int32)
            for i, c in enumerate(cols):
                fwd[i, : c.fwd.size] = c.fwd
            sc.fwd = put(fwd)
        else:
            mv_pad = max(1, max(c.metadata.max_num_multi_values for c in cols))
            mv_pad = config.pad_card(mv_pad)  # pow2 bucket
            mv = np.zeros((S, n_pad, mv_pad), dtype=np.int32)
            mvv = np.zeros((S, n_pad, mv_pad), dtype=bool)
            for i, c in enumerate(cols):
                offs = c.mv_offsets
                counts = np.diff(offs)
                n = counts.size
                # scatter CSR into padded matrix
                row_idx = np.repeat(np.arange(n), counts)
                col_idx = np.concatenate([np.arange(k) for k in counts]) if n else np.zeros(0, int)
                mv[i, row_idx, col_idx] = c.mv_values
                mvv[i, row_idx, col_idx] = True
            sc.mv_pad = mv_pad
            sc.mv = put(mv)
            sc.mv_valid = put(mvv)
        if sc.is_numeric:
            dv = np.zeros((S, card_pad), dtype=fdt)
            for i, c in enumerate(cols):
                dv[i, : cards[i]] = np.asarray(c.dictionary.values, dtype=fdt)
            sc.dict_vals = put(dv)
        staged.columns[name] = sc
    return staged


# ---------------------------------------------------------------------------
# Staging cache: segments are immutable, so staging is reusable per
# (segment set, column set) — the HBM-residency analog of the reference
# keeping segments mmap'd between queries.
# ---------------------------------------------------------------------------

_stage_cache: Dict[Tuple, StagedTable] = {}


def get_staged(
    segments: Sequence[ImmutableSegment],
    column_names: Sequence[str],
    pad_segments_to: int = 0,
) -> StagedTable:
    key = (
        tuple(f"{s.segment_name}:{s.metadata.crc}" for s in segments),
        tuple(sorted(column_names)),
        pad_segments_to,
    )
    st = _stage_cache.get(key)
    if st is None:
        st = stage_segments(segments, sorted(column_names), pad_segments_to=pad_segments_to)
        if len(_stage_cache) > 32:
            _stage_cache.clear()
        _stage_cache[key] = st
    return st


def clear_staging_cache() -> None:
    _stage_cache.clear()
