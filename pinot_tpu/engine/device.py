"""Device staging: immutable segments -> HBM-resident stacked arrays.

The analog of the reference's mmap staging (``PinotDataBuffer.java:45``)
plus the load path (``Loaders.java:40``): column data becomes jax device
arrays, ready for the jit'd query kernels.

Layout (S = number of segments stacked on the leading axis — the
parallelism axis that replaces MCombineOperator's thread pools and is
sharded over the chip mesh in ``pinot_tpu.parallel``):

  fwd        int8/16/32 [S, n_pad]          SV dictId forward index
  mv         int8/16/32 [S, n_pad, mv_pad]  MV dictIds (padded)
  mv_counts  int8/16    [S, n_pad]          per-doc MV entry count
  dict_vals  float      [S, card_pad]       numeric dictionary values
  num_docs_arr int32    [S]                 true doc count per segment

Integer widths are minimal for the column's cardinality
(``config.index_dtype``) — the kernels are HBM-bandwidth-bound, so a
card-3 column should cost 1 byte/row, not 4.  Validity masks are never
stored: the kernel derives doc validity from ``iota < num_docs`` and MV
entry validity from ``iota < mv_counts``, trading a free register
compare for an HBM byte per row (or per MV slot).

All shapes are bucketed (pow2 padding, ``config.pad_docs/pad_card``;
value-state holder axes use quarter-pow2 ``config.pad_value_card``) so
the jit cache stays bounded; padding docs carry dictId 0.
"""
from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pinot_tpu.common.schema import DataType
from pinot_tpu.engine import config
from pinot_tpu.segment.immutable import ImmutableSegment


@dataclass
class StagedColumn:
    name: str
    stored_type: DataType
    single_value: bool
    card_pad: int
    mv_pad: int
    cards: Tuple[int, ...]  # per-segment true cardinality
    fwd: Optional[jnp.ndarray] = None
    mv: Optional[jnp.ndarray] = None
    mv_counts: Optional[jnp.ndarray] = None
    dict_vals: Optional[jnp.ndarray] = None
    # optional role-specific arrays (big-dictionary gathers are slow on
    # TPU, so these trade HBM for streaming access):
    raw: Optional[jnp.ndarray] = None  # float [S, n_pad] dictionary-decoded values
    gfwd: Optional[jnp.ndarray] = None  # int32 [S, n_pad] global-dictId fwd
    hll_bucket: Optional[jnp.ndarray] = None  # uint8 [S, n_pad] HLL register index
    hll_rho: Optional[jnp.ndarray] = None  # uint8 [S, n_pad] HLL rank
    mv_raw: Optional[jnp.ndarray] = None  # float [S, n_pad, mv_pad] decoded MV values
    # bit-sliced tier planes (engine/bitsliced.py): dictId bit-planes
    # for bitwise filter/min/max evaluation, and value-offset planes
    # (value - per-segment vmin) for popcount-fused SUM
    bsi: Optional[jnp.ndarray] = None  # uint32 [S, W, n_pad//32] dictId planes
    bsiv: Optional[jnp.ndarray] = None  # uint32 [S, Wv, n_pad//32] value-offset planes
    bsi_width: int = 0
    bsiv_width: int = 0
    bsiv_min: Optional[Tuple[int, ...]] = None  # per-segment integer vmin

    @property
    def is_numeric(self) -> bool:
        return self.stored_type != DataType.STRING


import itertools

_stage_tokens = itertools.count()


@dataclass
class StagedTable:
    """A set of segments staged into device memory, stacked on axis 0."""

    segment_names: Tuple[str, ...]
    num_segments: int
    n_pad: int
    num_docs: Tuple[int, ...]
    num_docs_arr: jnp.ndarray  # int32 [S]
    columns: Dict[str, StagedColumn] = field(default_factory=dict)
    _valid: Optional[jnp.ndarray] = None
    # process-unique staging identity: the device lane's coalesce key
    # needs "same staged table" without pinning the object (an id()
    # would recycle after GC and could alias a RE-staged table into an
    # in-flight dispatch — silent stale results).  Sharded placements
    # (mesh execution) keep the same invariant: each (segment set,
    # placement) staging mints its OWN token, so a table re-staged onto
    # a different chip group can never alias an in-flight dispatch.
    token: int = field(default_factory=lambda: next(_stage_tokens))
    # placement of the leading segment axis (engine/mesh.py chip
    # groups): a jax Sharding splitting axis 0 across the group's
    # chips, or None for default single-device placement.  Role-array
    # augmentation and the on-demand valid mask must land on the SAME
    # placement, so it rides the staged table.
    sharding: Any = field(default=None, repr=False, compare=False)

    def column(self, name: str) -> StagedColumn:
        return self.columns[name]

    @property
    def total_docs(self) -> int:
        return int(sum(self.num_docs))

    @property
    def valid(self) -> jnp.ndarray:
        """bool [S, n_pad] doc-validity mask, materialized on demand —
        kernels derive validity from num_docs instead of reading this."""
        if self._valid is None:
            v = np.zeros((self.num_segments, self.n_pad), dtype=bool)
            for i, n in enumerate(self.num_docs):
                v[i, :n] = True
            # same placement as the staged columns: a default-device
            # mask fed to a chip-group program would force a reshard
            self._valid = (
                jax.device_put(v, self.sharding)
                if self.sharding is not None
                else jnp.asarray(v)
            )
        return self._valid


def _csr_scatter(values, offsets, out_row, *extra):
    """Fill one segment's padded [n_pad, mv_pad] matrix row block from
    CSR (values, offsets) — the ONE place the scatter-index math lives.
    ``extra`` pairs of (values2, out_row2) scatter through the same
    indices (mv ids + mv_raw share one offsets array)."""
    counts = np.diff(offsets)
    n = counts.size
    row_idx = np.repeat(np.arange(n), counts)
    col_idx = (
        np.concatenate([np.arange(k) for k in counts]) if n else np.zeros(0, int)
    )
    out_row[row_idx, col_idx] = values
    for v2, o2 in zip(extra[::2], extra[1::2]):
        o2[row_idx, col_idx] = v2
    return counts


def stage_segments(
    segments: Sequence[ImmutableSegment],
    column_names: Sequence[str],
    device=None,
    pad_segments_to: int = 0,
    raw_columns: Sequence[str] = (),
    gfwd_columns: Sequence[str] = (),
    hll_columns: Sequence[str] = (),
    ctx=None,
    skip_base_columns: Sequence[str] = (),
    sharding=None,
    bsi_columns: Sequence[str] = (),
    bsiv_columns: Sequence[str] = (),
) -> StagedTable:
    """Stack + pad + transfer the given columns of the segments.

    ``pad_segments_to`` rounds the segment axis up with all-invalid
    dummy segments so it divides the mesh's device count (multi-chip
    ``shard_map`` needs an evenly shardable leading axis).

    ``sharding`` (mesh execution, engine/mesh.py): a jax Sharding
    splitting the leading segment axis across a chip group — the
    GlobalDeviceArray-style staging where each chip's HBM holds only
    its shard of every column.  None keeps default placement (the
    single-chip path).

    ``raw_columns`` (numeric SV) additionally stage dictionary-decoded
    value arrays; ``gfwd_columns`` (SV, requires ``ctx``) stage
    global-dictId forward arrays; ``hll_columns`` (SV) stage per-row
    HLL (register, rank) uint8 streams. All are host-side numpy
    gathers done once at staging so query kernels stream instead of
    gathering.

    ``skip_base_columns``: SV columns whose base ``fwd``/``dict_vals``
    arrays are NOT uploaded — for columns the kernel reads only through
    a role stream (agg input / group key / HLL), the dictId stream is
    dead HBM weight; at 1B rows it decides whether the table fits on
    one chip at all.  The caller must guarantee no filter leaf,
    selection output, or dict-gather path touches these columns.
    """
    S = max(len(segments), pad_segments_to)
    n_pad = config.pad_docs(max(seg.num_docs for seg in segments))

    if sharding is not None:
        put = lambda x: jax.device_put(x, sharding)  # noqa: E731
    elif device is not None:
        put = lambda x: jax.device_put(x, device)  # noqa: E731
    else:
        put = jnp.asarray

    staged = StagedTable(
        segment_names=tuple(s.segment_name for s in segments),
        num_segments=S,
        n_pad=n_pad,
        num_docs=tuple(s.num_docs for s in segments) + (0,) * (S - len(segments)),
        num_docs_arr=put(
            np.asarray(
                [s.num_docs for s in segments] + [0] * (S - len(segments)),
                dtype=np.int32,
            )
        ),
        sharding=sharding,
    )

    fdt = config.np_float_dtype()
    for name in column_names:
        cols = [seg.column(name) for seg in segments]
        meta0 = cols[0].metadata
        cards = tuple(c.dictionary.cardinality for c in cols)
        card_pad = config.pad_card(max(cards))
        idt = config.index_dtype(card_pad)
        sc = StagedColumn(
            name=name,
            stored_type=meta0.data_type.stored_type,
            single_value=meta0.single_value,
            card_pad=card_pad,
            mv_pad=0,
            cards=cards,
        )
        skip_base = name in skip_base_columns and meta0.single_value
        if meta0.single_value:
            if not skip_base:
                # the stacked copy is built only when it uploads — at
                # 1B rows the transient alone is multiple GB of host RAM
                sc.fwd = put(_stack_fwd(cols, S, n_pad, idt))
            if name in raw_columns and sc.is_numeric:
                raw = np.zeros((S, n_pad), dtype=fdt)
                for i, c in enumerate(cols):
                    vals = np.asarray(c.dictionary.values, dtype=fdt)
                    raw[i, : c.fwd.size] = vals[c.fwd]
                sc.raw = put(raw)
            if name in gfwd_columns and ctx is not None:
                gdt = config.index_dtype(
                    config.pad_card(ctx.column(name).global_cardinality)
                )
                gf = np.zeros((S, n_pad), dtype=gdt)
                remaps = ctx.column(name).remaps
                for i, c in enumerate(cols):
                    gf[i, : c.fwd.size] = remaps[i][c.fwd]
                sc.gfwd = put(gf)
            if name in hll_columns:
                hb, hr = _hll_streams(cols, S, n_pad)
                sc.hll_rho = put(hr)  # rho first (see _augment_staged)
                sc.hll_bucket = put(hb)
            if name in bsi_columns:
                sc.bsi_width = bsi_filter_width(cols)
                sc.bsi = put(_bsi_planes(cols, S, n_pad, sc.bsi_width))
            if name in bsiv_columns and sc.is_numeric:
                spec = bsiv_value_spec(cols)
                if spec is not None:
                    sc.bsiv_width, sc.bsiv_min = spec
                    sc.bsiv = put(
                        _bsiv_planes(cols, S, n_pad, sc.bsiv_width, sc.bsiv_min)
                    )
        else:
            mv_pad = max(1, max(c.metadata.max_num_multi_values for c in cols))
            mv_pad = config.pad_card(mv_pad)  # pow2 bucket
            mv = np.zeros((S, n_pad, mv_pad), dtype=idt)
            mvc = np.zeros((S, n_pad), dtype=config.count_dtype(mv_pad))
            want_raw = name in raw_columns and sc.is_numeric
            mvr = np.zeros((S, n_pad, mv_pad), dtype=fdt) if want_raw else None
            for i, c in enumerate(cols):
                if mvr is not None:
                    vals = np.asarray(c.dictionary.values, dtype=fdt)
                    counts = _csr_scatter(
                        c.mv_values, c.mv_offsets, mv[i], vals[c.mv_values], mvr[i]
                    )
                else:
                    counts = _csr_scatter(c.mv_values, c.mv_offsets, mv[i])
                mvc[i, : counts.size] = counts
            sc.mv_pad = mv_pad
            sc.mv = put(mv)
            sc.mv_counts = put(mvc)
            if mvr is not None:
                sc.mv_raw = put(mvr)
        if sc.is_numeric and not skip_base:
            sc.dict_vals = put(_stack_dict_vals(cols, S, card_pad, fdt))
        staged.columns[name] = sc
    return staged


def _stack_fwd(cols, S: int, n_pad: int, idt) -> np.ndarray:
    """Stacked (S, n_pad) dictId forward array — the ONE layout shared
    by staging and the later-query backfill (_augment_staged)."""
    fwd = np.zeros((S, n_pad), dtype=idt)
    for i, c in enumerate(cols):
        fwd[i, : c.fwd.size] = c.fwd
    return fwd


def _stack_dict_vals(cols, S: int, card_pad: int, fdt) -> np.ndarray:
    dv = np.zeros((S, card_pad), dtype=fdt)
    for i, c in enumerate(cols):
        dv[i, : c.dictionary.cardinality] = np.asarray(c.dictionary.values, dtype=fdt)
    return dv


# ---------------------------------------------------------------------------
# Bit-sliced tier staging (engine/bitsliced.py): plane layouts are
# built host-side at staging time with the packing.py encoder, stacked
# [S, W, n_pad//32], and attached as role arrays so realtime
# staging-token advances invalidate them exactly like every other role.
# ---------------------------------------------------------------------------


def bsi_filter_width(cols) -> int:
    """Uniform dictId plane count across segments: enough planes for
    the widest per-segment dictionary."""
    from pinot_tpu.engine.packing import bit_width

    return max(bit_width(max(c.dictionary.cardinality - 1, 0)) for c in cols)


def bsiv_value_spec(cols) -> "Optional[Tuple[int, Tuple[int, ...]]]":
    """(plane count, per-segment integer vmin) for value-offset planes,
    or None when any segment's dictionary is not exactly integral —
    fused SUM is only offered where it is bit-exact vs the scan tier."""
    from pinot_tpu.engine.packing import bit_width, integral_dictionary_values

    vmins = []
    width = 1
    for c in cols:
        iv = integral_dictionary_values(c.dictionary.values)
        if iv is None:
            return None
        vmin, vmax = int(iv.min()), int(iv.max())
        vmins.append(vmin)
        width = max(width, bit_width(vmax - vmin))
    if width > 32:
        return None
    return width, tuple(vmins)


def _bsi_planes(cols, S: int, n_pad: int, width: int) -> np.ndarray:
    from pinot_tpu.engine.packing import bitslice_encode

    # round UP: segments smaller than one 32-row word still need a word
    nw = max(1, (n_pad + 31) // 32)
    planes = np.zeros((S, width, nw), dtype=np.uint32)
    for i, c in enumerate(cols):
        planes[i] = bitslice_encode(np.asarray(c.fwd), width, nw)
    return planes


def _bsiv_planes(
    cols, S: int, n_pad: int, width: int, vmins: Tuple[int, ...]
) -> np.ndarray:
    from pinot_tpu.engine.packing import bitslice_encode, integral_dictionary_values

    nw = max(1, (n_pad + 31) // 32)
    planes = np.zeros((S, width, nw), dtype=np.uint32)
    for i, c in enumerate(cols):
        iv = integral_dictionary_values(c.dictionary.values)
        planes[i] = bitslice_encode(iv[c.fwd] - vmins[i], width, nw)
    return planes


# ---------------------------------------------------------------------------
# HBM staging ledger: byte-accurate accounting of what the staging
# cache currently pins in device memory, per staged table / column /
# role — the capacity signal multichip staging and broker admission
# control consume.  One ledger per process (the staging cache is
# process-global too: in-process multi-server harnesses share one
# device, so their instances report the same process-wide figure).
# ---------------------------------------------------------------------------

# StagedColumn array attributes -> ledger role names
_ROLE_ATTRS = (
    ("fwd", "fwd"),
    ("mv", "mv"),
    ("mv_counts", "mvCounts"),
    ("dict_vals", "dict"),
    ("raw", "raw"),
    ("gfwd", "gfwd"),
    ("hll_bucket", "hll"),
    ("hll_rho", "hll"),
    ("mv_raw", "mvRaw"),
    ("bsi", "bsi"),
    ("bsiv", "bsi"),
)


def _device_label(dev) -> str:
    return f"{getattr(dev, 'platform', 'dev')}:{getattr(dev, 'id', '?')}"


def _add_device_bytes(arr, by_device: Dict[str, int]) -> None:
    """Attribute one staged array's bytes to the device(s) actually
    holding them.  Sharded placements (mesh execution) split across the
    chip group via ``addressable_shards`` — each shard's OWN nbytes, so
    a replicated array honestly counts once per holding device; plain
    single-device arrays land on their one device; host-side arrays
    (never the real staging path) attribute to "host"."""
    shards = None
    try:
        shards = getattr(arr, "addressable_shards", None)
    except Exception:
        shards = None
    if shards:
        try:
            # accumulate into a scratch map first: a mid-iteration
            # failure (buffer deleted concurrently) must not leave
            # partial per-shard bytes behind AND re-attribute the whole
            # array below — that would break "byDevice sums to total"
            local: Dict[str, int] = {}
            for sh in shards:
                key = _device_label(getattr(sh, "device", None))
                local[key] = local.get(key, 0) + int(sh.data.nbytes)
            for key, n in local.items():
                by_device[key] = by_device.get(key, 0) + n
            return
        except Exception:
            pass  # fall through to whole-array attribution
    by_device["host"] = by_device.get("host", 0) + int(getattr(arr, "nbytes", 0))


def _measure_staged(
    staged: StagedTable,
) -> Tuple[int, Dict[str, int], Dict[str, int], Dict[str, int]]:
    """(total bytes, per-column bytes, per-role bytes, per-device
    bytes) of a staged table's device arrays — read straight off the
    jax arrays' nbytes, so the ledger total matches the staged bytes
    exactly; the per-device map sums to the total for (non-replicated)
    sharded placements."""
    total = int(getattr(staged.num_docs_arr, "nbytes", 0))
    by_role: Dict[str, int] = {"meta": total}
    by_device: Dict[str, int] = {}
    _add_device_bytes(staged.num_docs_arr, by_device)
    if staged._valid is not None:
        n = int(staged._valid.nbytes)
        total += n
        by_role["meta"] = by_role.get("meta", 0) + n
        _add_device_bytes(staged._valid, by_device)
    by_column: Dict[str, int] = {}
    for name, sc in staged.columns.items():
        col_bytes = 0
        for attr, role in _ROLE_ATTRS:
            arr = getattr(sc, attr)
            if arr is None:
                continue
            n = int(arr.nbytes)
            col_bytes += n
            by_role[role] = by_role.get(role, 0) + n
            _add_device_bytes(arr, by_device)
        by_column[name] = col_bytes
        total += col_bytes
    return total, by_column, by_role, by_device


class StagingLedger:
    """Ledger of HBM-resident staged tables: byte totals, per-table /
    per-column-role breakdowns, a high-watermark, and eviction
    visibility.  Entries key on the StagedTable's process-unique
    ``token`` and are re-measured on role augmentation, so the totals
    stay byte-accurate as arrays attach."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._entries: Dict[int, Dict] = {}  # token -> entry
        self.high_watermark = 0
        self.evictions = 0
        self.evicted_bytes = 0

    def update(self, staged: StagedTable, table: str) -> int:
        total, by_column, by_role, by_device = _measure_staged(staged)
        with self._lock:
            self._entries[staged.token] = {
                "table": table,
                "segments": list(staged.segment_names),
                "bytes": total,
                "columns": by_column,
                "roles": by_role,
                "devices": by_device,
            }
            now = sum(e["bytes"] for e in self._entries.values())
            if now > self.high_watermark:
                self.high_watermark = now
        return total

    def drop(self, staged: StagedTable) -> None:
        with self._lock:
            entry = self._entries.pop(staged.token, None)
            if entry is not None:
                self.evictions += 1
                self.evicted_bytes += entry["bytes"]

    def total_bytes(self) -> int:
        with self._lock:
            return sum(e["bytes"] for e in self._entries.values())

    def table_count(self) -> int:
        with self._lock:
            return len(self._entries)

    def snapshot(self) -> Dict:
        """JSON-safe view served on server status() / /debug/metrics
        and aggregated cluster-wide by the controller /debug/capacity."""
        with self._lock:
            by_table: Dict[str, int] = {}
            by_role: Dict[str, int] = {}
            by_device: Dict[str, int] = {}
            entries = []
            for e in self._entries.values():
                by_table[e["table"]] = by_table.get(e["table"], 0) + e["bytes"]
                for role, n in e["roles"].items():
                    by_role[role] = by_role.get(role, 0) + n
                for dev, n in e.get("devices", {}).items():
                    by_device[dev] = by_device.get(dev, 0) + n
                entries.append(
                    {
                        "table": e["table"],
                        "segments": list(e["segments"]),
                        "bytes": e["bytes"],
                        "columns": dict(e["columns"]),
                        "devices": dict(e.get("devices", {})),
                    }
                )
            return {
                "stagedBytes": sum(e["bytes"] for e in self._entries.values()),
                "highWatermarkBytes": self.high_watermark,
                "stagedTables": len(self._entries),
                "evictions": self.evictions,
                "evictedBytes": self.evicted_bytes,
                "byTable": by_table,
                "byRole": by_role,
                "byDevice": by_device,
                "entries": entries,
            }


LEDGER = StagingLedger()


class TransferStats:
    """Cumulative host<->device transfer accounting — the measured-
    bandwidth half of the utilization plane (the staging ledger above
    tracks what is RESIDENT; this tracks what MOVED).  H2D marks come
    from the staging paths (``get_staged`` cache misses / role
    augmentation) and the batched query-input upload
    (``to_device_inputs``); D2H marks come from the packed result fetch
    (``engine/packing.py``) and the executor's raw-output fallback.
    Per-process, like the staging cache it instruments."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.h2d_bytes = 0
        self.h2d_transfers = 0
        self.d2h_bytes = 0
        self.d2h_transfers = 0
        # process identity in every snapshot: servers sharing a process
        # (in-process clusters, the chaos harness) all report THIS one
        # counter, and fleet rollups dedupe on the token instead of
        # multiply-counting the same bytes per server
        self.process_token = f"{os.getpid():x}-{id(self):x}"

    def record_h2d(self, nbytes: int) -> None:
        if nbytes <= 0:
            return
        with self._lock:
            self.h2d_bytes += int(nbytes)
            self.h2d_transfers += 1

    def record_d2h(self, nbytes: int) -> None:
        if nbytes <= 0:
            return
        with self._lock:
            self.d2h_bytes += int(nbytes)
            self.d2h_transfers += 1

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "h2dBytes": self.h2d_bytes,
                "h2dTransfers": self.h2d_transfers,
                "d2hBytes": self.d2h_bytes,
                "d2hTransfers": self.d2h_transfers,
                "processToken": self.process_token,
            }


TRANSFERS = TransferStats()


def _table_of(segments: Sequence[ImmutableSegment]) -> str:
    meta = getattr(segments[0], "metadata", None) if segments else None
    return getattr(meta, "table_name", "") or ""


# ---------------------------------------------------------------------------
# Staging cache: segments are immutable, so staging is reusable per
# (segment set, column set) — the HBM-residency analog of the reference
# keeping segments mmap'd between queries.
# ---------------------------------------------------------------------------

_stage_cache: Dict[Tuple, StagedTable] = {}
# per-key locks serialize staging (cache miss) and role-array
# augmentation so two concurrent queries over the same segments don't
# both materialize + transfer multi-GB column sets (ADVICE r1:
# redundant work + transient 2x HBM, not a race); distinct tables
# stage concurrently and cache hits never wait on a cold stage
_locks_guard = threading.Lock()
_key_locks: Dict[Tuple, "threading.Lock"] = {}
# cache-membership guard: insert/evict/clear AND the paired ledger
# bookkeeping happen atomically under this lock (per-key locks don't
# order distinct keys, so a size-cap clear racing another key's insert
# could otherwise iterate a mutating dict or strand a ledger entry for
# a table the cache no longer holds)
_cache_guard = threading.Lock()


def _lock_for(key: Tuple) -> "threading.Lock":
    with _locks_guard:
        lock = _key_locks.get(key)
        if lock is None:
            if len(_key_locks) > 256:
                _key_locks.clear()
            lock = _key_locks.setdefault(key, threading.Lock())
        return lock


def placement_key(sharding) -> Optional[Tuple]:
    """Hashable identity of a staging placement: None for default
    single-device placement, else the sharding's device set + spec.
    Part of the staging-cache key, so the same segments staged onto two
    chip groups are two entries — one group's arrays can never alias
    another group's dispatch (the sharded extension of the PR 3
    staging-token invariant)."""
    if sharding is None:
        return None
    try:
        ids = tuple(sorted(getattr(d, "id", -1) for d in sharding.device_set))
    except Exception:
        ids = (repr(sharding),)
    return (type(sharding).__name__, ids, str(getattr(sharding, "spec", "")))


def get_staged(
    segments: Sequence[ImmutableSegment],
    column_names: Sequence[str],
    pad_segments_to: int = 0,
    raw_columns: Sequence[str] = (),
    gfwd_columns: Sequence[str] = (),
    hll_columns: Sequence[str] = (),
    ctx=None,
    skip_base_columns: Sequence[str] = (),
    sharding=None,
    bsi_columns: Sequence[str] = (),
    bsiv_columns: Sequence[str] = (),
    pin: bool = False,
) -> StagedTable:
    """Cached staging. The cache key covers only the base arrays; role
    arrays (raw/gfwd/hll streams) are attached to the cached
    StagedTable on demand, so queries differing only in roles share one
    HBM copy of the base columns.  A column staged stream-only
    (skip_base_columns) gets its base arrays backfilled if a later
    query needs them (e.g. a filter arrives on a former agg-only
    column).  ``sharding`` places the segment axis across a chip group
    (mesh execution) and is part of the cache identity.

    Residency (engine/residency.py): a miss first checks the warm/cold
    tiers — a demoted table promotes back via pure device_put of its
    packed snapshot, zero re-encode — and every insert is registered
    with the residency manager, which enforces the HBM byte/entry caps
    by demoting the coldest unpinned tables instead of the old
    clear-everything size cap.  ``pin=True`` refcounts the staged
    table's token so tier demotion can never race this query's launch;
    the caller MUST ``RESIDENCY.unpin(st.token)`` when done."""
    from pinot_tpu.engine.residency import RESIDENCY
    # identity component: (name, claimed crc, instance token).  The
    # token (segment/immutable.py) is what makes a re-loaded copy of the
    # same segment a guaranteed MISS — name+crc alone would alias a
    # clean re-fetch onto arrays staged from a quarantined corrupt load,
    # even mid-flight (no eviction race can resurrect the old entry:
    # new instances simply never produce the old key).
    key = (
        tuple(
            (s.segment_name, s.metadata.crc, s.staging_token) for s in segments
        ),
        tuple(sorted(column_names)),
        pad_segments_to,
        placement_key(sharding),
    )
    with _lock_for(key):
        st = _stage_cache.get(key)
        if st is None:
            # warm/cold promotion first: a demoted table's packed
            # snapshot restores with pure device_puts — one read, zero
            # re-encode (sharded placements are drop-only, never
            # snapshotted, so they always re-stage from source)
            snap = RESIDENCY.take_resident(key) if sharding is None else None
            promoted = snap is not None
            if promoted:
                from pinot_tpu.engine.residency import restore_staged

                st = restore_staged(snap)
                # backfill any role/base arrays this query needs that
                # the resident copy was demoted without
                _augment_staged(
                    st,
                    segments,
                    raw_columns,
                    gfwd_columns,
                    hll_columns,
                    ctx,
                    base_columns=[
                        c
                        for c in column_names
                        if c not in set(skip_base_columns)
                    ],
                    bsi_columns=bsi_columns,
                    bsiv_columns=bsiv_columns,
                )
            else:
                st = stage_segments(
                    segments,
                    sorted(column_names),
                    pad_segments_to=pad_segments_to,
                    raw_columns=raw_columns,
                    gfwd_columns=gfwd_columns,
                    hll_columns=hll_columns,
                    ctx=ctx,
                    skip_base_columns=skip_base_columns,
                    sharding=sharding,
                    bsi_columns=bsi_columns,
                    bsiv_columns=bsiv_columns,
                )
            table = _table_of(segments)
            with _cache_guard:
                _stage_cache[key] = st
                staged_bytes = LEDGER.update(st, table)
                RESIDENCY.note_hot(
                    key,
                    st,
                    table,
                    staged_bytes,
                    demotable=sharding is None,
                    promoted=promoted,
                )
            # a cold stage IS one H2D transfer burst of the measured
            # array bytes (the utilization plane's upload accounting);
            # a promotion's device_puts are the same physical transfer
            TRANSFERS.record_h2d(staged_bytes)
            if promoted:
                # async promotion ahead of dispatch: lift the table's
                # remaining cold entries to warm in the background
                RESIDENCY.prefetch_siblings(key, table)
            # cap enforcement AFTER insert (outside _cache_guard): the
            # coldest unpinned residents demote to warm/cold instead of
            # the old clear-everything size cap
            RESIDENCY.enforce(exclude_tokens=(st.token,))
        else:
            attached = _augment_staged(
                st,
                segments,
                raw_columns,
                gfwd_columns,
                hll_columns,
                ctx,
                base_columns=[
                    c for c in column_names if c not in set(skip_base_columns)
                ],
                bsi_columns=bsi_columns,
                bsiv_columns=bsiv_columns,
            )
            RESIDENCY.touch(key)
            if attached:
                # re-measure (augmentation attached arrays) ONLY while
                # still cache-resident: a concurrent demotion already
                # counted this table out, and updating after that would
                # strand a ledger entry nothing will ever drop.  A
                # plain hit (attached == 0 — the overwhelmingly common
                # case) walks no arrays at all on this path.
                with _cache_guard:
                    if _stage_cache.get(key) is st:
                        nb = LEDGER.update(st, _table_of(segments))
                        RESIDENCY.set_bytes(key, nb)
                # augmentation's newly-attached role arrays ARE the H2D
                # delta (zero on a plain cache hit — no phantom transfers)
                TRANSFERS.record_h2d(attached)
        if pin:
            # refcount BEFORE releasing the key lock: demotion checks
            # pins under the manager lock, and an unpinned window here
            # could demote the table between staging and launch
            RESIDENCY.pin(st.token)
    return st


def _augment_staged(
    st: StagedTable,
    segments: Sequence[ImmutableSegment],
    raw_columns: Sequence[str],
    gfwd_columns: Sequence[str],
    hll_columns: Sequence[str],
    ctx,
    base_columns: Sequence[str] = (),
    bsi_columns: Sequence[str] = (),
    bsiv_columns: Sequence[str] = (),
) -> int:
    """Attach missing role arrays to an already-staged table.  Returns
    the bytes newly uploaded (0 on a plain hit) so the caller can record
    the exact H2D delta without re-walking every staged array."""
    attached = 0
    fdt = config.np_float_dtype()
    S, n_pad = st.num_segments, st.n_pad
    # augmentation lands on the SAME placement the base staging used:
    # a default-device role array attached to a chip-group table would
    # force a reshard on every launch
    put = (
        (lambda x: jax.device_put(x, st.sharding))
        if st.sharding is not None
        else jnp.asarray
    )
    for name in base_columns:
        # backfill base arrays a stream-only staging skipped
        sc = st.columns.get(name)
        if sc is None or not sc.single_value or sc.fwd is not None:
            continue
        cols = [seg.column(name) for seg in segments]
        sc.fwd = put(
            _stack_fwd(cols, S, n_pad, config.index_dtype(sc.card_pad))
        )
        attached += int(sc.fwd.nbytes)
        if sc.is_numeric and sc.dict_vals is None:
            sc.dict_vals = put(
                _stack_dict_vals(cols, S, sc.card_pad, fdt)
            )
            attached += int(sc.dict_vals.nbytes)
    for name in raw_columns:
        sc = st.columns.get(name)
        if sc is None or sc.raw is not None or not sc.is_numeric or not sc.single_value:
            continue
        raw = np.zeros((S, n_pad), dtype=fdt)
        for i, seg in enumerate(segments):
            c = seg.column(name)
            vals = np.asarray(c.dictionary.values, dtype=fdt)
            raw[i, : c.fwd.size] = vals[c.fwd]
        sc.raw = put(raw)
        attached += int(sc.raw.nbytes)
    for name in gfwd_columns:
        sc = st.columns.get(name)
        if sc is None or sc.gfwd is not None or not sc.single_value or ctx is None:
            continue
        gdt = config.index_dtype(config.pad_card(ctx.column(name).global_cardinality))
        gf = np.zeros((S, n_pad), dtype=gdt)
        remaps = ctx.column(name).remaps
        for i, seg in enumerate(segments):
            c = seg.column(name)
            gf[i, : c.fwd.size] = remaps[i][c.fwd]
        sc.gfwd = put(gf)
        attached += int(sc.gfwd.nbytes)
    for name in raw_columns:
        sc = st.columns.get(name)
        if (
            sc is None
            or sc.mv_raw is not None
            or sc.single_value
            or not sc.is_numeric
            or sc.mv is None
        ):
            continue
        mvr = np.zeros((S, n_pad, sc.mv_pad), dtype=fdt)
        for i, seg in enumerate(segments):
            c = seg.column(name)
            vals = np.asarray(c.dictionary.values, dtype=fdt)
            _csr_scatter(vals[c.mv_values], c.mv_offsets, mvr[i])
        sc.mv_raw = put(mvr)
        attached += int(sc.mv_raw.nbytes)
    for name in hll_columns:
        sc = st.columns.get(name)
        if sc is None or sc.hll_bucket is not None or not sc.single_value:
            continue
        hb, hr = _hll_streams([seg.column(name) for seg in segments], S, n_pad)
        # rho FIRST: readers holding this cached table guard on
        # hll_bucket, so both must be visible once bucket is
        sc.hll_rho = put(hr)
        sc.hll_bucket = put(hb)
        attached += int(sc.hll_rho.nbytes) + int(sc.hll_bucket.nbytes)
    for name in bsi_columns:
        sc = st.columns.get(name)
        if sc is None or sc.bsi is not None or not sc.single_value:
            continue
        cols = [seg.column(name) for seg in segments]
        sc.bsi_width = bsi_filter_width(cols)
        sc.bsi = put(_bsi_planes(cols, S, n_pad, sc.bsi_width))
        attached += int(sc.bsi.nbytes)
    for name in bsiv_columns:
        sc = st.columns.get(name)
        if (
            sc is None
            or sc.bsiv is not None
            or not sc.single_value
            or not sc.is_numeric
        ):
            continue
        cols = [seg.column(name) for seg in segments]
        spec = bsiv_value_spec(cols)
        if spec is None:
            continue
        width, vmins = spec
        planes = put(_bsiv_planes(cols, S, n_pad, width, vmins))
        # width/vmin metadata FIRST: readers holding this cached table
        # guard on bsiv, so the scalars must be visible once it is
        sc.bsiv_width, sc.bsiv_min = width, vmins
        sc.bsiv = planes
        attached += int(sc.bsiv.nbytes)
    return attached


def _hll_streams(cols, S: int, n_pad: int):
    """Per-row HLL (register index, rank) uint8 streams, computed
    host-side per dictionary entry then fanned out through the forward
    index — the kernel scatter-maxes the streams instead of gathering
    per-dictId tables on device."""
    from pinot_tpu.engine.hll import dictionary_tables

    hb = np.zeros((S, n_pad), dtype=np.uint8)
    hr = np.zeros((S, n_pad), dtype=np.uint8)
    for i, c in enumerate(cols):
        bt, rt = dictionary_tables(c.dictionary)
        hb[i, : c.fwd.size] = bt[c.fwd]
        hr[i, : c.fwd.size] = rt[c.fwd]
    return hb, hr


def clear_staging_cache() -> None:
    """Drop all staged tables AND their residency entries (every tier):
    callers clear to force genuine re-staging — a retained warm copy
    would silently turn the next stage into a promotion."""
    from pinot_tpu.engine.residency import RESIDENCY

    with _cache_guard:
        for st in list(_stage_cache.values()):
            LEDGER.drop(st)
        _stage_cache.clear()
    RESIDENCY.reset()


def evict_staged_segment(segment_name: str) -> int:
    """Drop every cached staged table containing ``segment_name`` — the
    quarantine path's HBM hygiene.  Correctness does not depend on this
    (the per-instance staging token already guarantees a re-loaded
    segment misses the cache); eviction just releases the quarantined
    copy's device arrays instead of waiting for the size-cap clear.
    Returns the number of cache entries dropped."""
    from pinot_tpu.engine.residency import RESIDENCY

    with _cache_guard:
        victims = []
        for key in list(_stage_cache):
            if any(e[0] == segment_name for e in key[0]):
                victims.append(key)
        for key in victims:
            st = _stage_cache.pop(key, None)
            if st is not None:
                LEDGER.drop(st)
    # residency hygiene runs on the SAME contract: the quarantined
    # copy's warm/cold snapshots must not survive either (a re-loaded
    # segment mints new tokens, so they could never be promoted — but
    # they would pin host RAM/disk for nothing)
    RESIDENCY.drop_segment(segment_name)
    return len(victims)


def to_device_inputs(tree, sharding=None):
    """Convert a numpy pytree (query inputs) to device arrays — the one
    converter production and benchmarks share.  All ndarray leaves ride
    ONE batched ``jax.device_put``: per-leaf puts each pay a host->
    device dispatch (a full round trip on a tunneled chip); the batched
    form coalesces the transfer.  ``sharding`` places every leaf across
    a chip group (mesh execution — query inputs lead with the segment
    axis, like the staged columns they join)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    idx = [i for i, leaf in enumerate(leaves) if isinstance(leaf, np.ndarray)]
    if idx:
        TRANSFERS.record_h2d(sum(leaves[i].nbytes for i in idx))
        batch = [leaves[i] for i in idx]
        if sharding is not None:
            put = jax.device_put(batch, [sharding] * len(batch))
        else:
            put = jax.device_put(batch)
        for i, v in zip(idx, put):
            leaves[i] = v
    return jax.tree_util.tree_unflatten(treedef, leaves)


def segment_arrays(staged: StagedTable, needed) -> Dict[str, jnp.ndarray]:
    """Assemble the kernel's ``seg`` pytree for the given columns.

    Row validity ships as the per-segment ``num_docs`` scalar (the
    kernel compares against an iota); the materialized ``valid`` mask is
    only sent when no row-shaped column array exists to take the row
    count from (e.g. ``SELECT COUNT(*)`` with no filter).
    """
    arrays: Dict[str, jnp.ndarray] = {}
    has_rows = False
    for name in needed:
        col = staged.columns.get(name)
        if col is None:
            continue
        if col.fwd is not None:
            arrays[f"{name}.fwd"] = col.fwd
            has_rows = True
        if col.mv is not None:
            arrays[f"{name}.mv"] = col.mv
            arrays[f"{name}.mvc"] = col.mv_counts
            has_rows = True
        if col.dict_vals is not None:
            arrays[f"{name}.dict"] = col.dict_vals
        if col.raw is not None:
            arrays[f"{name}.raw"] = col.raw
            has_rows = True
        if col.gfwd is not None:
            arrays[f"{name}.gfwd"] = col.gfwd
            has_rows = True
        if col.hll_bucket is not None:
            arrays[f"{name}.hllb"] = col.hll_bucket
            arrays[f"{name}.hllr"] = col.hll_rho
            has_rows = True
        if col.mv_raw is not None:
            arrays[f"{name}.mvraw"] = col.mv_raw
            has_rows = True
    if has_rows:
        arrays["num_docs"] = staged.num_docs_arr
    else:
        arrays["valid"] = staged.valid
    return arrays
