"""Host fallback path for queries whose dense device state would not fit
(group-by key spaces beyond ``MAX_GROUP_CAPACITY``, huge value-state
aggregations, composite sort keys beyond the key dtype).

The reference's analog is the hash-map group-by storage types
(``DefaultGroupKeyGenerator.java:60-63`` LONG_MAP_BASED/ARRAY_MAP_BASED)
that kick in when the dense ARRAY_BASED key space overflows.  Here the
filter still evaluates vectorized (numpy match-table gathers over the
forward index); only the aggregation of *matched* rows falls back to the
row-wise accumulators shared with the scan oracle.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from pinot_tpu.common.request import BrokerRequest, FilterOperator, FilterQueryTree
from pinot_tpu.common.values import render_value
from pinot_tpu.engine import config
from pinot_tpu.engine.context import TableContext
from pinot_tpu.engine.plan import match_table
from pinot_tpu.engine.results import IntermediateResult, make_partial
from pinot_tpu.segment.immutable import ImmutableSegment
from pinot_tpu.tools.scan_engine import _Accumulator


def _segment_mask(seg: ImmutableSegment, tree: Optional[FilterQueryTree]) -> np.ndarray:
    n = seg.num_docs
    if tree is None:
        return np.ones(n, dtype=bool)
    if tree.is_leaf:
        col = seg.column(tree.column)
        d = col.dictionary
        table = match_table(tree, d, d.cardinality if d.cardinality else 1)
        negative = tree.operator in (FilterOperator.NOT, FilterOperator.NOT_IN)
        if col.is_single_value:
            if negative:
                table = ~table
            return table[col.fwd]
        hits = table[col.mv_values]
        any_hit = np.zeros(n, dtype=bool)
        np.logical_or.at(any_hit, np.repeat(np.arange(n), np.diff(col.mv_offsets)), hits)
        return ~any_hit if negative else any_hit
    masks = [_segment_mask(seg, c) for c in tree.children]
    out = masks[0]
    for m in masks[1:]:
        out = (out & m) if tree.operator == FilterOperator.AND else (out | m)
    return out


def execute_host(
    segments: List[ImmutableSegment],
    ctx: TableContext,
    request: BrokerRequest,
    total_docs: int,
    sel_columns: Optional[List[str]],
) -> IntermediateResult:
    res = IntermediateResult(
        total_docs=total_docs,
        num_segments_queried=len(segments),
    )
    if request.is_group_by:
        res.groups = {}
    elif request.is_aggregation:
        res.aggregations = [make_partial(a.base_function) for a in request.aggregations]
    else:
        res.selection_rows = []
        res.selection_columns = sel_columns

    for seg in segments:
        mask = _segment_mask(seg, request.filter)
        matched = np.nonzero(mask)[0]
        res.num_docs_scanned += int(matched.size)

        if request.is_group_by:
            gb = request.group_by
            for doc in matched:
                row = seg.row(int(doc))
                for key in _group_keys(seg, row, gb.columns):
                    accs = res.groups.get(key)
                    if accs is None:
                        accs = [_Accumulator(a) for a in request.aggregations]
                        res.groups[key] = accs
                    for acc in accs:
                        acc.add(row)
        elif request.is_aggregation:
            for doc in matched:
                row = seg.row(int(doc))
                for acc, _a in zip(res.aggregations, request.aggregations):
                    acc.add(row)
        else:
            sel = request.selection
            k = sel.offset + sel.size
            take = matched[: k] if not sel.sorts else matched
            for doc in take:
                row = seg.row(int(doc))
                sort_vals = []
                for s in sel.sorts:
                    v = row[s.column]
                    if isinstance(v, list):
                        v = v[0] if v else None
                    sort_vals.append(v)
                res.selection_rows.append((sort_vals, [row[c] for c in sel_columns]))
            if sel.sorts and len(res.selection_rows) > 4 * k:
                pass  # bounded enough for fallback; final trim at reduce

    # adapt oracle accumulators -> mergeable partials
    if request.is_group_by:
        res.groups = {
            key: [_to_partial(acc) for acc in accs] for key, accs in res.groups.items()
        }
    elif request.is_aggregation:
        res.aggregations = [_to_partial(acc) for acc in res.aggregations]
    return res


def _group_keys(seg: ImmutableSegment, row, columns) -> List[Tuple[str, ...]]:
    keys: List[Tuple[str, ...]] = [()]
    for col in columns:
        st = seg.column(col).dictionary.stored_type
        v = row[col]
        vals = v if isinstance(v, list) else [v]
        keys = [k + (render_value(st, x),) for k in keys for x in vals]
    return keys


def _to_partial(acc):
    """Convert a scan-oracle accumulator (or an already-built partial)
    into a mergeable AggPartial."""
    from pinot_tpu.engine.results import (
        AggPartial,
        AvgPartial,
        CountPartial,
        DistinctPartial,
        HistogramPartial,
        HllPartial,
        MaxPartial,
        MinMaxRangePartial,
        MinPartial,
        SumPartial,
    )
    from pinot_tpu.engine import hll as hll_mod

    if isinstance(acc, AggPartial):
        return acc
    base = acc.base
    if base == "count":
        return CountPartial(acc.count)
    if base == "sum":
        return SumPartial(acc.sum)
    if base == "min":
        return MinPartial(acc.min)
    if base == "max":
        return MaxPartial(acc.max)
    if base == "avg":
        return AvgPartial(acc.sum, acc.count)
    if base == "minmaxrange":
        return MinMaxRangePartial(acc.min, acc.max)
    if base == "distinctcount":
        return DistinctPartial(set(acc.distinct))
    if base in ("distinctcounthll", "fasthll"):
        return HllPartial(hll_mod.registers_from_values(acc.distinct))
    if base.startswith("percentile"):
        p = int(base[len("percentileest"):]) if base.startswith("percentileest") else int(base[len("percentile"):])
        counts: Dict[float, int] = {}
        for v in acc.values:
            counts[v] = counts.get(v, 0) + 1
        return HistogramPartial(counts, percentile=p)
    raise ValueError(base)
