"""Host fallback path for queries whose dense device state would not fit
(group-by key spaces beyond ``MAX_GROUP_CAPACITY``, huge value-state
aggregations, composite sort keys beyond the key dtype).

The reference's analog is the hash-map group-by storage types
(``DefaultGroupKeyGenerator.java:60-63`` LONG_MAP_BASED/ARRAY_MAP_BASED)
that kick in when the dense ARRAY_BASED key space overflows — and in the
reference that map path is its *fast* path for big key spaces.  Here the
filter always evaluates vectorized (numpy match-table gathers over the
forward index), and group-by aggregation over huge key spaces runs a
vectorized numpy hash pipeline: mixed-radix global-id keys per matched
row -> ``np.unique`` factorization -> ``bincount``/``reduceat``
segmented reductions -> trim to topN*5 candidates before any Python
objects are built.  Only queries outside that shape (MV group columns,
value-state aggregations, radix overflow) drop to the row-wise
accumulators shared with the scan oracle.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from pinot_tpu.common.request import (
    BrokerRequest,
    FilterOperator,
    FilterQueryTree,
    group_sort_ascending,
)
from pinot_tpu.common.values import render_value
from pinot_tpu.engine import config
from pinot_tpu.engine.context import TableContext
from pinot_tpu.engine.plan import match_table
from pinot_tpu.engine.results import (
    AggPartial,
    AvgPartial,
    CountPartial,
    DistinctPartial,
    HllPartial,
    IntermediateResult,
    MaxPartial,
    MinMaxRangePartial,
    MinPartial,
    SumPartial,
    make_partial,
    trim_group_candidates,
)
from pinot_tpu.segment.immutable import ImmutableSegment
from pinot_tpu.tools.scan_engine import _Accumulator


def _segment_mask(seg: ImmutableSegment, tree: Optional[FilterQueryTree]) -> np.ndarray:
    n = seg.num_docs
    if tree is None:
        return np.ones(n, dtype=bool)
    if tree.is_leaf:
        col = seg.column(tree.column)
        d = col.dictionary
        table = match_table(tree, d, d.cardinality if d.cardinality else 1)
        negative = tree.operator in (FilterOperator.NOT, FilterOperator.NOT_IN)
        if col.is_single_value:
            if negative:
                table = ~table
            return table[col.fwd]
        hits = table[col.mv_values]
        any_hit = np.zeros(n, dtype=bool)
        np.logical_or.at(any_hit, np.repeat(np.arange(n), np.diff(col.mv_offsets)), hits)
        return ~any_hit if negative else any_hit
    masks = [_segment_mask(seg, c) for c in tree.children]
    out = masks[0]
    for m in masks[1:]:
        out = (out & m) if tree.operator == FilterOperator.AND else (out | m)
    return out


_VECTOR_AGGS = {"count", "sum", "min", "max", "avg", "minmaxrange"}
# distinct aggs vectorize in the GROUP-BY path via (group, gid) pair
# dedup (np.unique); they only touch global dict ids, so strings are
# fine.  Without this, a beyond-capacity group-by with distinctcount
# fell to the per-row Python loop — ~30 min at 134M rows vs ~80 s
# vectorized (NORTHSTAR_HLL.json aux paths).
_DISTINCT_AGGS = {"distinctcount", "distinctcounthll", "fasthll"}


def _vectorizable_groupby(request: BrokerRequest, segments, ctx: TableContext) -> bool:
    """True when the fast numpy hash path applies: SV group columns,
    scalar/pair aggregations over SV numeric columns, and a mixed-radix
    key that fits int64."""
    seg = segments[0]
    for c in request.group_by.columns:
        if c not in seg.columns or not seg.column(c).is_single_value:
            return False
    space = 1
    for c in request.group_by.columns:
        space *= max(ctx.column(c).global_cardinality, 1)
        if space >= (1 << 62):
            return False
    return _vectorizable_aggs(request, segments, allow_distinct=True)


def _default_matched_rows(request: BrokerRequest):
    """Row-id resolver: full vectorized mask + nonzero (O(n) host scan).
    The inverted-index path (engine/invindex_path.py) substitutes an
    O(matches) postings resolver through the same seam."""

    def resolve(si: int, seg: ImmutableSegment) -> np.ndarray:
        return np.nonzero(_segment_mask(seg, request.filter))[0]

    return resolve


def _vectorizable_aggs(
    request: BrokerRequest, segments, allow_distinct: bool = False
) -> bool:
    """True when every aggregation fits the numpy fast paths:
    scalar/pair functions over SV numeric columns (shared check of the
    group-by and aggregation-only vectorized paths); with
    ``allow_distinct``, SV distinct/HLL aggs of any stored type too."""
    seg = segments[0]
    for a in request.aggregations:
        base = a.base_function
        is_distinct = base in _DISTINCT_AGGS
        if base not in _VECTOR_AGGS and not (allow_distinct and is_distinct):
            return False
        if a.column == "*":
            if is_distinct:
                return False  # distinctcount(*) has no gid column: per-row path
            continue
        if a.column not in seg.columns:
            return False
        col = seg.column(a.column)
        if not col.is_single_value:
            return False
        if not is_distinct and col.dictionary.stored_type.name == "STRING":
            return False
    return True


def _aggregation_vectorized(
    segments: List[ImmutableSegment],
    request: BrokerRequest,
    res: IntermediateResult,
    matched_rows,
) -> None:
    """Scalar/pair aggregations over matched rows via numpy
    fancy-indexing — O(matches) when the resolver is postings-backed
    (engine/invindex_path.py), O(n) under the default mask resolver."""
    needed = {
        a.column
        for a in request.aggregations
        if a.base_function != "count" and a.column != "*"
    }
    col_sum = {c: 0.0 for c in needed}
    col_min = {c: float("inf") for c in needed}
    col_max = {c: float("-inf") for c in needed}
    total = 0
    for si, seg in enumerate(segments):
        matched = matched_rows(si, seg)
        res.num_docs_scanned += int(matched.size)
        total += int(matched.size)
        if matched.size == 0:
            continue
        for c in needed:
            col = seg.column(c)
            vals = np.asarray(col.dictionary.values, dtype=np.float64)[
                np.asarray(col.fwd)[matched]
            ]
            col_sum[c] += float(vals.sum())
            col_min[c] = min(col_min[c], float(vals.min()))
            col_max[c] = max(col_max[c], float(vals.max()))
    if total == 0:
        res.aggregations = [make_partial(a.base_function) for a in request.aggregations]
        return
    out: List[AggPartial] = []
    for a in request.aggregations:
        b = a.base_function
        if b == "count":
            out.append(CountPartial(float(total)))
        elif b == "sum":
            out.append(SumPartial(col_sum[a.column]))
        elif b == "avg":
            out.append(AvgPartial(col_sum[a.column], float(total)))
        elif b == "min":
            out.append(MinPartial(col_min[a.column]))
        elif b == "max":
            out.append(MaxPartial(col_max[a.column]))
        else:
            out.append(MinMaxRangePartial(col_min[a.column], col_max[a.column]))
    res.aggregations = out


def _groupby_vectorized(
    segments: List[ImmutableSegment],
    ctx: TableContext,
    request: BrokerRequest,
    res: IntermediateResult,
    matched_rows=None,
) -> None:
    """Vectorized LONG_MAP_BASED analog: one int64 key per matched row,
    factorized with np.unique; sums/counts via bincount, min/max via
    sorted reduceat; groups trimmed to topN*5 before materializing
    Python keys (MCombineGroupByOperator.java:216 trim semantics)."""
    gb = request.group_by
    gcards = [max(ctx.column(c).global_cardinality, 1) for c in gb.columns]
    # columns whose decoded values the states actually need (count reads
    # none); gathered once per (segment, column) even when several
    # aggregations share a column
    val_columns = {
        a.column
        for a in request.aggregations
        if a.base_function != "count"
        and a.column != "*"
        and a.base_function not in _DISTINCT_AGGS
    }
    gid_columns = {
        a.column
        for a in request.aggregations
        if a.base_function in _DISTINCT_AGGS
    }

    if matched_rows is None:
        matched_rows = _default_matched_rows(request)
    all_keys: List[np.ndarray] = []
    col_vals: Dict[str, List[np.ndarray]] = {c: [] for c in val_columns}
    col_gids: Dict[str, List[np.ndarray]] = {c: [] for c in gid_columns}
    for si, seg in enumerate(segments):
        matched = matched_rows(si, seg)
        res.num_docs_scanned += int(matched.size)
        if matched.size == 0:
            continue
        keys = np.zeros(matched.size, dtype=np.int64)
        for c, gcard in zip(gb.columns, gcards):
            col = seg.column(c)
            remap = ctx.column(c).remaps[si]
            keys = keys * gcard + remap[col.fwd[matched]].astype(np.int64)
        all_keys.append(keys)
        for c in val_columns:
            col = seg.column(c)
            col_vals[c].append(
                np.asarray(col.dictionary.values, dtype=np.float64)[col.fwd[matched]]
            )
        for c in gid_columns:
            col = seg.column(c)
            col_gids[c].append(ctx.column(c).remaps[si][col.fwd[matched]])

    if not all_keys:
        return
    keys = np.concatenate(all_keys)
    space = 1
    for g in gcards:
        space *= g
    if space <= (1 << 24) and space <= max(keys.size, 1) * 8:
        # small DENSE key space (sort-pairs overflow fallbacks group by
        # a low-card column): factorize with presence + rank gather
        # instead of np.unique's 134M-row argsort + cumsum (~30s saved
        # at north-star scale).  The dense-side peak is 5 bytes/slot
        # (bool presence + int32 cumsum ranks) — the r5 version's two
        # space-sized int64 arrays cost 16 bytes/slot, a peak-RSS
        # regression that bit even when only a handful of keys were
        # live; a space much larger than the matched-row count (sparse)
        # takes the sort path instead, whose footprint scales with rows.
        present = np.zeros(space, dtype=bool)
        present[keys] = True
        uniq = np.flatnonzero(present).astype(np.int64)
        rank = np.cumsum(present, dtype=np.int32)  # rank+1 at each live key
        inv = (rank[keys] - 1).astype(np.int64)
        del present, rank
        k = uniq.size
        counts = np.bincount(inv, minlength=k).astype(np.float64)
    else:
        uniq, inv = np.unique(keys, return_inverse=True)
        k = uniq.size
        counts = np.bincount(inv, minlength=k).astype(np.float64)

    # per-agg finalized state arrays, each [k]
    order = None  # lazily computed stable sort of inv, for reduceat
    boundaries = None

    def seg_minmax(vals: np.ndarray):
        nonlocal order, boundaries
        if order is None:
            order = np.argsort(inv, kind="stable")
            boundaries = np.searchsorted(inv[order], np.arange(k))
        sorted_vals = vals[order]
        return (
            np.minimum.reduceat(sorted_vals, boundaries),
            np.maximum.reduceat(sorted_vals, boundaries),
        )

    cat_vals = {c: np.concatenate(v) for c, v in col_vals.items()}
    minmax_cache: Dict[str, tuple] = {}

    # distinct/HLL: one (group, gid) pair dedup per column — sorted, so
    # each group's distinct gids are one contiguous slice
    distinct_cache: Dict[str, tuple] = {}

    def distinct_pairs(c: str):
        if c not in distinct_cache:
            gc = max(ctx.column(c).global_cardinality, 1)
            gid = np.concatenate(col_gids[c])
            if k * gc < (1 << 31):
                # int32 packed pairs sort ~2x faster than int64
                pair = np.unique(
                    inv.astype(np.int32) * np.int32(gc) + gid.astype(np.int32)
                ).astype(np.int64)
            else:
                pair = np.unique(inv.astype(np.int64) * gc + gid.astype(np.int64))
            pg = (pair // gc).astype(np.int64)  # sorted: per-group slices
            pgid = pair % gc
            dcounts = np.bincount(pg, minlength=k).astype(np.float64)
            bounds = np.searchsorted(pg, np.arange(k + 1))
            distinct_cache[c] = (pgid, bounds, dcounts)
        return distinct_cache[c]

    states: List[tuple] = []  # (kind, arrays...)
    order_vals: List[np.ndarray] = []
    for a in request.aggregations:
        base = a.base_function
        if base == "count":
            states.append(("count", counts))
            order_vals.append(counts)
            continue
        if base in _DISTINCT_AGGS:
            pgid, bounds, dcounts = distinct_pairs(a.column)
            if base == "distinctcount":
                states.append(("distinct", a.column, pgid, bounds))
                order_vals.append(dcounts)
            else:
                # distinctcounthll: ORDER/TRIM by the exact per-group
                # distinct count (monotone proxy for the estimate —
                # dense registers for all k >= 2^20 groups would cost
                # k*256 bytes + a per-group Python estimator before the
                # trim); registers are built per KEPT group in partial()
                states.append(("hll", a.column, pgid, bounds))
                order_vals.append(dcounts)
            continue
        vals = cat_vals[a.column]
        if base == "sum":
            s = np.bincount(inv, weights=vals, minlength=k)
            states.append(("sum", s))
            order_vals.append(s)
        elif base == "avg":
            s = np.bincount(inv, weights=vals, minlength=k)
            states.append(("avg", s, counts))
            order_vals.append(s / np.maximum(counts, 1))
        elif base in ("min", "max", "minmaxrange"):
            if a.column not in minmax_cache:
                minmax_cache[a.column] = seg_minmax(vals)
            mn, mx = minmax_cache[a.column]
            if base == "min":
                states.append(("min", mn))
                order_vals.append(mn)
            elif base == "max":
                states.append(("max", mx))
                order_vals.append(mx)
            else:
                states.append(("minmaxrange", mn, mx))
                order_vals.append(mx - mn)

    # trim to topN*5 + boundary ties per agg (union), as the device path
    keep = trim_group_candidates(
        order_vals,
        [group_sort_ascending(a.function) for a in request.aggregations],
        gb.top_n,
        k,
    )

    # decompose kept keys -> per-column global ids -> rendered tuples
    gids = []
    rem = uniq[keep].copy()
    for gcard in reversed(gcards):
        gids.append(rem % gcard)
        rem = rem // gcard
    gids.reverse()
    gdicts = [ctx.column(c).global_dict for c in gb.columns]

    def partial(state, i: int):
        kind = state[0]
        if kind == "count":
            return CountPartial(float(state[1][i]))
        if kind == "sum":
            return SumPartial(float(state[1][i]))
        if kind == "min":
            return MinPartial(float(state[1][i]))
        if kind == "max":
            return MaxPartial(float(state[1][i]))
        if kind == "avg":
            return AvgPartial(float(state[1][i]), float(state[2][i]))
        if kind == "distinct":
            _, c, pgid, bounds = state
            gdict = ctx.column(c).global_dict
            ids = pgid[bounds[i] : bounds[i + 1]]
            # pair-dedup'd gids are already unique; one vectorized gather
            # replaces the per-value Python set build (north-star groups
            # carry millions of distinct values each)
            return DistinctPartial(gdict.value_array()[ids])
        if kind == "hll":
            from pinot_tpu.engine import hll as hll_mod

            _, c, pgid, bounds = state
            bt, rt = hll_mod.dictionary_tables(ctx.column(c).global_dict)
            ids = pgid[bounds[i] : bounds[i + 1]]
            regs = np.zeros(hll_mod.M, dtype=np.uint8)
            np.maximum.at(regs, bt[ids], rt[ids])
            return HllPartial(regs)
        return MinMaxRangePartial(float(state[1][i]), float(state[2][i]))

    for row, i in enumerate(keep):
        ktup = tuple(
            render_value(gdicts[j].stored_type, gdicts[j].get(int(gids[j][row])))
            for j in range(len(gb.columns))
        )
        res.groups[ktup] = [partial(st, int(i)) for st in states]


def _referenced_column_bytes(
    segments: List[ImmutableSegment], request: BrokerRequest
) -> int:
    """Column-data bytes the host path reads, upper bound: the full
    forward index (SV) / MV value stream of every referenced column —
    the default mask resolver scans every row for the filter, and value
    columns gather through the same arrays.  Postings-backed callers
    (engine/invindex_path.py) overwrite this with their O(matches)
    figure."""
    total = 0
    cols = request.referenced_columns()
    for seg in segments:
        for name in cols:
            col = seg.columns.get(name)
            if col is None:
                continue
            fwd = getattr(col, "fwd", None)
            if fwd is not None:
                total += np.asarray(fwd).nbytes
            mv = getattr(col, "mv_values", None)
            if mv is not None:
                total += np.asarray(mv).nbytes
    return total


def execute_host(
    segments: List[ImmutableSegment],
    ctx: TableContext,
    request: BrokerRequest,
    total_docs: int,
    sel_columns: Optional[List[str]],
    matched_rows=None,
) -> IntermediateResult:
    """Cost-accounted wrapper: every host-served query reports hostMs,
    bytesScanned, and the host serving tier on its result's cost vector
    (engine/results.py COST_KEYS)."""
    import time as _time

    t0 = _time.perf_counter()
    res = _execute_host_impl(
        segments, ctx, request, total_docs, sel_columns, matched_rows
    )
    res.add_cost(
        hostMs=round((_time.perf_counter() - t0) * 1000, 3),
        bytesScanned=_referenced_column_bytes(segments, request),
        segmentsHost=len(segments),
    )
    return res


def _execute_host_impl(
    segments: List[ImmutableSegment],
    ctx: TableContext,
    request: BrokerRequest,
    total_docs: int,
    sel_columns: Optional[List[str]],
    matched_rows=None,
) -> IntermediateResult:
    res = IntermediateResult(
        total_docs=total_docs,
        num_segments_queried=len(segments),
    )
    if matched_rows is None:
        matched_rows = _default_matched_rows(request)
    if request.is_group_by:
        res.groups = {}
        if _vectorizable_groupby(request, segments, ctx):
            _groupby_vectorized(segments, ctx, request, res, matched_rows)
            return res
    elif request.is_aggregation:
        if _vectorizable_aggs(request, segments):
            _aggregation_vectorized(segments, request, res, matched_rows)
            return res
        # row-wise accumulators (NOT mergeable partials — those have no
        # .add); _to_partial adapts them below, same as the group-by path
        res.aggregations = [_Accumulator(a) for a in request.aggregations]
    else:
        res.selection_rows = []
        res.selection_columns = sel_columns

    for si, seg in enumerate(segments):
        matched = matched_rows(si, seg)
        res.num_docs_scanned += int(matched.size)

        if request.is_group_by:
            gb = request.group_by
            for doc in matched:
                row = seg.row(int(doc))
                for key in _group_keys(seg, row, gb.columns):
                    accs = res.groups.get(key)
                    if accs is None:
                        accs = [_Accumulator(a) for a in request.aggregations]
                        res.groups[key] = accs
                    for acc in accs:
                        acc.add(row)
        elif request.is_aggregation:
            for doc in matched:
                row = seg.row(int(doc))
                for acc, _a in zip(res.aggregations, request.aggregations):
                    acc.add(row)
        else:
            sel = request.selection
            k = sel.offset + sel.size
            take = matched[: k] if not sel.sorts else matched
            for doc in take:
                row = seg.row(int(doc))
                sort_vals = []
                for s in sel.sorts:
                    v = row[s.column]
                    if isinstance(v, list):
                        v = v[0] if v else None
                    sort_vals.append(v)
                res.selection_rows.append((sort_vals, [row[c] for c in sel_columns]))
            if sel.sorts and len(res.selection_rows) > 4 * k:
                pass  # bounded enough for fallback; final trim at reduce

    # adapt oracle accumulators -> mergeable partials
    if request.is_group_by:
        res.groups = {
            key: [_to_partial(acc) for acc in accs] for key, accs in res.groups.items()
        }
    elif request.is_aggregation:
        res.aggregations = [_to_partial(acc) for acc in res.aggregations]
    return res


def _group_keys(seg: ImmutableSegment, row, columns) -> List[Tuple[str, ...]]:
    keys: List[Tuple[str, ...]] = [()]
    for col in columns:
        st = seg.column(col).dictionary.stored_type
        v = row[col]
        vals = v if isinstance(v, list) else [v]
        keys = [k + (render_value(st, x),) for k in keys for x in vals]
    return keys


def _to_partial(acc):
    """Convert a scan-oracle accumulator (or an already-built partial)
    into a mergeable AggPartial."""
    from pinot_tpu.engine.results import (
        AggPartial,
        AvgPartial,
        CountPartial,
        DistinctPartial,
        HistogramPartial,
        HllPartial,
        MaxPartial,
        MinMaxRangePartial,
        MinPartial,
        SumPartial,
    )
    from pinot_tpu.engine import hll as hll_mod

    if isinstance(acc, AggPartial):
        return acc
    base = acc.base
    if base == "count":
        return CountPartial(acc.count)
    if base == "sum":
        return SumPartial(acc.sum)
    if base == "min":
        return MinPartial(acc.min)
    if base == "max":
        return MaxPartial(acc.max)
    if base == "avg":
        return AvgPartial(acc.sum, acc.count)
    if base == "minmaxrange":
        return MinMaxRangePartial(acc.min, acc.max)
    if base == "distinctcount":
        return DistinctPartial(set(acc.distinct))
    if base in ("distinctcounthll", "fasthll"):
        return HllPartial(hll_mod.registers_from_values(acc.distinct))
    if base.startswith("percentile"):
        p = int(base[len("percentileest"):]) if base.startswith("percentileest") else int(base[len("percentile"):])
        counts: Dict[float, int] = {}
        for v in acc.values:
            counts[v] = counts.get(v, 0) + 1
        return HistogramPartial(counts, percentile=p)
    raise ValueError(base)
