"""HyperLogLog sketch shared by the TPU engine and the scan oracle.

The reference uses clearspring's HyperLogLog with ``log2m = 8``
(pinot-core ``startree/hll/HllConstants.java`` DEFAULT_LOG2M) for
``distinctcounthll`` / ``fasthll``.  Here the sketch is a plain
``uint8[m]`` register array — a representation that maps directly onto
TPU ops: per-row (bucket, rho) pairs are precomputed per dictionary
entry host-side, the device does a scatter-max into registers, and
cross-segment / cross-chip merge is an elementwise ``maximum`` (instead
of the reference's Java-serialized sketch objects,
``DataTableCustomSerDe.java:49``).

Hashing is a deterministic 64-bit hash (xxhash-style mixing over
blake2b) — NOT Python's salted ``hash()`` — so oracle and engine agree
bit-for-bit.
"""
from __future__ import annotations

import hashlib
import math
import struct
from typing import Any, Iterable

import numpy as np

DEFAULT_LOG2M = 8  # HllConstants.java DEFAULT_LOG2M
M = 1 << DEFAULT_LOG2M


def value_hash64(value: Any) -> int:
    """Deterministic 64-bit hash of an ingest value."""
    if isinstance(value, float) and value.is_integer():
        # Hash 5.0 and 5 identically so INT/LONG/FLOAT columns agree.
        value = int(value)
    data = repr(value).encode("utf-8")
    return struct.unpack("<Q", hashlib.blake2b(data, digest_size=8).digest())[0]


def bucket_and_rho(h: int, log2m: int = DEFAULT_LOG2M) -> tuple:
    """Split a 64-bit hash into (register index, rank of first set bit)."""
    m = 1 << log2m
    bucket = h & (m - 1)
    rest = h >> log2m
    # rho = position of least-significant 1 bit in the remaining bits + 1
    width = 64 - log2m
    if rest == 0:
        rho = width + 1
    else:
        rho = (rest & -rest).bit_length()
    return bucket, rho


def registers_from_values(values: Iterable[Any], log2m: int = DEFAULT_LOG2M) -> np.ndarray:
    m = 1 << log2m
    regs = np.zeros(m, dtype=np.uint8)
    for v in values:
        b, r = bucket_and_rho(value_hash64(v), log2m)
        if r > regs[b]:
            regs[b] = r
    return regs


def _alpha(m: int) -> float:
    if m == 16:
        return 0.673
    if m == 32:
        return 0.697
    if m == 64:
        return 0.709
    return 0.7213 / (1.0 + 1.079 / m)


def estimate_from_registers(regs: np.ndarray) -> int:
    """Standard HLL estimator with small/large-range corrections
    (the clearspring ``HyperLogLog.cardinality()`` algorithm)."""
    regs = np.asarray(regs)
    m = regs.shape[-1]
    rsum = np.sum(np.power(2.0, -regs.astype(np.float64)), axis=-1)
    estimate = _alpha(m) * m * m / rsum
    zeros = np.sum(regs == 0, axis=-1)
    if np.ndim(estimate) == 0:
        return int(_correct(float(estimate), int(zeros), m))
    out = np.empty(estimate.shape, dtype=np.int64)
    flat_e, flat_z = estimate.ravel(), np.asarray(zeros).ravel()
    for i in range(flat_e.size):
        out.ravel()[i] = _correct(float(flat_e[i]), int(flat_z[i]), m)
    return out


def _correct(estimate: float, zeros: int, m: int) -> int:
    if estimate <= 2.5 * m and zeros > 0:
        # linear counting
        return int(round(m * math.log(m / float(zeros))))
    two64 = 2.0**64
    if estimate > two64 / 30.0:
        return int(round(-two64 * math.log(1.0 - estimate / two64)))
    return int(round(estimate))


def merge_registers(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    return np.maximum(a, b)


def hll_estimate_exact_values(values: Iterable[Any], log2m: int = DEFAULT_LOG2M) -> int:
    """Estimate cardinality of a concrete value set through the sketch
    (used by the oracle so engine and oracle agree exactly)."""
    return int(estimate_from_registers(registers_from_values(values, log2m)))


def dictionary_tables(dictionary):
    """Per-dictId (register index, rank) uint8 tables for a column
    dictionary — the ONE place the per-entry HLL hashing loop lives
    (shared by the staging stream builder and the planner's table
    fallback, which must agree bit-for-bit).  Cached on the dictionary:
    the hashing loop is Python-speed, and high-cardinality dictionaries
    (millions of entries at north-star scale) are re-staged per role
    augmentation."""
    cached = getattr(dictionary, "_hll_tables", None)
    if cached is not None:
        return cached
    card = max(dictionary.cardinality, 1)
    bt = np.zeros(card, dtype=np.uint8)
    rt = np.zeros(card, dtype=np.uint8)
    for j in range(dictionary.cardinality):
        b, r = bucket_and_rho(value_hash64(dictionary.get(j)))
        bt[j] = b
        rt[j] = r
    dictionary._hll_tables = (bt, rt)
    return bt, rt
