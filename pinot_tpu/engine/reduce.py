"""Reduce: merged IntermediateResults -> BrokerResponse.

The ``BrokerReduceService.reduceOnDataTable`` analog
(``core/query/reduce/BrokerReduceService.java:62``): merge per-server
partials, finalize aggregation values, sort + trim group-by results
(ascending iff the function name starts with "min",
``AggregationGroupByOperatorService.java:146``), window + render
selection rows, and sum execution stats.
"""
from __future__ import annotations

import math
from typing import Any, List, Optional, Sequence, Tuple

from pinot_tpu.common.request import BrokerRequest, group_sort_ascending
from pinot_tpu.common.response import (
    AggregationResult,
    BrokerResponse,
    GroupByResult,
    QueryException,
    SelectionResults,
)
from pinot_tpu.engine.results import IntermediateResult


class _SortKey:
    __slots__ = ("v", "desc")

    def __init__(self, v: Any, desc: bool) -> None:
        self.v = v
        self.desc = desc

    def __lt__(self, other: "_SortKey") -> bool:
        if self.desc:
            return other.v < self.v
        return self.v < other.v

    def __eq__(self, other: object) -> bool:
        return isinstance(other, _SortKey) and self.v == other.v


def merge_results(parts: Sequence[IntermediateResult]) -> Optional[IntermediateResult]:
    parts = [p for p in parts if p is not None]
    if not parts:
        return None
    merged = parts[0]
    for p in parts[1:]:
        merged.merge(p)
    return merged


def reduce_to_response(
    request: BrokerRequest,
    parts: Sequence[IntermediateResult],
    exceptions: Optional[List[QueryException]] = None,
) -> BrokerResponse:
    merged = merge_results(parts)
    resp = BrokerResponse(exceptions=list(exceptions or []))
    if merged is None:
        return resp

    resp.num_docs_scanned = merged.num_docs_scanned
    resp.total_docs = merged.total_docs
    resp.num_segments_queried = merged.num_segments_queried
    resp.num_entries_scanned_in_filter = merged.num_entries_scanned_in_filter
    resp.num_entries_scanned_post_filter = merged.num_entries_scanned_post_filter
    # broker totals == sum of server totals (additive merge invariant)
    resp.cost = dict(merged.cost)
    resp.trace_info = merged.trace

    if request.is_group_by:
        resp.aggregation_results = _reduce_group_by(request, merged)
    elif request.is_aggregation:
        resp.aggregation_results = [
            AggregationResult(function=a.display_name, value=p.finalize())
            for a, p in zip(request.aggregations, merged.aggregations or [])
        ]
    else:
        resp.selection_results = _reduce_selection(request, merged)
    return resp


def _reduce_group_by(request: BrokerRequest, merged: IntermediateResult):
    groups = merged.groups or {}
    out: List[AggregationResult] = []
    gb = request.group_by

    # SQL semantics: HAVING filters GROUPS, so a group failing the
    # predicate disappears from EVERY aggregation's result list, not
    # just the one the predicate mentions.  (optimize_request rejects a
    # predicate naming an unselected aggregation up front.)
    passing = None
    having_idx = -1
    having_vals = {}
    if request.having is not None:
        h = request.having
        for i, agg in enumerate(request.aggregations):
            if h.function == agg.function and (h.column == agg.column or h.column == "*"):
                having_idx = i
                hkeys = list(groups)
                having_vals = dict(
                    zip(hkeys, _batch_finalize([groups[k][i] for k in hkeys]))
                )
                passing = {
                    key
                    for key, v in having_vals.items()
                    if _having_ok(v, h.operator, h.value)
                }
                break

    keys = [k for k in groups if passing is None or k in passing]
    for i, agg in enumerate(request.aggregations):
        if i == having_idx:
            vals = [having_vals[k] for k in keys]
        else:
            vals = _batch_finalize([groups[k][i] for k in keys])
        pairs = list(zip(keys, vals))
        asc = group_sort_ascending(agg.function)
        pairs.sort(key=lambda kv: (kv[1], kv[0]) if asc else (-_num(kv[1]), kv[0]))
        trimmed = pairs[: gb.top_n]
        out.append(
            AggregationResult(
                function=agg.display_name,
                group_by_columns=list(gb.columns),
                group_by_result=[GroupByResult(group=list(k), value=v) for k, v in trimmed],
            )
        )
    return out


def _batch_finalize(partials: List[Any]) -> List[Any]:
    """Per-group finalize, vectorized where the partial type allows:
    a wide HLL group-by pays ~25us of estimator per group when called
    one-by-one; ONE stacked estimate over [G, 256] registers does the
    same math in a single numpy pass (engine/hll.py batch support)."""
    from pinot_tpu.engine import hll as hll_mod
    from pinot_tpu.engine.results import HllPartial

    if len(partials) > 8 and all(type(p) is HllPartial for p in partials):
        import numpy as np

        ests = hll_mod.estimate_from_registers(
            np.stack([p.registers for p in partials])
        )
        return [int(e) for e in np.asarray(ests).ravel()]
    return [p.finalize() for p in partials]


def _num(v: Any) -> float:
    try:
        return float(v)
    except (TypeError, ValueError):
        return -math.inf


def _having_ok(value: Any, op: str, target: float) -> bool:
    v = _num(value)
    if op == "=":
        return v == target
    if op in ("<>", "!="):
        return v != target
    if op == "<":
        return v < target
    if op == ">":
        return v > target
    if op == "<=":
        return v <= target
    if op == ">=":
        return v >= target
    return True


def _reduce_selection(request: BrokerRequest, merged: IntermediateResult) -> SelectionResults:
    sel = request.selection
    rows = merged.selection_rows or []
    if sel.sorts:
        descs = [not s.ascending for s in sel.sorts]

        def key(entry: Tuple[list, list]):
            return [_SortKey(v, d) for v, d in zip(entry[0], descs)]

        rows = sorted(rows, key=key)
    window = rows[sel.offset : sel.offset + sel.size]
    columns = getattr(merged, "selection_columns", None) or _selection_columns(request, window)
    return SelectionResults(columns=columns, rows=[r for _, r in window])


def _selection_columns(request: BrokerRequest, window) -> List[str]:
    cols = request.selection.columns
    if cols and cols != ["*"]:
        return list(cols)
    # '*' with no schema knowledge at reduce: executor attaches names
    return [f"col{i}" for i in range(len(window[0][1]))] if window else []
