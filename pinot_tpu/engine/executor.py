"""Per-instance query executor: segments + BrokerRequest -> IntermediateResult.

The ``ServerQueryExecutorV1Impl.processQuery`` analog
(``core/query/executor/ServerQueryExecutorV1Impl.java:88``):
prune -> stage -> plan -> run compiled kernel -> finalize partials.

Unlike the reference's per-segment operator trees + combine thread pool,
ALL segments execute in one vmapped XLA program with the cross-segment
merge fused in (see ``kernel.py``); this host class only prepares inputs
and converts device outputs to mergeable ``IntermediateResult`` partials.
"""
from __future__ import annotations

import math
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pinot_tpu.common.request import BrokerRequest, group_sort_ascending
from pinot_tpu.common.schema import DataType
from pinot_tpu.common.values import render_value
from pinot_tpu.engine import config
from pinot_tpu.engine.context import TableContext, get_table_context
from pinot_tpu.engine.device import StagedTable, get_staged
from pinot_tpu.engine.plan import StaticPlan, build_query_inputs, build_static_plan
from pinot_tpu.engine.pruner import prune_segments
from pinot_tpu.engine.results import (
    AggPartial,
    AvgPartial,
    CountPartial,
    DistinctPartial,
    HistogramPartial,
    HllPartial,
    IntermediateResult,
    MaxPartial,
    MinMaxRangePartial,
    MinPartial,
    SumPartial,
)
from pinot_tpu.segment.immutable import ImmutableSegment


class _PairsState:
    """Host-side index over a compacted (group slot, valueId) pair
    buffer from the sort reduce (kernel.py ``_reduce_distinct_pairs``):
    per-slot distinct counts for trim ordering, per-slot gid slices for
    DistinctPartial building, and per-pair OCCURRENCE counts (run
    lengths off the carried start positions) for exact percentile
    histograms."""

    def __init__(self, state, capacity: int) -> None:
        slots, gids, starts, n, total_valid = state
        n = int(n)
        # the device reduce's stable unique-first compaction leaves the
        # first n entries already sorted by (slot, gid) — no host re-sort
        self._slots_sorted = np.asarray(slots)[:n].astype(np.int64)
        self._gids_sorted = np.asarray(gids)[:n]
        self._pair_counts = np.diff(
            np.append(np.asarray(starts)[:n].astype(np.int64), int(total_valid))
        )
        self._bounds = np.searchsorted(
            self._slots_sorted, np.arange(capacity + 1, dtype=np.int64)
        )
        self.counts = np.diff(self._bounds).astype(np.float64)

    def gids_for(self, key: int) -> np.ndarray:
        a, b = self._bounds[key], self._bounds[key + 1]
        return self._gids_sorted[a:b]

    def gid_counts_for(self, key: int):
        """(gids ascending, occurrence counts) for one group slot."""
        a, b = self._bounds[key], self._bounds[key + 1]
        return self._gids_sorted[a:b], self._pair_counts[a:b]

    def gids_rows_for(self, keys: np.ndarray):
        """Batched slice gather: (gids, rows) where ``rows[i]`` is the
        position in ``keys`` whose slot owns ``gids[i]`` — the input
        shape ``_regs_from_gids`` batch-decodes."""
        if not keys.size:
            empty = np.zeros(0, dtype=np.int64)
            return empty, empty
        lo, hi = self._bounds[keys], self._bounds[keys + 1]
        counts = hi - lo
        total = int(counts.sum())
        # vectorized ragged gather: per-element position minus its own
        # slice's cumulative start, plus the slice's lo
        offs = np.concatenate(([0], np.cumsum(counts)[:-1])).astype(np.int64)
        take = np.arange(total) - np.repeat(offs, counts) + np.repeat(lo, counts)
        return self._gids_sorted[take], np.repeat(np.arange(keys.size), counts)

    def percentiles_for(self, keys: np.ndarray, p: int, vals: np.ndarray) -> np.ndarray:
        """Vectorized exact percentile per requested group slot from the
        sparse (gid, count) runs — mirrors the dense-histogram math."""
        csum = np.concatenate([[0], np.cumsum(self._pair_counts)])
        lo, hi = self._bounds[keys], self._bounds[keys + 1]
        n = csum[hi] - csum[lo]
        idx = np.minimum((n * p / 100.0).astype(np.int64), np.maximum(n - 1, 0))
        # global cumulative position of each group's idx-th element
        pos = np.searchsorted(csum[1:], csum[lo] + idx, side="right")
        pos = np.minimum(pos, self._gids_sorted.size - 1) if self._gids_sorted.size else pos
        gid = self._gids_sorted[pos] if self._gids_sorted.size else np.zeros_like(pos)
        out = np.where(n > 0, vals[np.minimum(gid, vals.size - 1)], -np.inf)
        return out


def _regs_from_gids(
    gids: np.ndarray, rows: np.ndarray | None = None, n_rows: int = 0
) -> np.ndarray:
    """Decode packed (bucket*64 + rho) pair gids into HLL registers
    (max rho per bucket) — the one place the gid packing is interpreted
    on host.  Without ``rows``: one uint8[HLL_M] register array.  With
    ``rows`` (same shape as ``gids``) and ``n_rows``: a batched
    uint8[n_rows, HLL_M] decode, one register array per row."""
    from pinot_tpu.utils.npgroup import scatter_max_2d

    g = gids.astype(np.int64)
    rho = (g & 63).astype(np.uint8)
    if rows is None:
        return scatter_max_2d(np.zeros(g.size, np.int64), 1, g >> 6, rho, config.HLL_M)[0]
    return scatter_max_2d(rows, n_rows, g >> 6, rho, config.HLL_M)


def _regs_from_value_gids(
    ctx, column: str, gids: np.ndarray, rows: np.ndarray | None = None, n_rows: int = 0
) -> np.ndarray:
    """HLL registers from GLOBAL dictionary value ids (the
    hll_from_presence finalize: registers depend only on the distinct
    value set).  Batched like ``_regs_from_gids`` when ``rows`` given."""
    from pinot_tpu.engine import hll as hll_mod
    from pinot_tpu.utils.npgroup import scatter_max_2d

    bt, rt = hll_mod.dictionary_tables(ctx.column(column).global_dict)
    g = np.asarray(gids, dtype=np.int64)
    ok = g < bt.size  # padded/overflow slots carry no value
    g = g[ok]
    if rows is None:
        return scatter_max_2d(np.zeros(g.size, np.int64), 1, bt[g], rt[g], config.HLL_M)[0]
    return scatter_max_2d(np.asarray(rows)[ok], n_rows, bt[g], rt[g], config.HLL_M)


def _hist_partial(gdict, gids, cnts, p: int) -> "HistogramPartial":
    counts = {
        float(gdict.get(int(g))): int(c)
        for g, c in zip(gids, cnts)
        if g < gdict.cardinality
    }
    return HistogramPartial(counts, percentile=p)


class QueryExecutor:
    """Executes queries over a set of immutable segments on this host's
    device(s).

    With ``mesh`` set, the stacked segment axis is sharded over the
    device mesh and cross-chip merge rides ICI collectives
    (``pinot_tpu.parallel.multichip``); without it, the vmapped
    single-device kernel runs.
    """

    def __init__(self, mesh=None, metrics=None, lane=None, lanes=None) -> None:
        self.mesh = mesh
        # mesh execution plane (engine/mesh.py + dispatch.LaneGroup):
        # with a lane group set, every query is routed to a chip-group
        # lane by its literal-erased plan-shape digest — staging,
        # kernel compilation, and the launch all happen against THAT
        # group's mesh.  ``mesh``/``lane`` stay as the single-lane
        # (pre-mesh) configuration for standalone executors.
        self.lanes = lanes
        if lanes is not None and lane is None:
            lane = lanes.primary
        if metrics is None:
            # the registry is the single source of truth for phase
            # timers AND the self-healing counters (heal.*), so a
            # standalone executor gets a private one instead of
            # branching on None at every mark
            from pinot_tpu.utils.metrics import ServerMetrics

            metrics = ServerMetrics("executor")
        self.metrics = metrics  # MetricsRegistry: per-phase timers + heal.*
        # pre-register the self-healing series so /metrics exposes them
        # at zero from process start (a scrape gap is not "no failures")
        for name in self._HEAL_COUNTERS:
            metrics.meter(f"heal.{name}")
        # three-stage serving pipeline (engine/dispatch.py): with a
        # DeviceLane set, kernel launches leave this worker thread and
        # coalesce with identical in-flight dispatches; without one,
        # launch + fetch run inline (the serial path, byte-identical
        # results — the differential suite holds the two together)
        self.lane = lane
        self._sharded_kernels: Dict[Any, Any] = {}
        self._mesh_shardings: Dict[Any, Any] = {}  # mesh id -> NamedSharding
        from collections import OrderedDict

        self._qinput_cache: "OrderedDict[Any, Any]" = OrderedDict()
        self._qinput_cache_bytes = 0
        # the QueryScheduler runs queries on a worker pool; byte
        # accounting must not drift under concurrent misses/evictions
        import threading

        self._qinput_cache_lock = threading.Lock()
        # self-healing state: device failures fail over to the host
        # path, and a (plan digest, segment set) that keeps failing on
        # device is quarantined so repeat offenders skip the device
        # entirely (engine/dispatch.py classification contract).
        # Counters live in the metrics registry (heal.*) — ONE source
        # of truth for status(), /metrics, and /debug/metrics.
        self._heal_lock = threading.Lock()
        # poison key -> (reason, expiry): quarantine entries carry a TTL
        # (PINOT_TPU_POISON_TTL_S, default 300s) so a plan poisoned by a
        # transient burst is eventually re-admitted to the device — the
        # worst case of a wrong verdict is one more failover cycle, the
        # worst case of a permanent verdict is serving a healthy plan
        # from the slow host path forever
        self._poisoned: Dict[Any, Tuple[str, float]] = {}
        import os as _os

        self._poison_ttl_s = float(_os.environ.get("PINOT_TPU_POISON_TTL_S", "300"))
        # audit-plane quarantine flag: True once any ("audit", digest,
        # tier) key entered the poison map, so the serving path only
        # pays a plan-digest derivation when a quarantine could apply
        self._has_audit_poison = False

    # -- self-healing bookkeeping --------------------------------------
    _HEAL_COUNTERS = (
        "deviceFailures",
        "deviceRetries",
        "hostFailovers",
        "poisonSkips",
        # allocation-failure heals: RESOURCE_EXHAUSTED launches that
        # recovered by demoting the coldest residents and retrying
        # (engine/residency.py) — never poisoned, host only as last
        # resort
        "resourceExhausted",
    )

    def _heal_mark(self, name: str, **tags) -> None:
        self.metrics.meter(f"heal.{name}").mark()
        from pinot_tpu.utils.trace import current_trace

        tr = current_trace()
        if tr is not None and tr.enabled:
            tr.event(name, **tags)

    def healing_stats(self) -> Dict[str, int]:
        now = time.monotonic()
        stats = {
            name: self.metrics.meter(f"heal.{name}").count
            for name in self._HEAL_COUNTERS
        }
        with self._heal_lock:
            stats["poisonedPlans"] = sum(
                1 for _, exp in self._poisoned.values() if now < exp
            )
        return stats

    def _is_poisoned(self, key: Any) -> bool:
        with self._heal_lock:
            entry = self._poisoned.get(key)
            if entry is None:
                return False
            if time.monotonic() >= entry[1]:
                self._poisoned.pop(key, None)  # TTL expired: re-admit
                return False
            return True

    def poisoned_entry(self, key: Any) -> Optional[Dict[str, Any]]:
        """Live quarantine record for a (plan digest, segment set) key,
        or None — the EXPLAIN plane's honesty hook: a poisoned plan's
        EXPLAIN must report the host tier it will ACTUALLY serve from,
        not the device tier it would have picked."""
        now = time.monotonic()
        with self._heal_lock:
            entry = self._poisoned.get(key)
            if entry is None or now >= entry[1]:
                return None
            return {"reason": entry[0], "ttlRemainingS": round(entry[1] - now, 3)}

    def _poison(self, key: Any, reason: str) -> None:
        expiry = time.monotonic() + self._poison_ttl_s
        with self._heal_lock:
            self._poisoned[key] = (reason, expiry)
            if len(self._poisoned) > 1024:  # runaway-workload backstop
                self._poisoned.clear()
                self._poisoned[key] = (reason, expiry)

    def clear_poisoned(self) -> None:
        """Ops/test hook: re-admit quarantined plans to the device (a
        rolled-out runtime fix makes old poison verdicts stale)."""
        with self._heal_lock:
            self._poisoned.clear()
        self._has_audit_poison = False

    # -- audit-plane quarantine (utils/audit.py) -----------------------
    def audit_quarantine(self, digest: str, tier: str, reason: str) -> None:
        """Shadow-audit verdict: ``tier`` produced a WRONG answer for
        plan shape ``digest``.  Rides the same TTL'd poison map as the
        device-failure quarantine — the serving path skips the
        quarantined tier for that shape (postings/bitsliced fall
        through to the next tier, device fails over to host) until the
        TTL re-admits it."""
        self._poison(("audit", str(digest), str(tier)), f"audit: {reason}")
        self._has_audit_poison = True
        self._heal_mark("auditQuarantines", tier=tier)

    def audit_quarantined_snapshot(self) -> List[Dict[str, Any]]:
        """Live audit-quarantine entries for ``/debug/audit``."""
        now = time.monotonic()
        out: List[Dict[str, Any]] = []
        with self._heal_lock:
            for key, (reason, exp) in self._poisoned.items():
                if (
                    isinstance(key, tuple)
                    and len(key) == 3
                    and key[0] == "audit"
                    and now < exp
                ):
                    out.append(
                        {
                            "planDigest": key[1],
                            "tier": key[2],
                            "reason": reason,
                            "ttlRemainingS": round(exp - now, 3),
                        }
                    )
        return out

    def _audit_digest(self, request: BrokerRequest) -> Optional[str]:
        """The shape digest for quarantine checks — derived ONLY when
        some audit quarantine exists (zero serving-path overhead while
        the audit plane has never fired)."""
        if not self._has_audit_poison:
            return None
        from pinot_tpu.engine.plandigest import plan_shape_digest

        return plan_shape_digest(request)

    def _audit_blocked(self, digest: Optional[str], tier: str) -> bool:
        if digest is None:
            return False
        if self._is_poisoned(("audit", digest, tier)):
            self._heal_mark("auditTierSkips", tier=tier)
            return True
        return False

    def _fault_injector(self):
        lane = self.lane
        inj = getattr(lane, "fault_injector", None) if lane is not None else None
        if inj is None and self.lanes is not None:
            for lane in self.lanes.lanes:
                inj = getattr(lane, "fault_injector", None)
                if inj is not None:
                    break
        return inj

    def _finish_tier(
        self, result: IntermediateResult, request: BrokerRequest, tier: str
    ) -> IntermediateResult:
        """Every ``_execute_engine`` exit point: stamp which serving
        tier produced the answer (the audit plane's quarantine key) and
        consult the armed wrong-answer injection, if any (chaos tests
        only — production lanes have no fault injector)."""
        result._served_tier = tier
        inj = self._fault_injector()
        if inj is not None and getattr(inj, "corruption_armed", False):
            from pinot_tpu.engine.plandigest import plan_shape_digest

            delta = inj.check_corrupt(plan_shape_digest(request), tier)
            if delta is not None:
                from pinot_tpu.common.faults import apply_result_corruption

                apply_result_corruption(result, delta)
        return result

    def execute_host_oracle(
        self, segments: Sequence[ImmutableSegment], request: BrokerRequest
    ) -> IntermediateResult:
        """The shadow-audit oracle: re-execute ``request`` over the
        exact views a production reply served, on the always-correct
        host path — no device lane, no result cache, no tier ladder.
        Pruning is correctness-preserving, so the payload (modulo
        accounting) must match whatever tier served production."""
        from pinot_tpu.engine.host_fallback import execute_host

        segments = list(segments)
        total_docs = sum(s.num_docs for s in segments)
        live = prune_segments(segments, request)
        if not live:
            res = self._empty_result(request, total_docs)
        else:
            sel_columns = (
                self._resolve_selection_columns(request, live[0])
                if request.is_selection
                else None
            )
            ctx = get_table_context(live)
            res = execute_host(live, ctx, request, total_docs, sel_columns)
        res._served_tier = "host"
        return res

    # -- mesh / lane-group routing -------------------------------------
    def lane_selection(self, request: BrokerRequest):
        """Shape-hashed chip-group routing (dispatch.LaneGroup.select),
        or None without a lane group.  Shared by the serving path and
        EXPLAIN so the phantom plan stages/pads exactly like the lane
        that would execute it."""
        if self.lanes is None:
            return None
        from pinot_tpu.engine.plandigest import plan_shape_digest

        return self.lanes.select(plan_shape_digest(request))

    def _mesh_sharding(self, mesh):
        """NamedSharding splitting the segment axis over ``mesh`` (one
        cached instance per mesh — it is part of staging-cache keys)."""
        if mesh is None:
            return None
        key = id(mesh)
        sh = self._mesh_shardings.get(key)
        if sh is None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            # axis 0 shards over EVERY mesh axis — the same spec the
            # sharded kernels' in_specs use (multichip._make_sharded),
            # so staged arrays arrive already laid out for shard_map
            sh = NamedSharding(mesh, P(tuple(mesh.axis_names)))
            self._mesh_shardings[key] = sh
        return sh

    def _mesh_key(self, mesh) -> Any:
        """Hashable kernel-cache component for a mesh (per-lane meshes
        must not share compiled sharded kernels)."""
        if mesh is None:
            return None
        return tuple(getattr(d, "id", i) for i, d in enumerate(mesh.devices.flat))

    def _phase(self, name: str, t0: float, **tags) -> float:
        """Record a ServerQueryPhase-style timer (SURVEY §5: pruning /
        planBuild / planExec phases) AND, when the request is traced, a
        span on the current trace tree; returns a fresh t0."""
        now = time.perf_counter()
        ms = (now - t0) * 1000
        self.metrics.timer(f"phase.{name}").update(ms)
        from pinot_tpu.utils.trace import current_trace

        tr = current_trace()
        if tr is not None and tr.enabled:
            tr.add(name, ms, **tags)
        return now

    def execute(
        self,
        segments: Sequence[ImmutableSegment],
        request: BrokerRequest,
        deadline: Optional[float] = None,
    ) -> IntermediateResult:
        """``deadline`` (monotonic seconds) is the broker-propagated
        budget; threaded into the device lane so a query whose budget
        drained while queued there is shed, not executed."""
        total_docs = sum(s.num_docs for s in segments)
        live = prune_segments(segments, request)
        pruned = len(segments) - len(live)
        if not live:
            res = self._empty_result(request, total_docs)
            res.add_cost(segmentsPruned=pruned)
            return res

        # star-tree routing: eligible segments answer from their
        # pre-aggregated cube (startree/operator.py); the rest take the
        # normal device path, partials merge below
        from pinot_tpu.startree.operator import execute_star_tree, is_fit_for_star_tree

        star = [s for s in live if is_fit_for_star_tree(request, s)]
        if star:
            normal = [s for s in live if s not in star]
            parts = [execute_star_tree(s, request) for s in star]
            if normal:
                parts.append(self._execute_engine(normal, request, deadline))
            merged = parts[0]
            for p in parts[1:]:
                merged.merge(p)
            merged.total_docs = total_docs
            merged.add_cost(segmentsPruned=pruned)
            merged._served_tier = (
                "starTree"
                if not normal
                else getattr(parts[-1], "_served_tier", "starTree")
            )
            return merged

        result = self._execute_engine(live, request, deadline)
        result.total_docs = total_docs
        result.add_cost(segmentsPruned=pruned)
        return result

    def _execute_engine(
        self,
        live: List[ImmutableSegment],
        request: BrokerRequest,
        deadline: Optional[float] = None,
    ) -> IntermediateResult:
        t0 = time.perf_counter()
        total_docs = sum(s.num_docs for s in live)
        needed = set(request.referenced_columns())
        sel_columns: Optional[List[str]] = None
        if request.is_selection:
            sel_columns = self._resolve_selection_columns(request, live[0])
            needed.update(sel_columns)

        # chip-group routing (mesh execution): the lane group picks the
        # lane/mesh this shape executes on; without one, the legacy
        # single-mesh (or no-mesh) configuration applies
        sel = self.lane_selection(request)
        mesh = sel.group.mesh if sel is not None else self.mesh
        pad_to = 0
        if mesh is not None:
            n = int(mesh.devices.size)
            pad_to = -(-len(live) // n) * n

        # columns used ONLY by doc-range predicates on sorted columns
        # never reach the device (the kernel compares row ids against
        # host-computed doc bounds) — skip staging them entirely
        needed -= self._docrange_only_columns(request, live, sel_columns)

        ctx = get_table_context(live)

        # audit-plane quarantine (utils/audit.py): a tier caught
        # serving wrong answers for this shape is skipped — derived
        # only while some audit quarantine is live
        audit_digest = self._audit_digest(request)

        # selective predicates answer from host postings in O(matches)
        # (engine/invindex_path.py — BitmapBasedFilterOperator analog);
        # unselective ones fall through to the device scan below
        from pinot_tpu.engine.invindex_path import try_index_path

        ires = None
        if not self._audit_blocked(audit_digest, "postings"):
            ires = try_index_path(request, live, ctx, total_docs, sel_columns)
        if ires is not None:
            self._phase("indexPath", t0)
            return self._finish_tier(ires, request, "postings")

        # mid-selectivity scalar aggregations the postings tier just
        # declined evaluate as O(bit-width) bulk-bitwise passes over
        # bit-sliced planes (engine/bitsliced.py) — single-device only;
        # mesh placements keep the sharded scan path.  A device fault
        # here falls through to the scan section's healing loop below
        # instead of failing the query on an optimization tier.
        if mesh is None and not self._audit_blocked(audit_digest, "bitsliced"):
            from pinot_tpu.engine.bitsliced import try_bitsliced_path

            try:
                bres = try_bitsliced_path(
                    self, request, live, ctx, total_docs, deadline,
                    lane=sel.lane if sel is not None else None,
                    lane_index=sel.index if sel is not None else 0,
                )
            except Exception as e:
                from pinot_tpu.engine.dispatch import LaneClosedError
                from pinot_tpu.server.scheduler import QueryAbandonedError

                if isinstance(
                    e, (QueryAbandonedError, LaneClosedError, TimeoutError)
                ):
                    raise
                self._heal_mark("bitslicedFallbacks", error=str(e)[:200])
                bres = None
            if bres is not None:
                self._phase("bitslicedPath", t0)
                return self._finish_tier(bres, request, "bitsliced")

        # queries the planner can only send to the host (group space or
        # guaranteed pair overflow) skip device staging entirely
        from pinot_tpu.engine.plan import plan_forced_host

        if plan_forced_host(request, ctx):
            from pinot_tpu.engine.host_fallback import execute_host

            res = execute_host(live, ctx, request, total_docs, sel_columns)
            self._phase("hostPath", t0)
            return self._finish_tier(res, request, "host")

        # -- device section under the self-healing contract -----------
        # The WHOLE device path (staging, H2D uploads, kernel dispatch,
        # D2H fetch, finalize) is covered: classify the failure
        # (engine/dispatch.py), retry ONCE on device for transients,
        # then quarantine the (plan digest, segment set) and serve the
        # same request via the always-correct host path.  Deadline and
        # shutdown control flow propagates untouched.
        from pinot_tpu.engine.dispatch import (
            DeviceExecutionError,
            LaneClosedError,
            classify_device_error,
        )
        from pinot_tpu.server.scheduler import QueryAbandonedError

        if self._audit_blocked(audit_digest, "device"):
            # wrong-answer quarantine: unlike a device FAILURE (which
            # retries), a tier caught lying never gets another attempt
            # inside the TTL — straight to the host oracle path
            from pinot_tpu.engine.host_fallback import execute_host

            self._heal_mark("hostFailovers", reason="auditQuarantine")
            t0 = time.perf_counter()
            res = execute_host(live, ctx, request, total_docs, sel_columns)
            self._phase("hostFailover", t0)
            return self._finish_tier(res, request, "host")

        poison_ref: Dict[str, Any] = {}  # device section records the key
        last: Optional[DeviceExecutionError] = None
        # attempt budget: one plain device retry for transients (PR 3),
        # plus one extra round reserved for RESOURCE_EXHAUSTED — an OOM
        # retried into the same full HBM would fail identically, so
        # each OOM round first demotes the coldest unpinned residents
        # (engine/residency.py) to make room.  Host failover stays the
        # LAST resort.
        for attempt in (0, 1, 2):
            if attempt:
                if last is None or not last.retryable:
                    break  # poison/stall: deterministic, a device retry
                    # would fail (or wedge the fresh lane) identically
                if getattr(last, "resource_exhausted", False):
                    from pinot_tpu.engine.residency import RESIDENCY

                    exclude = tuple(
                        t for t in (poison_ref.get("token"),) if t is not None
                    )
                    freed = RESIDENCY.demote_for_pressure(
                        exclude_tokens=exclude
                    )
                    self._heal_mark("resourceExhausted", freedBytes=freed)
                elif attempt > 1:
                    break  # plain transients get exactly ONE device retry
                self._heal_mark("deviceRetries")
            try:
                return self._finish_tier(
                    self._device_section(
                        live, request, deadline, ctx, needed, sel_columns,
                        pad_to, total_docs, t0, poison_ref, sel=sel, mesh=mesh,
                    ),
                    request,
                    "device",
                )
            except (QueryAbandonedError, LaneClosedError, TimeoutError):
                raise
            except Exception as e:
                if poison_ref.pop("host", False):
                    # the section had already LEFT the device path (plan
                    # not on device / poison skip / pair overflow) — a
                    # host execution error is not a device failure and
                    # re-running the host path could only fail again
                    raise
                last = classify_device_error(e)
                self._heal_mark(
                    "deviceFailures", retryable=last.retryable, error=str(last)[:200]
                )
        # device exhausted: quarantine (when the section got far enough
        # to know its plan) and transparently fail over.  Coalesced
        # waiters each land here and each finalize from the host.
        from pinot_tpu.engine.host_fallback import execute_host

        if poison_ref.get("key") is not None and not getattr(
            last, "resource_exhausted", False
        ):
            # OOM never poisons: the plan is healthy, the device was
            # full — quarantining it would strand a good plan on the
            # slow host path after pressure subsides
            self._poison(poison_ref["key"], str(last))
        self._heal_mark("hostFailovers", reason=str(last)[:200])
        t0 = time.perf_counter()
        res = execute_host(live, ctx, request, total_docs, sel_columns)
        self._phase("hostFailover", t0)
        return self._finish_tier(res, request, "host")

    def _device_section(
        self,
        live: List[ImmutableSegment],
        request: BrokerRequest,
        deadline: Optional[float],
        ctx: TableContext,
        needed: set,
        sel_columns: Optional[List[str]],
        pad_to: int,
        total_docs: int,
        t0: float,
        poison_ref: Dict[str, Any],
        sel=None,
        mesh=None,
    ) -> IntermediateResult:
        if sel is None and mesh is None:
            mesh = self.mesh  # standalone callers (no lane group)
        lane = sel.lane if sel is not None else self.lane
        sharding = self._mesh_sharding(mesh)
        raw_cols, gfwd_cols, hll_cols = self._role_columns(request, live, ctx)
        skip_base = self._skip_base_columns(
            request, live, raw_cols, gfwd_cols, hll_cols
        )
        # pin=True: the staged table's token is refcounted for this
        # query's whole device section, so tier demotion under memory
        # pressure (engine/residency.py) can never race the launch
        staged = get_staged(
            live,
            sorted(needed),
            pad_segments_to=pad_to,
            raw_columns=raw_cols,
            gfwd_columns=gfwd_cols,
            hll_columns=hll_cols,
            ctx=ctx,
            skip_base_columns=skip_base,
            sharding=sharding,
            pin=True,
        )
        # the OOM heal's demotion pass must not evict the very table
        # this query is about to retry against
        poison_ref["token"] = staged.token
        from pinot_tpu.engine.residency import RESIDENCY

        try:
            return self._device_section_staged(
                live, request, deadline, ctx, needed, sel_columns,
                total_docs, t0, poison_ref, sel, mesh, lane, sharding,
                staged,
            )
        finally:
            RESIDENCY.unpin(staged.token)

    def _device_section_staged(
        self,
        live: List[ImmutableSegment],
        request: BrokerRequest,
        deadline: Optional[float],
        ctx: TableContext,
        needed: set,
        sel_columns: Optional[List[str]],
        total_docs: int,
        t0: float,
        poison_ref: Dict[str, Any],
        sel,
        mesh,
        lane,
        sharding,
        staged,
    ) -> IntermediateResult:
        t0 = self._phase("staging", t0)
        scratch: Dict[Any, Any] = {}  # plan->inputs table cache (regex)
        plan = build_static_plan(request, ctx, staged, scratch=scratch)

        if not plan.on_device:
            from pinot_tpu.engine.host_fallback import execute_host

            poison_ref["host"] = True  # host path from here: not a device fault
            return execute_host(live, ctx, request, total_docs, sel_columns)

        # poison quarantine: this (plan digest, segment set) keeps
        # failing on device — skip the device entirely and serve from
        # the always-correct host path (PIMDAL-style contract: the host
        # path stays a correct fallback for the accelerator path).  The
        # digest is computed ONCE here and shared with the lane's
        # injector hook and the failover wrapper's quarantine.
        from pinot_tpu.engine.dispatch import plan_digest as _plan_digest

        pdigest = _plan_digest(plan)
        poison_ref["key"] = (pdigest, staged.segment_names)
        if self._is_poisoned(poison_ref["key"]):
            from pinot_tpu.engine.host_fallback import execute_host

            self._heal_mark("poisonSkips")
            t0 = self._phase("planBuild", t0)
            poison_ref["host"] = True  # host path from here: not a device fault
            res = execute_host(live, ctx, request, total_docs, sel_columns)
            self._phase("hostFailover", t0)
            return res

        from pinot_tpu.engine.device import segment_arrays

        cost: Dict[str, float] = {}  # per-query cost vector accumulator
        q_np = build_query_inputs(request, plan, ctx, staged, scratch=scratch)
        digest = self._inputs_digest(q_np)
        seg_arrays = segment_arrays(staged, needed)
        block_ids, scanned_rows = self._block_skip_ids(plan, q_np, live, staged)
        from pinot_tpu.engine.kernel import chunk_rows_limit

        _limit = chunk_rows_limit()
        if block_ids is not None and _limit and staged.num_segments * staged.n_pad > _limit:
            # the block kernel has no segment-chunked variant: beyond the
            # per-dispatch row budget its single dispatch would exhaust
            # HBM at compile time — fall through to the chunked full
            # kernel instead (correctness over the block-skip win)
            block_ids = None
        t0 = self._phase("planBuild", t0)
        # kernel outputs fetch via ONE packed D2H transfer
        # (engine/packing.py): per-leaf fetches pay a tunnel RTT each
        batch_spec = None
        analysis_args = None

        def upload_inputs():
            return self._to_device_inputs(
                q_np, plan=plan, digest=digest, cost=cost, sharding=sharding
            )

        if block_ids is not None:
            from pinot_tpu.engine.zonemap import zone_block_rows

            block = zone_block_rows()
            if mesh is None:
                from pinot_tpu.engine.kernel import make_packed_block_table_kernel

                kernel = make_packed_block_table_kernel(plan, block)
            else:
                kernel = self._block_kernel(plan, block, mesh)
            # block ids shard over the segment axis with everything else
            ids_dev = (
                jax.device_put(np.asarray(block_ids), sharding)
                if sharding is not None
                else jnp.asarray(block_ids)
            )
            args = (seg_arrays, upload_inputs(), ids_dev)
        else:
            kernel = self._kernel(plan, staged, mesh)
            if lane is not None and mesh is None and sharding is None:
                # cross-query micro-batching eligibility: the plain
                # packed single-device kernel only (no mesh collectives,
                # no per-query block-id gathers, no chunked dispatch
                # sequence) — exactly the path _kernel chose above when
                # the table fits the per-dispatch row budget
                batch_spec = self._batch_spec(plan, staged, q_np, seg_arrays)
            if batch_spec is not None:
                # defer the solo upload into the launch closure: a
                # dispatch that rides a batched launch never uses its
                # own device copy (the batch uploads ONE stacked
                # pytree), so an eager per-member put would be dead H2D
                # weight exactly on the shapes that batch most
                args = lambda: (seg_arrays, upload_inputs())
                # cost analysis traces shapes only: the host numpy
                # pytree stands in so the helper thread never uploads
                analysis_args = (seg_arrays, q_np)
            else:
                args = (seg_arrays, upload_inputs())
        exec_info: Dict[str, Any] = {}
        outs = self._run_kernel(
            kernel, args, plan, staged, digest, block_ids, deadline, pdigest,
            cost=cost, lane=lane, batch_spec=batch_spec, exec_info=exec_info,
            analysis_args=analysis_args,
        )
        t0 = time.perf_counter()  # laneWait/planExec timed inside _run_kernel

        # sort-dedup distinct overflow: more unique pairs than the
        # device buffer holds — only the host path can finish exactly
        for i, agg in enumerate(plan.aggs):
            if agg.sort_pairs:
                state = (
                    outs[f"gb_{i}"] if plan.group_by is not None else outs[f"agg_{i}"]
                )
                if int(state[3]) > state[0].shape[0]:
                    from pinot_tpu.engine.host_fallback import execute_host

                    # pair overflow: host finishes exactly — leaving the
                    # device path, so host errors are not device faults
                    poison_ref["host"] = True
                    return execute_host(live, ctx, request, total_docs, sel_columns)

        result = self._finalize(request, plan, ctx, staged, live, outs, total_docs, sel_columns)
        if scanned_rows is not None:
            # zone maps skipped non-candidate blocks: filter scan cost
            # is O(candidate rows), the point of the skipping path
            result.num_entries_scanned_in_filter = len(plan.leaves) * scanned_rows
        # device-path cost vector: staged bytes the kernel read (the
        # block path reads only the candidate fraction), the serving
        # tier, and the dispatch-side hits recorded into ``cost``
        dev_bytes = sum(getattr(a, "nbytes", 0) for a in seg_arrays.values())
        if block_ids is not None and scanned_rows is not None and staged.total_docs:
            dev_bytes = int(
                dev_bytes * min(1.0, scanned_rows / staged.total_docs)
            )
        result.add_cost(bytesScanned=dev_bytes, deviceBytes=dev_bytes, **cost)
        if block_ids is not None:
            result.add_cost(segmentsZonemap=len(live))
        else:
            result.add_cost(segmentsFullScan=len(live))
        # device-plan identity for the utilization plane: lets the
        # plan-stats recorder join this shape's measured wall time with
        # the lane's static cost analysis (roofline numerator); the
        # lane index attributes it to the chip group that executed
        result._device_digest = pdigest
        result._lane_index = sel.index if sel is not None else 0
        # batching actuals for EXPLAIN ANALYZE's device node: how many
        # same-shape queries this member's launch actually carried
        result._batch_size = int(exec_info.get("batchSize", 1) or 1)
        self._phase("finalize", t0)
        return result

    def _docrange_only_columns(
        self,
        request: BrokerRequest,
        live: List[ImmutableSegment],
        sel_columns: Optional[List[str]],
    ) -> set:
        """Filter columns whose every use qualifies for the docrange
        fast path (plan.py StaticLeaf) and which appear nowhere else in
        the query."""
        qualifying = self._docrange_qualifying_cols(request, live)
        used_elsewhere = {a.column for a in request.aggregations}
        if request.is_group_by:
            used_elsewhere.update(request.group_by.columns)
        if request.is_selection:
            used_elsewhere.update(sel_columns or [])
            used_elsewhere.update(s.column for s in request.selection.sorts)
        return qualifying - used_elsewhere

    def _docrange_qualifying_cols(
        self, request: BrokerRequest, live: List[ImmutableSegment]
    ) -> set:
        """Filter columns whose EVERY leaf use classifies docrange
        (sorted in every segment, SV, RANGE or single-value EQ).  MUST
        mirror build_static_plan's classification: a column dropped or
        base-skipped on a wrong prediction would leave the kernel
        without its arrays."""
        if request.filter is None:
            return set()
        from pinot_tpu.common.request import FilterOperator

        qualifies: Dict[str, bool] = {}
        for node in request.filter.walk():
            if not node.is_leaf:
                continue
            col = node.column
            ok = False
            if live and live[0].has_column(col):
                meta0 = live[0].column(col).metadata
                shape_ok = node.operator == FilterOperator.RANGE or (
                    node.operator == FilterOperator.EQUALITY
                    and len(node.values) == 1
                )
                ok = (
                    meta0.single_value
                    and shape_ok
                    and all(s.column(col).metadata.is_sorted for s in live)
                )
            qualifies[col] = qualifies.get(col, True) and ok
        return {c for c, ok in qualifies.items() if ok}

    def _block_skip_ids(
        self,
        plan: StaticPlan,
        q_np: Dict[str, Any],
        live: List[ImmutableSegment],
        staged: StagedTable,
    ):
        """Zone-map block pruning decision (engine/zonemap.py): returns
        (block_ids [S, nb_pad] or None, candidate_rows or None).

        Engages when the candidate set is under half the table — below
        that the gather overhead beats the full scan it saves.  On a
        mesh, the ids array shards over the segment axis like every
        other per-segment input (nb_pad is a global bucket)."""
        import os

        if os.environ.get("PINOT_TPU_ZONEMAP") == "0":
            return None, None
        from pinot_tpu.engine import zonemap

        cand = zonemap.candidate_blocks(plan, q_np, live, staged.n_pad)
        if cand is None:
            return None, None
        block = zonemap.zone_block_rows()
        nb_total = staged.num_segments * (staged.n_pad // block)
        nb_max = int(cand.sum(axis=1).max()) if cand.size else 0
        if plan.selection is not None:
            # the gathered view exposes only nb_pad*block rows per
            # segment; top_k(k) requires k <= operand length, so grow
            # the candidate window to cover the selection k (falls back
            # to full scan below when that defeats the pruning win)
            nb_max = max(nb_max, -(-plan.selection.k // block))
        nb_pad = 1
        while nb_pad < nb_max:
            nb_pad *= 2
        if nb_pad * staged.num_segments > nb_total // 2:
            return None, None
        ids = zonemap.block_ids_input(cand, nb_pad)
        if ids.shape[0] < staged.num_segments:  # mesh-padding segments
            pad = np.full(
                (staged.num_segments - ids.shape[0], nb_pad), -1, dtype=np.int32
            )
            ids = np.concatenate([ids, pad], axis=0)
        return ids, int(cand.sum()) * block

    def _cached_sharded(self, key, factory):
        k = self._sharded_kernels.get(key)
        if k is None:
            k = factory()
            if len(self._sharded_kernels) > 128:
                self._sharded_kernels.clear()
            self._sharded_kernels[key] = k
        return k

    def _block_kernel(self, plan: StaticPlan, block: int, mesh=None):
        from pinot_tpu.engine.packing import make_packed_kernel
        from pinot_tpu.parallel.multichip import make_sharded_block_table_kernel

        if mesh is None:
            mesh = self.mesh
        return self._cached_sharded(
            (plan, "block", block, self._mesh_key(mesh)),
            lambda: make_packed_kernel(
                make_sharded_block_table_kernel(plan, mesh, block)
            ),
        )

    def _kernel(self, plan: StaticPlan, staged, mesh=None):
        if mesh is None and self.lanes is None:
            mesh = self.mesh
        if mesh is None:
            from pinot_tpu.engine.kernel import (
                chunk_rows_limit,
                make_chunked_table_kernel,
                make_packed_table_kernel,
                plan_chunkable,
            )

            limit = chunk_rows_limit()
            if (
                limit
                and staged.num_segments * staged.n_pad > limit
                and plan_chunkable(plan)
            ):
                # beyond the per-dispatch row budget the kernel's
                # per-row temporaries exceed HBM at compile time: run
                # segment-axis chunks and combine the reduced outputs.
                # Outputs are holder-sized (small), so the single-
                # transfer packing wrapper isn't needed here.
                return make_chunked_table_kernel(
                    plan, staged.num_segments, staged.n_pad
                )
            return make_packed_table_kernel(plan)
        from pinot_tpu.engine.kernel import chunk_rows_limit, make_chunked_sharded_kernel

        # the per-DEVICE row budget binds on a mesh too; the factory
        # falls back to the plain packed sharded kernel when chunking
        # is off or unnecessary
        return self._cached_sharded(
            (
                plan,
                "mesh",
                staged.num_segments,
                staged.n_pad,
                chunk_rows_limit(),
                self._mesh_key(mesh),
            ),
            lambda: make_chunked_sharded_kernel(
                plan, mesh, staged.num_segments, staged.n_pad
            ),
        )

    def _skip_base_columns(
        self,
        request: BrokerRequest,
        live: Sequence[ImmutableSegment],
        raw_cols,
        gfwd_cols,
        hll_cols,
    ) -> set:
        """Columns the kernel reads ONLY through a role stream skip
        their base fwd/dict arrays: at 1B rows the dictId stream is the
        difference between fitting in HBM and not.  Filter leaves and
        selection outputs read base arrays, so those columns keep them.
        Shared by the staging path and the prewarm aval builder
        (engine/explain.py) — the two must agree bit-for-bit or a
        prewarmed executable never matches a serving launch."""
        if request.is_selection:
            return set()
        # filter leaves need base arrays on device — EXCEPT leaves
        # whose every use classifies docrange (the kernel compares
        # row ids against host-computed bounds, reading no column)
        filter_cols = (
            {n.column for n in request.filter.walk() if n.is_leaf}
            if request.filter is not None
            else set()
        ) - self._docrange_qualifying_cols(request, live)
        from pinot_tpu.engine.plan import _agg_kind

        # scalar/pair agg inputs OUTSIDE raw_cols (small dictionaries)
        # read dict[fwd] on device — their base arrays must stay
        gather_agg_cols = {
            a.column
            for a in request.aggregations
            if _agg_kind(a.base_function) in ("scalar", "pair")
            and a.column not in raw_cols
        }
        return (
            set(raw_cols) | set(gfwd_cols) | set(hll_cols)
        ) - filter_cols - gather_agg_cols

    # ------------------------------------------------------------------
    def _resolve_selection_columns(
        self, request: BrokerRequest, seg: ImmutableSegment
    ) -> List[str]:
        cols = request.selection.columns
        if not cols or cols == ["*"]:
            return list(seg.columns.keys())
        return list(cols)

    def _role_columns(
        self,
        request: BrokerRequest,
        live: Sequence[ImmutableSegment],
        ctx: Optional[TableContext] = None,
    ):
        """Columns to stage with role-specific arrays: aggregation
        inputs get raw value arrays, group-by/sort keys get global-id
        forward arrays (both avoid slow big-table gathers on device)."""
        seg = live[0]

        def big_card(c: str) -> bool:
            # raw_card_min() is 0 on accelerators (TPU gathers serialize
            # — see engine/config.py measurement); on CPU the narrow
            # fwd + dict-gather feed stands below the threshold.  The
            # staged dtype is sized by the table-wide max cardinality,
            # so the decision must be too.
            card = max(s.column(c).metadata.cardinality for s in live)
            return card > config.raw_card_min()

        def sv(c: str) -> bool:
            return c in seg.columns and seg.column(c).metadata.single_value

        from pinot_tpu.engine.plan import _agg_kind

        # only scalar/pair agg kernels read .raw (presence/hist/hll work
        # in dictId space)
        def numeric_any(c: str) -> bool:
            if c == "*" or c not in seg.columns:
                return False
            return seg.column(c).metadata.data_type.stored_type != DataType.STRING

        raw_cols = {
            a.column
            for a in request.aggregations
            if numeric_any(a.column)
            and big_card(a.column)
            and _agg_kind(a.base_function) in ("scalar", "pair")
        }
        gfwd_cols = set()
        if request.is_group_by:
            gfwd_cols.update(c for c in request.group_by.columns if sv(c))
        if request.is_selection:
            gfwd_cols.update(s.column for s in request.selection.sorts if sv(s.column))
        # presence/hist aggs (distinctcount, percentile) read global
        # value ids per row: stage them host-side (gfwd) so the kernel
        # streams instead of gathering a remap table on device (slow at
        # any cardinality on TPU — MICROBENCH_TPU.json).  Both kinds
        # stay on device at any cardinality (dense holders within the
        # budget, the sort-pairs path beyond it).
        gfwd_cols.update(
            a.column
            for a in request.aggregations
            if _agg_kind(a.base_function) in ("presence", "hist") and sv(a.column)
        )
        # HLL aggs: modest-cardinality SV columns lower to a presence
        # contraction over gfwd streams (plan.hll_lowers_to_presence —
        # registers depend only on the distinct value set); the rest
        # stream host-computed (register, rank) pairs
        from pinot_tpu.engine.plan import hll_lowers_to_presence

        hll_cols = set()
        for a in request.aggregations:
            if _agg_kind(a.base_function) == "hll" and sv(a.column):
                if hll_lowers_to_presence(request, ctx, a.column):
                    gfwd_cols.add(a.column)
                else:
                    hll_cols.add(a.column)
        return tuple(sorted(raw_cols)), tuple(sorted(gfwd_cols)), tuple(sorted(hll_cols))

    def _batch_spec(self, plan: StaticPlan, staged, q_np, seg_arrays):
        """BatchSpec for the lane micro-batching tier (PIMDAL-style
        cross-query amortization — engine/dispatch.py module
        docstring): same-StaticPlan dispatches over the same staged
        table stack their query inputs along a leading batch axis and
        execute as ONE vmapped launch reading the resident columns
        once.

        The key is (StaticPlan, staging token, input signature):
        literal-bucketed program identity (``a>5`` and ``a>999`` build
        the SAME StaticPlan — only their match tables/bounds differ) x
        resident-table identity x structural input identity.
        ``max_members`` keeps batch x rows under the per-dispatch row
        budget so batching can never blow the compile-time working set
        the chunked path exists to bound."""
        from pinot_tpu.engine.dispatch import BatchSpec
        from pinot_tpu.engine.kernel import chunk_rows_limit
        from pinot_tpu.engine.packing import batch_input_signature

        limit = chunk_rows_limit()
        rows = max(1, staged.num_segments * staged.n_pad)
        if limit:
            # the launch pads member count UP to a power of two, so the
            # cap must be the largest power of two whose padded batch
            # still fits the row budget — a plain floor-divide cap of 5
            # would pad to 8 and overshoot the budget by ~1.5x
            cap = limit // rows
            max_members = 1
            while max_members * 2 <= cap:
                max_members *= 2
        else:
            max_members = 0
        if max_members == 1:
            return None  # one batch member already fills the budget
        key = (plan, staged.token, batch_input_signature(q_np))

        def launch_batched(inputs_list):
            from pinot_tpu.engine.device import to_device_inputs
            from pinot_tpu.engine.kernel import make_packed_batched_table_kernel
            from pinot_tpu.engine.packing import stack_query_inputs

            bkernel = make_packed_batched_table_kernel(plan)
            # pad the member count to a power of two (repeat member 0 —
            # harmless extra lanes whose outputs are never sliced) so
            # compile count per plan is bounded at log2(BATCH_MAX)
            # distinct batch shapes instead of one per observed size
            b = len(inputs_list)
            b_pad = 1
            while b_pad < b:
                b_pad *= 2
            if b_pad > b:
                inputs_list = list(inputs_list) + [inputs_list[0]] * (b_pad - b)
            stacked = stack_query_inputs(inputs_list)
            # ONE stacked H2D upload for the whole batch (recorded by
            # to_device_inputs); the per-member device-resident input
            # cache is bypassed — literals differ per member by design
            qb = to_device_inputs(stacked)
            return bkernel.fetch, bkernel.dispatch(seg_arrays, qb)

        return BatchSpec(key, q_np, launch_batched, max_members=max_members)

    def _run_kernel(
        self, kernel, args, plan, staged, digest, block_ids, deadline,
        pdigest=None, cost: Optional[Dict[str, float]] = None, lane=None,
        batch_spec=None, exec_info: Optional[Dict[str, Any]] = None,
        analysis_args=None,
    ) -> Dict[str, Any]:
        """DISPATCH + output fetch.  Serial mode (no lane): launch and
        fetch inline, the pre-pipeline behavior.  Pipelined: the launch
        runs on the (shape-selected) device lane — coalesced with
        identical in-flight dispatches, or micro-batched with same-plan
        peers when ``batch_spec`` is set — and this worker blocks only
        when FINALIZE first reads the outputs (the packed D2H
        transfer).  ``args`` may be a zero-arg callable (batch-eligible
        dispatches defer their solo H2D upload into the launch itself);
        ``analysis_args`` is the host-shaped stand-in the cost-analysis
        helper lowers with in that case."""
        if lane is None:
            lane = self.lane
        cost_args = args if not callable(args) else analysis_args

        def launch():
            a = args() if callable(args) else args
            disp = getattr(kernel, "dispatch", None)
            if disp is not None:
                return kernel.fetch, disp(*a)
            return None, kernel(*a)  # raw jit: device arrays out

        t0 = time.perf_counter()
        coalesced = False
        if lane is None:
            fetch, handle = launch()
        else:
            # coalesce key: identical (plan, staged-table token, inputs
            # digest, block-id set) => identical device outputs.  The
            # token is process-unique (device.py), so a table re-staged
            # after GC can never alias an in-flight dispatch.
            bkey = (
                None
                if block_ids is None
                else (block_ids.shape, block_ids.tobytes())
            )
            from pinot_tpu.engine.packing import kernel_cost_analysis

            ticket = lane.submit(
                (plan, staged.token, digest, bkey),
                launch,
                deadline,
                plan_digest=pdigest,
                # static roofline numerator: flops/bytes per launch of
                # this compiled plan, resolved ONCE per digest on the
                # lane's async analysis thread (graceful None fallback)
                cost_provider=lambda: kernel_cost_analysis(kernel, cost_args),
                batch=batch_spec,
            )
            fetch, handle = ticket.result(deadline)
            # queue + coalesce wait only; the coalesced tag marks a
            # query that rode an identical in-flight dispatch
            coalesced = ticket.coalesced
            t0 = self._phase("laneWait", t0, coalesced=coalesced)
            if cost is not None and coalesced:
                cost["coalesceHits"] = cost.get("coalesceHits", 0) + 1
            bsize = int(getattr(ticket, "batch_size", 1) or 1)
            if exec_info is not None:
                exec_info["batchSize"] = bsize
            if cost is not None and bsize > 1:
                # this query rode a cross-query batched launch (its
                # literals stacked with bsize-1 same-plan peers)
                cost["batchHits"] = cost.get("batchHits", 0) + 1
        # exactly ONE waiter per dispatch is non-coalesced, so the
        # physical D2H copy is counted once no matter how many queries
        # rode the dispatch (coalesced waiters read the cached host copy)
        outs = fetch(handle, count_transfer=not coalesced) if fetch is not None else handle
        outs = {
            k: np.asarray(v)
            if not isinstance(v, tuple)
            else tuple(np.asarray(x) for x in v)
            for k, v in outs.items()
        }
        if fetch is None:
            # raw-jit path (mesh/chunked kernels): the np.asarray calls
            # above were the D2H transfers — the packed path counts its
            # own single buffer inside packing.fetch
            from pinot_tpu.engine.device import TRANSFERS

            if not coalesced:
                TRANSFERS.record_d2h(
                    sum(
                        x.nbytes
                        for v in outs.values()
                        for x in (v if isinstance(v, tuple) else (v,))
                    )
                )
        # planExec excludes lane queueing (timed above as laneWait): it
        # covers launch (serial mode) + the blocking packed D2H fetch,
        # so the per-stage timers on status() sum to wall time instead
        # of double-counting the wait inside planExec
        if cost is not None:
            # the cost vector's deviceMs is this same window: device
            # execution + the packed D2H fetch, not lane queueing
            cost["deviceMs"] = cost.get("deviceMs", 0.0) + round(
                (time.perf_counter() - t0) * 1000, 3
            )
        self._phase("planExec", t0)
        return outs

    def _inputs_digest(self, inputs: Dict[str, Any]) -> str:
        """Content digest of the numpy query-inputs pytree — one
        computation shared by the device-resident input cache and the
        lane's coalesce key."""
        import hashlib

        h = hashlib.blake2b(digest_size=16)
        leaves, _ = jax.tree_util.tree_flatten(inputs)
        for leaf in leaves:
            if isinstance(leaf, np.ndarray):
                part = str((leaf.shape, str(leaf.dtype))).encode() + leaf.tobytes()
            else:
                part = repr(leaf).encode()
            # length-prefix each leaf so adjacent contributions can't
            # re-split into the same byte stream ((1, 23) vs (12, 3))
            h.update(len(part).to_bytes(8, "little"))
            h.update(part)
        return h.hexdigest()

    def _to_device_inputs(
        self,
        inputs: Dict[str, Any],
        plan=None,
        digest: Optional[str] = None,
        cost: Optional[Dict[str, float]] = None,
        sharding=None,
    ) -> Dict[str, Any]:
        """Device-resident query-inputs cache: a repeated query (same
        plan, same literal tables) reuses the arrays already in HBM
        instead of re-uploading — on a tunneled chip every upload pays
        a host->device round trip.  Keyed by (plan, content digest,
        placement), so realtime watermark changes, different literals,
        or a different chip group miss safely."""
        from pinot_tpu.engine.device import placement_key, to_device_inputs

        if plan is None:
            return to_device_inputs(inputs, sharding=sharding)
        if digest is None:
            digest = self._inputs_digest(inputs)
        key = (plan, digest, placement_key(sharding))
        with self._qinput_cache_lock:
            cached = self._qinput_cache.get(key)
            if cached is not None:
                self._qinput_cache.move_to_end(key)
                if cost is not None:
                    cost["qinputCacheHits"] = cost.get("qinputCacheHits", 0) + 1
                return cached[0]
        dev = to_device_inputs(inputs, sharding=sharding)
        # Evict by HBM bytes, not entry count: one entry can hold
        # per-segment match tables of S x card_pad, so 128 entries of a
        # high-cardinality workload would pin multiple GB (ADVICE r3).
        nbytes = sum(
            getattr(leaf, "nbytes", 0)
            for leaf in jax.tree_util.tree_flatten(dev)[0]
        )
        from pinot_tpu.engine.config import qinput_cache_budget_bytes

        budget = qinput_cache_budget_bytes()
        if nbytes == 0 or nbytes > budget // 4:
            # zero-byte entries would never be evicted by byte pressure;
            # oversized ones would churn the whole cache for one query
            return dev
        with self._qinput_cache_lock:
            if key not in self._qinput_cache:
                self._qinput_cache[key] = (dev, nbytes)
                self._qinput_cache_bytes += nbytes
            # bytes bound HBM; the entry cap bounds per-entry host/device
            # allocator overhead that logical nbytes doesn't see
            while self._qinput_cache and (
                self._qinput_cache_bytes > budget or len(self._qinput_cache) > 128
            ):
                _, (_, old_bytes) = self._qinput_cache.popitem(last=False)
                self._qinput_cache_bytes -= old_bytes
        return dev

    def _empty_result(self, request: BrokerRequest, total_docs: int) -> IntermediateResult:
        res = IntermediateResult(total_docs=total_docs)
        if request.is_aggregation and not request.is_group_by:
            from pinot_tpu.engine.results import make_partial

            res.aggregations = [make_partial(a.base_function) for a in request.aggregations]
        elif request.is_group_by:
            res.groups = {}
        else:
            res.selection_rows = []
        return res

    # ------------------------------------------------------------------
    def _finalize(
        self,
        request: BrokerRequest,
        plan: StaticPlan,
        ctx: TableContext,
        staged: StagedTable,
        live: List[ImmutableSegment],
        outs: Dict[str, Any],
        total_docs: int,
        sel_columns: Optional[List[str]],
    ) -> IntermediateResult:
        matched = int(outs["num_docs"])
        res = IntermediateResult(
            num_docs_scanned=matched,
            total_docs=total_docs,
            num_segments_queried=len(live),
            num_entries_scanned_in_filter=len(plan.leaves) * staged.total_docs,
            num_entries_scanned_post_filter=matched * max(1, len(plan.aggs)),
        )

        if plan.group_by is not None:
            res.groups = self._finalize_groups(request, plan, ctx, outs)
        elif plan.aggs:
            res.aggregations = [
                self._scalar_partial(agg, outs[f"agg_{i}"], ctx)
                for i, agg in enumerate(plan.aggs)
            ]
        if plan.selection is not None:
            res.selection_rows = self._finalize_selection(
                request, plan, live, outs, sel_columns
            )
            res.selection_columns = sel_columns
        return res

    def _scalar_partial(self, agg, state, ctx: TableContext) -> AggPartial:
        base = agg.base
        if base == "count":
            return CountPartial(float(state))
        if base == "sum":
            return SumPartial(float(state))
        if base == "min":
            return MinPartial(float(state))
        if base == "max":
            return MaxPartial(float(state))
        if base == "avg":
            return AvgPartial(float(state[0]), float(state[1]))
        if base == "minmaxrange":
            return MinMaxRangePartial(float(state[0]), float(state[1]))
        if agg.kind == "presence":
            gdict = ctx.column(agg.column).global_dict
            if agg.sort_pairs:
                ids = np.asarray(state[1])[: int(state[3])]
            else:
                ids = np.nonzero(np.asarray(state))[0]
            if agg.hll_from_presence:
                return HllPartial(_regs_from_value_gids(ctx, agg.column, ids))
            ids = np.asarray(ids, dtype=np.int64)
            ids = ids[ids < gdict.cardinality]
            return DistinctPartial(gdict.value_array()[ids])
        if agg.kind == "hist":
            gdict = ctx.column(agg.column).global_dict
            p = int(base[len("percentileest"):]) if base.startswith("percentileest") else int(base[len("percentile"):])
            if agg.sort_pairs:
                ps = _PairsState(state, 1)
                return _hist_partial(gdict, *ps.gid_counts_for(0), p)
            h = np.asarray(state)
            ids = np.nonzero(h)[0]
            counts = {
                float(gdict.get(int(i))): int(h[i]) for i in ids if i < gdict.cardinality
            }
            return HistogramPartial(counts, percentile=p)
        if agg.kind == "hll":
            return HllPartial(np.asarray(state).astype(np.uint8))
        raise AssertionError(agg)

    # ------------------------------------------------------------------
    def _finalize_groups(
        self, request: BrokerRequest, plan: StaticPlan, ctx: TableContext, outs
    ) -> Dict[Tuple[str, ...], List[AggPartial]]:
        gb = plan.group_by
        presence = np.asarray(outs["gb_presence"]).astype(bool)
        keys = np.nonzero(presence)[0]
        if keys.size == 0:
            return {}

        # sort-dedup distinct states arrive as compacted (slot, gid)
        # pair buffers; index them once per agg for the per-group reads
        for i, agg in enumerate(plan.aggs):
            if agg.sort_pairs and not isinstance(outs[f"gb_{i}"], _PairsState):
                outs[f"gb_{i}"] = _PairsState(outs[f"gb_{i}"], gb.capacity)

        # Trim candidate groups per aggregation (reference trims to
        # topN*5 per server, MCombineGroupByOperator.java:216); the
        # union over aggregations (incl. capped boundary ties) is kept
        # so merges stay consistent.
        from pinot_tpu.engine.results import trim_group_candidates

        if keys.size > max(gb.top_n * 5, 100):
            keep = trim_group_candidates(
                [
                    self._group_order_values(agg, outs[f"gb_{i}"], keys, ctx)
                    for i, agg in enumerate(plan.aggs)
                ],
                [group_sort_ascending(agg.func) for agg in plan.aggs],
                gb.top_n,
                keys.size,
            )
            keys = keys[keep]

        # decompose mixed-radix keys -> per-column global ids
        gids = []
        rem = keys.copy()
        for gcard in reversed(gb.gcards):
            gids.append(rem % gcard)
            rem = rem // gcard
        gids.reverse()

        gdicts = [ctx.column(c).global_dict for c in gb.columns]
        key_tuples: List[Tuple[str, ...]] = []
        for row in range(keys.size):
            key_tuples.append(
                tuple(
                    render_value(gdicts[j].stored_type, gdicts[j].get(int(gids[j][row])))
                    for j in range(len(gb.columns))
                )
            )

        groups: Dict[Tuple[str, ...], List[AggPartial]] = {}
        for row, ktup in enumerate(key_tuples):
            k = int(keys[row])
            partials: List[AggPartial] = []
            for i, agg in enumerate(plan.aggs):
                partials.append(self._group_partial(agg, outs[f"gb_{i}"], k, ctx))
            groups[ktup] = partials
        return groups

    def _group_order_values(self, agg, state, keys: np.ndarray, ctx: TableContext) -> np.ndarray:
        """Exact finalized per-group values, used for trim ordering."""
        base = agg.base
        if base in ("count", "sum", "min", "max"):
            return np.asarray(state)[keys]
        if base == "avg":
            s = np.asarray(state[0])[keys]
            c = np.asarray(state[1])[keys]
            with np.errstate(divide="ignore", invalid="ignore"):
                return np.where(c > 0, s / np.maximum(c, 1), -np.inf)
        if base == "minmaxrange":
            return np.asarray(state[1])[keys] - np.asarray(state[0])[keys]
        if agg.kind == "presence":
            if agg.hll_from_presence:
                # never sort_pairs: hll_lowers_to_presence admits only
                # shapes whose dense holder fits (plan.py asserts this)
                from pinot_tpu.engine import hll as hll_mod

                occ = np.asarray(state)[keys]  # [K, gcard_pad]
                r, c = np.nonzero(occ)
                regs = _regs_from_value_gids(ctx, agg.column, c, r, keys.size)
                return np.asarray(
                    hll_mod.estimate_from_registers(regs), dtype=np.float64
                )
            if agg.sort_pairs:
                return state.counts[keys]
            return np.asarray(state)[keys].sum(axis=1).astype(float)
        if agg.kind == "hist":
            # exact percentile from histogram rows, vectorized:
            # sorted[int(n * p/100)] per group (PercentileUtil.java:50)
            p = int(base[len("percentileest"):]) if base.startswith("percentileest") else int(base[len("percentile"):])
            gdict = ctx.column(agg.column).global_dict
            vals = np.asarray(gdict.values, dtype=np.float64)
            if agg.sort_pairs:
                return state.percentiles_for(keys, p, vals)
            h = np.asarray(state)[keys]  # [K, gcard_pad]
            cs = np.cumsum(h, axis=1)
            n = cs[:, -1]
            idx = np.minimum((n * p / 100.0).astype(np.int64), np.maximum(n - 1, 0))
            pos = (cs <= idx[:, None]).sum(axis=1)
            pos = np.minimum(pos, vals.size - 1)
            return np.where(n > 0, vals[pos], -np.inf)
        if agg.kind == "hll":
            from pinot_tpu.engine import hll as hll_mod

            if agg.sort_pairs:
                # vectorized over ALL requested keys: one batched decode
                # over the concatenated per-slot gid slices
                gids, rows = state.gids_rows_for(keys)
                regs = _regs_from_gids(gids, rows, keys.size)
                ests = hll_mod.estimate_from_registers(regs)
            else:
                ests = hll_mod.estimate_from_registers(np.asarray(state)[keys])
            return np.asarray(ests, dtype=np.float64)
        raise AssertionError(agg)

    def _group_partial(self, agg, state, key: int, ctx: TableContext) -> AggPartial:
        base = agg.base
        if base == "count":
            return CountPartial(float(np.asarray(state)[key]))
        if base == "sum":
            return SumPartial(float(np.asarray(state)[key]))
        if base == "min":
            return MinPartial(float(np.asarray(state)[key]))
        if base == "max":
            return MaxPartial(float(np.asarray(state)[key]))
        if base == "avg":
            return AvgPartial(float(np.asarray(state[0])[key]), float(np.asarray(state[1])[key]))
        if base == "minmaxrange":
            return MinMaxRangePartial(float(np.asarray(state[0])[key]), float(np.asarray(state[1])[key]))
        if agg.kind == "presence":
            gdict = ctx.column(agg.column).global_dict
            if agg.sort_pairs:
                ids = state.gids_for(key)
            else:
                row = np.asarray(state)[key]
                ids = np.nonzero(row)[0]
            if agg.hll_from_presence:
                return HllPartial(_regs_from_value_gids(ctx, agg.column, ids))
            ids = np.asarray(ids, dtype=np.int64)
            ids = ids[ids < gdict.cardinality]
            return DistinctPartial(gdict.value_array()[ids])
        if agg.kind == "hist":
            gdict = ctx.column(agg.column).global_dict
            p = int(base[len("percentileest"):]) if base.startswith("percentileest") else int(base[len("percentile"):])
            if agg.sort_pairs:
                return _hist_partial(gdict, *state.gid_counts_for(key), p)
            row = np.asarray(state)[key]
            ids = np.nonzero(row)[0]
            counts = {float(gdict.get(int(i))): int(row[i]) for i in ids if i < gdict.cardinality}
            return HistogramPartial(counts, percentile=p)
        if agg.kind == "hll":
            if agg.sort_pairs:
                return HllPartial(_regs_from_gids(state.gids_for(key)))
            return HllPartial(np.asarray(state)[key].astype(np.uint8))
        raise AssertionError(agg)

    # ------------------------------------------------------------------
    # distributed joins (engine/join.py): device hash-join under the
    # SAME self-healing contract as scans — classify, retry once on
    # transients, quarantine the join-plan digest, heal to the exact
    # host join.  A poisoned join plan heals exactly like a poisoned
    # scan plan (shared poison map, shared heal.* counters).
    # ------------------------------------------------------------------
    def execute_join(
        self,
        request: BrokerRequest,
        build,
        probe,
        deadline: Optional[float] = None,
    ) -> IntermediateResult:
        from pinot_tpu.engine import join as join_mod

        t0 = time.perf_counter()
        side_bytes = build.nbytes() + probe.nbytes()
        try:
            planned = join_mod.build_join_plan(request, build, probe)
        except join_mod.JoinValidationError:
            raise  # typed client error, not a healable fault
        except Exception as e:
            # host-side packing is part of the device section's promise:
            # a packing bug degrades to the exact host join, it never
            # takes the query down
            self._heal_mark("hostFailovers", reason=f"joinPack: {e}"[:200])
            planned = None
        if planned is None:
            res = join_mod.host_join(request, build, probe)
            res.add_cost(buildRows=build.n, probeRows=probe.n)
            self._phase("hostPath", t0)
            return res
        plan, inputs, meta = planned
        jdigest = join_mod.join_plan_digest(plan)

        from pinot_tpu.engine.dispatch import (
            DeviceExecutionError,
            LaneClosedError,
            classify_device_error,
        )
        from pinot_tpu.server.scheduler import QueryAbandonedError

        poison_key = (jdigest, "join")
        sel = self.lane_selection(request)
        lane = sel.lane if sel is not None else self.lane
        if self._is_poisoned(poison_key):
            self._heal_mark("poisonSkips")
            res = join_mod.host_join(request, build, probe)
            res.add_cost(buildRows=build.n, probeRows=probe.n)
            self._phase("hostFailover", t0)
            return res

        last: Optional[DeviceExecutionError] = None
        for attempt in (0, 1):
            if attempt:
                if last is None or not last.retryable:
                    break
                self._heal_mark("deviceRetries")
            try:
                return self._join_device_section(
                    request, plan, inputs, meta, build, probe, deadline,
                    jdigest, lane, sel, side_bytes, t0,
                )
            except (QueryAbandonedError, LaneClosedError, TimeoutError):
                raise
            except Exception as e:
                last = classify_device_error(e)
                self._heal_mark(
                    "deviceFailures", retryable=last.retryable, error=str(last)[:200]
                )
        self._poison(poison_key, str(last))
        self._heal_mark("hostFailovers", reason=str(last)[:200])
        t0 = time.perf_counter()
        res = join_mod.host_join(request, build, probe)
        res.add_cost(buildRows=build.n, probeRows=probe.n)
        self._phase("hostFailover", t0)
        return res

    def _join_device_section(
        self, request, plan, inputs, meta, build, probe, deadline,
        jdigest, lane, sel, side_bytes, t0,
    ) -> IntermediateResult:
        from pinot_tpu.engine import join as join_mod
        from pinot_tpu.engine.kernel import make_join_kernel

        kernel = make_join_kernel(plan)
        digest = self._inputs_digest(inputs)
        cost: Dict[str, float] = {}

        class _JoinToken:
            # stands in for the staged-table token in _run_kernel's
            # coalesce key: join inputs are content-digested, so the
            # constant token can never alias distinct data generations
            token = ("join",)
            num_segments = 0
            n_pad = 0

        dev_bytes = sum(a.nbytes for a in inputs.values())
        # joins are deliberately EXCLUDED from the micro-batching tier
        # (batch_spec=None): stacking distinct join payloads has no
        # shared-column amortization to win, and the byte-identity
        # proof for batched joins hasn't been done (ISSUE 14 guard)
        outs = self._run_kernel(
            kernel, (inputs,), plan, _JoinToken(), digest, None, deadline,
            pdigest=jdigest, cost=cost, lane=lane, batch_spec=None,
        )
        if not bool(outs.get("join_ok", True)):
            # the parallel-claim build ran out of rounds (cannot happen
            # with unique keys and a half-full table, but a wrong
            # answer must never ship): heal to the exact host join
            raise RuntimeError("join hash-table build did not converge")
        t_fin = time.perf_counter()
        result = join_mod.finalize_device_join(
            request, plan, meta, build, probe, outs
        )
        result.add_cost(
            buildRows=build.n,
            probeRows=probe.n,
            bytesScanned=side_bytes,
            deviceBytes=dev_bytes,
            **cost,
        )
        result._device_digest = jdigest
        result._lane_index = sel.index if sel is not None else 0
        result._batch_size = 1
        self._phase("finalize", t_fin)
        return result

    # ------------------------------------------------------------------
    def _finalize_selection(
        self,
        request: BrokerRequest,
        plan: StaticPlan,
        live: List[ImmutableSegment],
        outs,
        sel_columns: List[str],
    ) -> List[Tuple[list, list]]:
        sel = request.selection
        docids = np.asarray(outs["sel_docids"])  # [S, k]
        valid = np.asarray(outs["sel_valid"])  # [S, k]
        rows: List[Tuple[list, list]] = []
        for si, seg in enumerate(live):
            for j in range(docids.shape[1]):
                if not valid[si, j]:
                    continue
                doc = int(docids[si, j])
                if doc >= seg.num_docs:
                    continue
                full = seg.row(doc)
                sort_vals = []
                for s in sel.sorts:
                    v = full[s.column]
                    if isinstance(v, list):
                        v = v[0] if v else None
                    sort_vals.append(v)
                rows.append((sort_vals, [full[c] for c in sel_columns]))
        return rows
