"""Three-tier staged-table residency: HBM (hot) <-> host RAM (warm) <->
disk (cold), driven by workload heat.

PIMDAL's thesis (2504.01948) is that data MOVEMENT, not compute, is the
bottleneck to manage — and at fleet scale (100+ tables, PR 15) the
working set simply does not fit HBM.  This module turns the staging
cache's old all-or-nothing size-cap clear into an explicit residency
model:

  hot   — device arrays live in ``device._stage_cache`` / the staging
          ledger; queries launch against them directly.
  warm  — the SAME packed layout snapshotted to host numpy arrays
          (one D2H per array); promotion back to HBM is a pure
          device_put, zero re-encode.
  cold  — the warm snapshot spooled to disk as one ``.npz`` in the
          packed layout (one read, zero re-encode); column/shape
          metadata stays in RAM so promotion needs no segment access.

Heat is the instrument the PR 10 ledger and ``/debug/plans`` already
suggested: an exponentially-decayed touch counter per resident table,
weighted by its reload cost (``tiercost.h2d_cost_ns``) — frequency x
cost, so a rarely-hit giant outranks a hot midget only when re-loading
the giant would actually hurt more.

Correctness invariants:

- Demotion NEVER invalidates an in-flight launch.  The staging token is
  process-unique, Python references keep a demoted table's device
  arrays alive until its last launch finishes, and queries additionally
  ``pin()`` their staged table (refcount by token) so the victim picker
  skips anything mid-flight — demotion can free the HBM of a table a
  query needs *next*, never one it is using *now*.
- Promotion mints a NEW staging token (``restore_staged`` builds a
  fresh StagedTable), so the PR 3 alias-safety invariant holds across a
  demote -> promote round trip: an old token can never match a new
  resident.
- Tier transitions are ledger-exact: demote drops the ledger entry
  (visible as an eviction), promote re-measures and re-registers, and
  the warm/cold byte totals are measured off the actual numpy arrays.

Caps (read fresh per call, junk-safe — the tiercost knob idiom):

  PINOT_TPU_HBM_CAP_BYTES       hot-tier byte cap; 0/unset = uncapped.
  PINOT_TPU_HOST_CAP_BYTES      warm-tier byte cap; 0/unset = warm
                                snapshots never spill to disk by bytes.
  PINOT_TPU_STAGE_CACHE_ENTRIES hot entry-count cap (default 32 — the
                                pre-residency size cap, now a demotion
                                threshold instead of a clear-all).
  PINOT_TPU_RESIDENCY_DIR       cold spool directory (default: a
                                process-lifetime temp dir).

Lock order (deadlock discipline): ``device._cache_guard`` is always
acquired BEFORE ``RESIDENCY._lock``; the ledger's internal lock is a
leaf.  Demotion never takes per-key staging locks.
"""
from __future__ import annotations

import atexit
import itertools
import os
import queue
import shutil
import tempfile
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np


def _int_knob(env: str, default: int) -> int:
    raw = os.environ.get(env)
    if raw:
        try:
            return int(float(raw))
        except ValueError:
            pass
    return default


def hbm_cap_bytes() -> int:
    """Hot-tier (HBM) byte cap; 0 = uncapped (the pre-residency
    behavior, minus the entry-count cap below)."""
    return _int_knob("PINOT_TPU_HBM_CAP_BYTES", 0)


def host_cap_bytes() -> int:
    """Warm-tier (host RAM) byte cap; 0 = warm snapshots stay in RAM."""
    return _int_knob("PINOT_TPU_HOST_CAP_BYTES", 0)


def stage_cache_entry_cap() -> int:
    """Hot entry-count cap — the old 32-entry size cap, kept as a
    demotion threshold so unbounded distinct tables still can't pin
    unbounded HBM even with no byte cap configured."""
    return _int_knob("PINOT_TPU_STAGE_CACHE_ENTRIES", 32)


# ---------------------------------------------------------------------------
# Packed-layout snapshot / restore (the zero-re-encode contract)
# ---------------------------------------------------------------------------


def snapshot_staged(st) -> Tuple[Dict[str, Any], int]:
    """Snapshot a StagedTable's device arrays to host numpy in the SAME
    packed layout.  Returns (snapshot, host bytes).  The snapshot holds
    everything ``restore_staged`` needs — no segment objects, so a cold
    table promotes without touching the segment store."""
    from pinot_tpu.engine.device import _ROLE_ATTRS

    nbytes = 0
    nd = np.asarray(st.num_docs_arr)
    nbytes += int(nd.nbytes)
    columns: Dict[str, Dict[str, Any]] = {}
    for name, sc in st.columns.items():
        arrays: Dict[str, np.ndarray] = {}
        for attr, _role in _ROLE_ATTRS:
            arr = getattr(sc, attr)
            if arr is None:
                continue
            host = np.asarray(arr)
            arrays[attr] = host
            nbytes += int(host.nbytes)
        columns[name] = {
            "meta": {
                "stored_type": sc.stored_type,
                "single_value": sc.single_value,
                "card_pad": sc.card_pad,
                "mv_pad": sc.mv_pad,
                "cards": sc.cards,
                "bsi_width": sc.bsi_width,
                "bsiv_width": sc.bsiv_width,
                "bsiv_min": sc.bsiv_min,
            },
            "arrays": arrays,
        }
    snap = {
        "segment_names": st.segment_names,
        "num_segments": st.num_segments,
        "n_pad": st.n_pad,
        "num_docs": st.num_docs,
        "num_docs_arr": nd,
        "columns": columns,
    }
    return snap, nbytes


def restore_staged(snap: Dict[str, Any]):
    """Rebuild a hot StagedTable from a warm snapshot: one device_put
    per array, zero re-encode.  Mints a NEW staging token (dataclass
    default), so the promoted table can never alias a launch that was
    in flight against the demoted one."""
    import jax.numpy as jnp

    from pinot_tpu.engine.device import StagedColumn, StagedTable

    st = StagedTable(
        segment_names=tuple(snap["segment_names"]),
        num_segments=int(snap["num_segments"]),
        n_pad=int(snap["n_pad"]),
        num_docs=tuple(snap["num_docs"]),
        num_docs_arr=jnp.asarray(snap["num_docs_arr"]),
    )
    for name, col in snap["columns"].items():
        meta = col["meta"]
        sc = StagedColumn(
            name=name,
            stored_type=meta["stored_type"],
            single_value=bool(meta["single_value"]),
            card_pad=int(meta["card_pad"]),
            mv_pad=int(meta["mv_pad"]),
            cards=tuple(meta["cards"]),
            bsi_width=int(meta["bsi_width"]),
            bsiv_width=int(meta["bsiv_width"]),
            bsiv_min=meta["bsiv_min"],
        )
        for attr, host in col["arrays"].items():
            setattr(sc, attr, jnp.asarray(host))
        st.columns[name] = sc
    return st


# ---------------------------------------------------------------------------
# The residency manager
# ---------------------------------------------------------------------------


@dataclass
class _Entry:
    key: Tuple
    table: str
    segments: Tuple[str, ...]
    state: str  # "hot" | "warm" | "cold"
    nbytes: int
    demotable: bool
    heat: float = 1.0
    last_touch: float = field(default_factory=time.monotonic)
    staged: Any = None  # StagedTable while hot (identity check on demote)
    snap: Optional[Dict[str, Any]] = None  # packed snapshot while warm
    path: Optional[str] = None  # .npz spool file while cold
    meta: Optional[Dict[str, Any]] = None  # shape/column meta while cold


_COUNTER_NAMES = (
    "demotions",  # hot -> warm
    "promotions",  # warm/cold -> hot
    "coldDemotions",  # warm -> cold (disk spill)
    "coldLoads",  # cold -> warm (disk read, promotion or prefetch)
    "coldDrops",  # spool unwritable: entry dropped instead of spilled
    "pressureDemotions",  # demotions forced by an OOM heal, not a cap
    "capEvictions",  # non-demotable (sharded) entries dropped at cap
    "prefetches",  # async cold -> warm lifts ahead of dispatch
    "demotedBytes",
    "promotedBytes",
)


class ResidencyManager:
    """Process-global tier state for staged tables (one per process,
    like the staging cache it manages)."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._entries: Dict[Tuple, _Entry] = {}
        self._pins: Dict[int, int] = {}  # staging token -> refcount
        self._token_keys: Dict[int, Tuple] = {}  # hot token -> cache key
        self._dir: Optional[str] = None
        self._dir_owned = False
        self._file_seq = itertools.count()
        self.counters: Dict[str, int] = {n: 0 for n in _COUNTER_NAMES}
        # async promotion worker (cold -> warm ahead of lane dispatch):
        # lazily started, daemon, swallows I/O errors (prefetch is an
        # optimization — the synchronous path stays correct without it)
        self._prefetch_q: "queue.Queue[Tuple]" = queue.Queue()
        self._prefetch_thread: Optional[threading.Thread] = None

    # -- pins (in-flight queries) -------------------------------------
    def pin(self, token: int) -> None:
        with self._lock:
            self._pins[token] = self._pins.get(token, 0) + 1

    def unpin(self, token: int) -> None:
        with self._lock:
            n = self._pins.get(token, 0) - 1
            if n > 0:
                self._pins[token] = n
            else:
                self._pins.pop(token, None)

    def pin_count(self, token: int) -> int:
        with self._lock:
            return self._pins.get(token, 0)

    # -- heat -----------------------------------------------------------
    def _halflife_s(self) -> float:
        from pinot_tpu.engine import tiercost

        return tiercost.residency_halflife_s()

    def _decayed_heat(self, e: _Entry, now: float) -> float:
        hl = max(1e-3, self._halflife_s())
        return e.heat * (0.5 ** (max(0.0, now - e.last_touch) / hl))

    def _score(self, e: _Entry, now: float) -> float:
        """Victim ordering: decayed touch frequency x reload cost —
        the /debug/plans frequency-x-cost shape applied to residency.
        Lowest score = coldest = first demoted."""
        from pinot_tpu.engine import tiercost

        return self._decayed_heat(e, now) * tiercost.h2d_cost_ns(
            max(1, e.nbytes)
        )

    def _touch_locked(self, e: _Entry, weight: float = 1.0) -> None:
        now = time.monotonic()
        e.heat = self._decayed_heat(e, now) + weight
        e.last_touch = now

    # -- registration (called by device.get_staged) --------------------
    def note_hot(
        self,
        key: Tuple,
        staged,
        table: str,
        nbytes: int,
        demotable: bool,
        promoted: bool,
    ) -> None:
        """A table just became HBM-resident (cold stage or promotion).
        Caller holds ``device._cache_guard``."""
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                e = _Entry(
                    key=key,
                    table=table,
                    segments=tuple(staged.segment_names),
                    state="hot",
                    nbytes=int(nbytes),
                    demotable=demotable,
                )
                self._entries[key] = e
            else:
                self._remove_payload_locked(e)
                e.state, e.nbytes, e.demotable = "hot", int(nbytes), demotable
                self._touch_locked(e)
            e.staged = staged
            self._token_keys[staged.token] = key
            if promoted:
                self.counters["promotions"] += 1
                self.counters["promotedBytes"] += int(nbytes)

    def touch(self, key: Tuple) -> None:
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                self._touch_locked(e)

    def set_bytes(self, key: Tuple, nbytes: int) -> None:
        """Role augmentation re-measured a hot table."""
        with self._lock:
            e = self._entries.get(key)
            if e is not None and e.state == "hot":
                e.nbytes = int(nbytes)

    def take_resident(self, key: Tuple) -> Optional[Dict[str, Any]]:
        """Pop the warm/cold payload for promotion (caller holds the
        per-key staging lock, so nobody else promotes this key
        concurrently).  Returns the packed snapshot, or None if the key
        has no resident copy."""
        with self._lock:
            e = self._entries.get(key)
            if e is None or e.state == "hot":
                return None
            if e.state == "warm":
                return e.snap
            return self._load_cold_locked(e)

    def drop_key(self, key: Tuple) -> None:
        """Entry removed entirely (quarantine eviction / cache clear):
        a warm or cold copy must NOT survive — a later re-load of the
        same segments mints new staging tokens and can never produce
        this key again, so any retained payload would be dead weight."""
        with self._lock:
            e = self._entries.pop(key, None)
            if e is not None:
                self._remove_payload_locked(e)
                if e.staged is not None:
                    self._token_keys.pop(e.staged.token, None)

    def drop_segment(self, segment_name: str) -> int:
        """Drop every entry (any tier) containing the segment — the
        quarantine path's residency hygiene."""
        with self._lock:
            victims = [
                k for k, e in self._entries.items() if segment_name in e.segments
            ]
            for k in victims:
                self.drop_key(k)
            return len(victims)

    # -- demotion / enforcement ----------------------------------------
    def enforce(self, exclude_tokens: Sequence[int] = ()) -> int:
        """Demote until the hot tier fits its caps (byte cap + entry
        cap).  Returns HBM bytes freed.  Pinned and excluded tables are
        never victims, so the hot set a query is actively using can
        exceed the cap — the cap bounds *idle* residency, not
        correctness."""
        from pinot_tpu.engine import device as dev

        freed = 0
        exclude = set(exclude_tokens)
        cap = hbm_cap_bytes()
        entry_cap = stage_cache_entry_cap()
        while True:
            with dev._cache_guard:
                with self._lock:
                    hot = [e for e in self._entries.values() if e.state == "hot"]
                    hot_bytes = sum(e.nbytes for e in hot)
                    over = (cap > 0 and hot_bytes > cap) or (
                        entry_cap > 0 and len(dev._stage_cache) > entry_cap
                    )
                    if not over:
                        break
                    victim = self._pick_victim_locked(hot, exclude)
                    if victim is None:
                        break  # everything hot is pinned/excluded
                    freed += self._demote_locked(victim, dev)
        self._enforce_warm_cap()
        return freed

    def demote_for_pressure(
        self, exclude_tokens: Sequence[int] = (), min_bytes: int = 1
    ) -> int:
        """OOM heal hook: the device just refused an allocation, so free
        the coldest unpinned residents regardless of the configured cap
        (the cap clearly overestimates what actually fits).  Returns
        bytes freed (0 = nothing demotable — the caller's retry will
        fail over to the host path)."""
        from pinot_tpu.engine import device as dev

        freed = 0
        exclude = set(exclude_tokens)
        while freed < max(1, min_bytes):
            with dev._cache_guard:
                with self._lock:
                    hot = [e for e in self._entries.values() if e.state == "hot"]
                    victim = self._pick_victim_locked(hot, exclude)
                    if victim is None:
                        break
                    freed += self._demote_locked(victim, dev)
                    self.counters["pressureDemotions"] += 1
        self._enforce_warm_cap()
        return freed

    def _pick_victim_locked(
        self, hot: List[_Entry], exclude: set
    ) -> Optional[_Entry]:
        now = time.monotonic()
        best: Optional[_Entry] = None
        best_score = 0.0
        for e in hot:
            tok = e.staged.token if e.staged is not None else None
            if tok is None or tok in exclude or self._pins.get(tok, 0) > 0:
                continue
            score = self._score(e, now)
            if not e.demotable:
                # sharded placements have no single-device snapshot
                # path; they remain drop-only, ranked after every
                # demotable entry so data is preferentially preserved
                score += 1e18
            if best is None or score < best_score:
                best, best_score = e, score
        return best

    def _demote_locked(self, e: _Entry, dev) -> int:
        """hot -> warm (or outright drop for non-demotable entries).
        Caller holds ``dev._cache_guard`` + ``self._lock``."""
        st = dev._stage_cache.get(e.key)
        if st is not None and st is e.staged:
            dev._stage_cache.pop(e.key, None)
        hot_bytes = int(e.nbytes)
        staged = e.staged
        if staged is not None:
            dev.LEDGER.drop(staged)
            self._token_keys.pop(staged.token, None)
        e.staged = None
        if not e.demotable or staged is None:
            self._entries.pop(e.key, None)
            self.counters["capEvictions"] += 1
            return hot_bytes
        snap, host_bytes = snapshot_staged(staged)
        dev.TRANSFERS.record_d2h(host_bytes)
        e.snap, e.state, e.nbytes = snap, "warm", host_bytes
        self.counters["demotions"] += 1
        self.counters["demotedBytes"] += host_bytes
        return hot_bytes

    def _enforce_warm_cap(self) -> None:
        """Spill coldest warm snapshots to disk while over the host
        byte cap.  Disk-unwritable degrades to dropping the entry (the
        segments still exist — a future query re-stages from source)."""
        cap = host_cap_bytes()
        if cap <= 0:
            return
        with self._lock:
            while True:
                warm = [e for e in self._entries.values() if e.state == "warm"]
                if sum(e.nbytes for e in warm) <= cap or not warm:
                    return
                now = time.monotonic()
                victim = min(warm, key=lambda e: self._score(e, now))
                self._spill_locked(victim)

    def _spool_dir(self) -> Optional[str]:
        if self._dir is None:
            configured = os.environ.get("PINOT_TPU_RESIDENCY_DIR")
            try:
                if configured:
                    os.makedirs(configured, exist_ok=True)
                    self._dir = configured
                else:
                    self._dir = tempfile.mkdtemp(prefix="pinot_tpu_resid_")
                    self._dir_owned = True
                    atexit.register(
                        shutil.rmtree, self._dir, ignore_errors=True
                    )
            except OSError:
                self._dir = None
        return self._dir

    def _spill_locked(self, e: _Entry) -> None:
        """warm -> cold: arrays to one .npz in the packed layout;
        shape/column metadata stays in RAM so promotion never touches
        the segment store."""
        snap = e.snap
        d = self._spool_dir()
        if snap is None or d is None:
            self._entries.pop(e.key, None)
            self.counters["coldDrops"] += 1
            return
        arrays: Dict[str, np.ndarray] = {"nd:num_docs_arr": snap["num_docs_arr"]}
        order = sorted(snap["columns"])
        meta = {
            "segment_names": snap["segment_names"],
            "num_segments": snap["num_segments"],
            "n_pad": snap["n_pad"],
            "num_docs": snap["num_docs"],
            "column_order": order,
            "column_meta": {n: snap["columns"][n]["meta"] for n in order},
            "column_attrs": {
                n: sorted(snap["columns"][n]["arrays"]) for n in order
            },
        }
        for ci, name in enumerate(order):
            for attr, arr in snap["columns"][name]["arrays"].items():
                arrays[f"{ci}:{attr}"] = arr
        path = os.path.join(d, f"resid_{os.getpid()}_{next(self._file_seq)}.npz")
        try:
            with open(path, "wb") as f:
                np.savez(f, **arrays)
        except OSError:
            self._entries.pop(e.key, None)
            self.counters["coldDrops"] += 1
            return
        e.snap, e.state, e.path, e.meta = None, "cold", path, meta
        self.counters["coldDemotions"] += 1

    def _load_cold_locked(self, e: _Entry) -> Optional[Dict[str, Any]]:
        """cold -> packed snapshot (one sequential read, zero
        re-encode).  On read failure the entry is dropped — the caller
        falls back to staging from source segments."""
        meta, path = e.meta, e.path
        if meta is None or path is None:
            self._entries.pop(e.key, None)
            return None
        try:
            with np.load(path) as z:
                files = dict(z)
        except (OSError, ValueError):
            self._entries.pop(e.key, None)
            self.counters["coldDrops"] += 1
            return None
        columns: Dict[str, Dict[str, Any]] = {}
        for ci, name in enumerate(meta["column_order"]):
            columns[name] = {
                "meta": meta["column_meta"][name],
                "arrays": {
                    attr: files[f"{ci}:{attr}"]
                    for attr in meta["column_attrs"][name]
                },
            }
        snap = {
            "segment_names": meta["segment_names"],
            "num_segments": meta["num_segments"],
            "n_pad": meta["n_pad"],
            "num_docs": meta["num_docs"],
            "num_docs_arr": files["nd:num_docs_arr"],
            "columns": columns,
        }
        nbytes = int(files["nd:num_docs_arr"].nbytes) + sum(
            int(a.nbytes)
            for col in columns.values()
            for a in col["arrays"].values()
        )
        try:
            os.unlink(path)
        except OSError:
            pass
        e.snap, e.state, e.path, e.meta, e.nbytes = snap, "warm", None, None, nbytes
        self.counters["coldLoads"] += 1
        return snap

    def _remove_payload_locked(self, e: _Entry) -> None:
        if e.path is not None:
            try:
                os.unlink(e.path)
            except OSError:
                pass
        e.snap, e.path, e.meta = None, None, None

    # -- async promotion (cold -> warm ahead of lane dispatch) ---------
    def prefetch_siblings(self, key: Tuple, table: str) -> None:
        """A promotion just happened for ``table``: lift its OTHER cold
        entries to warm in the background, so the table's next
        segment-set launch pays a RAM copy instead of a disk read —
        the async-promotion half of the tier contract."""
        with self._lock:
            targets = [
                k
                for k, e in self._entries.items()
                if e.state == "cold" and e.table == table and k != key
            ]
            if not targets:
                return
            for k in targets:
                self._prefetch_q.put(k)
            if self._prefetch_thread is None or not self._prefetch_thread.is_alive():
                self._prefetch_thread = threading.Thread(
                    target=self._prefetch_loop,
                    name="residency-prefetch",
                    daemon=True,
                )
                self._prefetch_thread.start()

    def _prefetch_loop(self) -> None:
        while True:
            try:
                k = self._prefetch_q.get(timeout=5.0)
            except queue.Empty:
                return
            try:
                with self._lock:
                    e = self._entries.get(k)
                    if e is not None and e.state == "cold":
                        if self._load_cold_locked(e) is not None:
                            self.counters["prefetches"] += 1
            except Exception:
                pass  # prefetch is best-effort by contract

    # -- observability --------------------------------------------------
    def _tier_totals_locked(self) -> Dict[str, Tuple[int, int]]:
        out = {"hot": [0, 0], "warm": [0, 0], "cold": [0, 0]}
        for e in self._entries.values():
            out[e.state][0] += 1
            out[e.state][1] += e.nbytes
        return {k: (v[0], v[1]) for k, v in out.items()}

    def hot_bytes(self) -> int:
        with self._lock:
            return self._tier_totals_locked()["hot"][1]

    def warm_bytes(self) -> int:
        with self._lock:
            return self._tier_totals_locked()["warm"][1]

    def cold_bytes(self) -> int:
        with self._lock:
            return self._tier_totals_locked()["cold"][1]

    def counter(self, name: str) -> int:
        with self._lock:
            return self.counters.get(name, 0)

    def pressure(self) -> float:
        """Hot bytes as a fraction of the HBM cap (0.0 when uncapped) —
        the signal ingest backpressure and the rebalancer learn."""
        cap = hbm_cap_bytes()
        if cap <= 0:
            return 0.0
        return self.hot_bytes() / cap

    def segment_tiers(
        self,
        table: str,
        segment_names: Sequence[str],
        raw_match: bool = False,
    ) -> Dict[str, str]:
        """Best residency state per segment ("hot" > "warm" > "cold"),
        for EXPLAIN's per-segment reporting; unknown segments are
        simply absent (caller reports them "unstaged").  With
        ``raw_match`` the table comparison strips TYPE suffixes
        (EXPLAIN's logical-vs-physical naming); entries whose table is
        unknown (segment metadata without a table_name) match on
        segment membership alone, mirroring the ledger snapshot
        rules."""
        if raw_match and table:
            from pinot_tpu.engine.plandigest import _raw_table

            table = _raw_table(table)
        rank = {"hot": 0, "warm": 1, "cold": 2}
        wanted = set(segment_names)
        out: Dict[str, str] = {}
        with self._lock:
            for e in self._entries.values():
                etable = e.table
                if raw_match and etable:
                    from pinot_tpu.engine.plandigest import _raw_table

                    etable = _raw_table(etable)
                if table and etable and etable != table:
                    continue
                for s in e.segments:
                    if s in wanted and (
                        s not in out or rank[e.state] < rank[out[s]]
                    ):
                        out[s] = e.state
        return out

    def snapshot(self) -> Dict[str, Any]:
        """JSON-safe tier view for server status() / /debug/residency /
        the controller capacity rollup."""
        with self._lock:
            totals = self._tier_totals_locked()
            by_table: Dict[str, Dict[str, int]] = {}
            for e in self._entries.values():
                t = by_table.setdefault(e.table, {"hot": 0, "warm": 0, "cold": 0})
                t[e.state] += 1
            cap = hbm_cap_bytes()
            hot_bytes = totals["hot"][1]
            return {
                "hbmCapBytes": cap,
                "hostCapBytes": host_cap_bytes(),
                "hotTables": totals["hot"][0],
                "hotBytes": hot_bytes,
                "warmTables": totals["warm"][0],
                "warmBytes": totals["warm"][1],
                "coldTables": totals["cold"][0],
                "coldBytes": totals["cold"][1],
                "pinnedTokens": len(self._pins),
                "pressure": round(hot_bytes / cap, 4) if cap > 0 else 0.0,
                "counters": dict(self.counters),
                "byTable": by_table,
            }

    def reset(self) -> None:
        """Drop all tier state (tests / chaos scenarios).  Pins are
        preserved — they belong to in-flight queries, not to entries."""
        with self._lock:
            for e in list(self._entries.values()):
                self._remove_payload_locked(e)
            self._entries.clear()
            self._token_keys.clear()
            for n in self.counters:
                self.counters[n] = 0


RESIDENCY = ResidencyManager()
