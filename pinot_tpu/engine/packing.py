"""Single-transfer kernel-output fetch.

The executor's host cost on a tunneled/remote device is dominated by
per-array device-to-host round trips: a Q1-shaped query returns ~10
output leaves, and fetching them one ``np.asarray`` at a time pays one
RTT each (~26 ms over the chip tunnel) — ~260 ms of pure latency on
47 ms of device work (BENCH r3 broker_p50 before this module).

Fix: bitcast every output leaf to bytes ON DEVICE, concatenate into one
``uint8`` buffer inside the same jitted program, fetch it with a single
transfer, and slice/view it back into numpy arrays on host.  The
reference lands on the same design point for its server->broker hop:
all result sections ride in one contiguous binary DataTable payload
(``common/utils/DataTable.java:304-325``), not an object per column.

The layout (shapes/dtypes/offsets) is derived host-side with
``jax.eval_shape`` — a trace, not an execution — and cached per input
shape signature, mirroring jit's own executable cache.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import numpy as np

import jax
import jax.numpy as jnp


def _to_bytes(x: jnp.ndarray) -> jnp.ndarray:
    """Flatten one leaf to a 1-D uint8 view (device-side)."""
    if x.dtype == jnp.bool_:
        x = x.astype(jnp.uint8)
    b = jax.lax.bitcast_convert_type(x, jnp.uint8)
    return b.reshape(-1)


def _np_dtype(dt) -> np.dtype:
    return np.dtype(np.bool_) if dt == jnp.bool_ else np.dtype(dt)


def _layout_for(out_shapes) -> Tuple[Any, list]:
    leaves, treedef = jax.tree_util.tree_flatten(out_shapes)
    layout = []
    off = 0
    for s in leaves:
        dt = _np_dtype(s.dtype)
        nbytes = int(np.prod(s.shape, dtype=np.int64)) * dt.itemsize
        pad = (-nbytes) % 8  # 8-byte aligned parts: safe host .view()
        layout.append((tuple(s.shape), dt, off, nbytes))
        off += nbytes + pad
    return treedef, layout


def make_packed_kernel(fn: Callable) -> Callable:
    """Wrap a kernel-like callable (pytree of device arrays out) so a
    call returns the same pytree as HOST numpy arrays via one packed
    device-to-host transfer.

    The returned callable also exposes the two pipeline halves as
    attributes: ``.dispatch(*args) -> handle`` launches the packed
    program and returns WITHOUT reading it back (jax dispatch is
    asynchronous — the device lane uses this to keep the device queue
    fed), and ``.fetch(handle)`` performs the single blocking D2H
    transfer + unpack (the FINALIZE stage, safe to call from any
    thread and from several waiters of one coalesced dispatch)."""

    @jax.jit
    def packed(*args):
        leaves = jax.tree_util.tree_leaves(fn(*args))
        parts = []
        for x in leaves:
            b = _to_bytes(jnp.asarray(x))
            pad = (-b.size) % 8
            if pad:
                b = jnp.pad(b, (0, pad))
            parts.append(b)
        if not parts:
            return jnp.zeros((0,), jnp.uint8)
        return jnp.concatenate(parts)

    layout_cache: Dict[Tuple, Tuple] = {}

    def dispatch(*args):
        """Launch the packed program; returns an opaque (layout, device
        buffer) handle without blocking on execution."""
        key = tuple(
            (tuple(l.shape), str(l.dtype))
            for l in jax.tree_util.tree_leaves(args)
            if hasattr(l, "shape")
        )
        lay = layout_cache.get(key)
        if lay is None:
            lay = _layout_for(jax.eval_shape(fn, *args))
            if len(layout_cache) > 64:
                layout_cache.clear()
            layout_cache[key] = lay
        return lay, packed(*args)

    def fetch(handle, count_transfer: bool = True):
        """ONE device->host transfer + unpack; blocks until the
        dispatched program completes."""
        (treedef, layout), buf_dev = handle
        buf = np.asarray(buf_dev)
        # D2H accounting for the utilization plane: this is THE packed
        # result transfer, so counting here captures every pipelined
        # and serial device query's fetch bytes.  Coalesced waiters
        # pass count_transfer=False — they unpack the SAME cached host
        # copy, and N records for one physical copy would inflate
        # d2hBytes with the coalescing rate.
        from pinot_tpu.engine.device import TRANSFERS

        if count_transfer:
            TRANSFERS.record_d2h(buf.nbytes)
        outs = []
        for shape, dt, off, nbytes in layout:
            if nbytes == 0:
                outs.append(np.zeros(shape, dt))
                continue
            part = buf[off : off + nbytes]
            if dt == np.bool_:
                outs.append(part.copy().reshape(shape).astype(np.bool_))
            else:
                outs.append(part.copy().view(dt).reshape(shape))
        return jax.tree_util.tree_unflatten(treedef, outs)

    def call(*args):
        return fetch(dispatch(*args))

    call.dispatch = dispatch
    call.fetch = fetch
    # AOT lowering handle for the static cost analysis (the jitted
    # packed program is what actually runs, so its analysis is the
    # honest one — packing copies included)
    call.lower = packed.lower
    return call


# ---------------------------------------------------------------------------
# Bit-sliced index (BSI) encoding — the fourth filter/aggregate tier's
# segment-pack-time layout (engine/bitsliced.py, engine/kernel.py).
# A width-W non-negative integer column becomes W bit-planes of packed
# uint32 words: row r lands in word r // 32 at bit r % 32 (LSB-first
# within a word, plane b holds bit b of every row).  Predicates then
# evaluate as O(W) wide AND/OR/popcount passes over n/32 words instead
# of O(n) per-row compares — the bulk-bitwise PIM formulation.
# ---------------------------------------------------------------------------


def bit_width(max_value: int) -> int:
    """Planes needed for values in [0, max_value] — at least 1 so a
    constant column still round-trips through the encoder."""
    return max(1, int(max_value).bit_length())


def bitslice_encode(
    values: np.ndarray, width: int, n_words: int
) -> np.ndarray:
    """uint32 [width, n_words] bit-planes of a non-negative int array.

    Rows beyond ``values.size`` (up to ``n_words * 32``) encode as 0 —
    the kernels mask padding through the validity words, mirroring how
    the forward-index staging zero-pads (device.py _stack_fwd)."""
    v = np.ascontiguousarray(values, dtype=np.int64)
    if v.size and (int(v.min()) < 0 or bit_width(int(v.max())) > width):
        raise ValueError(
            f"values out of range for {width}-plane bit-slice encoding"
        )
    planes = np.zeros((width, n_words), dtype=np.uint32)
    n = min(v.size, n_words * 32)
    for b in range(width):
        bits = np.zeros(n_words * 32, dtype=np.uint8)
        bits[:n] = (v[:n] >> b) & 1
        planes[b] = np.packbits(bits, bitorder="little").view(np.uint32)
    return planes


def bitslice_decode(planes: np.ndarray, num_rows: int) -> np.ndarray:
    """Inverse of bitslice_encode: int64 [num_rows] values."""
    width, n_words = planes.shape
    out = np.zeros(num_rows, dtype=np.int64)
    for b in range(width):
        bits = np.unpackbits(
            np.ascontiguousarray(planes[b]).view(np.uint8), bitorder="little"
        )[:num_rows]
        out |= bits.astype(np.int64) << b
    return out


def integral_dictionary_values(values) -> "np.ndarray | None":
    """Dictionary values as exact non-negative-offsettable int64, or
    None when the dictionary is not exactly integral (fused SUM must be
    bit-exact against the scan tier's float accumulation, which it is
    for integral values below 2**53 — engine/bitsliced.py)."""
    vals = np.asarray(values)
    if not np.issubdtype(vals.dtype, np.number) or vals.size == 0:
        return None
    v = np.asarray(vals, dtype=np.float64)
    if not np.all(np.isfinite(v)):
        return None
    if np.any(np.abs(v) >= 2.0**53) or not np.all(v == np.floor(v)):
        return None
    return v.astype(np.int64)


# ---------------------------------------------------------------------------
# Cross-query batching helpers (engine/dispatch.py micro-batching tier):
# stack B queries' host input pytrees along a new leading axis before the
# one vmapped launch, and slice one member's outputs back out of the
# fetched batch.
# ---------------------------------------------------------------------------


def stack_query_inputs(inputs_list):
    """Stack B structurally-identical numpy query-input pytrees into one
    pytree whose ndarray leaves lead with the batch axis.  Callers
    guarantee structural identity (same StaticPlan => same treedef and
    leaf shapes — the batch key enforces it); non-array leaves must be
    equal across members and pass through unstacked."""
    leaves0, treedef = jax.tree_util.tree_flatten(inputs_list[0])
    stacked = []
    columns = [jax.tree_util.tree_flatten(t)[0] for t in inputs_list]
    for i, leaf in enumerate(leaves0):
        if isinstance(leaf, np.ndarray):
            stacked.append(np.stack([col[i] for col in columns]))
        else:
            stacked.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, stacked)


def batch_input_signature(inputs) -> tuple:
    """Hashable (shape, dtype) signature of a query-input pytree — the
    belt-and-braces component of the lane batch key: two dispatches
    stack only when their leaves agree exactly."""
    return tuple(
        (tuple(leaf.shape), str(leaf.dtype))
        if isinstance(leaf, np.ndarray)
        else ("scalar", repr(leaf))
        for leaf in jax.tree_util.tree_leaves(inputs)
    )


def slice_batched_outputs(outs, index: int):
    """Member ``index``'s output pytree from a batched launch's fetched
    host outputs (every array leaf leads with the batch axis)."""
    return jax.tree_util.tree_map(lambda x: x[index], outs)


# ---------------------------------------------------------------------------
# Static XLA cost analysis (the utilization plane's "paper roofline"
# numerator): flops + bytes-accessed estimates per compiled plan.
# ---------------------------------------------------------------------------


def _normalize_cost_analysis(ca) -> "dict | None":
    """XLA cost-analysis output (dict, or list-of-dicts on older
    backends) -> {"flops", "bytesAccessed"} floats, or None when the
    backend reported nothing usable."""
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    out = {}
    flops = ca.get("flops")
    if isinstance(flops, (int, float)) and flops >= 0:
        out["flops"] = float(flops)
    nbytes = ca.get("bytes accessed")
    if isinstance(nbytes, (int, float)) and nbytes >= 0:
        out["bytesAccessed"] = float(nbytes)
    return out or None


def kernel_cost_analysis(kernel, args) -> "dict | None":
    """Static per-plan cost analysis for a kernel callable — the packed
    wrapper above (``.lower`` re-exported) or a plain ``jax.jit``
    object.  Tries the cheap path first (``lowered.cost_analysis()`` —
    a trace plus HLO-level analysis, no XLA optimization pass), and
    falls back to ``lowered.compile().cost_analysis()`` plus
    ``memory_analysis`` only when ``PINOT_TPU_COST_ANALYSIS=compile``
    (a SECOND full compile: ~free on CPU, ~25s cold on a tunneled
    chip, so never implicit).  Returns ``{"flops", "bytesAccessed"[,
    "peakMemoryBytes"], "source"}`` or None — every backend gap
    degrades to None, never an exception (the graceful-fallback
    contract the tests hold)."""
    import os

    mode = os.environ.get("PINOT_TPU_COST_ANALYSIS", "lowered")
    if mode == "0" or mode == "off":
        return None
    lower = getattr(kernel, "lower", None)
    if lower is None:
        return None
    try:
        lowered = lower(*args)
    except Exception:
        return None
    out = None
    try:
        out = _normalize_cost_analysis(lowered.cost_analysis())
    except Exception:
        out = None
    if out is not None:
        out["source"] = "lowered"
    if mode == "compile":
        try:
            compiled = lowered.compile()
            full = _normalize_cost_analysis(compiled.cost_analysis())
            if full is not None:
                out = dict(full)
                out["source"] = "compiled"
            try:
                mem = compiled.memory_analysis()
                peak = sum(
                    int(getattr(mem, attr, 0) or 0)
                    for attr in (
                        "argument_size_in_bytes",
                        "output_size_in_bytes",
                        "temp_size_in_bytes",
                    )
                )
                if out is not None and peak > 0:
                    out["peakMemoryBytes"] = peak
            except Exception:
                pass
        except Exception:
            pass
    return out
