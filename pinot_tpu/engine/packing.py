"""Single-transfer kernel-output fetch.

The executor's host cost on a tunneled/remote device is dominated by
per-array device-to-host round trips: a Q1-shaped query returns ~10
output leaves, and fetching them one ``np.asarray`` at a time pays one
RTT each (~26 ms over the chip tunnel) — ~260 ms of pure latency on
47 ms of device work (BENCH r3 broker_p50 before this module).

Fix: bitcast every output leaf to bytes ON DEVICE, concatenate into one
``uint8`` buffer inside the same jitted program, fetch it with a single
transfer, and slice/view it back into numpy arrays on host.  The
reference lands on the same design point for its server->broker hop:
all result sections ride in one contiguous binary DataTable payload
(``common/utils/DataTable.java:304-325``), not an object per column.

The layout (shapes/dtypes/offsets) is derived host-side with
``jax.eval_shape`` — a trace, not an execution — and cached per input
shape signature, mirroring jit's own executable cache.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import numpy as np

import jax
import jax.numpy as jnp


def _to_bytes(x: jnp.ndarray) -> jnp.ndarray:
    """Flatten one leaf to a 1-D uint8 view (device-side)."""
    if x.dtype == jnp.bool_:
        x = x.astype(jnp.uint8)
    b = jax.lax.bitcast_convert_type(x, jnp.uint8)
    return b.reshape(-1)


def _np_dtype(dt) -> np.dtype:
    return np.dtype(np.bool_) if dt == jnp.bool_ else np.dtype(dt)


def _layout_for(out_shapes) -> Tuple[Any, list]:
    leaves, treedef = jax.tree_util.tree_flatten(out_shapes)
    layout = []
    off = 0
    for s in leaves:
        dt = _np_dtype(s.dtype)
        nbytes = int(np.prod(s.shape, dtype=np.int64)) * dt.itemsize
        pad = (-nbytes) % 8  # 8-byte aligned parts: safe host .view()
        layout.append((tuple(s.shape), dt, off, nbytes))
        off += nbytes + pad
    return treedef, layout


def make_packed_kernel(fn: Callable) -> Callable:
    """Wrap a kernel-like callable (pytree of device arrays out) so a
    call returns the same pytree as HOST numpy arrays via one packed
    device-to-host transfer.

    The returned callable also exposes the two pipeline halves as
    attributes: ``.dispatch(*args) -> handle`` launches the packed
    program and returns WITHOUT reading it back (jax dispatch is
    asynchronous — the device lane uses this to keep the device queue
    fed), and ``.fetch(handle)`` performs the single blocking D2H
    transfer + unpack (the FINALIZE stage, safe to call from any
    thread and from several waiters of one coalesced dispatch)."""

    @jax.jit
    def packed(*args):
        leaves = jax.tree_util.tree_leaves(fn(*args))
        parts = []
        for x in leaves:
            b = _to_bytes(jnp.asarray(x))
            pad = (-b.size) % 8
            if pad:
                b = jnp.pad(b, (0, pad))
            parts.append(b)
        if not parts:
            return jnp.zeros((0,), jnp.uint8)
        return jnp.concatenate(parts)

    layout_cache: Dict[Tuple, Tuple] = {}

    def dispatch(*args):
        """Launch the packed program; returns an opaque (layout, device
        buffer) handle without blocking on execution."""
        key = tuple(
            (tuple(l.shape), str(l.dtype))
            for l in jax.tree_util.tree_leaves(args)
            if hasattr(l, "shape")
        )
        lay = layout_cache.get(key)
        if lay is None:
            lay = _layout_for(jax.eval_shape(fn, *args))
            if len(layout_cache) > 64:
                layout_cache.clear()
            layout_cache[key] = lay
        return lay, packed(*args)

    def fetch(handle):
        """ONE device->host transfer + unpack; blocks until the
        dispatched program completes."""
        (treedef, layout), buf_dev = handle
        buf = np.asarray(buf_dev)
        outs = []
        for shape, dt, off, nbytes in layout:
            if nbytes == 0:
                outs.append(np.zeros(shape, dt))
                continue
            part = buf[off : off + nbytes]
            if dt == np.bool_:
                outs.append(part.copy().reshape(shape).astype(np.bool_))
            else:
                outs.append(part.copy().view(dt).reshape(shape))
        return jax.tree_util.tree_unflatten(treedef, outs)

    def call(*args):
        return fetch(dispatch(*args))

    call.dispatch = dispatch
    call.fetch = fetch
    return call
