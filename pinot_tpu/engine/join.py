"""Distributed hash-join engine: side extraction, exchange payloads,
device hash-join execution, and the exact host-reference join.

The broker plans a two-table equi-join (``broker/joinplan.py``) into one
of three strategies — colocated / broadcast / shuffle — but every
strategy bottoms out in the same server-side pipeline implemented here:

1. **extract**: one side's matched rows become a ``SideRows`` — the
   join key plus every referenced column, dict-encoded per column
   (``ids`` int32 into a compact sorted ``values`` vocabulary).  The
   encoding is the exchange wire format AND the device-friendly form:
   after the broker (or the local server) merges the two sides' key
   vocabularies, the join compares int32 ids, never raw values — string
   keys cost the same as ints (JSPIM's select-side framing: move ids,
   not values).

2. **join**: build-side rows pre-aggregate per unique key on host (the
   packing step), then the device kernel (``kernel.make_join_kernel``)
   runs the build phase (parallel-claim insertion into an int32
   open-addressing table over padded lanes) and the probe phase
   (vectorized linear probing) and reduces aggregates/group holders in
   the same program.  Anything outside the device shape (selections,
   value-state aggregations, group spaces past the holder budget,
   build-side group columns under duplicate build keys) runs the exact
   host join — and a device failure heals through the executor's
   standard classify/retry/poison/host-failover contract
   (``executor.execute_join``), exactly like a poisoned scan.

3. **skew plan** (shuffle only): ``plan_shuffle_partitions`` assigns
   key-hash partitions to owners and detects heavy-hitter keys from the
   extracted per-key counts (dictionary-derived — the sides are already
   dict-encoded); a heavy key's build rows REPLICATE to every owner and
   its probe rows split round-robin across them (PIM-tree's
   split-and-replicate playbook), so no owner receives >2x the mean
   exchange bytes under zipf-skewed keys.
"""
from __future__ import annotations

import hashlib
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from pinot_tpu.common.request import (
    BrokerRequest,
    FilterOperator,
    FilterQueryTree,
    JoinSpec,
    group_sort_ascending,
)
from pinot_tpu.common.schema import DataType
from pinot_tpu.common.values import render_value
from pinot_tpu.engine.results import (
    AvgPartial,
    CountPartial,
    DistinctPartial,
    HistogramPartial,
    HllPartial,
    IntermediateResult,
    MaxPartial,
    MinMaxRangePartial,
    MinPartial,
    SumPartial,
    make_partial,
    trim_group_candidates,
)

_KNUTH = np.uint64(2654435761)

_PARTITION_RE = __import__("re").compile(r"_+p(\d+)$")


def partition_of_segment(name: str) -> Optional[int]:
    """Partition id carried in a segment name (``..._p3`` / ``...__p3``)
    or None — the colocated strategy's placement channel: partitioned
    tables name their segments with the partition suffix, so both the
    broker planner and the server-side coverage re-check can read
    placement straight off the external view."""
    m = _PARTITION_RE.search(name)
    return int(m.group(1)) if m else None


class JoinValidationError(ValueError):
    """A join query the planner cannot execute (mixed-side OR
    predicates, MV columns, type-mismatched keys…) — a typed client
    error (QUERY_VALIDATION), never a server crash."""


# ---------------------------------------------------------------------------
# SideRows: the dict-encoded columnar exchange form of one join side
# ---------------------------------------------------------------------------


@dataclass
class Col:
    """One dict-encoded column: ``values[ids[i]]`` is row i's value.
    ``values`` is a sorted unique numpy array (numeric) or list[str]."""

    stored: str  # DataType name
    ids: np.ndarray  # int32 [n]
    values: Any  # np.ndarray (numeric) | List[str]

    @property
    def card(self) -> int:
        return len(self.values)

    def nbytes(self) -> int:
        vb = (
            self.values.nbytes
            if isinstance(self.values, np.ndarray)
            else sum(len(v) for v in self.values)
        )
        return int(self.ids.nbytes + vb)

    def row_values(self) -> np.ndarray:
        """Per-row value array (numeric columns only)."""
        return np.asarray(self.values, dtype=np.float64)[self.ids]

    def stored_type(self) -> DataType:
        return DataType[self.stored]

    def py_value(self, vid: int):
        v = self.values[vid]
        st = self.stored_type()
        if st in (DataType.INT, DataType.LONG):
            return int(v)
        if st in (DataType.FLOAT, DataType.DOUBLE):
            return float(v)
        return str(v)


@dataclass
class SideRows:
    """One join side's extracted rows: the key column plus every
    referenced column, all dict-encoded.  ``cols`` is keyed by the
    REQUEST-level column name (left side bare, right side
    ``"<right_table>.<col>"``), so execution reads straight off the
    parsed request."""

    n: int
    key: Col
    cols: Dict[str, Col] = field(default_factory=dict)

    def nbytes(self) -> int:
        return self.key.nbytes() + sum(c.nbytes() for c in self.cols.values())

    def key_counts(self) -> np.ndarray:
        """Per-key row counts (heavy-hitter statistic) — a bincount over
        the dictionary-encoded key ids."""
        return np.bincount(self.key.ids, minlength=self.key.card)


def _dict_encode(values: np.ndarray, stored: DataType) -> Col:
    if stored == DataType.STRING:
        arr = np.asarray(values, dtype=object)
        uniq, inv = np.unique(arr.astype(str), return_inverse=True)
        return Col(stored.name, inv.astype(np.int32), [str(v) for v in uniq])
    uniq, inv = np.unique(np.asarray(values), return_inverse=True)
    return Col(stored.name, inv.astype(np.int32), uniq)


def _col_take(col: Col, rows: np.ndarray) -> Col:
    """Row subset with a re-compacted vocabulary (exchange slices ship
    only the values they reference)."""
    ids = col.ids[rows]
    uniq, inv = np.unique(ids, return_inverse=True)
    if isinstance(col.values, np.ndarray):
        values = col.values[uniq]
    else:
        values = [col.values[i] for i in uniq.tolist()]
    return Col(col.stored, inv.astype(np.int32), values)


def side_take(side: SideRows, rows: np.ndarray) -> SideRows:
    return SideRows(
        n=int(rows.size),
        key=_col_take(side.key, rows),
        cols={name: _col_take(c, rows) for name, c in side.cols.items()},
    )


def _merge_cols(cols: List[Col]) -> Col:
    """Concatenate dict-encoded columns, merging vocabularies."""
    stored = cols[0].stored
    if any(c.stored != stored for c in cols):
        raise JoinValidationError(
            f"column stored types differ across segments/servers: "
            f"{sorted({c.stored for c in cols})}"
        )
    if stored == DataType.STRING.name:
        vocab = sorted({v for c in cols for v in c.values})
        index = {v: i for i, v in enumerate(vocab)}
        # O(vocab) Python + O(rows) numpy: per-part remap tables, never
        # a per-row Python loop (this runs on the broker's merge path)
        ids = np.concatenate(
            [
                np.asarray(
                    [index[v] for v in c.values], dtype=np.int32
                )[c.ids]
                if c.ids.size
                else np.zeros(0, dtype=np.int32)
                for c in cols
            ]
        )
        return Col(stored, ids, vocab)
    vocab = np.unique(np.concatenate([np.asarray(c.values) for c in cols]))
    ids = np.concatenate(
        [
            np.searchsorted(vocab, np.asarray(c.values)[c.ids]).astype(np.int32)
            if c.ids.size
            else np.zeros(0, dtype=np.int32)
            for c in cols
        ]
    )
    return Col(stored, ids, vocab)


def merge_sides(parts: List[SideRows]) -> SideRows:
    # drop empty-extract placeholders (transient serving gaps): their
    # typeless empty key column must not fight the real parts' vocab
    parts = [p for p in parts if p is not None and (p.n or p.cols)]
    if not parts:
        return SideRows(n=0, key=Col(DataType.INT.name, np.zeros(0, np.int32), np.zeros(0, np.int64)))
    names = set()
    for p in parts:
        names.update(p.cols)
    return SideRows(
        n=sum(p.n for p in parts),
        key=_merge_cols([p.key for p in parts]),
        cols={
            name: _merge_cols([p.cols[name] for p in parts if name in p.cols])
            for name in sorted(names)
        },
    )


# -- wire encode/decode (rides the datatable tagged codec: arrays via
# the 'a' tag, string vocabularies as plain lists) ----------------------


def _enc_col(col: Col) -> Dict[str, Any]:
    values = col.values if isinstance(col.values, np.ndarray) else list(col.values)
    return {"stored": col.stored, "ids": col.ids, "values": values}


def _dec_col(d: Dict[str, Any]) -> Col:
    values = d["values"]
    if not isinstance(values, np.ndarray):
        values = [str(v) for v in values]
    return Col(str(d["stored"]), np.asarray(d["ids"], dtype=np.int32), values)


def encode_side(side: SideRows) -> Dict[str, Any]:
    return {
        "n": int(side.n),
        "key": _enc_col(side.key),
        "cols": {name: _enc_col(c) for name, c in side.cols.items()},
    }


def decode_side(d: Dict[str, Any]) -> SideRows:
    return SideRows(
        n=int(d["n"]),
        key=_dec_col(d["key"]),
        cols={name: _dec_col(c) for name, c in (d.get("cols") or {}).items()},
    )


# ---------------------------------------------------------------------------
# request decomposition: per-side filters and referenced columns
# ---------------------------------------------------------------------------


def _copy_leaf(node: FilterQueryTree, column: str) -> FilterQueryTree:
    return FilterQueryTree(
        operator=node.operator,
        column=column,
        values=list(node.values),
        range_spec=node.range_spec,
        children=[],
    )


def _strip_tree(node: FilterQueryTree, spec: JoinSpec) -> FilterQueryTree:
    if node.is_leaf:
        return _copy_leaf(node, spec.strip_right(node.column))
    return FilterQueryTree(
        operator=node.operator,
        children=[_strip_tree(c, spec) for c in node.children],
    )


def _copy_tree(node: FilterQueryTree) -> FilterQueryTree:
    if node.is_leaf:
        return _copy_leaf(node, node.column)
    return FilterQueryTree(
        operator=node.operator, children=[_copy_tree(c) for c in node.children]
    )


def split_join_filter(
    request: BrokerRequest,
) -> Tuple[Optional[FilterQueryTree], Optional[FilterQueryTree]]:
    """Split the WHERE tree into (left filter, right filter).  The top
    level must be a conjunction of single-side predicates: each AND arm
    is pushed down to its side's extraction; an arm mixing sides (an OR
    spanning the join) cannot be pushed through an inner join's
    extraction and is a typed validation error.  Right-side trees come
    back with the ``<right_table>.`` prefix stripped (segment-level
    column names)."""
    spec = request.join
    tree = request.filter
    if tree is None:
        return None, None
    arms = (
        list(tree.children)
        if (not tree.is_leaf and tree.operator == FilterOperator.AND)
        else [tree]
    )
    left: List[FilterQueryTree] = []
    right: List[FilterQueryTree] = []
    for arm in arms:
        sides = {
            "r" if spec.is_right_column(n.column) else "l"
            for n in arm.walk()
            if n.is_leaf
        }
        if len(sides) > 1:
            raise JoinValidationError(
                "join WHERE predicates must each reference a single side "
                "(an OR spanning both join sides cannot be pushed down)"
            )
        if sides == {"r"}:
            right.append(_strip_tree(arm, spec))
        else:
            left.append(_copy_tree(arm))

    def _pack(arms_: List[FilterQueryTree]) -> Optional[FilterQueryTree]:
        if not arms_:
            return None
        if len(arms_) == 1:
            return arms_[0]
        return FilterQueryTree(operator=FilterOperator.AND, children=arms_)

    return _pack(left), _pack(right)


def side_columns(request: BrokerRequest) -> Tuple[List[str], List[str]]:
    """Referenced VALUE columns per side (request-level names; join keys
    excluded — they ship as ``SideRows.key``).  Filter columns are
    excluded too: filters apply during extraction and never ship."""
    spec = request.join
    names: List[str] = []

    def add(c: Optional[str]) -> None:
        if c and c != "*" and c not in names:
            names.append(c)

    for a in request.aggregations:
        add(a.column)
    if request.is_group_by:
        for c in request.group_by.columns:
            add(c)
    if request.selection is not None:
        for c in request.selection.columns:
            add(c)
        for s in request.selection.sorts:
            add(s.column)
    left = [c for c in names if not spec.is_right_column(c)]
    right = [c for c in names if spec.is_right_column(c)]
    return left, right


# ---------------------------------------------------------------------------
# extraction: local segments -> SideRows
# ---------------------------------------------------------------------------


def extract_side(
    segments: Sequence[Any],
    filter_tree: Optional[FilterQueryTree],
    key_col: str,
    value_cols: Sequence[str],
    name_of: Optional[Dict[str, str]] = None,
) -> Tuple[SideRows, int]:
    """Matched rows of one side from local segments: apply the side's
    filter, gather the key + value columns, dict-encode.  ``name_of``
    maps segment-level column names to request-level names (the
    right side's ``<table>.<col>`` prefix).  Returns (rows, matched) —
    ``matched`` doubles as the extraction's numDocsScanned.

    MV columns cannot flatten into joined rows deterministically and
    are rejected (typed validation error)."""
    from pinot_tpu.engine.host_fallback import _segment_mask

    name_of = name_of or {}
    # dedupe: the join key may ALSO be referenced as a value column
    # (sum(f.k), GROUP BY d.k) — reading it twice per segment would
    # silently double every per-row array while n stays correct
    read_cols = list(dict.fromkeys([key_col, *value_cols]))
    per_seg_vals: Dict[str, List[np.ndarray]] = {c: [] for c in read_cols}
    stored: Dict[str, DataType] = {}
    matched_total = 0
    for seg in segments:
        mask = _segment_mask(seg, filter_tree)
        rows = np.nonzero(mask)[0]
        matched_total += int(rows.size)
        for c in read_cols:
            col = seg.column(c)  # KeyError -> caught by the server as 200
            if not col.is_single_value:
                raise JoinValidationError(
                    f"multi-value column {c!r} is not supported in joins"
                )
            st = col.dictionary.stored_type
            prev = stored.setdefault(c, st)
            if prev != st:
                raise JoinValidationError(
                    f"column {c!r} stored type differs across segments"
                )
            per_seg_vals[c].append(col.dictionary.value_array()[col.fwd[rows]])
    if not segments:
        # a transient serving gap (segment move mid-query): an EMPTY
        # side, not a client error — the broker's unserved-segment
        # accounting re-covers or degrades, exactly like the scan path
        return SideRows(
            n=0,
            key=Col(DataType.INT.name, np.zeros(0, np.int32), np.zeros(0, np.int64)),
        ), 0

    def enc(c: str) -> Col:
        vals = (
            np.concatenate(per_seg_vals[c])
            if per_seg_vals[c]
            else np.zeros(0, dtype=np.int64)
        )
        return _dict_encode(vals, stored[c])

    side = SideRows(
        n=matched_total,
        key=enc(key_col),
        cols={name_of.get(c, c): enc(c) for c in value_cols},
    )
    return side, matched_total


# ---------------------------------------------------------------------------
# shared key space + shuffle partition planning
# ---------------------------------------------------------------------------


def shared_key_ids(
    build: SideRows, probe: SideRows
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Map both sides' key ids into ONE merged vocabulary; returns
    (build ids, probe ids, vocab size).  Key stored types must be
    jointly numeric or jointly string."""
    b_st, p_st = build.key.stored, probe.key.stored
    # an all-empty side (zero matched rows on every server) carries the
    # typeless placeholder key: adopt the live side's type — an empty
    # inner join is a valid empty answer, not a type error
    if build.n == 0 and build.key.card == 0:
        b_st = p_st
    if probe.n == 0 and probe.key.card == 0:
        p_st = b_st
    b_str = b_st == DataType.STRING.name
    p_str = p_st == DataType.STRING.name
    if b_str != p_str:
        raise JoinValidationError(
            f"join key types are incompatible ({p_st} vs {b_st})"
        )
    if b_str:
        vocab = sorted(set(build.key.values) | set(probe.key.values))
        index = {v: i for i, v in enumerate(vocab)}
        kb = np.asarray([index[v] for v in build.key.values], dtype=np.int32)
        kp = np.asarray([index[v] for v in probe.key.values], dtype=np.int32)
    else:
        # integer keys merge in int64 space: a float64 vocabulary would
        # collide distinct 64-bit ids above 2^53 (snowflake-style keys)
        # and silently cross-join unrelated rows
        ints = {DataType.INT.name, DataType.LONG.name}
        dt = np.int64 if b_st in ints and p_st in ints else np.float64
        bv = np.asarray(build.key.values, dtype=dt)
        pv = np.asarray(probe.key.values, dtype=dt)
        vocab = np.unique(np.concatenate([bv, pv]))
        kb = np.searchsorted(vocab, bv).astype(np.int32)
        kp = np.searchsorted(vocab, pv).astype(np.int32)
    V = len(vocab)
    kb_rows = kb[build.key.ids] if build.n else np.zeros(0, np.int32)
    kp_rows = kp[probe.key.ids] if probe.n else np.zeros(0, np.int32)
    return kb_rows, kp_rows, V


def _key_hash(ids: np.ndarray) -> np.ndarray:
    return (ids.astype(np.uint64) * _KNUTH) & np.uint64(0xFFFFFFFF)


def plan_shuffle_partitions(
    build: SideRows,
    probe: SideRows,
    n_owners: int,
    split_heavy: bool = True,
    heavy_factor: float = 0.5,
) -> Tuple[List[Tuple[np.ndarray, np.ndarray]], int]:
    """Assign every build/probe row to an owner partition.

    Normal keys route by hash; a HEAVY key — one whose probe-row count
    alone exceeds ``heavy_factor`` x the per-owner mean — would
    hot-spot its hash owner, so its probe rows split round-robin across
    ALL owners and its build rows replicate to all owners (inner-join
    correctness: every probe row still meets every matching build row
    exactly once).  Returns ([(build row idx, probe row idx)] per
    owner, heavy key count)."""
    kb, kp, V = shared_key_ids(build, probe)
    n_owners = max(1, int(n_owners))
    pid_of_key = (_key_hash(np.arange(V, dtype=np.int64)) % n_owners).astype(np.int32)
    probe_counts = np.bincount(kp, minlength=V) if kp.size else np.zeros(V, np.int64)
    mean_rows = max(1.0, probe.n / n_owners)
    heavy = np.zeros(V, dtype=bool)
    if split_heavy and n_owners > 1:
        heavy = probe_counts > heavy_factor * mean_rows
    n_heavy = int(heavy.sum())

    probe_pid = pid_of_key[kp] if kp.size else np.zeros(0, np.int32)
    if n_heavy:
        idx = np.nonzero(heavy[kp])[0]
        probe_pid = probe_pid.copy()
        probe_pid[idx] = (np.arange(idx.size) % n_owners).astype(np.int32)
    build_pid = pid_of_key[kb] if kb.size else np.zeros(0, np.int32)
    heavy_build = np.nonzero(heavy[kb])[0] if kb.size else np.zeros(0, np.int64)

    owners: List[Tuple[np.ndarray, np.ndarray]] = []
    for o in range(n_owners):
        b_idx = np.nonzero((build_pid == o) & ~heavy[kb])[0] if kb.size else np.zeros(0, np.int64)
        if heavy_build.size:
            b_idx = np.concatenate([b_idx, heavy_build])
            b_idx.sort()
        p_idx = np.nonzero(probe_pid == o)[0] if kp.size else np.zeros(0, np.int64)
        owners.append((b_idx, p_idx))
    return owners, n_heavy


# ---------------------------------------------------------------------------
# device join plan + packing
# ---------------------------------------------------------------------------

_SCALAR_AGGS = {"count", "sum", "min", "max", "avg", "minmaxrange"}


def join_group_capacity() -> int:
    try:
        return int(os.environ.get("PINOT_TPU_JOIN_GROUP_CAP", str(1 << 16)))
    except ValueError:
        return 1 << 16


@dataclass(frozen=True)
class JoinPlan:
    """Static shape of one device join program (the kernel-cache and
    poison-quarantine key): padded lane counts, the open-addressing
    capacity, and the aggregation spec — never literals or data."""

    n_build_pad: int
    n_probe_pad: int
    cap: int  # hash-table slots (pow2, >= 2x build keys)
    # one entry per aggregation: (kind, side 'p'|'b'|None, value index)
    aggs: Tuple[Tuple[str, Optional[str], int], ...]
    n_groups: int  # 0 = scalar aggregation
    bg_space: int  # build-side group radix multiplier (1 = none)
    n_pv: int  # stacked probe value columns
    n_bv: int  # stacked build value columns


def join_plan_digest(plan: JoinPlan) -> str:
    return hashlib.blake2b(repr(plan).encode(), digest_size=8).hexdigest()


def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def _numeric(col: Col) -> bool:
    return col.stored != DataType.STRING.name


def build_join_plan(
    request: BrokerRequest, build: SideRows, probe: SideRows
) -> Optional[Tuple[JoinPlan, Dict[str, np.ndarray], Dict[str, Any]]]:
    """Device eligibility + input packing.  Returns ``(plan, inputs,
    meta)`` or None when the query must take the host join: selections,
    value-state aggregations (distinct/percentile/HLL), group spaces
    past the holder budget, non-numeric aggregation inputs, build-side
    group columns under duplicate build keys, or probe sizes past the
    per-dispatch row budget."""
    spec = request.join
    if os.environ.get("PINOT_TPU_JOIN_DEVICE", "1") in ("0", "false"):
        return None  # host-reference mode (bench differential / tests)
    if request.selection is not None or not request.aggregations:
        return None
    if build.n == 0 or probe.n == 0:
        return None  # empty side: host path answers trivially (and exactly)
    kb, kp, _v = shared_key_ids(build, probe)

    gb_cols: List[str] = list(request.group_by.columns) if request.is_group_by else []
    b_group = [c for c in gb_cols if spec.is_right_column(c)]
    p_group = [c for c in gb_cols if not spec.is_right_column(c)]
    keys_unique = np.unique(kb).size == kb.size
    if b_group and not keys_unique:
        # a duplicate build key can carry distinct group values: the
        # per-key pre-aggregation below would conflate them
        return None
    g_space = 1
    for c in gb_cols:
        side = build if spec.is_right_column(c) else probe
        col = side.cols.get(c)
        if col is None:
            return None
        g_space *= max(1, col.card)
    if g_space > join_group_capacity():
        return None

    p_cols: List[str] = []
    b_cols: List[str] = []
    aggs: List[Tuple[str, Optional[str], int]] = []
    for a in request.aggregations:
        base = a.base_function
        if base not in _SCALAR_AGGS or a.is_mv:
            return None
        if a.column == "*":
            aggs.append(("count", None, 0))
            continue
        is_b = spec.is_right_column(a.column)
        side = build if is_b else probe
        col = side.cols.get(a.column)
        if col is None or not _numeric(col):
            return None
        pool = b_cols if is_b else p_cols
        if a.column not in pool:
            pool.append(a.column)
        aggs.append((base, "b" if is_b else "p", pool.index(a.column)))

    from pinot_tpu.engine.kernel import chunk_rows_limit

    n_probe_pad = _pow2(probe.n)
    limit = chunk_rows_limit()
    if limit and n_probe_pad > limit:
        return None

    # -- pack build side: pre-aggregate per unique merged key (host) ---
    uniq_k, inv = np.unique(kb, return_inverse=True)
    U = uniq_k.size
    bcnt = np.bincount(inv, minlength=U).astype(np.int32)
    bg = np.zeros(U, dtype=np.int32)
    bg_space = 1
    # keys_unique holds whenever b_group is non-empty: inv is then a
    # permutation, and argsort(inv)[u] is the one build row of key u
    row_of_key = np.argsort(inv, kind="stable")[:U] if b_group else None
    for c in b_group:
        col = build.cols[c]
        bg = bg * col.card + col.ids[row_of_key]
        bg_space *= col.card
    from pinot_tpu.engine.config import np_float_dtype

    fdt = np_float_dtype()  # f64 under x64 (exact differentials), f32 otherwise
    bs = np.zeros((max(1, len(b_cols)), U), dtype=fdt)
    bmn = np.full((max(1, len(b_cols)), U), np.inf, dtype=fdt)
    bmx = np.full((max(1, len(b_cols)), U), -np.inf, dtype=fdt)
    for i, c in enumerate(b_cols):
        vals = build.cols[c].row_values()
        bs[i] = np.bincount(inv, weights=vals, minlength=U).astype(fdt)
        order = np.argsort(inv, kind="stable")
        bounds = np.searchsorted(inv[order], np.arange(U))
        bmn[i] = np.minimum.reduceat(vals[order], bounds).astype(fdt)
        bmx[i] = np.maximum.reduceat(vals[order], bounds).astype(fdt)

    n_build_pad = _pow2(max(U, 1))
    cap = _pow2(max(2 * U, 8))

    def pad1(a: np.ndarray, n: int, fill) -> np.ndarray:
        out = np.full((n,), fill, dtype=a.dtype)
        out[: a.shape[0]] = a
        return out

    def pad2(a: np.ndarray, n: int, fill) -> np.ndarray:
        out = np.full((a.shape[0], n), fill, dtype=a.dtype)
        out[:, : a.shape[1]] = a
        return out

    pg = np.zeros(probe.n, dtype=np.int32)
    for c in p_group:
        col = probe.cols[c]
        pg = pg * col.card + col.ids
    pv = np.zeros((max(1, len(p_cols)), probe.n), dtype=fdt)
    for i, c in enumerate(p_cols):
        pv[i] = probe.cols[c].row_values().astype(fdt)

    plan = JoinPlan(
        n_build_pad=n_build_pad,
        n_probe_pad=n_probe_pad,
        cap=cap,
        aggs=tuple(aggs),
        n_groups=int(g_space) if gb_cols else 0,
        bg_space=int(bg_space),
        n_pv=max(1, len(p_cols)),
        n_bv=max(1, len(b_cols)),
    )
    inputs = {
        "bk": pad1(uniq_k.astype(np.int32), n_build_pad, -1),
        "bc": pad1(bcnt, n_build_pad, 0),
        "bg": pad1(bg, n_build_pad, 0),
        "bs": pad2(bs, n_build_pad, 0.0),
        "bmn": pad2(bmn, n_build_pad, np.inf),
        "bmx": pad2(bmx, n_build_pad, -np.inf),
        "pk": pad1(kp.astype(np.int32), n_probe_pad, -1),
        "pg": pad1(pg, n_probe_pad, 0),
        "pv": pad2(pv, n_probe_pad, 0.0),
    }
    meta = {"p_group": p_group, "b_group": b_group, "gb_cols": gb_cols}
    return plan, inputs, meta


# ---------------------------------------------------------------------------
# finalize: device outputs -> IntermediateResult partials
# ---------------------------------------------------------------------------


def _scalar_from_state(kind: str, state) -> Any:
    if kind == "count":
        return CountPartial(float(state))
    if kind == "sum":
        return SumPartial(float(state))
    if kind == "min":
        return MinPartial(float(state))
    if kind == "max":
        return MaxPartial(float(state))
    if kind == "avg":
        return AvgPartial(float(state[0]), float(state[1]))
    return MinMaxRangePartial(float(state[0]), float(state[1]))


def _group_tuple(
    request: BrokerRequest,
    meta: Dict[str, Any],
    build: SideRows,
    probe: SideRows,
    slot: int,
) -> Tuple[str, ...]:
    """Decode a mixed-radix group slot back to rendered key values, in
    the request's GROUP BY column order."""
    spec = request.join
    gb_cols = meta["gb_cols"]
    cards = []
    for c in gb_cols:
        side = build if spec.is_right_column(c) else probe
        cards.append(max(1, side.cols[c].card))
    # the slot was built probe-major then build-minor? No: pg covers the
    # probe columns in order, bg the build columns in order, and the
    # kernel computes pg * bg_space + bg — so decompose in that layout,
    # then re-emit in the request's column order.
    p_cards = [max(1, probe.cols[c].card) for c in meta["p_group"]]
    b_cards = [max(1, build.cols[c].card) for c in meta["b_group"]]
    bg_space = 1
    for c in b_cards:
        bg_space *= c
    pg, bg = divmod(slot, bg_space) if bg_space > 1 else (slot, 0)
    vids: Dict[str, int] = {}
    rem = pg
    for c, card in zip(reversed(meta["p_group"]), reversed(p_cards)):
        vids[c] = rem % card
        rem //= card
    rem = bg
    for c, card in zip(reversed(meta["b_group"]), reversed(b_cards)):
        vids[c] = rem % card
        rem //= card
    out = []
    for c in gb_cols:
        side = build if spec.is_right_column(c) else probe
        col = side.cols[c]
        out.append(render_value(col.stored_type(), col.py_value(vids[c])))
    return tuple(out)


def finalize_device_join(
    request: BrokerRequest,
    plan: JoinPlan,
    meta: Dict[str, Any],
    build: SideRows,
    probe: SideRows,
    outs: Dict[str, Any],
) -> IntermediateResult:
    joined = int(outs["num_docs"])
    res = IntermediateResult(
        num_docs_scanned=joined,
        num_entries_scanned_post_filter=joined * max(1, len(plan.aggs)),
    )
    if plan.n_groups:
        cnt = np.asarray(outs["gb_cnt"])
        live = np.nonzero(cnt > 0)[0]
        groups: Dict[Tuple[str, ...], list] = {}
        # trim like every other serving path (reference topN*5 semantics)
        if live.size > max(request.group_by.top_n * 5, 100):
            order_vals = []
            for i, (kind, _s, _x) in enumerate(plan.aggs):
                st = outs[f"gb_{i}"]
                if kind == "count":
                    order_vals.append(cnt[live].astype(np.float64))
                elif kind in ("sum", "min", "max"):
                    order_vals.append(np.asarray(st)[live].astype(np.float64))
                elif kind == "avg":
                    with np.errstate(divide="ignore", invalid="ignore"):
                        order_vals.append(
                            np.where(
                                cnt[live] > 0,
                                np.asarray(st[0])[live] / np.maximum(cnt[live], 1),
                                -np.inf,
                            )
                        )
                else:
                    order_vals.append(
                        (np.asarray(st[1])[live] - np.asarray(st[0])[live]).astype(
                            np.float64
                        )
                    )
            keep = trim_group_candidates(
                order_vals,
                [group_sort_ascending(a.function) for a in request.aggregations],
                request.group_by.top_n,
                live.size,
            )
            live = live[keep]
        for slot in live.tolist():
            partials = []
            for i, (kind, _side, _idx) in enumerate(plan.aggs):
                st = outs[f"gb_{i}"]
                if kind == "count":
                    partials.append(CountPartial(float(cnt[slot])))
                elif kind == "avg":
                    partials.append(
                        AvgPartial(float(np.asarray(st[0])[slot]), float(cnt[slot]))
                    )
                elif kind == "minmaxrange":
                    partials.append(
                        MinMaxRangePartial(
                            float(np.asarray(st[0])[slot]),
                            float(np.asarray(st[1])[slot]),
                        )
                    )
                elif kind == "sum":
                    partials.append(SumPartial(float(np.asarray(st)[slot])))
                elif kind == "min":
                    partials.append(MinPartial(float(np.asarray(st)[slot])))
                else:
                    partials.append(MaxPartial(float(np.asarray(st)[slot])))
            groups[_group_tuple(request, meta, build, probe, slot)] = partials
        res.groups = groups
    else:
        res.aggregations = [
            _scalar_from_state(kind, outs[f"agg_{i}"])
            for i, (kind, _side, _idx) in enumerate(plan.aggs)
        ]
    return res


# ---------------------------------------------------------------------------
# exact host join (the reference path every strategy differentials against)
# ---------------------------------------------------------------------------


def _joined_indices(
    build: SideRows, probe: SideRows
) -> Tuple[np.ndarray, np.ndarray]:
    """Inner-join row index pairs: (probe_idx, build_idx), probe-major
    and deterministic (build matches in stable build-row order)."""
    kb, kp, _v = shared_key_ids(build, probe)
    if kb.size == 0 or kp.size == 0:
        z = np.zeros(0, dtype=np.int64)
        return z, z
    order = np.argsort(kb, kind="stable")
    kb_sorted = kb[order]
    lo = np.searchsorted(kb_sorted, kp, side="left")
    hi = np.searchsorted(kb_sorted, kp, side="right")
    counts = (hi - lo).astype(np.int64)
    total = int(counts.sum())
    if total == 0:
        z = np.zeros(0, dtype=np.int64)
        return z, z
    probe_idx = np.repeat(np.arange(kp.size, dtype=np.int64), counts)
    offs = np.concatenate(([0], np.cumsum(counts)[:-1])).astype(np.int64)
    take = np.arange(total, dtype=np.int64) - np.repeat(offs, counts) + np.repeat(
        lo.astype(np.int64), counts
    )
    return probe_idx, order[take]


def host_join(
    request: BrokerRequest, build: SideRows, probe: SideRows
) -> IntermediateResult:
    """Exact numpy inner join + aggregation/selection — the correctness
    oracle the device kernel must match byte-identically, and the heal
    target when a join plan poisons."""
    import time as _time

    t0 = _time.perf_counter()
    res = _host_join_impl(request, build, probe)
    res.add_cost(
        hostMs=round((_time.perf_counter() - t0) * 1000, 3),
        bytesScanned=build.nbytes() + probe.nbytes(),
    )
    return res


def _host_join_impl(
    request: BrokerRequest, build: SideRows, probe: SideRows
) -> IntermediateResult:
    spec = request.join
    probe_idx, build_idx = _joined_indices(build, probe)
    joined = int(probe_idx.size)
    res = IntermediateResult(
        num_docs_scanned=joined,
        num_entries_scanned_post_filter=joined * max(1, len(request.aggregations)),
    )

    def col_of(name: str) -> Tuple[Col, np.ndarray]:
        if spec.is_right_column(name):
            return build.cols[name], build_idx
        return probe.cols[name], probe_idx

    def joined_ids(name: str) -> Tuple[Col, np.ndarray]:
        col, idx = col_of(name)
        return col, col.ids[idx]

    def joined_vals(name: str) -> np.ndarray:
        col, ids = joined_ids(name)
        return np.asarray(col.values, dtype=np.float64)[ids]

    # -- selection ----------------------------------------------------
    if request.selection is not None:
        sel = request.selection
        res.selection_columns = list(sel.columns)
        rows: List[Tuple[list, list]] = []
        k = sel.offset + sel.size
        take = np.arange(joined) if sel.sorts else np.arange(min(joined, k))
        cols_py: Dict[str, list] = {}
        for name in {*sel.columns, *(s.column for s in sel.sorts)}:
            col, ids = joined_ids(name)
            cols_py[name] = [col.py_value(int(v)) for v in ids[take]]
        for j in range(take.size):
            sort_vals = [cols_py[s.column][j] for s in sel.sorts]
            rows.append((sort_vals, [cols_py[c][j] for c in sel.columns]))
        res.selection_rows = rows
        return res

    # -- group-by -----------------------------------------------------
    if request.is_group_by:
        res.groups = {}
        gb = request.group_by
        if joined == 0:
            return res
        gcols = [joined_ids(c) for c in gb.columns]
        keys = np.zeros(joined, dtype=np.int64)
        for col, ids in gcols:
            keys = keys * max(1, col.card) + ids
        uniq, inv = np.unique(keys, return_inverse=True)
        k = uniq.size
        counts = np.bincount(inv, minlength=k).astype(np.float64)
        order = None
        bounds = None

        def minmax(vals: np.ndarray):
            nonlocal order, bounds
            if order is None:
                order = np.argsort(inv, kind="stable")
                bounds = np.searchsorted(inv[order], np.arange(k))
            sv = vals[order]
            return (
                np.minimum.reduceat(sv, bounds),
                np.maximum.reduceat(sv, bounds),
            )

        states: List[tuple] = []
        order_vals: List[np.ndarray] = []
        for a in request.aggregations:
            base = a.base_function
            if base == "count":
                states.append(("count", counts))
                order_vals.append(counts)
                continue
            col, ids = joined_ids(a.column)
            if base in ("distinctcount", "distinctcounthll", "fasthll"):
                pair = np.unique(inv.astype(np.int64) * max(1, col.card) + ids)
                pg_ = pair // max(1, col.card)
                pgid = pair % max(1, col.card)
                pbounds = np.searchsorted(pg_, np.arange(k + 1))
                dcounts = np.diff(pbounds).astype(np.float64)
                kind = "distinct" if base == "distinctcount" else "hll"
                states.append((kind, col, pgid, pbounds))
                order_vals.append(dcounts)
                continue
            if base.startswith("percentile"):
                p = int(
                    base[len("percentileest"):]
                    if base.startswith("percentileest")
                    else base[len("percentile"):]
                )
                states.append(("hist", col, ids, p))
                # order by the exact percentile value per group
                vals = np.asarray(col.values, dtype=np.float64)[ids]
                ov = np.zeros(k)
                so = np.lexsort((vals, inv))
                sb = np.searchsorted(inv[so], np.arange(k + 1))
                for gi in range(k):
                    seg = vals[so[sb[gi]:sb[gi + 1]]]
                    n = seg.size
                    ov[gi] = seg[min(int(n * p / 100.0), n - 1)] if n else -np.inf
                order_vals.append(ov)
                continue
            vals = np.asarray(col.values, dtype=np.float64)[ids]
            if base == "sum":
                s = np.bincount(inv, weights=vals, minlength=k)
                states.append(("sum", s))
                order_vals.append(s)
            elif base == "avg":
                s = np.bincount(inv, weights=vals, minlength=k)
                states.append(("avg", s, counts))
                order_vals.append(s / np.maximum(counts, 1))
            else:
                mn, mx = minmax(vals)
                if base == "min":
                    states.append(("min", mn))
                    order_vals.append(mn)
                elif base == "max":
                    states.append(("max", mx))
                    order_vals.append(mx)
                else:
                    states.append(("minmaxrange", mn, mx))
                    order_vals.append(mx - mn)

        keep = trim_group_candidates(
            order_vals,
            [group_sort_ascending(a.function) for a in request.aggregations],
            gb.top_n,
            k,
        )

        def partial(state, i: int):
            kind = state[0]
            if kind == "count":
                return CountPartial(float(state[1][i]))
            if kind == "sum":
                return SumPartial(float(state[1][i]))
            if kind == "min":
                return MinPartial(float(state[1][i]))
            if kind == "max":
                return MaxPartial(float(state[1][i]))
            if kind == "avg":
                return AvgPartial(float(state[1][i]), float(state[2][i]))
            if kind == "minmaxrange":
                return MinMaxRangePartial(float(state[1][i]), float(state[2][i]))
            if kind == "distinct":
                _, col, pgid, pbounds = state
                ids = pgid[pbounds[i]:pbounds[i + 1]]
                vals = {col.py_value(int(v)) for v in ids}
                return DistinctPartial(vals)
            if kind == "hll":
                from pinot_tpu.engine import hll as hll_mod

                _, col, pgid, pbounds = state
                ids = pgid[pbounds[i]:pbounds[i + 1]]
                return HllPartial(
                    hll_mod.registers_from_values(
                        [col.py_value(int(v)) for v in ids]
                    )
                )
            # hist
            _, col, ids, p = state
            seg_ids = ids[inv == i]
            vals, cts = np.unique(seg_ids, return_counts=True)
            counts_map = {
                float(np.asarray(col.values, dtype=np.float64)[int(v)]): int(c)
                for v, c in zip(vals, cts)
            }
            return HistogramPartial(counts_map, percentile=p)

        # decompose kept slots -> rendered key tuples
        for i in keep.tolist():
            rem = int(uniq[i])
            vids = []
            for col, _ids in reversed(gcols):
                vids.append(rem % max(1, col.card))
                rem //= max(1, col.card)
            vids.reverse()
            ktup = tuple(
                render_value(col.stored_type(), col.py_value(v))
                for (col, _ids), v in zip(gcols, vids)
            )
            res.groups[ktup] = [partial(st, int(i)) for st in states]
        return res

    # -- plain aggregation --------------------------------------------
    partials = []
    for a in request.aggregations:
        base = a.base_function
        if joined == 0:
            partials.append(make_partial(base))
            continue
        if base == "count":
            partials.append(CountPartial(float(joined)))
            continue
        col, ids = joined_ids(a.column)
        if base in ("distinctcount", "distinctcounthll", "fasthll"):
            uids = np.unique(ids)
            values = [col.py_value(int(v)) for v in uids]
            if base == "distinctcount":
                partials.append(DistinctPartial(set(values)))
            else:
                from pinot_tpu.engine import hll as hll_mod

                partials.append(HllPartial(hll_mod.registers_from_values(values)))
            continue
        if base.startswith("percentile"):
            p = int(
                base[len("percentileest"):]
                if base.startswith("percentileest")
                else base[len("percentile"):]
            )
            uids, cts = np.unique(ids, return_counts=True)
            vals = np.asarray(col.values, dtype=np.float64)[uids]
            partials.append(
                HistogramPartial(
                    {float(v): int(c) for v, c in zip(vals, cts)}, percentile=p
                )
            )
            continue
        vals = np.asarray(col.values, dtype=np.float64)[ids]
        if base == "sum":
            partials.append(SumPartial(float(vals.sum())))
        elif base == "avg":
            partials.append(AvgPartial(float(vals.sum()), float(joined)))
        elif base == "min":
            partials.append(MinPartial(float(vals.min())))
        elif base == "max":
            partials.append(MaxPartial(float(vals.max())))
        else:
            partials.append(MinMaxRangePartial(float(vals.min()), float(vals.max())))
    res.aggregations = partials
    return res
