"""Zone maps: per-block dictId min/max for host-side block pruning.

The reference answers selective queries in O(matches) via inverted
indexes (``BitmapInvertedIndexReader.java:28``,
``SortedInvertedIndexBasedFilterOperator.java``); a full-scan engine
pays O(n) regardless of selectivity.  The TPU-native substitute is a
**zone map**: per 64k-row block, per SV column, the min/max dictId.
Because dictionaries are sorted, dictId order == value order, so every
predicate the planner already rewrote into dictId space (intervals,
point lists, match tables) can be tested per block on the host:

  interval [lo,hi)   -> candidate iff  zmax >= lo and zmin < hi
  points   {p...}    -> candidate iff  some p in [zmin, zmax]
                        (sorted points: two searchsorted calls)
  match table        -> candidate iff  any(match[zmin : zmax+1])
                        (prefix-sum lookup)

AND/OR trees combine candidacy bitwise; MV leaves are conservatively
all-candidate.  The executor gathers only candidate blocks onto the
device (``kernel.make_block_table_kernel``), so work scales with
selectivity — a point query on a clustered column touches one block per
segment instead of the whole table.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from pinot_tpu.engine import config
from pinot_tpu.engine.plan import MV_ANY, MV_NONE, SV, StaticPlan
from pinot_tpu.segment.immutable import ImmutableSegment


def zone_block_rows() -> int:
    import os

    v = os.environ.get("PINOT_TPU_ZONE_BLOCK")
    return int(v) if v else 65536


def column_zones(
    seg: ImmutableSegment, column: str, block: int
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """(zmin, zmax) dictId per block for an SV column; cached on the
    segment (segments are immutable). None for MV columns."""
    col = seg.column(column)
    if not col.metadata.single_value:
        return None
    cache = getattr(seg, "_zone_cache", None)
    if cache is None:
        cache = {}
        object.__setattr__(seg, "_zone_cache", cache)
    key = (column, block)
    z = cache.get(key)
    if z is None:
        # persisted zones may use a different (write-time) block size;
        # a coarser request that is a multiple of it can be derived by
        # grouped min/max instead of rescanning the column
        for (cname, pblock), (pmin, pmax) in cache.items():
            if cname != column or pblock >= block or block % pblock:
                continue
            g = block // pblock
            nb = -(-pmin.size // g)
            pad = nb * g - pmin.size
            if pad:
                pmin = np.concatenate([pmin, np.full(pad, pmin[-1])])
                pmax = np.concatenate([pmax, np.full(pad, pmax[-1])])
            z = (pmin.reshape(nb, g).min(axis=1), pmax.reshape(nb, g).max(axis=1))
            cache[key] = z
            return z
    if z is None:
        if col.fwd is None:
            # no persisted zones to derive from and nothing to scan:
            # degrade to all-candidate (matches the MV handling) rather
            # than crash the query-time pruning path
            return None
        fwd = np.asarray(col.fwd)
        n = fwd.size
        nb = -(-n // block) if n else 0
        pad = nb * block - n
        if pad:
            # pad with the last real value so padding never widens a zone
            fill = fwd[-1] if n else 0
            fwd = np.concatenate([fwd, np.full(pad, fill, fwd.dtype)])
        f2 = fwd.reshape(nb, block) if nb else fwd.reshape(0, block)
        z = (f2.min(axis=1).astype(np.int64), f2.max(axis=1).astype(np.int64))
        cache[key] = z
    return z


def _leaf_candidates(
    leaf, i: int, q_np: Dict, seg: ImmutableSegment, si: int, nb: int, block: int
) -> Optional[np.ndarray]:
    """bool[nb] conservative candidacy for one filter leaf on one
    segment; None = cannot evaluate (treat as all-candidate)."""
    if leaf.mode != SV:
        return None  # MV predicates: conservative
    kind = leaf.eval_kind
    if kind == "docrange":
        # doc-interval predicate: candidacy is exact block overlap —
        # no zones needed (and the column may not even be staged)
        nb_real = -(-seg.num_docs // block)
        out = np.zeros(nb, dtype=bool)
        lo_doc, hi_doc = q_np["bounds"][i][si]
        blk = np.arange(nb_real, dtype=np.int64)
        out[:nb_real] = (blk * block < hi_doc) & ((blk + 1) * block > lo_doc)
        return out
    z = column_zones(seg, leaf.column, block)
    if z is None:
        return None
    zmin, zmax = z
    nb_real = zmin.shape[0]
    out = np.zeros(nb, dtype=bool)  # blocks past the data are dead
    if kind == "interval":
        lo, hi = q_np["bounds"][i][si]
        out[:nb_real] = (zmax >= lo) & (zmin < hi)
        return out
    if kind == "points":
        pts = q_np["pts"][i][si]
        pts = np.sort(pts[pts >= 0])
        if pts.size == 0:
            return out
        out[:nb_real] = np.searchsorted(pts, zmin, "left") < np.searchsorted(
            pts, zmax, "right"
        )
        return out
    if kind == "points_none":
        # NOT IN: a block is excluded only if every row hits the point
        # set — provable from zones only for single-value blocks
        pts = q_np["pts"][i][si]
        pts = set(int(p) for p in pts if p >= 0)
        single = zmin == zmax
        excluded = single & np.isin(zmin, list(pts) or [-1])
        out[:nb_real] = ~excluded
        return out
    if kind == "runs":
        # interval union: candidate when ANY run overlaps the zone
        rr = q_np["runs"][i][si]  # [k, 2], empty runs lo == hi == 0
        hit = np.zeros(nb_real, dtype=bool)
        for lo, hi in rr:
            if hi > lo:
                hit |= (zmax >= lo) & (zmin < hi)
        out[:nb_real] = hit
        return out
    # match table: any matching dictId within [zmin, zmax]
    table = q_np["match"][i][si]
    csum = np.concatenate([[0], np.cumsum(table.astype(np.int64))])
    hi = np.minimum(zmax + 1, csum.size - 1)
    lo = np.minimum(zmin, csum.size - 1)
    out[:nb_real] = (csum[hi] - csum[lo]) > 0
    return out


def _tree_candidates(
    plan: StaticPlan, node, q_np, seg, si: int, nb: int, block: int
) -> np.ndarray:
    kind = node[0]
    if kind == "leaf":
        leaf = plan.leaves[node[1]]
        c = _leaf_candidates(leaf, node[1], q_np, seg, si, nb, block)
        if c is None:
            c = np.ones(nb, dtype=bool)
        return c
    parts = [_tree_candidates(plan, ch, q_np, seg, si, nb, block) for ch in node[1]]
    out = parts[0]
    for p in parts[1:]:
        out = (out & p) if kind == "and" else (out | p)
    return out


def candidate_blocks(
    plan: StaticPlan,
    q_np: Dict,
    live: Sequence[ImmutableSegment],
    n_pad: int,
    block: Optional[int] = None,
) -> Optional[np.ndarray]:
    """bool [len(live), n_pad//block] candidate map, or None when block
    pruning does not apply (no filter, or segments smaller than one
    block)."""
    if plan.filter_tree is None:
        return None
    block = block or zone_block_rows()
    if n_pad < 2 * block or n_pad % block:
        return None
    nb = n_pad // block
    out = np.zeros((len(live), nb), dtype=bool)
    for si, seg in enumerate(live):
        cand = _tree_candidates(plan, plan.filter_tree, q_np, seg, si, nb, block)
        # blocks fully past the segment's rows stay dead
        nb_live = -(-seg.num_docs // block)
        cand[nb_live:] = False
        out[si] = cand
    return out


def block_ids_input(cand: np.ndarray, nb_pad: int) -> np.ndarray:
    """Pack the candidate map into a padded int32 id array [S, nb_pad]
    (-1 = no block)."""
    S, _ = cand.shape
    ids = np.full((S, nb_pad), -1, dtype=np.int32)
    for s in range(S):
        sel = np.nonzero(cand[s])[0]
        ids[s, : sel.size] = sel
    return ids
