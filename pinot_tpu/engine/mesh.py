"""Mesh execution plane: device topology for pod-scale multichip serving.

One server process owns a set of chips (a v5e-8 slice, or N virtual CPU
devices under ``--xla_force_host_platform_device_count``).  This module
carves them into **chip groups** — each group drives one ``DeviceLane``
(engine/dispatch.py ``LaneGroup``) and executes queries as ONE SPMD
program over its own 1-D ``segments`` mesh (``parallel/multichip.py``):
segment columns stage as sharded arrays across the group
(``device.stage_segments`` with a ``NamedSharding``), and the
per-segment combine lowers to an on-device ``psum``/``pmin``/``pmax``
over ICI instead of a host-side merge.

Topology is env-configured (read once at server construction):

  PINOT_TPU_MESH_SHAPE=LxC   L lane groups of C chips each ("2x4");
                             a bare "8" means one lane of 8 chips
  PINOT_TPU_LANES=L          L lane groups over all visible devices,
                             split evenly (devices // L chips per lane)

With neither set the topology is the **trivial single lane** — exactly
the pre-mesh serving path (one lane, no mesh, default device), so
existing deployments and tests see zero behavior change.  Tier-1 runs
simulate a pod slice with ``XLA_FLAGS=--xla_force_host_platform_device_
count=N`` (``utils/platform.force_cpu_mesh`` — the conftest already
forces 8).

Fallback matrix (README "Mesh execution" has the operator view):

  group size 1 + trivial topology  -> single-chip vmapped kernel (the
                                      pre-mesh path, byte-identical)
  group size >= 1, explicit shape  -> shard_map SPMD kernel over the
                                      group's mesh (size-1 groups run
                                      the same program; psum over one
                                      device is the identity)
  device failure / poisoned plan   -> the owning lane quarantines and
                                      the query serves via the host
                                      path; OTHER lanes keep serving
                                      (per-lane supervision is
                                      unchanged from the single lane)
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

SEGMENT_AXIS = "segments"  # mirrors parallel.multichip.SEGMENT_AXIS


@dataclass(frozen=True)
class ChipGroup:
    """One lane's slice of the server's devices.  ``mesh`` is the 1-D
    ``segments`` Mesh the group's kernels shard over, or None for the
    trivial single-chip group (the pre-mesh fallback path)."""

    index: int
    devices: Tuple[Any, ...] = ()
    mesh: Any = None  # jax.sharding.Mesh | None

    @property
    def size(self) -> int:
        return max(1, len(self.devices))

    # NOTE: the group's NamedSharding is derived (and cached) by
    # QueryExecutor._mesh_sharding, and placement identity by
    # device.placement_key — ONE implementation each, shared by the
    # serving path, EXPLAIN, and the staging cache.

    def snapshot(self) -> Dict[str, Any]:
        return {
            "index": self.index,
            "size": self.size,
            "deviceIds": [getattr(d, "id", None) for d in self.devices],
            "sharded": self.mesh is not None,
        }


@dataclass(frozen=True)
class MeshTopology:
    """The server's chip-group layout: ``groups[i]`` backs lane ``i``."""

    groups: Tuple[ChipGroup, ...]
    source: str = "single"  # "single" | "env" | "mesh-arg"

    @property
    def num_lanes(self) -> int:
        return len(self.groups)

    @property
    def num_devices(self) -> int:
        return sum(g.size for g in self.groups)

    @property
    def devices_per_lane(self) -> int:
        return max(g.size for g in self.groups)

    @property
    def trivial(self) -> bool:
        """True for the pre-mesh single-lane/no-mesh layout."""
        return self.num_lanes == 1 and self.groups[0].mesh is None

    @property
    def primary_mesh(self):
        return self.groups[0].mesh

    def snapshot(self) -> Dict[str, Any]:
        return {
            "shape": f"{self.num_lanes}x{self.devices_per_lane}",
            "lanes": self.num_lanes,
            "devicesPerLane": self.devices_per_lane,
            "devices": self.num_devices,
            "shardAxis": SEGMENT_AXIS if not self.trivial else None,
            "source": self.source,
            "groups": [g.snapshot() for g in self.groups],
        }

    # -- constructors --------------------------------------------------
    @staticmethod
    def single() -> "MeshTopology":
        """The trivial topology: one lane, no mesh, default device —
        the exact pre-mesh serving path.  Touches no jax state (safe
        to build before backend init)."""
        return MeshTopology(groups=(ChipGroup(index=0),), source="single")

    @staticmethod
    def from_mesh(mesh) -> "MeshTopology":
        """Legacy adapter: one lane driving an explicit Mesh (the old
        ``ServerInstance(mesh=...)`` / ``QueryExecutor(mesh=...)``
        configuration)."""
        if mesh is None:
            return MeshTopology.single()
        devices = tuple(mesh.devices.flat)
        return MeshTopology(
            groups=(ChipGroup(index=0, devices=devices, mesh=mesh),),
            source="mesh-arg",
        )

    @staticmethod
    def env_configured() -> bool:
        """True when the env requests a non-trivial topology — the
        gate that keeps default construction from touching
        ``jax.devices()`` (backend init) at all."""
        return bool(
            os.environ.get("PINOT_TPU_MESH_SHAPE")
            or os.environ.get("PINOT_TPU_LANES")
        )

    @staticmethod
    def from_env(devices: Optional[Sequence[Any]] = None) -> "MeshTopology":
        """Topology from ``PINOT_TPU_MESH_SHAPE`` / ``PINOT_TPU_LANES``
        (module docstring).  Unset env -> the trivial single lane,
        with NO backend init.  Impossible requests degrade instead of
        raising: lane count clamps to the visible device count, chips
        per lane clamp to what divides evenly — a misconfigured env
        must not take serving down."""
        if not MeshTopology.env_configured():
            return MeshTopology.single()
        if devices is None:
            import jax

            devices = jax.devices()
        devices = list(devices)
        n = len(devices)
        lanes, per_lane = _parse_topology_env(n)
        if lanes <= 1 and per_lane <= 1:
            return MeshTopology.single()
        return build_topology(devices, lanes, per_lane, source="env")


def _parse_topology_env(n_devices: int) -> Tuple[int, int]:
    """(lanes, chips per lane) from the env, clamped to ``n_devices``."""
    shape = os.environ.get("PINOT_TPU_MESH_SHAPE", "").strip().lower()
    lanes_env = os.environ.get("PINOT_TPU_LANES", "").strip()
    lanes = 0
    per_lane = 0
    if shape:
        parts = shape.replace("*", "x").split("x")
        try:
            if len(parts) == 2:
                lanes, per_lane = int(parts[0]), int(parts[1])
            elif len(parts) == 1:
                per_lane = int(parts[0])
        except ValueError:
            lanes = per_lane = 0  # junk env must not take serving down
    if lanes_env:
        try:
            lanes = int(lanes_env)
        except ValueError:
            pass
    lanes = max(1, min(lanes, n_devices)) if lanes else 0
    if not lanes:
        lanes = max(1, n_devices // per_lane) if per_lane else 1
    if not per_lane:
        per_lane = max(1, n_devices // lanes)
    per_lane = max(1, min(per_lane, n_devices // lanes))
    return lanes, per_lane


def build_topology(
    devices: Sequence[Any], lanes: int, per_lane: int, source: str = "env"
) -> "MeshTopology":
    """Partition ``devices`` into ``lanes`` groups of ``per_lane`` chips
    (clamped to what is available).  Every group gets a 1-D
    ``segments`` Mesh — including size-1 groups, whose shard_map
    program is the single-chip program with identity collectives, so
    placement (each lane pinned to ITS chip) stays uniform."""
    from pinot_tpu.parallel.multichip import default_mesh

    devices = list(devices)
    lanes = max(1, min(lanes, len(devices)))
    per_lane = max(1, min(per_lane, len(devices) // lanes))
    groups: List[ChipGroup] = []
    for i in range(lanes):
        devs = tuple(devices[i * per_lane : (i + 1) * per_lane])
        groups.append(ChipGroup(index=i, devices=devs, mesh=default_mesh(devs)))
    return MeshTopology(groups=tuple(groups), source=source)


def collective_names(plan) -> List[str]:
    """The XLA collectives a plan's cross-chip merge lowers to, from
    its output reducers (parallel/multichip.py ``_collective``) — the
    EXPLAIN ``mesh.collective`` field."""
    from pinot_tpu.engine.kernel import output_reducers

    ops = set()
    for op in output_reducers(plan).values():
        if op == "sum" or op == "sum_pair":
            ops.add("psum")
        elif op == "min":
            ops.add("pmin")
        elif op == "max" or op.startswith("hll_sort:"):
            ops.add("pmax")
        elif op == "minmax_pair":
            ops.update(("pmin", "pmax"))
        elif op == "distinct_pairs":
            ops.update(("all_gather", "psum"))
        elif op == "none":
            ops.add("gather")  # sharded outputs gather host-side
    return sorted(ops)
