"""Query planning: BrokerRequest -> (StaticPlan, QueryInputs).

The reference's plan maker (``InstancePlanMakerImplV2.java:40``) builds a
virtual-call operator tree per segment.  Here planning splits a query
into:

- **StaticPlan** — a hashable description of the kernel's *structure*:
  filter tree shape, leaf modes, aggregation list, group-by strides and
  capacity, selection spec.  It is the jit-cache key: two queries with
  the same StaticPlan and array shapes share one compiled XLA program.

- **QueryInputs** — per-segment *data* for that structure, all computed
  host-side in O(cardinality) per column: predicate match tables in
  dictId space (the PredicateEvaluator analog — an EQ/IN/RANGE/REGEX
  predicate becomes a ``bool[card]`` table; the device then does ONE
  gather per leaf, which is the vectorized inverted index), global-id
  remap tables for group-by/distinct/percentile, HLL (bucket, rho)
  tables per dictionary entry.

Filter leaf modes:
  SV      — mask = table[fwd]
  MV_ANY  — mask = any(table[mv] & mv_valid)         (positive predicates)
  MV_NONE — mask = ~any(member[mv] & mv_valid)       (NOT / NOT_IN)
"""
from __future__ import annotations

import re
from dataclasses import dataclass, replace
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from pinot_tpu.common.request import (
    AggregationInfo,
    BrokerRequest,
    FilterOperator,
    FilterQueryTree,
    RangeSpec,
)
from pinot_tpu.common.schema import DataType
from pinot_tpu.engine import config
from pinot_tpu.engine import hll as hll_mod
from pinot_tpu.engine.context import TableContext
from pinot_tpu.engine.device import StagedTable
from pinot_tpu.segment.dictionary import Dictionary


# ---------------------------------------------------------------------------
# Static plan
# ---------------------------------------------------------------------------

SV, MV_ANY, MV_NONE = "sv", "mv_any", "mv_none"


@dataclass(frozen=True)
class StaticLeaf:
    column: str
    mode: str  # SV | MV_ANY | MV_NONE
    # Gathers through big tables are slow on TPU, but dictIds are
    # order-preserving, so most predicates become vector compares:
    #   docrange    — (iota >= lo_doc) & (iota < hi_doc): a RANGE/EQ on
    #                 a column sorted in every segment is a contiguous
    #                 doc interval found host-side by binary search; the
    #                 kernel never reads the column at all (the
    #                 SortedInvertedIndexBasedFilterOperator analog)
    #   interval    — (fwd >= lo) & (fwd < hi), bounds from q["bounds"]
    #   points      — any(fwd == pts[k]) for small IN/EQ sets
    #   points_none — complement of points (NOT / NOT_IN)
    #   table       — bool[card] gather (regex, large IN lists)
    eval_kind: str = "table"
    k_pad: int = 0  # static points-array length (pow2-padded)


@dataclass(frozen=True)
class StaticAgg:
    func: str  # full function name e.g. "sum", "summv"
    base: str  # base function e.g. "sum"
    column: str  # "*" for count(*)
    is_mv: bool
    # device state kind: scalar | pair | presence | hist | hll
    kind: str
    # static size of the value-state axis (presence/hist), 0 otherwise
    gcard_pad: int = 0
    # read values from the staged raw array (streaming) instead of
    # gathering dict_vals[fwd] — big-dictionary gathers are slow on TPU
    use_raw: bool = False
    # exact distinct via device sort-dedup of (group, valueId) pairs
    # instead of the dense [capacity, gcard_pad] presence holder — the
    # high-cardinality path that keeps distinctcount on-chip where the
    # reference switches to map-based storage
    # (DefaultGroupKeyGenerator.java:60-63)
    sort_pairs: bool = False
    # distinctcounthll lowered to a presence contraction: HLL registers
    # depend only on the DISTINCT value set, so for dictionary columns
    # with modest global cardinality the device computes per-(group,
    # globalDictId) occupancy (K = cap * gcard_pad) and finalize maps
    # present ids -> registers via the global dict's (bucket, rho)
    # tables — bit-identical registers at a fraction of the FLOPs of
    # the direct (group, bucket, rho) contraction (K = cap * 16384)
    hll_from_presence: bool = False


@dataclass(frozen=True)
class StaticGroupBy:
    columns: Tuple[str, ...]
    col_is_mv: Tuple[bool, ...]
    gcards: Tuple[int, ...]  # global cardinalities (strides derive from these)
    capacity: int  # dense holder size = prod(gcards), device path only
    top_n: int
    # per column: read staged global-id fwd (gfwd) instead of gathering
    # remap[fwd] on device (remap gathers are slow for big dictionaries)
    use_gfwd: Tuple[bool, ...] = ()


@dataclass(frozen=True)
class StaticSelection:
    columns: Tuple[str, ...]
    sort_columns: Tuple[str, ...]
    sort_ascending: Tuple[bool, ...]
    sort_gcards: Tuple[int, ...]  # global cards = composite-key radices
    k: int  # per-segment candidates = offset + size
    # True -> sort key packs into one integer (radix product fits key dtype,
    # lax.top_k path); False -> multi-operand lexicographic lax.sort path.
    packed: bool = True
    use_gfwd: Tuple[bool, ...] = ()  # per sort column, as StaticGroupBy


@dataclass(frozen=True)
class StaticPlan:
    # filter tree encoded as nested tuples: ("leaf", i) | ("and"|"or", (...))
    filter_tree: Optional[tuple]
    leaves: Tuple[StaticLeaf, ...]
    aggs: Tuple[StaticAgg, ...]
    group_by: Optional[StaticGroupBy]
    selection: Optional[StaticSelection]
    on_device: bool  # False -> host (numpy) fallback path


def group_capacity(request, ctx) -> int:
    """Dense group-key space: product of the group columns' global
    cardinalities — the ONE definition build_static_plan and the
    pre-staging host check share."""
    cap = 1
    for c in request.group_by.columns:
        cap *= max(ctx.column(c).global_cardinality, 1)
    return cap


def group_capacity_forces_host(cap: int) -> bool:
    return cap > config.MAX_GROUP_CAPACITY or cap > config.max_key_space()


def value_state_sort_pairs(kind: str, gcard_pad: int, cap: Optional[int]) -> bool:
    """Whether a value-state agg (presence/hist/hll) leaves the dense
    holder for the pair-sort path: per-agg state too big, or (grouped)
    the [capacity, state] product too big.  Shared by build_static_plan
    and plan_forced_host so the two can never drift."""
    if kind in ("presence", "hist") and gcard_pad > config.MAX_VALUE_STATE:
        return True
    if cap is not None:
        state = gcard_pad if kind != "hll" else config.HLL_M
        return cap * state > config.MAX_VALUE_STATE * 4
    return False


def plan_forced_host(request, ctx) -> bool:
    """Host-path decisions decidable BEFORE staging — a strict subset of
    the ``on_device = False`` conditions ``build_static_plan`` applies
    (via the same shared predicates above).  The executor consults this
    first so a query that can only run on the host never pays device
    staging (at north-star scale that's a 1GB+ transfer for nothing;
    VERDICT r4 #4 measured the waste at ~30 minutes through a tunneled
    chip)."""
    try:
        cap = group_capacity(request, ctx) if request.is_group_by else None
        if cap is not None and group_capacity_forces_host(cap):
            return True
        if request.filter is None:
            for a in request.aggregations:
                if a.column == "*":
                    continue
                if _agg_kind(a.base_function) not in ("presence", "hist"):
                    continue
                gcard = ctx.column(a.column).global_cardinality
                if gcard <= config.DISTINCT_PAIR_CAP:
                    continue
                # with no filter every dictionary entry lands in >= 1
                # (group, valueId) pair, so a sort-pairs agg at this
                # cardinality is guaranteed to overflow the device
                # buffer (the same condition build_static_plan applies)
                if value_state_sort_pairs(
                    _agg_kind(a.base_function), config.pad_value_card(gcard), cap
                ):
                    return True
    except KeyError:
        return False  # unknown column: let the normal path raise properly
    return False


def hll_lowers_to_presence(request, ctx, column: str) -> bool:
    """Whether an SV distinctcounthll lowers to a presence contraction
    (see StaticAgg.hll_from_presence).  Shared by the planner and the
    executor's staging-role decision (gfwd stream vs per-row HLL
    streams) — the two MUST agree or the kernel reads missing arrays.

    Presence wins when the per-group value state (gcard_pad) is smaller
    than the direct register state (HLL_M * 64 rho lanes); the dense
    holder must also fit the same cap the presence guard applies."""
    import os

    if os.environ.get("PINOT_TPU_HLL_PRESENCE", "1") == "0":
        return False  # A/B kill switch: force the per-row register streams
    gcard_pad = config.pad_value_card(ctx.column(column).global_cardinality)
    if gcard_pad > config.HLL_M * 64:
        return False
    cap = 1
    if request.is_group_by:
        for c in request.group_by.columns:
            cap *= max(ctx.column(c).global_cardinality, 1)
    return cap * gcard_pad <= config.MAX_VALUE_STATE * 4


def _agg_kind(base: str) -> str:
    if base in ("count", "sum", "min", "max"):
        return "scalar"
    if base in ("avg", "minmaxrange"):
        return "pair"
    if base == "distinctcount":
        return "presence"
    if base in ("distinctcounthll", "fasthll"):
        return "hll"
    if base.startswith("percentile"):
        return "hist"
    raise ValueError(f"unknown aggregation {base!r}")


_MAX_POINTS = 16  # IN lists up to this size evaluate as compares
_MAX_RUNS = 64  # match tables with <= this many dictId runs evaluate as interval unions


# regex tables are the one plan-time cost that SCANS a dictionary (re
# over every value); identical regex leaves across queries hit this
# LRU instead, keyed by segment identity so reloads can't alias
_regex_tables: "OrderedDict[tuple, np.ndarray]" = OrderedDict()


def cached_match_table(
    leaf_node, d: Dictionary, card_pad: int, cache_key: Optional[tuple]
) -> np.ndarray:
    """``match_table`` with the regex LRU in front — regex is the only
    operator whose table costs a full dictionary scan.  Raw (pre-
    complement) tables key under a distinct tag so they can never alias
    ``_effective_table`` entries."""
    if cache_key is None or leaf_node.operator != FilterOperator.REGEX:
        return match_table(leaf_node, d, card_pad)
    key = ("raw", cache_key, card_pad, tuple(leaf_node.values))
    cached = _regex_tables.get(key)
    if cached is not None:
        _regex_tables.move_to_end(key)
        return cached
    t = match_table(leaf_node, d, card_pad)
    _regex_tables[key] = t
    if len(_regex_tables) > 256:
        _regex_tables.popitem(last=False)
    return t


def _effective_table(
    leaf_node,
    mode: str,
    d: Dictionary,
    card_pad: int,
    true_card: int,
    cache_key: Optional[tuple] = None,
) -> np.ndarray:
    """The table the kernel would read for this leaf: SV NOT/NOT_IN
    bakes the complement (kernel negates MV_NONE after the
    any-reduce).  Shared by plan-time run counting and input build so
    they can never disagree."""
    key = None
    if cache_key is not None and leaf_node.operator == FilterOperator.REGEX:
        key = (cache_key, mode, card_pad, true_card, tuple(leaf_node.values))
        cached = _regex_tables.get(key)
        if cached is not None:
            _regex_tables.move_to_end(key)
            return cached
    t = match_table(leaf_node, d, card_pad)
    if mode == SV and leaf_node.operator in (FilterOperator.NOT, FilterOperator.NOT_IN):
        flipped = np.zeros(card_pad, dtype=bool)
        flipped[:true_card] = ~t[:true_card]
        t = flipped
    if key is not None:
        _regex_tables[key] = t
        if len(_regex_tables) > 256:
            _regex_tables.popitem(last=False)
    return t


def _table_runs(t: np.ndarray):
    """Maximal True runs of a bool table -> [(lo, hi)) dictId ranges."""
    if not t.any():
        return []
    d = np.diff(t.astype(np.int8))
    starts = list(np.nonzero(d == 1)[0] + 1)
    ends = list(np.nonzero(d == -1)[0] + 1)
    if t[0]:
        starts.insert(0, 0)
    if t[-1]:
        ends.append(t.size)
    return list(zip(starts, ends))


def _pad_pow2(k: int) -> int:
    p = 1
    while p < k:
        p *= 2
    return p


def _leaf_eval_kind(node: FilterQueryTree) -> Tuple[str, int]:
    op = node.operator
    if op == FilterOperator.RANGE:
        return "interval", 0
    if op in (FilterOperator.EQUALITY, FilterOperator.IN):
        k = len(node.values)
        if 0 < k <= _MAX_POINTS:
            return "points", _pad_pow2(k)
    if op in (FilterOperator.NOT, FilterOperator.NOT_IN):
        k = len(node.values)
        if 0 < k <= _MAX_POINTS:
            return "points_none", _pad_pow2(k)
    return "table", 0


def build_static_plan(
    request: BrokerRequest,
    ctx: TableContext,
    staged: StagedTable,
    scratch: Optional[Dict[Any, Any]] = None,
) -> StaticPlan:
    """``scratch`` (optional dict the executor threads into
    build_query_inputs) caches plan-time effective match tables so a
    regex never scans a dictionary twice per query."""
    # ---- filter -----------------------------------------------------
    leaves: List[StaticLeaf] = []

    def encode(node: FilterQueryTree) -> tuple:
        if node.is_leaf:
            # mode from segment metadata, not the staged column: a
            # docrange-only column may be dropped from staging entirely
            if ctx.segments[0].column(node.column).metadata.single_value:
                mode = SV
            elif node.operator in (FilterOperator.NOT, FilterOperator.NOT_IN):
                mode = MV_NONE
            else:
                mode = MV_ANY
            eval_kind, k_pad = _leaf_eval_kind(node)
            if eval_kind == "table":
                # gathers through big match tables serialize on TPU; a
                # table that is a FEW contiguous dictId runs (regex on
                # ordered values, big IN lists over ranges) evaluates as
                # a vectorized interval union instead.  Values-based
                # operators bound their run count by the value count
                # (complements add one run) without building tables;
                # only regex pays a plan-time table scan.
                if node.operator != FilterOperator.REGEX:
                    max_runs = len(node.values) + 1
                else:
                    max_runs = 0
                    for si, seg in enumerate(ctx.segments):
                        scol = seg.column(node.column)
                        stg = staged.column(node.column)
                        t = _effective_table(
                            node, mode, scol.dictionary, stg.card_pad, stg.cards[si],
                            cache_key=(seg.segment_name, seg.metadata.crc, node.column),
                        )
                        if scratch is not None:
                            scratch[(id(node), si)] = t
                        max_runs = max(max_runs, len(_table_runs(t)))
                if max_runs <= _MAX_RUNS:
                    eval_kind, k_pad = "runs", _pad_pow2(max(max_runs, 1))
            if (
                mode == SV
                and (
                    eval_kind == "interval"
                    or (eval_kind == "points" and len(node.values) == 1
                        and node.operator == FilterOperator.EQUALITY)
                )
                and all(
                    seg.column(node.column).metadata.is_sorted
                    for seg in ctx.segments
                )
            ):
                # sorted in every segment: the predicate is one doc
                # interval per segment — no column read in the kernel
                eval_kind, k_pad = "docrange", 0
            leaves.append(
                StaticLeaf(
                    column=node.column, mode=mode, eval_kind=eval_kind, k_pad=k_pad
                )
            )
            return ("leaf", len(leaves) - 1)
        op = "and" if node.operator == FilterOperator.AND else "or"
        return (op, tuple(encode(c) for c in node.children))

    tree = encode(request.filter) if request.filter is not None else None

    on_device = True

    # ---- aggregations ----------------------------------------------
    aggs: List[StaticAgg] = []
    for a in request.aggregations:
        base = a.base_function
        kind = _agg_kind(base)
        gcard_pad = 0
        sort_pairs = False
        hll_from_presence = False
        if (
            kind == "hll"
            and a.column != "*"
            and staged.column(a.column).single_value
            and hll_lowers_to_presence(request, ctx, a.column)
        ):
            kind = "presence"
            hll_from_presence = True
        if kind in ("presence", "hist"):
            gcol = ctx.column(a.column)
            gcard_pad = config.pad_value_card(gcol.global_cardinality)
            if value_state_sort_pairs(kind, gcard_pad, None):
                # dense state would not fit: sort the (group, valueId)
                # pairs on device instead — dedup covers distinctcount,
                # run-length counts cover exact percentile histograms
                sort_pairs = True
        is_mv = a.is_mv
        if a.column != "*" and not staged.column(a.column).single_value:
            is_mv = True
        use_raw = (
            a.column != "*"
            and not is_mv
            and staged.column(a.column).raw is not None
        )
        aggs.append(
            StaticAgg(
                func=a.function,
                base=base,
                column=a.column,
                is_mv=is_mv,
                kind=kind,
                gcard_pad=gcard_pad,
                use_raw=use_raw,
                sort_pairs=sort_pairs,
                hll_from_presence=hll_from_presence,
            )
        )

    # ---- group-by ---------------------------------------------------
    group_by: Optional[StaticGroupBy] = None
    if request.is_group_by:
        cols = tuple(request.group_by.columns)
        col_is_mv = tuple(not staged.column(c).single_value for c in cols)
        gcards = tuple(ctx.column(c).global_cardinality for c in cols)
        cap = group_capacity(request, ctx)
        if group_capacity_forces_host(cap):
            on_device = False
        # value-state aggs need [capacity, gcard] holders — cap the
        # product; presence escapes to the sort-dedup path instead of
        # leaving the device
        for ai, a in enumerate(aggs):
            if a.sort_pairs:
                continue
            if a.kind in ("presence", "hist", "hll"):
                if value_state_sort_pairs(a.kind, a.gcard_pad, cap):
                    # every value-state kind sorts instead of leaving
                    # the device: presence dedups, hist counts runs,
                    # hll packs (bucket, rho) into the pair gid
                    aggs[ai] = replace(a, sort_pairs=True)
        for a in aggs:
            # the finalize paths for hll_from_presence handle only the
            # dense holder (hll_lowers_to_presence admits exactly the
            # shapes the presence guards keep dense)
            assert not (a.hll_from_presence and a.sort_pairs), a
        group_by = StaticGroupBy(
            columns=cols,
            col_is_mv=col_is_mv,
            gcards=gcards,
            capacity=int(cap),
            top_n=request.group_by.top_n,
            use_gfwd=tuple(
                not mv and staged.column(c).gfwd is not None
                for c, mv in zip(cols, col_is_mv)
            ),
        )
        # MV group-by expansion blowup guard
        expansion = 1
        for c, mv in zip(cols, col_is_mv):
            if mv:
                expansion *= staged.column(c).mv_pad
        if expansion > 64:
            on_device = False

    # Guaranteed sort-pairs overflow: the global dictionary holds only
    # values PRESENT in the data, so with no filter every dict entry
    # lands in >= 1 (group, valueId) pair — more unique pairs than the
    # device compaction buffer can return.  Skip the doomed device sort
    # (staging + compile + a 134M-row sort at north-star scale) and go
    # straight to the host path the overflow would reach anyway.
    if request.filter is None:
        for a in aggs:
            if (
                a.sort_pairs
                and a.kind in ("presence", "hist")
                and ctx.column(a.column).global_cardinality
                > config.DISTINCT_PAIR_CAP
            ):
                on_device = False

    # ---- selection --------------------------------------------------
    selection: Optional[StaticSelection] = None
    if request.is_selection:
        sel = request.selection
        cols = tuple(sel.columns) if sel.columns and sel.columns != ["*"] else ("*",)
        sort_cols = tuple(s.column for s in sel.sorts)
        sort_asc = tuple(s.ascending for s in sel.sorts)
        k = min(sel.offset + sel.size, staged.n_pad)
        # Composite sort key packs into one integer only when the radix
        # product fits the key dtype; wider key spaces stay on device via
        # multi-operand lexicographic lax.sort (no host fallback needed).
        sort_gcards = tuple(max(ctx.column(c).global_cardinality, 1) for c in sort_cols)
        space = 1
        for g in sort_gcards:
            space *= g
        selection = StaticSelection(
            columns=cols,
            sort_columns=sort_cols,
            sort_ascending=sort_asc,
            sort_gcards=sort_gcards,
            k=int(k),
            packed=space <= config.max_key_space(),
            use_gfwd=tuple(
                staged.column(c).single_value and staged.column(c).gfwd is not None
                for c in sort_cols
            ),
        )

    return StaticPlan(
        filter_tree=tree,
        leaves=tuple(leaves),
        aggs=tuple(aggs),
        group_by=group_by,
        selection=selection,
        on_device=on_device,
    )


# ---------------------------------------------------------------------------
# Match tables (host-side predicate evaluation in dictId space)
# ---------------------------------------------------------------------------


def _coerce(literal: str, stored: DataType) -> Any:
    return stored.convert(literal)


def _doc_bound(fwd: np.ndarray, dict_id: int) -> int:
    """First doc index with fwd >= dict_id on a sorted column.

    The scalar is cast to the forward index's (narrow) dtype before the
    binary search — a plain Python int makes numpy promote-and-copy the
    whole array (250us on a 250k-row uint16 column vs ~1us)."""
    if dict_id <= 0:
        return 0
    if np.issubdtype(fwd.dtype, np.integer) and dict_id > int(np.iinfo(fwd.dtype).max):
        return int(fwd.size)
    return int(np.searchsorted(fwd, np.asarray(dict_id, dtype=fwd.dtype), "left"))


def leaf_interval(node: FilterQueryTree, dictionary: Dictionary) -> Tuple[int, int]:
    """Half-open [lo, hi) dictId interval satisfying a RANGE leaf —
    dictIds are order-preserving, so range predicates are interval
    compares in dictId space (no table, no gather)."""
    stored = dictionary.stored_type
    card = dictionary.cardinality
    r = node.range_spec or RangeSpec()
    lo = 0
    hi = card
    if r.lower is not None and r.lower != "*":
        v = _coerce(r.lower, stored)
        i = dictionary.insertion_index(v)
        if r.include_lower:
            lo = i
        else:
            lo = i + 1 if (i < card and dictionary._eq(dictionary.values[i], v)) else i
    if r.upper is not None and r.upper != "*":
        v = _coerce(r.upper, stored)
        i = dictionary.insertion_index(v)
        if r.include_upper:
            hi = i + 1 if (i < card and dictionary._eq(dictionary.values[i], v)) else i
        else:
            hi = i
    return lo, max(lo, hi)


def leaf_points(node: FilterQueryTree, dictionary: Dictionary, k_pad: int) -> np.ndarray:
    """dictIds of a small EQ/IN/NOT_IN value set, padded with -1 (which
    never matches a forward index)."""
    stored = dictionary.stored_type
    pts = np.full(k_pad, -1, dtype=np.int32)
    j = 0
    for v in node.values:
        i = dictionary.index_of(_coerce(v, stored))
        if i >= 0:
            pts[j] = i
            j += 1
    return pts


def match_table(node: FilterQueryTree, dictionary: Dictionary, card_pad: int) -> np.ndarray:
    """bool[card_pad] — True at dictIds whose value satisfies the leaf.

    For MV_NONE leaves the table is *membership* of the excluded set
    (the kernel negates after the any-reduction).
    """
    stored = dictionary.stored_type
    card = dictionary.cardinality
    table = np.zeros(card_pad, dtype=bool)
    op = node.operator
    if op in (FilterOperator.EQUALITY, FilterOperator.IN):
        for v in node.values:
            i = dictionary.index_of(_coerce(v, stored))
            if i >= 0:
                table[i] = True
    elif op in (FilterOperator.NOT, FilterOperator.NOT_IN):
        # SV: complement table; MV: membership table (kernel handles NONE)
        member = np.zeros(card_pad, dtype=bool)
        for v in node.values:
            i = dictionary.index_of(_coerce(v, stored))
            if i >= 0:
                member[i] = True
        table = member  # caller flips for SV below
    elif op == FilterOperator.RANGE:
        lo, hi = leaf_interval(node, dictionary)
        if hi > lo:
            table[lo:hi] = True
    elif op == FilterOperator.REGEX:
        pattern = re.compile(node.values[0])
        for i in range(card):
            if pattern.search(str(dictionary.get(i))) is not None:
                table[i] = True
    else:
        raise ValueError(f"unsupported leaf operator {op}")
    return table


# ---------------------------------------------------------------------------
# Query inputs (per-segment arrays, stacked [S, ...])
# ---------------------------------------------------------------------------


def build_query_inputs(
    request: BrokerRequest,
    plan: StaticPlan,
    ctx: TableContext,
    staged: StagedTable,
    scratch: Optional[Dict[Any, Any]] = None,
) -> Dict[str, Any]:
    S = staged.num_segments
    inputs: Dict[str, Any] = {}

    # filter leaf match tables
    if plan.filter_tree is not None:
        # walk request filter leaves in the same order encode() visited them
        flat_leaves: List[FilterQueryTree] = []

        def collect(node: FilterQueryTree) -> None:
            if node.is_leaf:
                flat_leaves.append(node)
            else:
                for c in node.children:
                    collect(c)

        collect(request.filter)
        tables = []
        bounds = []
        points = []
        run_arrays = []
        for leaf_node, leaf_static in zip(flat_leaves, plan.leaves):
            kind = leaf_static.eval_kind
            # dummies keep the pytree structure identical per plan
            table_e = np.zeros((S, 1), dtype=bool)
            bound_e = np.zeros((S, 2), dtype=np.int32)
            point_e = np.zeros((S, max(leaf_static.k_pad, 1)), dtype=np.int32)
            runs_e = np.zeros(
                (S, max(leaf_static.k_pad, 1) if kind == "runs" else 1, 2),
                dtype=np.int32,
            )
            for i, seg in enumerate(ctx.segments):
                scol = seg.column(leaf_static.column)
                d = scol.dictionary
                if kind == "runs":
                    t = None if scratch is None else scratch.get((id(leaf_node), i))
                    if t is None:
                        stg = staged.column(leaf_static.column)
                        t = _effective_table(
                            leaf_node, leaf_static.mode, d, stg.card_pad, stg.cards[i],
                            cache_key=(seg.segment_name, seg.metadata.crc, leaf_static.column),
                        )
                    for ri, (lo, hi) in enumerate(_table_runs(t)):
                        runs_e[i, ri] = (lo, hi)
                elif kind == "interval":
                    bound_e[i] = leaf_interval(leaf_node, d)
                elif kind == "docrange":
                    if leaf_node.operator == FilterOperator.EQUALITY:
                        did = d.index_of(d.stored_type.convert(leaf_node.values[0]))
                        lo, hi = (did, did + 1) if did >= 0 else (0, 0)
                    else:
                        lo, hi = leaf_interval(leaf_node, d)
                    bound_e[i] = (
                        _doc_bound(scol.fwd, lo),
                        _doc_bound(scol.fwd, hi),
                    )
                elif kind in ("points", "points_none"):
                    point_e[i] = leaf_points(leaf_node, d, leaf_static.k_pad)
                else:
                    col = staged.column(leaf_static.column)
                    if table_e.shape[1] == 1:
                        table_e = np.zeros((S, col.card_pad), dtype=bool)
                    t = None if scratch is None else scratch.get((id(leaf_node), i))
                    if t is None:
                        t = _effective_table(
                            leaf_node, leaf_static.mode, d, col.card_pad, col.cards[i],
                            cache_key=(seg.segment_name, seg.metadata.crc, leaf_static.column),
                        )
                    table_e[i] = t
            tables.append(table_e)
            bounds.append(bound_e)
            points.append(point_e)
            run_arrays.append(runs_e)
        inputs["match"] = tables
        inputs["bounds"] = bounds
        inputs["pts"] = points
        inputs["runs"] = run_arrays

    # per-agg auxiliary tables
    agg_aux: List[Dict[str, np.ndarray]] = []
    for a in plan.aggs:
        aux: Dict[str, np.ndarray] = {}
        if a.kind in ("presence", "hist"):
            # SV presence/hist read the staged .gfwd stream (kernel
            # _value_gids); shipping the full remap table then would
            # be dead H2D weight — dummy it, as group_remap does
            if not a.is_mv and staged.column(a.column).gfwd is not None:
                aux["remap"] = np.zeros((S, 1), dtype=np.int32)
            else:
                aux["remap"] = _stacked_remap(ctx, staged, a.column)
        elif a.kind == "hll":
            if not a.is_mv and staged.column(a.column).hll_bucket is not None:
                # staged per-row streams: the tables would be dead H2D
                aux["bucket"] = np.zeros((S, 1), dtype=np.int32)
                aux["rho"] = np.zeros((S, 1), dtype=np.int32)
            else:
                bucket, rho = _hll_tables(ctx, staged, a.column)
                aux["bucket"] = bucket
                aux["rho"] = rho
        agg_aux.append(aux)
    inputs["agg_aux"] = agg_aux

    # group-by remaps (dummy entry when the staged gfwd array is used)
    if plan.group_by is not None and plan.on_device:
        inputs["group_remap"] = [
            np.zeros((S, 1), dtype=np.int32)
            if use_g
            else _stacked_remap(ctx, staged, c)
            for c, use_g in zip(plan.group_by.columns, plan.group_by.use_gfwd)
        ]

    # selection sort remaps
    if plan.selection is not None and plan.selection.sort_columns:
        inputs["sel_remap"] = [
            np.zeros((S, 1), dtype=np.int32)
            if use_g
            else _stacked_remap(ctx, staged, c)
            for c, use_g in zip(
                plan.selection.sort_columns, plan.selection.use_gfwd
            )
        ]

    return inputs


def _stacked_remap(ctx: TableContext, staged: StagedTable, column: str) -> np.ndarray:
    col = staged.column(column)
    gcol = ctx.column(column)
    out = np.zeros((staged.num_segments, col.card_pad), dtype=np.int32)
    for i, remap in enumerate(gcol.remaps):
        out[i, : remap.size] = remap
    return out


def _hll_tables(ctx: TableContext, staged: StagedTable, column: str):
    """Per-dictId (bucket, rho) tables: the HLL hash work happens once
    per dictionary entry on host; the device only scatter-maxes."""
    col = staged.column(column)
    S = staged.num_segments
    bucket = np.zeros((S, col.card_pad), dtype=np.int32)
    rho = np.zeros((S, col.card_pad), dtype=np.int32)
    for i, seg in enumerate(ctx.segments):
        d = seg.column(column).dictionary
        bt, rt = hll_mod.dictionary_tables(d)
        bucket[i, : bt.size] = bt
        rho[i, : rt.size] = rt
    return bucket, rho
