"""Table dictionary context: global (query-level) dictionaries + per-segment
remaps.

Dictionaries are per-segment in the reference, and cross-segment group-by
merge happens by *materialized value* in Java HashMaps
(``MCombineGroupByOperator.java:152``).  That doesn't vectorize.  The
TPU-native design instead builds a **table-level global dictionary** per
column (the sorted union of the segments' dictionaries) plus one small
``remap: int32[segment_card]`` array per (segment, column) translating
local dictIds to global ids.  Group keys, distinct-count presence vectors
and percentile histograms are then indexed in the *global* id space —
identical across segments — so cross-segment (and cross-chip) merge is a
plain elementwise reduction (``psum``-able over ICI), with group-key
materialization a single host-side lookup at reduce time.

Contexts are cached per (table, segment-set fingerprint): segments are
immutable, so remaps never change for a sealed segment.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from pinot_tpu.common.schema import DataType
from pinot_tpu.segment.dictionary import Dictionary
from pinot_tpu.segment.immutable import ImmutableSegment


@dataclass
class GlobalColumn:
    """Global dictionary + per-segment remap arrays for one column."""

    name: str
    stored_type: DataType
    global_dict: Dictionary
    # remaps[i][local_dict_id] -> global_dict_id  (int32, len = segment card)
    remaps: List[np.ndarray]

    @property
    def global_cardinality(self) -> int:
        return self.global_dict.cardinality


class TableContext:
    """Global dictionaries for one set of segments (one query's scope)."""

    def __init__(self, segments: Sequence[ImmutableSegment]):
        self.segments = list(segments)
        self._columns: Dict[str, GlobalColumn] = {}

    def column(self, name: str) -> GlobalColumn:
        gc = self._columns.get(name)
        if gc is None:
            gc = self._build(name)
            self._columns[name] = gc
        return gc

    def _build(self, name: str) -> GlobalColumn:
        dicts = [seg.column(name).dictionary for seg in self.segments]
        stored = dicts[0].stored_type
        if stored == DataType.STRING:
            union = sorted(set().union(*[set(d.values) for d in dicts]))
            gdict = Dictionary(stored, union)
            lookup = {v: i for i, v in enumerate(union)}
            remaps = [
                np.fromiter((lookup[v] for v in d.values), dtype=np.int32, count=len(d))
                for d in dicts
            ]
        else:
            union = np.unique(np.concatenate([np.asarray(d.values) for d in dicts]))
            gdict = Dictionary(stored, union)
            remaps = [
                np.searchsorted(union, np.asarray(d.values)).astype(np.int32) for d in dicts
            ]
        return GlobalColumn(name=name, stored_type=stored, global_dict=gdict, remaps=remaps)


_context_cache: Dict[Tuple[str, ...], TableContext] = {}


def get_table_context(segments: Sequence[ImmutableSegment]) -> TableContext:
    # (name, crc, instance token): the token makes a re-loaded segment
    # (quarantine re-fetch) miss — a context built from a corrupt load's
    # dictionaries must never serve the clean copy (see engine/device.py)
    key = tuple((s.segment_name, s.metadata.crc, s.staging_token) for s in segments)
    ctx = _context_cache.get(key)
    if ctx is None:
        ctx = TableContext(segments)
        if len(_context_cache) > 64:
            _context_cache.clear()
        _context_cache[key] = ctx
    return ctx
