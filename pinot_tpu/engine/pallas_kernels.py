"""Pallas TPU kernels — fused hot-path experiments.

The default engine path is plain XLA (gathers + masked reductions +
one-hot matmul group-by), which XLA fuses well.  This module provides a
hand-fused Pallas version of the hottest query shape — filtered
multi-SUM group-by (TPC-H Q1) — keeping each row block's entire
pipeline (filter -> mask -> dictionary lookup -> one-hot matmul
accumulate) inside VMEM, one HBM read per forward-index element.

TPU lowering notes (validated on a real v5e chip):

* Mosaic has no arbitrary VMEM int-indexing; ``table[idx]`` does not
  lower.  Two TPU-native substitutes are used instead:
  - **interval filters** (the common case after the planner's
    dictId-space rewrite, e.g. ``l_shipdate <= '1998-09-02'``) become
    pure vector compares ``lo <= fwd < hi`` — no table at all;
  - **table lookups** (match tables, value dictionaries) become
    chunked lane shuffles: the table is cut into 128-lane chunks, each
    chunk is broadcast across sublanes and gathered with
    ``jnp.take_along_axis(chunk, idx - c*128, axis=1)``, which lowers
    to ``tpu.dynamic_gather``; out-of-chunk lanes are masked.  Cost is
    O(card/128) vector ops per block, so tables are capped at
    ``MAX_TABLE_CARD``; higher-cardinality value columns must be fed as
    raw float rows (``value_dicts[i] is None``).
* Group accumulation stays a one-hot matmul into a persistent VMEM
  scratch across grid steps (the MXU path, mirroring
  ``kernel._segment_add_matmul``).

Status: compiled + validated on TPU v5e; also runs in interpret mode on
CPU for the unit tests.  Wiring into the executor is gated on the
microbench (see ``tools/microbench.py``): XLA's own fusion of the same
pipeline is the default.
"""
from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pinot_tpu.engine import config

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    PALLAS_AVAILABLE = True
except ImportError:  # pragma: no cover
    PALLAS_AVAILABLE = False

import os as _os

# sublanes per grid step; the sublane walk is unrolled at trace time, so
# larger blocks trade Mosaic compile time for fewer grid steps
BLOCK_ROWS = int(_os.environ.get("PINOT_TPU_PALLAS_ROWS", "8"))
BLOCK_COLS = 128  # lanes
BLOCK = BLOCK_ROWS * BLOCK_COLS
LANE = 128
MAX_TABLE_CARD = 4096  # beyond this a lookup is 32+ chunked shuffles — feed raw


def _pad_rows(n: int) -> int:
    return -(-n // BLOCK) * BLOCK


def _pad_lane(c: int) -> int:
    return max(LANE, -(-c // LANE) * LANE)


def use_pallas() -> bool:
    import os

    return PALLAS_AVAILABLE and os.environ.get("PINOT_TPU_USE_PALLAS") == "1"


def _table_gather(tab_row: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """``tab_row[idx]`` via chunked lane shuffles.

    tab_row: [card_pad] (card_pad % 128 == 0), idx: [R, 128] int32.
    Lowers to ``tpu.dynamic_gather`` per 128-wide chunk.
    """
    card_pad = tab_row.shape[0]
    out = jnp.zeros(idx.shape, tab_row.dtype)
    for c in range(card_pad // LANE):
        chunk = jnp.broadcast_to(tab_row[c * LANE : (c + 1) * LANE][None, :], idx.shape)
        local = idx - c * LANE
        in_chunk = (local >= 0) & (local < LANE)
        g = jnp.take_along_axis(chunk, jnp.clip(local, 0, LANE - 1), axis=1)
        out = jnp.where(in_chunk, g, out)
    return out


def fused_filtered_groupby_sums(
    filter_fwd: jnp.ndarray,  # int [n]
    match: Optional[jnp.ndarray],  # bool [card_f] (table mode) or None
    valid: jnp.ndarray,  # bool  [n]
    group_keys: jnp.ndarray,  # int32 [n] precombined mixed-radix keys
    value_fwds: Sequence[Optional[jnp.ndarray]],  # int [n] or None (raw mode)
    value_dicts: Sequence[Optional[jnp.ndarray]],  # float [card_v] or None
    capacity: int,
    interpret: bool = False,
    filter_bounds: Optional[Tuple[int, int]] = None,  # interval mode [lo, hi)
    value_raws: Optional[Sequence[Optional[jnp.ndarray]]] = None,  # float [n]
):
    """Returns (num_docs, count[K], [sums[K] per value column]).

    One fused pass: mask = filter(filter_fwd) & valid; per value column
    v = dict[v_fwd] (or raw rows); scatter via one-hot matmul into K
    buckets.  Filter is either a match table (``match``) or a dictId
    interval (``filter_bounds``); exactly one must be given.
    """
    if (match is None) == (filter_bounds is None):
        raise ValueError("exactly one of match / filter_bounds required")
    if match is not None and match.shape[0] > MAX_TABLE_CARD:
        raise ValueError(
            f"match table card {match.shape[0]} > {MAX_TABLE_CARD}: the chunked "
            "lane-shuffle unrolls O(card/128) ops per block — rewrite the "
            "predicate as an interval or split it before the pallas path"
        )
    fdt = jnp.float32 if not config.x64_enabled() else jnp.float64
    n = filter_fwd.shape[0]
    n_pad = _pad_rows(n)
    k_pad = _pad_lane(capacity)
    nv = len(value_dicts)
    value_raws = list(value_raws) if value_raws is not None else [None] * nv
    for i in range(nv):
        if (value_dicts[i] is None) == (value_raws[i] is None):
            raise ValueError(f"value column {i}: exactly one of dict/raw required")
        if value_dicts[i] is not None and value_dicts[i].shape[0] > MAX_TABLE_CARD:
            raise ValueError(
                f"value dict card {value_dicts[i].shape[0]} > {MAX_TABLE_CARD}; "
                "stage this column raw for the pallas path"
            )

    def pad1(x, fill=0):
        return jnp.pad(x, (0, n_pad - n), constant_values=fill)

    # filter fwd only read in table mode or interval mode — always staged
    f2 = pad1(filter_fwd.astype(jnp.int32)).reshape(-1, BLOCK_COLS)
    valid2 = pad1(valid, False).reshape(-1, BLOCK_COLS)
    keys2 = pad1(group_keys.astype(jnp.int32)).reshape(-1, BLOCK_COLS)

    row_inputs: List[jnp.ndarray] = []  # per-value row-shaped inputs
    table_inputs: List[jnp.ndarray] = []  # per-value dict tables [1, card_pad]
    val_is_raw: List[bool] = []
    for i in range(nv):
        if value_dicts[i] is None:
            row_inputs.append(pad1(value_raws[i].astype(fdt)).reshape(-1, BLOCK_COLS))
            val_is_raw.append(True)
        else:
            row_inputs.append(
                pad1(value_fwds[i].astype(jnp.int32)).reshape(-1, BLOCK_COLS)
            )
            d = value_dicts[i].astype(fdt)
            dp = _pad_lane(d.shape[0])
            table_inputs.append(jnp.pad(d, (0, dp - d.shape[0]))[None, :])
            val_is_raw.append(False)

    table_mode = match is not None
    if table_mode:
        m = match.astype(fdt)
        mp = _pad_lane(m.shape[0])
        match_in = [jnp.pad(m, (0, mp - m.shape[0]))[None, :]]
        bounds_in = []
    else:
        match_in = []
        lo, hi = filter_bounds
        bounds_in = [jnp.asarray([[int(lo), int(hi)]], dtype=jnp.int32)]

    num_blocks = n_pad // BLOCK
    grid = (num_blocks,)
    n_tables = len(table_inputs)

    def kernel(*refs):
        i = 0
        f_ref = refs[i]; i += 1
        valid_ref = refs[i]; i += 1
        keys_ref = refs[i]; i += 1
        v_refs = refs[i : i + nv]; i += nv
        if table_mode:
            match_ref = refs[i]; i += 1
        else:
            bounds_ref = refs[i]; i += 1
        d_refs = refs[i : i + n_tables]; i += n_tables
        out_docs = refs[i]; i += 1
        out_count = refs[i]; i += 1
        out_sums = refs[i]; i += 1
        acc = refs[i]  # VMEM scratch [nv + 2, k_pad]

        step = pl.program_id(0)

        @pl.when(step == 0)
        def _init():
            acc[:, :] = jnp.zeros((nv + 2, k_pad), dtype=fdt)

        fidx = f_ref[:, :]  # [R, 128] int32
        if table_mode:
            hit = _table_gather(match_ref[0, :], fidx) > 0
        else:
            lo = bounds_ref[0, 0]
            hi = bounds_ref[0, 1]
            hit = (fidx >= lo) & (fidx < hi)
        mask = hit & valid_ref[:, :]
        maskf = mask.astype(fdt)

        lane0 = jax.lax.broadcasted_iota(jnp.int32, (k_pad,), 0) == 0
        acc[0, :] = acc[0, :] + jnp.where(lane0, jnp.sum(maskf), jnp.zeros((), fdt))

        # Mosaic rejects the [R*128, 1] shape cast a full-block one-hot
        # needs, so: transpose each [R, 128] operand once to [128, R]
        # (tpu.transpose) and walk the R sublanes, building the one-hot
        # [128, k_pad] once per sublane and contracting ALL value
        # columns against it in a single [128, nv+1] x [128, k_pad]
        # MXU matmul.
        ti = 0
        cols = [maskf]  # count column
        for vi in range(nv):
            if val_is_raw[vi]:
                vals = v_refs[vi][:, :]
            else:
                vals = _table_gather(d_refs[ti][0, :], v_refs[vi][:, :])
                ti += 1
            cols.append(vals * maskf)
        keys_t = jax.lax.transpose(keys_ref[:, :], (1, 0))  # [128, R]
        cols_t = [jax.lax.transpose(c, (1, 0)) for c in cols]
        iota_k = jax.lax.broadcasted_iota(jnp.int32, (1, k_pad), 1)
        delta = jnp.zeros((nv + 1, k_pad), fdt)
        for s in range(BLOCK_ROWS):
            onehot = (keys_t[:, s : s + 1] == iota_k).astype(fdt)  # [128, k_pad]
            a = jnp.concatenate([c[:, s : s + 1] for c in cols_t], axis=1)
            delta = delta + jax.lax.dot_general(
                a,
                onehot,
                (((0,), (0,)), ((), ())),
                precision=jax.lax.Precision.HIGHEST,
                preferred_element_type=fdt,
            )
        acc[1:, :] = acc[1:, :] + delta

        @pl.when(step == num_blocks - 1)
        def _emit():
            out_docs[0, 0] = acc[0, 0]
            out_count[0, :] = acc[1, :]
            if nv:
                out_sums[:, :] = acc[2:, :]
            else:  # count-only group-by: the padded slot must be written
                out_sums[:, :] = jnp.zeros((1, k_pad), dtype=fdt)

    row_spec = pl.BlockSpec(
        (BLOCK_ROWS, BLOCK_COLS), lambda b: (b, 0), memory_space=pltpu.VMEM
    )
    table_spec = pl.BlockSpec(memory_space=pltpu.VMEM)
    smem_spec = pl.BlockSpec(memory_space=pltpu.SMEM)

    in_specs = (
        [row_spec, row_spec, row_spec]
        + [row_spec] * nv
        + ([table_spec] if table_mode else [smem_spec])
        + [table_spec] * n_tables
    )
    inputs = (
        [f2, valid2, keys2]
        + row_inputs
        + match_in
        + bounds_in
        + table_inputs
    )

    out_docs, out_count, out_sums = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1), lambda b: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, k_pad), lambda b: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((max(nv, 1), k_pad), lambda b: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, 1), fdt),
            jax.ShapeDtypeStruct((1, k_pad), fdt),
            jax.ShapeDtypeStruct((max(nv, 1), k_pad), fdt),
        ],
        scratch_shapes=[pltpu.VMEM((nv + 2, k_pad), fdt)],
        interpret=interpret,
    )(*inputs)

    return (
        out_docs[0, 0],
        out_count[0, :capacity],
        [out_sums[i, :capacity] for i in range(nv)],
    )
