"""Pallas TPU kernels — fused hot-path experiments.

The default engine path is plain XLA (gathers + masked reductions +
one-hot matmul group-by), which XLA fuses well.  This module provides a
hand-fused Pallas version of the hottest query shape — filtered
multi-SUM group-by (TPC-H Q1) — keeping each row block's entire
pipeline (match-table gather -> mask -> dictionary gather -> one-hot
matmul accumulate) inside VMEM, one HBM read per forward-index element.

Status: flag-gated (``PINOT_TPU_USE_PALLAS=1``), validated in
interpret mode on CPU; intended for real-chip validation when TPU
hardware is attached (dynamic VMEM gathers require a recent Mosaic).

Layout: rows are processed in (8, 128)-aligned blocks; dictionary
tables (match tables, value arrays, remaps) are small and live whole in
VMEM; group sums accumulate into a [K_pad] VMEM scratch across grid
steps and are written out on the last step.
"""
from __future__ import annotations

import functools
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pinot_tpu.engine import config

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    PALLAS_AVAILABLE = True
except ImportError:  # pragma: no cover
    PALLAS_AVAILABLE = False

BLOCK_ROWS = 8  # sublanes
BLOCK_COLS = 128  # lanes
BLOCK = BLOCK_ROWS * BLOCK_COLS


def _pad_rows(n: int) -> int:
    return -(-n // BLOCK) * BLOCK


def use_pallas() -> bool:
    import os

    return PALLAS_AVAILABLE and os.environ.get("PINOT_TPU_USE_PALLAS") == "1"


def fused_filtered_groupby_sums(
    filter_fwd: jnp.ndarray,  # int32 [n]
    match: jnp.ndarray,  # bool  [card_f]
    valid: jnp.ndarray,  # bool  [n]
    group_keys: jnp.ndarray,  # int32 [n] precombined mixed-radix keys
    value_fwds: Sequence[jnp.ndarray],  # each int32 [n]
    value_dicts: Sequence[jnp.ndarray],  # each float [card_v]
    capacity: int,
    interpret: bool = False,
):
    """Returns (num_docs, count[K], [sums[K] per value column]).

    One fused pass: mask = match[filter_fwd] & valid; per value column
    v = dict[v_fwd]; scatter via one-hot matmul into K buckets.
    """
    fdt = jnp.float32 if not config.x64_enabled() else jnp.float64
    n = filter_fwd.shape[0]
    n_pad = _pad_rows(n)
    k_pad = max(128, -(-capacity // 128) * 128)
    nv = len(value_fwds)

    def pad1(x, fill=0):
        return jnp.pad(x, (0, n_pad - n), constant_values=fill)

    f2 = pad1(filter_fwd).reshape(-1, BLOCK_COLS)
    valid2 = pad1(valid, False).reshape(-1, BLOCK_COLS)
    keys2 = pad1(group_keys).reshape(-1, BLOCK_COLS)
    vals2 = [pad1(v).reshape(-1, BLOCK_COLS) for v in value_fwds]
    match_i = match.astype(fdt)
    dicts = [d.astype(fdt) for d in value_dicts]

    num_blocks = n_pad // BLOCK
    grid = (num_blocks,)

    def kernel(*refs):
        # refs: f_ref, valid_ref, keys_ref, v_refs..., match_ref, d_refs...,
        #       out_docs, out_count, out_sums, acc_scratch
        f_ref = refs[0]
        valid_ref = refs[1]
        keys_ref = refs[2]
        v_refs = refs[3 : 3 + nv]
        match_ref = refs[3 + nv]
        d_refs = refs[4 + nv : 4 + 2 * nv]
        out_docs = refs[4 + 2 * nv]
        out_count = refs[5 + 2 * nv]
        out_sums = refs[6 + 2 * nv]
        acc = refs[7 + 2 * nv]  # VMEM scratch [nv + 2, k_pad]

        step = pl.program_id(0)

        @pl.when(step == 0)
        def _init():
            acc[:, :] = jnp.zeros((nv + 2, k_pad), dtype=fdt)

        fidx = f_ref[:, :]  # [8, 128] int32
        mask = (match_ref[fidx] > 0) & valid_ref[:, :]
        maskf = mask.astype(fdt)

        keys = keys_ref[:, :]
        flat_keys = keys.reshape(-1)
        flat_mask = maskf.reshape(-1)
        onehot = (
            flat_keys[:, None]
            == jax.lax.broadcasted_iota(jnp.int32, (1, k_pad), 1)
        ).astype(fdt)  # [BLOCK, k_pad]
        onehot = onehot * flat_mask[:, None]

        # docs + count rows
        acc[0, :] = acc[0, :] + jnp.zeros(k_pad, fdt).at[0].add(jnp.sum(maskf))
        acc[1, :] = acc[1, :] + jnp.sum(onehot, axis=0)
        for i in range(nv):
            vals = d_refs[i][v_refs[i][:, :]].reshape(-1)  # gather + flatten
            acc[2 + i, :] = acc[2 + i, :] + jnp.dot(
                vals, onehot, preferred_element_type=fdt
            )

        @pl.when(step == num_blocks - 1)
        def _emit():
            out_docs[0, 0] = acc[0, 0]
            out_count[0, :] = acc[1, :]
            out_sums[:, :] = acc[2:, :]

    row_spec = pl.BlockSpec(
        (BLOCK_ROWS, BLOCK_COLS), lambda b: (b, 0), memory_space=pltpu.VMEM
    )
    table_spec = pl.BlockSpec(memory_space=pltpu.VMEM)

    out_docs, out_count, out_sums = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[row_spec, row_spec, row_spec]
        + [row_spec] * nv
        + [table_spec]
        + [table_spec] * nv,
        out_specs=[
            pl.BlockSpec((1, 1), lambda b: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, k_pad), lambda b: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((nv, k_pad), lambda b: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, 1), fdt),
            jax.ShapeDtypeStruct((1, k_pad), fdt),
            jax.ShapeDtypeStruct((nv, k_pad), fdt),
        ],
        scratch_shapes=[pltpu.VMEM((nv + 2, k_pad), fdt)],
        interpret=interpret,
    )(f2, valid2, keys2, *vals2, match_i, *dicts)

    return (
        out_docs[0, 0],
        out_count[0, :capacity],
        [out_sums[i, :capacity] for i in range(nv)],
    )
