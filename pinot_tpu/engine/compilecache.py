"""Persistent compile cache: warm restarts for the device lanes.

PR 8 measured the failure mode this module kills: every plan shape pays
a cold XLA compile (~25s on a real TPU) on its first launch, so a server
restart, rollout, or rebalance destination is a p99 cliff until the
whole working set has recompiled.  jax already ships a persistent
compilation cache (keyed on the serialized HLO + compile options); this
module wires it under the lanes and adds the two properties jax's cache
cannot give us by itself:

- **Topology isolation.**  The on-disk XLA cache lives under
  ``<root>/xla/<fingerprint>`` where the fingerprint digests the jax
  version, backend platform, device count/kind, and the x64 flag.  A
  cache written on a different mesh shape or jax version lands in a
  different directory — it can *miss*, never poison.  (jax's own key
  covers most of this too; the directory split makes the isolation
  auditable and survives jax key-scheme changes.)

- **A plan ledger.**  jax's cache is opaque: a lane cannot ask "is this
  plan-shape digest warm on disk?" before paying the compile.  The
  ledger records one tiny JSON file per (plan digest, fingerprint) after
  each successful compile, so the first launch of a shape can be
  *classified* — ``persistent`` (ledger hit: the XLA cache will serve
  the binary) vs genuinely ``cold`` — and the ``compile.cold`` meter
  stays honest across restarts.  Corrupt or alien ledger entries are a
  miss, never a crash: the ledger is advisory accounting, the XLA cache
  is the actual store.

Everything is gated on ``PINOT_TPU_COMPILE_CACHE_DIR``; unset means
fully disabled (no config writes, no ledger I/O) so default test runs
and in-process harnesses see the pre-existing cold/warm behavior.
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time
from typing import Optional

logger = logging.getLogger(__name__)

_lock = threading.Lock()
# directory most recently handed to jax_compilation_cache_dir (idempotence
# guard: lanes call configure() per construction, jax.config once)
_configured_dir: Optional[str] = None


def cache_root() -> Optional[str]:
    """The persistent-cache root, or None when the feature is off."""
    root = os.environ.get("PINOT_TPU_COMPILE_CACHE_DIR", "").strip()
    return root or None


def enabled() -> bool:
    return cache_root() is not None


def topology_fingerprint(
    jax_version: Optional[str] = None,
    platform: Optional[str] = None,
    device_count: Optional[int] = None,
    device_kind: Optional[str] = None,
    x64: Optional[bool] = None,
) -> str:
    """Short stable digest of everything that must invalidate the cache.

    A compiled executable is only reusable on the same jax version,
    backend platform, device count (mesh shape), device kind, and
    float-width mode — any of these changing must produce a different
    fingerprint so the old entries become unreachable, not wrong.  All
    parameters are overridable so tests can prove each axis separates
    keys without owning a second topology.
    """
    import jax

    if jax_version is None:
        jax_version = jax.__version__
    if platform is None or device_count is None or device_kind is None:
        devices = jax.devices()
        if platform is None:
            platform = devices[0].platform if devices else "none"
        if device_count is None:
            device_count = len(devices)
        if device_kind is None:
            device_kind = getattr(devices[0], "device_kind", "") if devices else ""
    if x64 is None:
        x64 = bool(jax.config.jax_enable_x64)
    payload = json.dumps(
        {
            "jax": jax_version,
            "platform": platform,
            "devices": int(device_count),
            "kind": device_kind,
            "x64": bool(x64),
        },
        sort_keys=True,
    )
    return hashlib.blake2b(payload.encode(), digest_size=8).hexdigest()


def configure_jax_cache(root: Optional[str] = None) -> Optional[str]:
    """Point jax's persistent compilation cache under the root.

    Returns the per-topology XLA cache directory in use, or None when
    the feature is disabled or jax refused the config (old jax builds
    without the knobs must degrade to plain cold compiles, not crash
    lane construction).  Idempotent: repeat calls with the same root are
    free; a changed root re-points the cache.
    """
    global _configured_dir
    if root is None:
        root = cache_root()
    if root is None:
        return None
    xla_dir = os.path.join(root, "xla", topology_fingerprint())
    with _lock:
        if _configured_dir == xla_dir:
            return xla_dir
        try:
            os.makedirs(xla_dir, exist_ok=True)
        except OSError:
            logger.warning("compile cache dir unusable: %s", xla_dir, exc_info=True)
            return None
        import jax

        try:
            jax.config.update("jax_compilation_cache_dir", xla_dir)
        except Exception:
            logger.warning("jax persistent compile cache unavailable", exc_info=True)
            return None
        # CPU/test compiles finish in milliseconds; without zeroing the
        # floor nothing would ever be written and every restart test
        # would silently exercise the cold path
        for knob, value in (
            ("jax_persistent_cache_min_compile_time_secs", 0.0),
            ("jax_persistent_cache_min_entry_size_bytes", 0),
        ):
            try:
                jax.config.update(knob, value)
            except Exception:
                pass
        _configured_dir = xla_dir
        return xla_dir


# -- plan ledger ------------------------------------------------------------


def _plan_path(root: str, digest: str, fingerprint: str) -> str:
    # digest and fingerprint are short hex; sanitize anyway so a hostile
    # digest string can never escape the ledger directory
    safe = "".join(c for c in f"{digest}-{fingerprint}" if c.isalnum() or c == "-")
    return os.path.join(root, "plans", f"{safe}.json")


def record_plan(
    digest: str,
    fingerprint: Optional[str] = None,
    root: Optional[str] = None,
) -> bool:
    """Mark a plan-shape digest as compiled under this topology.

    Atomic (tmp + rename) so a crash mid-write leaves either a valid
    entry or none — never a truncated file another process would have
    to tolerate (it would anyway: see ``known_plan``).
    """
    if root is None:
        root = cache_root()
    if root is None or not digest:
        return False
    if fingerprint is None:
        fingerprint = topology_fingerprint()
    path = _plan_path(root, digest, fingerprint)
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(
                {
                    "digest": digest,
                    "fingerprint": fingerprint,
                    "jaxVersion": __import__("jax").__version__,
                    "recordedAtMs": int(time.time() * 1000),
                },
                f,
            )
        os.replace(tmp, path)
        return True
    except OSError:
        logger.warning("plan ledger write failed: %s", path, exc_info=True)
        return False


def known_plan(
    digest: str,
    fingerprint: Optional[str] = None,
    root: Optional[str] = None,
) -> bool:
    """True when the ledger proves this digest compiled on THIS topology.

    Every failure mode — missing file, unreadable file, corrupt JSON,
    an alien entry whose recorded digest/fingerprint disagrees with its
    filename — is a miss.  The ledger only reclassifies accounting; a
    wrong False costs one cold-meter tick, a crash would cost the lane.
    """
    if root is None:
        root = cache_root()
    if root is None or not digest:
        return False
    if fingerprint is None:
        fingerprint = topology_fingerprint()
    path = _plan_path(root, digest, fingerprint)
    try:
        with open(path) as f:
            entry = json.load(f)
    except (OSError, ValueError):
        return False
    return (
        isinstance(entry, dict)
        and entry.get("digest") == digest
        and entry.get("fingerprint") == fingerprint
    )


def _reset_for_tests() -> None:
    """Forget the idempotence guard so a test can re-point the cache."""
    global _configured_dir
    with _lock:
        _configured_dir = None
