"""Per-tier filter cost model — the measured crossover constants that
pick between the four filter/aggregate tiers, in ONE env-tunable place.

The tiers (engine/invindex_path.py, engine/zonemap.py,
engine/bitsliced.py, engine/kernel.py) each win a region of the
(selectivity, layout) plane — FILTER_MATRIX_CPU_r17.json is the
measured map.  The constants below encode the crossovers; every one is
overridable via ``PINOT_TPU_TIER_COST_*`` so the model can be
recalibrated per host (a tunneled TPU, a fat CPU dev box) without code
edits.  Defaults reproduce the pre-knob behavior bit-for-bit: the
postings bound ``total_docs * (1/64.0)`` floors to exactly
``total_docs // 64`` (a power-of-two reciprocal is fp-exact).
"""
from __future__ import annotations

import os

# name -> default; read fresh per call so tests/benches can flip them
# without cache invalidation ceremony
_DEFAULTS = {
    # postings/scan crossover: host fancy-index aggregation costs
    # ~10 ns/row vs the device scan's ~0.35 ns/row + dispatch floor;
    # the 1/64-of-table bound keeps postings an order of magnitude
    # under the scan at any size (invindex_path.py)
    "POSTINGS_MATCH_FRACTION": 1.0 / 64.0,
    "POSTINGS_NS_PER_ROW": 10.0,
    "SCAN_NS_PER_ROW": 0.35,
    # fixed per-query device overhead (dispatch + tunnel RTT), ns
    "DISPATCH_FLOOR_NS": 200_000.0,
    # bit-sliced tier: the bitwise pass touches W packed planes of
    # n/32 words each, so its per-row cost scales with planes/32 of
    # the scan's (0.35 / 32 ~= 0.011) — plus the same dispatch floor
    # (engine/bitsliced.py)
    "BSI_NS_PER_ROW_PER_PLANE": 0.011,
    # eligibility cap on total planes a bit-sliced evaluation may
    # touch (filter + fused-agg planes); above it the encoding stops
    # paying for itself against the plain scan
    "BSI_MAX_PLANES": 24.0,
    # host->device reload cost (engine/residency.py victim scoring):
    # per-byte PCIe/tunnel transfer plus the same dispatch floor — a
    # demotion candidate's score is touch-frequency x THIS, so evicting
    # a big table is charged what re-promoting it will actually cost
    "H2D_NS_PER_BYTE": 0.0625,  # ~16 GB/s effective H2D
    # exponential-decay halflife (seconds) of the residency heat signal
    "RESIDENCY_HALFLIFE_S": 30.0,
}


def _knob(name: str) -> float:
    env = os.environ.get(f"PINOT_TPU_TIER_COST_{name}")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    return _DEFAULTS[name]


def postings_max_matches(total_docs: int) -> int:
    """Postings/scan crossover in rows (invindex_path._max_matches)."""
    return int(total_docs * _knob("POSTINGS_MATCH_FRACTION"))


def scan_cost_ns(total_docs: int) -> float:
    """Full device scan: per-row stream cost + the dispatch floor."""
    return total_docs * _knob("SCAN_NS_PER_ROW") + _knob("DISPATCH_FLOOR_NS")


def postings_cost_ns(matches: int) -> float:
    return matches * _knob("POSTINGS_NS_PER_ROW")


def bitsliced_cost_ns(total_docs: int, planes: int) -> float:
    """Bit-sliced pass over ``planes`` packed bit-planes of the table."""
    return (
        total_docs * planes * _knob("BSI_NS_PER_ROW_PER_PLANE")
        + _knob("DISPATCH_FLOOR_NS")
    )


def bsi_max_planes() -> int:
    return int(_knob("BSI_MAX_PLANES"))


def h2d_cost_ns(nbytes: int) -> float:
    """Cost of re-promoting ``nbytes`` from host to device — the
    reload-cost half of the residency heat score."""
    return nbytes * _knob("H2D_NS_PER_BYTE") + _knob("DISPATCH_FLOOR_NS")


def residency_halflife_s() -> float:
    """Heat-decay halflife for tier victim selection."""
    return _knob("RESIDENCY_HALFLIFE_S")
