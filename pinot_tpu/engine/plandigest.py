"""Plan-shape digest: the workload-introspection key.

A *plan shape* is a query with its literals erased: the table (logical
— physical ``_OFFLINE``/``_REALTIME`` suffixes stripped so broker and
server agree), the filter tree's (column, operator) structure, the
aggregation list, group-by columns + topN, and the selection's
columns/sorts/limit.  Two queries that differ only in filter literals
(``dimInt > 40`` vs ``dimInt > 90``) share a digest — exactly the
equivalence class the ROADMAP's cross-query batched serving needs
("batch same-plan-shape queries with different literals into one
vmapped launch"), and the granularity at which the PlanStatsStore
(``utils/planstats.py``) accumulates frequency/latency/cost.

This is deliberately a LEVEL ABOVE ``engine/dispatch.plan_digest``:
that one digests the compiled ``StaticPlan`` (literal-bucketed device
program identity — the jit-cache / poison-quarantine key); this one
digests the request shape (workload identity).  EXPLAIN reports both
(``planDigest`` vs ``device.planDigest``).
"""
from __future__ import annotations

import hashlib
from typing import Optional, Tuple

from pinot_tpu.common.request import BrokerRequest, FilterQueryTree

_PHYSICAL_SUFFIXES = ("_OFFLINE", "_REALTIME")


def _raw_table(table: str) -> str:
    for suffix in _PHYSICAL_SUFFIXES:
        if table.endswith(suffix):
            return table[: -len(suffix)]
    return table


def _filter_shape(node: Optional[FilterQueryTree]) -> Optional[tuple]:
    if node is None:
        return None
    if node.is_leaf:
        # literals erased: only (column, operator) — a RANGE keeps no
        # bound values, an IN keeps no list (nor its length: the planner
        # buckets k_pad anyway, and ``x IN (1,2)`` vs ``x IN (3,4,5)``
        # is the same workload shape)
        return (node.column, node.operator.value)
    return (node.operator.value, tuple(_filter_shape(c) for c in node.children))


def plan_shape(request: BrokerRequest) -> tuple:
    """The hashable literal-erased shape tuple (deterministic repr)."""
    aggs = tuple((a.function, a.column) for a in request.aggregations)
    gb = None
    if request.is_group_by:
        gb = (tuple(request.group_by.columns), request.group_by.top_n)
    sel = None
    if request.selection is not None:
        s = request.selection
        sel = (
            tuple(s.columns),
            tuple((x.column, x.ascending) for x in s.sorts),
            s.offset,
            s.size,
        )
    having = None
    if request.having is not None:
        h = request.having
        having = (h.function, h.column, h.operator)
    join = None
    if request.join is not None:
        j = request.join
        # the join is part of the plan shape: a joined scan and a plain
        # scan of the same left table are different workloads (and the
        # broker's strategy planner keys per-shape stats off this)
        join = (_raw_table(j.right_table), j.left_key, j.right_key)
    return (
        _raw_table(request.table_name),
        _filter_shape(request.filter),
        aggs,
        gb,
        sel,
        having,
        join,
    )


def plan_shape_digest(request: BrokerRequest) -> str:
    """Stable 16-hex-char digest of the plan shape.  Compute it on the
    OPTIMIZED request (broker and server both run ``optimize_request``
    on the same text, so the two sides key the same series)."""
    return hashlib.blake2b(
        repr(plan_shape(request)).encode(), digest_size=8
    ).hexdigest()


def plan_literals(request: BrokerRequest) -> tuple:
    """The literal complement of ``plan_shape``: every value the shape
    erased, in deterministic walk order — filter leaf value lists,
    having bounds, and the debug options that can steer execution.
    ``plan_shape(request) + plan_literals(request)`` together identify
    the full query text semantically, which is exactly what the
    ingest-aware result cache (engine/rescache.py) keys on:
    (segment set + staging tokens, plan digest, literal values)."""
    lits = []
    if request.filter is not None:
        for node in request.filter.walk():
            if node.is_leaf:
                # RANGE bounds live in range_spec, not values — a
                # literal digest blind to them would collide `a>5`
                # with `a>999` (tests/test_batching.py regression)
                rng = None
                if node.range_spec is not None:
                    r = node.range_spec
                    rng = (r.lower, r.upper, r.include_lower, r.include_upper)
                lits.append(
                    (node.column, node.operator.value, tuple(node.values), rng)
                )
    having = None
    if request.having is not None:
        having = request.having.value
    opts = tuple(sorted((request.query_options or {}).items()))
    dbg = tuple(sorted((request.debug_options or {}).items()))
    return (tuple(lits), having, opts, dbg)


def plan_literal_digest(request: BrokerRequest) -> str:
    """Stable 16-hex-char digest of the literal tuple."""
    return hashlib.blake2b(
        repr(plan_literals(request)).encode(), digest_size=8
    ).hexdigest()


def plan_shape_summary(request: BrokerRequest) -> str:
    """Short human label for a digest ("what shape is this?"), rendered
    on /debug/plans, /debug/workload, and the controller dashboard."""
    parts = []
    if request.aggregations:
        parts.append(",".join(a.display_name for a in request.aggregations))
    elif request.selection is not None:
        cols = ",".join(request.selection.columns) or "*"
        parts.append(f"select({cols})")
    if request.filter is not None:
        leaves = [n for n in request.filter.walk() if n.is_leaf]
        parts.append(
            "where " + "&".join(f"{n.column}:{n.operator.value}" for n in leaves)
        )
    if request.is_group_by:
        parts.append("by " + ",".join(request.group_by.columns))
    if request.selection is not None and request.selection.sorts:
        parts.append(
            "order " + ",".join(s.column for s in request.selection.sorts)
        )
    parts.append(f"from {_raw_table(request.table_name)}")
    if request.join is not None:
        j = request.join
        parts.append(
            f"join {_raw_table(j.right_table)} on {j.left_key}={j.right_key}"
        )
    return " ".join(parts)
