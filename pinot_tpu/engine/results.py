"""Mergeable aggregation partials + finalization.

These are the *contents* of the server->broker partial results (the
DataTable payload analog).  Each aggregation function has a partial
state that merges associatively — across segments, servers, and chips:

  count/sum         float        merge = +
  min / max         float        merge = min / max
  avg               (sum, count) merge = pairwise +      (AvgPair analog)
  minmaxrange       (min, max)
  distinctcount     value set    merge = union           (IntOpenHashSet analog)
  distinctcounthll  uint8[m] HLL registers, merge = elementwise max
                    (vs the reference's Java-serialized HLL objects,
                     DataTableCustomSerDe.java:49)
  percentile*       value->count histogram, merge = counter add
                    (vs the reference shipping the raw DoubleArrayList —
                     strictly smaller, and exact)

Group-by partials are {group key tuple -> per-function partial} maps,
merged key-wise (MCombineGroupByOperator.java:152 semantics) and trimmed
to top_n at final reduce.
"""
from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from pinot_tpu.engine import hll as hll_mod


class AggPartial:
    """Base: merge in place, then finalize to the response value."""

    def merge(self, other: "AggPartial") -> None:
        raise NotImplementedError

    def finalize(self) -> Any:
        raise NotImplementedError


class CountPartial(AggPartial):
    def __init__(self, count: float = 0.0) -> None:
        self.count = float(count)

    def merge(self, other: "CountPartial") -> None:
        self.count += other.count

    def finalize(self) -> Any:
        return int(self.count)


class SumPartial(AggPartial):
    def __init__(self, total: float = 0.0) -> None:
        self.total = float(total)

    def merge(self, other: "SumPartial") -> None:
        self.total += other.total

    def finalize(self) -> float:
        return self.total


class MinPartial(AggPartial):
    def __init__(self, value: float = math.inf) -> None:
        self.value = float(value)

    def merge(self, other: "MinPartial") -> None:
        self.value = min(self.value, other.value)

    def finalize(self) -> float:
        return self.value


class MaxPartial(AggPartial):
    def __init__(self, value: float = -math.inf) -> None:
        self.value = float(value)

    def merge(self, other: "MaxPartial") -> None:
        self.value = max(self.value, other.value)

    def finalize(self) -> float:
        return self.value


class AvgPartial(AggPartial):
    def __init__(self, total: float = 0.0, count: float = 0.0) -> None:
        self.total = float(total)
        self.count = float(count)

    def merge(self, other: "AvgPartial") -> None:
        self.total += other.total
        self.count += other.count

    def finalize(self) -> float:
        return self.total / self.count if self.count else -math.inf


class MinMaxRangePartial(AggPartial):
    def __init__(self, mn: float = math.inf, mx: float = -math.inf) -> None:
        self.mn = float(mn)
        self.mx = float(mx)

    def merge(self, other: "MinMaxRangePartial") -> None:
        self.mn = min(self.mn, other.mn)
        self.mx = max(self.mx, other.mx)

    def finalize(self) -> float:
        return self.mx - self.mn


class DistinctPartial(AggPartial):
    """Exact distinct value set for one group.

    ``values`` is either a Python set (small results, wire
    deserialization) or a UNIQUE numpy array (host/device bulk paths —
    at north-star cardinality a 4M-entry Python set costs tens of
    seconds per group to build, a vectorized gather milliseconds)."""

    def __init__(self, values: Optional[object] = None) -> None:
        self.values = values if values is not None else set()

    def merge(self, other: "DistinctPartial") -> None:
        a, b = self.values, other.values
        if isinstance(a, set) and isinstance(b, set):
            a |= b
            return
        na = np.asarray(sorted(a, key=repr)) if isinstance(a, set) else a
        nb = np.asarray(sorted(b, key=repr)) if isinstance(b, set) else b
        if na.size == 0:
            self.values = nb
        elif nb.size == 0:
            self.values = na
        else:
            self.values = np.union1d(na, nb)

    def iter_sorted(self):
        """Values in a deterministic order (serde contract)."""
        if isinstance(self.values, set):
            return sorted(self.values, key=repr)
        return np.sort(self.values).tolist()

    def finalize(self) -> int:
        return len(self.values) if isinstance(self.values, set) else int(self.values.size)


class HllPartial(AggPartial):
    def __init__(self, registers: Optional[np.ndarray] = None) -> None:
        self.registers = (
            registers.astype(np.uint8)
            if registers is not None
            else np.zeros(hll_mod.M, dtype=np.uint8)
        )

    def merge(self, other: "HllPartial") -> None:
        self.registers = np.maximum(self.registers, other.registers)

    def finalize(self) -> int:
        return int(hll_mod.estimate_from_registers(self.registers))


class HistogramPartial(AggPartial):
    """Exact value histogram for percentiles."""

    def __init__(self, counts: Optional[Dict[float, int]] = None, percentile: int = 50) -> None:
        self.counts: Dict[float, int] = counts or {}
        self.percentile = percentile

    def merge(self, other: "HistogramPartial") -> None:
        for v, c in other.counts.items():
            self.counts[v] = self.counts.get(v, 0) + c

    def finalize(self) -> float:
        """Reference formula sorted[int(n * p/100)]
        (quantile/PercentileUtil.java:50) over the histogram."""
        if not self.counts:
            return -math.inf
        items = sorted(self.counts.items())
        n = sum(c for _, c in items)
        idx = min(int(n * self.percentile / 100.0), n - 1)
        acc = 0
        for v, c in items:
            acc += c
            if acc > idx:
                return v
        return items[-1][0]


def make_partial(base_function: str) -> AggPartial:
    if base_function == "count":
        return CountPartial()
    if base_function == "sum":
        return SumPartial()
    if base_function == "min":
        return MinPartial()
    if base_function == "max":
        return MaxPartial()
    if base_function == "avg":
        return AvgPartial()
    if base_function == "minmaxrange":
        return MinMaxRangePartial()
    if base_function == "distinctcount":
        return DistinctPartial()
    if base_function in ("distinctcounthll", "fasthll"):
        return HllPartial()
    if base_function.startswith("percentileest"):
        return HistogramPartial(percentile=int(base_function[len("percentileest"):]))
    if base_function.startswith("percentile"):
        return HistogramPartial(percentile=int(base_function[len("percentile"):]))
    raise ValueError(f"unknown aggregation {base_function!r}")


GroupKey = Tuple[str, ...]


# Canonical per-query cost-vector keys (the execution-stats extension
# beyond the reference's numDocsScanned/numEntriesScanned* — see
# PARITY.md "Cost accounting").  Every value is additive, so the merge
# is a plain key-wise sum and the broker's totals are exactly the sum
# of the per-server totals (the invariant tests/test_cost.py holds):
#
#   bytesScanned       column bytes the serving path read (device: staged
#                      array bytes handed to the kernel, scaled by the
#                      zone-map candidate fraction; host: forward-index
#                      bytes of referenced columns; postings: O(matches))
#   deviceMs / hostMs  kernel-execution wall ms split by where the
#                      filter/aggregate work actually ran
#   deviceBytes        the DEVICE-TIER share of bytesScanned (staged
#                      array bytes the kernel read) — the utilization
#                      plane's achieved-bandwidth numerator; host/
#                      postings bytes never pollute the roofline
#   coalesceHits       queries served by riding an identical in-flight
#                      device dispatch (engine/dispatch.py)
#   qinputCacheHits    device-resident query-input cache hits
#   batchHits          queries that rode a cross-query batched launch
#                      (literals stacked with same-plan peers into one
#                      vmapped kernel — the lane micro-batching tier)
#   rescacheHits       queries answered from the ingest-aware result
#                      cache (engine/rescache.py) — a hit marks ZERO
#                      device/host work by construction
#   segmentsPruned     segments dropped by metadata pruning (pruner.py)
#   segmentsPostings   segments answered from host postings (invindex)
#   segmentsBitsliced  segments answered by the bit-sliced bulk-bitwise
#                      tier (engine/bitsliced.py — popcount-fused aggs)
#   segmentsZonemap    segments scanned via the zone-map block kernel
#   segmentsFullScan   segments scanned by the full device kernel
#   segmentsHost       segments served by the host path (forced,
#                      failover, or pair overflow)
#   segmentsStarTree   segments answered from their star-tree cube
#   buildRows          join build-side rows extracted / hash-table
#                      inserted (engine/join.py — dim-side work)
#   probeRows          join probe-side rows extracted / probed against
#                      the build hash table (fact-side work)
#   shuffleBytes       serialized join-exchange bytes a server RECEIVED
#                      in a shuffle join (the skew-balance observable:
#                      no server should receive >2x the mean)
#   broadcastBytes     serialized build-side bytes a server received in
#                      a broadcast join (one copy per probe server)
COST_KEYS = (
    "bytesScanned",
    "deviceMs",
    "hostMs",
    "deviceBytes",
    "coalesceHits",
    "qinputCacheHits",
    "batchHits",
    "rescacheHits",
    "buildRows",
    "probeRows",
    "shuffleBytes",
    "broadcastBytes",
    "segmentsPruned",
    "segmentsPostings",
    "segmentsBitsliced",
    "segmentsZonemap",
    "segmentsFullScan",
    "segmentsHost",
    "segmentsStarTree",
)

# Serving-tier subset of COST_KEYS — THE single source the introspection
# plane derives from (server cost.tier.* meters, EXPLAIN tier records,
# trace_dump's tier footer): a tier added here propagates everywhere.
# All but segmentsPruned partition numSegmentsQueried exactly.
SEGMENT_TIER_KEYS = tuple(k for k in COST_KEYS if k.startswith("segments"))

# cost-vector key -> short display tier name ("segmentsFullScan" ->
# "fullScan"), shared by EXPLAIN records and trace_dump's footer so the
# two surfaces can never render the same tier differently
SEGMENT_TIER_NAMES = {
    k: k[len("segments"):][0].lower() + k[len("segments"):][1:]
    for k in SEGMENT_TIER_KEYS
}


class IntermediateResult:
    """One executor's (server's) partial answer for a query — the unit
    that flows broker-ward and merges with peers
    (BrokerReduceService.reduceOnDataTable analog)."""

    def __init__(
        self,
        aggregations: Optional[List[AggPartial]] = None,
        groups: Optional[Dict[GroupKey, List[AggPartial]]] = None,
        selection_rows: Optional[List[Tuple[list, list]]] = None,  # (sort_key_values, row)
        num_docs_scanned: int = 0,
        total_docs: int = 0,
        num_segments_queried: int = 0,
        num_entries_scanned_in_filter: int = 0,
        num_entries_scanned_post_filter: int = 0,
        trace: Optional[Dict[str, Any]] = None,
        selection_columns: Optional[List[str]] = None,
        exceptions: Optional[List[Tuple[int, str]]] = None,
        unserved_segments: Optional[List[str]] = None,
        cost: Optional[Dict[str, float]] = None,
        plan_info: Optional[List[Dict[str, Any]]] = None,
    ) -> None:
        self.selection_columns = selection_columns
        self.exceptions: List[Tuple[int, str]] = exceptions or []
        # requested segments this server could not serve (dropped /
        # quarantined pending re-fetch): the broker re-covers them on a
        # replica or folds them into partialResponse/numSegmentsUnserved
        self.unserved_segments: List[str] = unserved_segments or []
        self.aggregations = aggregations
        self.groups = groups
        self.selection_rows = selection_rows
        self.num_docs_scanned = num_docs_scanned
        self.total_docs = total_docs
        self.num_segments_queried = num_segments_queried
        self.num_entries_scanned_in_filter = num_entries_scanned_in_filter
        self.num_entries_scanned_post_filter = num_entries_scanned_post_filter
        self.trace = trace or {}
        # per-query cost vector (COST_KEYS above): sparse — absent keys
        # mean zero, so empty-path results stay cheap to build and ship
        self.cost: Dict[str, float] = dict(cost or {})
        # per-REPLY saturation snapshot of the answering server (NOT
        # additive — never merged): {"pending", "maxPending", "laneDepth"}
        # set by ServerInstance.handle_request; the broker's admission
        # controller reads it to drive the per-server AIMD concurrency
        # window (shed early with 429 instead of feeding a saturated
        # server until 210s appear)
        self.backpressure: Dict[str, float] = {}
        # EXPLAIN / EXPLAIN ANALYZE plan trees: one JSON-safe node per
        # answering server (engine/explain.py), concatenated on merge
        # like traces (never summed) — the broker collects them into
        # BrokerResponse.explain["servers"]
        self.plan_info: List[Dict[str, Any]] = list(plan_info or [])
        # join-extract payload (engine/join.py SideRows wire dict):
        # columnar key/value arrays a join-extract phase returns to the
        # broker exchange.  NOT additive — the broker drains it before
        # the result joins the reduce merge; always None on the normal
        # single-table serving path.
        self.join_payload: Optional[Dict[str, Any]] = None
        # event-time freshness stamp (broker/freshness.py): for replies
        # covering realtime tables, {"minEventMs": <max consumed
        # event-time in ms, min over served partitions>}.  Merged with
        # MIN semantics — the broker's freshnessMs must reflect the
        # STALEST data that contributed to the answer.  None for
        # offline-only replies and for peers predating the audit plane.
        self.freshness: Optional[Dict[str, Any]] = None

    def add_cost(self, **kv: float) -> None:
        """Accumulate cost-vector components (key-wise add)."""
        for k, v in kv.items():
            if v:
                self.cost[k] = self.cost.get(k, 0) + v

    def merge(self, other: "IntermediateResult") -> None:
        self.exceptions.extend(other.exceptions)
        self.unserved_segments.extend(other.unserved_segments)
        self.plan_info.extend(other.plan_info)
        # freshness min-combines: an answer is only as fresh as its
        # stalest contributing realtime partition
        of = getattr(other, "freshness", None)
        if of is not None and of.get("minEventMs") is not None:
            mine = self.freshness
            if mine is None or mine.get("minEventMs") is None:
                self.freshness = dict(of)
            else:
                mine["minEventMs"] = min(mine["minEventMs"], of["minEventMs"])
        # cost vectors are additive by construction: the broker's merged
        # totals equal the sum of the per-server totals EXACTLY
        for k, v in other.cost.items():
            self.cost[k] = self.cost.get(k, 0) + v
        self.num_docs_scanned += other.num_docs_scanned
        self.total_docs += other.total_docs
        self.num_segments_queried += other.num_segments_queried
        self.num_entries_scanned_in_filter += other.num_entries_scanned_in_filter
        self.num_entries_scanned_post_filter += other.num_entries_scanned_post_filter
        # trace values are span LISTS keyed by scope: two partials from
        # the same scope concatenate instead of clobbering each other
        for scope, spans in other.trace.items():
            mine = self.trace.get(scope)
            if isinstance(mine, list) and isinstance(spans, list):
                self.trace[scope] = mine + spans
            else:
                self.trace[scope] = spans
        if other.aggregations is not None:
            if self.aggregations is None:
                self.aggregations = other.aggregations
            else:
                for mine, theirs in zip(self.aggregations, other.aggregations):
                    mine.merge(theirs)
        if other.groups is not None:
            if self.groups is None:
                self.groups = other.groups
            else:
                for key, partials in other.groups.items():
                    existing = self.groups.get(key)
                    if existing is None:
                        self.groups[key] = partials
                    else:
                        for mine, theirs in zip(existing, partials):
                            mine.merge(theirs)
        if other.selection_rows is not None:
            if self.selection_rows is None:
                self.selection_rows = other.selection_rows
            else:
                self.selection_rows.extend(other.selection_rows)
        if self.selection_columns is None:
            self.selection_columns = other.selection_columns


# Cap on boundary-tie groups admitted past the trim: final ordering
# breaks value ties by rendered key (which the trim cannot see), so
# tied-at-the-boundary groups are kept — but at huge key spaces a
# degenerate workload (e.g. COUNT(*) over near-unique keys, every group
# tied at 1) would otherwise re-admit millions of groups and defeat the
# trim entirely.  Beyond the cap a deterministic subset is kept; the
# reference's per-server topN*5 trim makes the same non-guarantee for
# deep ties (MCombineGroupByOperator.java:216).
MAX_TRIM_TIES = 10_000


def trim_group_candidates(
    order_vals_list: List[np.ndarray],
    ascending_list: List[bool],
    top_n: int,
    k: int,
) -> np.ndarray:
    """Candidate group indices to keep after the per-server trim.

    ``order_vals_list`` holds one finalized-value array of shape [k] per
    aggregation; a group survives if it is within topN*5 (min 100) of
    any aggregation's ordering, or tied (capped) with that boundary.
    Returns sorted indices into [0, k).
    """
    trim = max(top_n * 5, 100)
    if k <= trim:
        return np.arange(k)
    candidates: set = set()
    for ov, asc in zip(order_vals_list, ascending_list):
        order = np.argsort(ov, kind="stable")
        chosen = order[:trim] if asc else order[-trim:]
        candidates.update(chosen.tolist())
        boundary = ov[order[trim - 1 if asc else -trim]]
        ties = np.nonzero(ov == boundary)[0]
        if ties.size > MAX_TRIM_TIES:
            ties = ties[:MAX_TRIM_TIES]
        candidates.update(ties.tolist())
    return np.asarray(sorted(candidates), dtype=np.int64)
