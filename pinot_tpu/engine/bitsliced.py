"""Bit-sliced (BSI) filter/aggregate tier — the fourth filter tier.

The bulk-bitwise PIM formulation applied to the device engine: columns
are staged as packed int32 bit-planes (device.py ``bsi``/``bsiv`` role
arrays, built with the packing.py encoder at staging time), and an
eligible scalar aggregation evaluates its whole filter as O(bit-width)
wide AND/OR/popcount passes over n/32-word planes — with COUNT/SUM/
MIN/MAX fused INTO the bitwise pass (kernel.py bitsliced kernels), so
mid-selectivity aggregations never materialize row indices at all.

Position in the tier ladder (engine/executor.py):

  postings (invindex_path)  — needle queries, O(matches) on host
  bit-sliced (this module)  — mid-selectivity scalar aggs, O(W * n/32)
  zone-map (zonemap.py)     — clustered predicates, O(candidate blocks)
  full scan (kernel.py)     — everything else, O(n)

The decision mirrors ``index_path_decision``'s contract: a JSON-safe
verdict EXPLAIN can report without serving the query, plus an opaque
execution state when taken.  Crossover constants live in
engine/tiercost.py (``PINOT_TPU_TIER_COST_*``); ``PINOT_TPU_BITSLICED``
is the tier switch: "0" disables, "force" skips the cost model (the
filter-matrix bench pins tiers this way), unset/auto applies it.

Fused SUM is offered only where it is bit-exact against the scan
tier: exactly-integral dictionaries (packing.integral_dictionary_values)
with offset width <= 32, summed host-side in exact integer arithmetic
as  sum = vmin_s * count_s + sum_b 2^b * popcount(value_plane_b & bitmap).
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from pinot_tpu.common.request import BrokerRequest, FilterOperator, FilterQueryTree
from pinot_tpu.engine import config
from pinot_tpu.common.schema import DataType
from pinot_tpu.engine.context import TableContext
from pinot_tpu.engine.results import (
    AvgPartial,
    CountPartial,
    IntermediateResult,
    MaxPartial,
    MinPartial,
    SumPartial,
    make_partial,
)
from pinot_tpu.segment.immutable import ImmutableSegment

_MAX_POINTS = 16  # same IN-list bound the StaticPlan leaf lowering uses
_SCALAR_AGGS = ("count", "sum", "min", "max", "avg")


def _k_pad(n: int) -> int:
    return 1 << max(0, int(n - 1).bit_length()) if n > 1 else 1


def _leaf_kind(op: FilterOperator) -> Optional[str]:
    if op == FilterOperator.RANGE:
        return "interval"
    if op in (FilterOperator.EQUALITY, FilterOperator.IN):
        return "points"
    if op in (FilterOperator.NOT, FilterOperator.NOT_IN):
        return "points_none"
    return None  # REGEX needs the match-table path


def _encode_tree(
    node: FilterQueryTree,
    live: List[ImmutableSegment],
    leaves: List[Tuple[FilterQueryTree, str, str, int, int]],
):
    """-> nested ("leaf", i) / ("and"|"or", ...) encoding, or a string
    reason why the subtree is not bit-sliceable."""
    from pinot_tpu.engine.device import bsi_filter_width

    if node.is_leaf:
        kind = _leaf_kind(node.operator)
        if kind is None:
            return f"operator {node.operator.name} not bit-sliceable"
        col = node.column
        if not all(s.has_column(col) for s in live):
            return f"column {col!r} missing from a segment"
        cols = [s.column(col) for s in live]
        if not cols[0].metadata.single_value:
            return f"column {col!r} is multi-value"
        if any(c.dictionary.cardinality <= 0 for c in cols):
            return f"column {col!r} has no dictionary"
        if kind != "interval" and len(node.values) > _MAX_POINTS:
            return f"point set over {_MAX_POINTS} values"
        width = bsi_filter_width(cols)
        k_pad = _k_pad(len(node.values)) if kind != "interval" else 0
        leaves.append((node, kind, col, width, k_pad))
        return ("leaf", len(leaves) - 1)
    if node.operator not in (FilterOperator.AND, FilterOperator.OR):
        return f"operator {node.operator.name} not bit-sliceable"
    children = []
    for c in node.children:
        enc = _encode_tree(c, live, leaves)
        if isinstance(enc, str):
            return enc
        children.append(enc)
    op = "and" if node.operator == FilterOperator.AND else "or"
    return (op, *children)


def bitsliced_decision(
    request: BrokerRequest,
    live: List[ImmutableSegment],
    ctx: TableContext,
    total_docs: int,
):
    """The bit-sliced tier verdict, separated from execution so EXPLAIN
    can report it without serving the query (index_path_decision's
    contract).  Returns ``(decision, state)``: a JSON-safe record plus
    the execution handoff (kernel spec, leaf nodes, fused-agg
    descriptors) present only when taken."""
    from pinot_tpu.engine import tiercost
    from pinot_tpu.engine.device import bsi_filter_width, bsiv_value_spec

    mode = os.environ.get("PINOT_TPU_BITSLICED", "")
    if mode == "0":
        return {
            "taken": False,
            "reason": "bit-sliced tier disabled (PINOT_TPU_BITSLICED=0)",
        }, None
    if not live:
        return {"taken": False, "reason": "no live segments"}, None
    if (
        not request.is_aggregation
        or request.is_group_by
        or request.is_selection
        or request.join is not None
        or not request.aggregations
    ):
        return {
            "taken": False,
            "reason": "tier serves single-table scalar aggregations only",
        }, None
    for a in request.aggregations:
        if a.base_function not in _SCALAR_AGGS or a.is_mv:
            return {
                "taken": False,
                "reason": f"aggregation {a.function} not popcount-fusable",
            }, None
    if request.filter is None:
        return {
            "taken": False,
            "reason": "no filter: the plain scan already streams every row once",
        }, None

    leaves: List[Tuple[FilterQueryTree, str, str, int, int]] = []
    tree = _encode_tree(request.filter, live, leaves)
    if isinstance(tree, str):
        return {"taken": False, "reason": tree}, None

    # fused-aggregate eligibility: SUM/AVG need exactly-integral value
    # planes (bit-exactness vs the scan tier), MIN/MAX descend dictId
    # planes (dictionaries are sorted, so extreme dictId = extreme value)
    sums: Dict[str, int] = {}
    extremes: Dict[Tuple[str, bool], int] = {}
    agg_descs = []
    for a in request.aggregations:
        base = a.base_function
        if base == "count":
            agg_descs.append(("count", None))
            continue
        col = a.column
        if not all(s.has_column(col) for s in live):
            return {"taken": False, "reason": f"agg column {col!r} missing"}, None
        cols = [s.column(col) for s in live]
        if (
            not cols[0].metadata.single_value
            or cols[0].metadata.data_type.stored_type == DataType.STRING
        ):
            return {
                "taken": False,
                "reason": f"agg column {col!r} not a numeric SV column",
            }, None
        if base in ("sum", "avg"):
            spec_v = bsiv_value_spec(cols)
            if spec_v is None:
                return {
                    "taken": False,
                    "reason": f"sum({col}) not fusable: dictionary values "
                    "not exactly integral (bit-exactness contract)",
                }, None
            sums[col] = spec_v[0]
        else:
            extremes[(col, base == "max")] = bsi_filter_width(cols)
        agg_descs.append((base, col))

    filter_planes = sum(w for (_, _, _, w, _) in leaves)
    planes_total = (
        filter_planes + sum(sums.values()) + sum(extremes.values())
    )
    plane_counts = {col: w for (_, _, col, w, _) in leaves}
    decision: Dict[str, Any] = {
        "column": next(iter(plane_counts), None),
        "planes": int(planes_total),
        "planeCounts": plane_counts,
        "fusedAggs": [
            base if col is None else f"{base}({col})" for base, col in agg_descs
        ],
    }
    cap = tiercost.bsi_max_planes()
    if planes_total > cap and mode != "force":
        decision.update(
            taken=False,
            reason=f"{planes_total} planes over the bit-sliced budget ({cap})",
        )
        return decision, None

    if mode != "force":
        # clustered interval predicates belong to the zone-map/doc-range
        # tier: block pruning reads O(candidate blocks), which no
        # bitwise full-width pass can beat
        if os.environ.get("PINOT_TPU_ZONEMAP") != "0":
            for node, kind, col, _, _ in leaves:
                sortedish = kind == "interval" or (
                    kind == "points" and len(node.values) == 1
                )
                if sortedish and all(
                    s.column(col).metadata.is_sorted for s in live
                ):
                    decision.update(
                        taken=False,
                        reason=f"sorted column {col!r} defers to zone-map/"
                        "doc-range block pruning",
                    )
                    return decision, None
        bsi_ns = tiercost.bitsliced_cost_ns(total_docs, planes_total)
        scan_ns = tiercost.scan_cost_ns(total_docs)
        decision["estCostNs"] = int(bsi_ns)
        decision["scanCostNs"] = int(scan_ns)
        if bsi_ns >= scan_ns:
            decision.update(
                taken=False,
                reason="cost model favors the full scan "
                f"({planes_total} planes)",
            )
            return decision, None

    decision.update(
        taken=True,
        reason="mid-selectivity scalar aggregation fuses into the "
        f"bitwise pass over {planes_total} planes",
    )
    spec = (
        tuple((kind, col, w, k) for (_, kind, col, w, k) in leaves),
        tree,
        tuple(sorted(sums.items())),
        tuple(sorted((c, w, m) for (c, m), w in extremes.items())),
    )
    return decision, (spec, leaves, agg_descs, planes_total, filter_planes)


def _query_inputs(
    spec, leaves, live: List[ImmutableSegment], S: int
) -> Dict[str, np.ndarray]:
    """Per-segment dictId thresholds/point sets for every leaf —
    dictionaries are per-segment, so each segment lowers its own
    literals (plan.py leaf_interval / leaf_points).  Padded dummy
    segments get empty intervals / all-pad points."""
    from pinot_tpu.engine.plan import leaf_interval, leaf_points

    q: Dict[str, np.ndarray] = {}
    for i, (node, kind, col, _, k_pad) in enumerate(leaves):
        if kind == "interval":
            b = np.zeros((S, 2), dtype=np.int32)
            for s, seg in enumerate(live):
                b[s] = leaf_interval(node, seg.column(col).dictionary)
            q[f"bounds:{i}"] = b
        else:
            p = np.full((S, k_pad), -1, dtype=np.int32)
            for s, seg in enumerate(live):
                p[s] = leaf_points(node, seg.column(col).dictionary, k_pad)
            q[f"pts:{i}"] = p
    return q


def _finalize(
    request: BrokerRequest,
    agg_descs,
    staged,
    live: List[ImmutableSegment],
    outs: Dict[str, np.ndarray],
):
    """Host-side merge of the per-segment kernel outputs into agg
    partials — exact integer arithmetic end to end (python ints), so
    fused SUM is bit-exact against the scan tier's float64 result for
    the integral values the eligibility gate admits."""
    counts = np.asarray(outs["count"], dtype=np.int64)
    matched = int(counts.sum())
    partials = []
    for base, col in agg_descs:
        if base == "count":
            partials.append(CountPartial(float(matched)))
            continue
        if base in ("sum", "avg"):
            sc = staged.columns[col]
            psum = np.asarray(outs[f"psum:{col}"])  # int32 [S, Wv]
            total = 0
            for b in range(sc.bsiv_width):
                total += (1 << b) * int(psum[:, b].sum())
            for s in range(len(live)):
                total += int(sc.bsiv_min[s]) * int(counts[s])
            if base == "sum":
                partials.append(SumPartial(float(total)))
            else:
                partials.append(AvgPartial(float(total), float(matched)))
            continue
        # min/max: per-segment extreme dictId -> host dictionary lookup
        # (empty segments report garbage ids and are masked on count);
        # round-trip through the device value dtype so the answer is
        # bit-identical to the scan tier's staged-dict_vals extreme
        ids = np.asarray(outs[f"ext:{'mx' if base == 'max' else 'mn'}:{col}"])
        fdt = config.np_float_dtype()
        vals = [
            float(fdt(seg.column(col).dictionary.get(int(ids[s]))))
            for s, seg in enumerate(live)
            if counts[s] > 0
        ]
        if not vals:
            partials.append(make_partial(base))
        elif base == "min":
            partials.append(MinPartial(min(vals)))
        else:
            partials.append(MaxPartial(max(vals)))
    return partials, matched


def try_bitsliced_path(
    executor,
    request: BrokerRequest,
    live: List[ImmutableSegment],
    ctx: TableContext,
    total_docs: int,
    deadline: Optional[float] = None,
    lane=None,
    lane_index: int = 0,
) -> Optional[IntermediateResult]:
    """Serve an eligible scalar aggregation from the bit-sliced tier,
    or None to fall through to the zone-map/scan device section.  Rides
    the same lane dispatch plumbing as the scan kernels (coalescing,
    micro-timers, static cost analysis -> achievedBytesPerSec), with
    the kernel spec standing in for the StaticPlan in every cache key —
    both are process-stable hashables."""
    decision, state = bitsliced_decision(request, live, ctx, total_docs)
    if state is None:
        return None
    spec, leaves, agg_descs, planes_total, filter_planes = state
    leaf_spec, _tree, sums, extremes = spec

    from pinot_tpu.engine.device import get_staged
    from pinot_tpu.engine.dispatch import plan_digest
    from pinot_tpu.engine.kernel import make_packed_bitsliced_kernel

    bsi_cols = sorted(
        {col for (_, col, _, _) in leaf_spec} | {c for (c, _, _) in extremes}
    )
    bsiv_cols = sorted({c for (c, _) in sums})
    all_cols = sorted(set(bsi_cols) | set(bsiv_cols))
    # plane arrays ARE this tier's column layout: the base fwd/dict
    # streams stay host-side (skip_base) unless another query's staging
    # of the same segments backfills them.  The staging-token cache key
    # makes realtime LLC-offset advances invalidate the planes with
    # everything else.
    staged = get_staged(
        live,
        all_cols,
        ctx=ctx,
        skip_base_columns=all_cols,
        bsi_columns=bsi_cols,
        bsiv_columns=bsiv_cols,
        pin=True,  # tier demotion must not race this launch
    )
    from pinot_tpu.engine.residency import RESIDENCY

    try:
        return _dispatch_bitsliced(
            executor, request, live, total_docs, deadline, lane,
            lane_index, staged, spec, leaves, agg_descs, planes_total,
            filter_planes, bsi_cols, bsiv_cols,
        )
    finally:
        RESIDENCY.unpin(staged.token)


def _dispatch_bitsliced(
    executor,
    request: BrokerRequest,
    live: List[ImmutableSegment],
    total_docs: int,
    deadline: Optional[float],
    lane,
    lane_index: int,
    staged,
    spec,
    leaves,
    agg_descs,
    planes_total: int,
    filter_planes: int,
    bsi_cols,
    bsiv_cols,
) -> Optional[IntermediateResult]:
    from pinot_tpu.engine.dispatch import plan_digest
    from pinot_tpu.engine.kernel import make_packed_bitsliced_kernel

    for col in bsi_cols:
        if staged.columns[col].bsi is None:
            return None  # staging declined (shape changed underneath)
    for col in bsiv_cols:
        if staged.columns[col].bsiv is None:
            return None

    segs: Dict[str, Any] = {"nd": staged.num_docs_arr}
    dev_bytes = 0
    for col in bsi_cols:
        segs[f"p:{col}"] = staged.columns[col].bsi
        dev_bytes += int(staged.columns[col].bsi.nbytes)
    for col in bsiv_cols:
        segs[f"v:{col}"] = staged.columns[col].bsiv
        dev_bytes += int(staged.columns[col].bsiv.nbytes)

    q_np = _query_inputs(spec, leaves, live, staged.num_segments)
    digest = executor._inputs_digest(q_np)
    pdigest = plan_digest(("bsi", spec))
    cost: Dict[str, float] = {}
    kernel = make_packed_bitsliced_kernel(spec)
    # lane micro-batching (PR 13 tier): the per-leaf bounds/points
    # arrays are plain stackable int32s, so same-spec BSI queries with
    # different literals ride ONE vmapped launch reading the resident
    # planes once — the same amortization the scan kernels get
    batch_spec = None
    exec_info: Dict[str, Any] = {}
    analysis_args = None
    if lane is not None:
        batch_spec = _bsi_batch_spec(executor, spec, staged, q_np, segs)
    if batch_spec is not None:
        # defer the solo upload into the launch closure (executor
        # _device_section idiom): a member that rides a batched launch
        # never uses its own device copy
        args = lambda: (
            segs,
            executor._to_device_inputs(
                q_np, plan=spec, digest=digest, cost=cost
            ),
        )
        analysis_args = (segs, q_np)
    else:
        args = (
            segs,
            executor._to_device_inputs(
                q_np, plan=spec, digest=digest, cost=cost
            ),
        )
    outs = executor._run_kernel(
        kernel, args, spec, staged, digest, None, deadline, pdigest,
        cost=cost, lane=lane, batch_spec=batch_spec, exec_info=exec_info,
        analysis_args=analysis_args,
    )

    partials, matched = _finalize(request, agg_descs, staged, live, outs)
    res = IntermediateResult(
        num_docs_scanned=matched,
        total_docs=total_docs,
        num_segments_queried=len(live),
        # the bitwise pass reads words, not rows: planes * n/32 words
        # of 32-bit filter work per leaf plane (the O(W * n/32) claim)
        num_entries_scanned_in_filter=(filter_planes * total_docs) // 32,
        num_entries_scanned_post_filter=matched * max(1, len(agg_descs)),
    )
    res.aggregations = partials
    res.add_cost(
        bytesScanned=dev_bytes,
        deviceBytes=dev_bytes,
        segmentsBitsliced=len(live),
        **cost,
    )
    res._device_digest = pdigest
    res._lane_index = lane_index
    res._batch_size = int(exec_info.get("batchSize", 1) or 1)
    m = executor.metrics
    m.meter("filter.bitsliced.queries").mark()
    m.meter("filter.bitsliced.planes").mark(planes_total)
    m.meter("filter.bitsliced.fusedAggs").mark(len(agg_descs))
    m.meter("filter.bitsliced.bytes").mark(dev_bytes)
    return res


def _bsi_batch_spec(executor, spec, staged, q_np, segs):
    """BatchSpec for same-spec bit-sliced dispatches (the BSI analog of
    executor._batch_spec): key is (("bsi", spec), staging token, input
    signature) — literal-bucketed spec identity x resident-plane
    identity x structural input identity.  The row budget counts padded
    docs, matching the scan tier's cap, so a batched plane launch can
    never blow the compile-time working set."""
    from pinot_tpu.engine.dispatch import BatchSpec
    from pinot_tpu.engine.kernel import chunk_rows_limit
    from pinot_tpu.engine.packing import batch_input_signature

    limit = chunk_rows_limit()
    rows = max(1, staged.num_segments * staged.n_pad)
    if limit:
        cap = limit // rows
        max_members = 1
        while max_members * 2 <= cap:
            max_members *= 2
    else:
        max_members = 0
    if max_members == 1:
        return None  # one member already fills the budget
    key = (("bsi", spec), staged.token, batch_input_signature(q_np))

    def launch_batched(inputs_list):
        from pinot_tpu.engine.device import to_device_inputs
        from pinot_tpu.engine.kernel import make_packed_batched_bitsliced_kernel
        from pinot_tpu.engine.packing import stack_query_inputs

        bkernel = make_packed_batched_bitsliced_kernel(spec)
        # pad member count to a power of two (repeat member 0, whose
        # extra outputs are never sliced) — compile count stays bounded
        # at log2 distinct batch shapes per spec
        b = len(inputs_list)
        b_pad = 1
        while b_pad < b:
            b_pad *= 2
        if b_pad > b:
            inputs_list = list(inputs_list) + [inputs_list[0]] * (b_pad - b)
        stacked = stack_query_inputs(inputs_list)
        qb = to_device_inputs(stacked)
        return bkernel.fetch, bkernel.dispatch(segs, qb)

    return BatchSpec(key, q_np, launch_batched, max_members=max_members)
