"""Selective-query fast path: host postings instead of a device scan.

The reference picks its filter operator per predicate by selectivity:
``BitmapBasedFilterOperator.java:34`` walks the inverted index in
O(matches); ``ScanBasedFilterOperator.java:38`` scans.  This module is
that dispatch re-cut for TPU economics: the device scan path runs at
~2.8 B rows/s but costs a dispatch + tunnel round trip; for a
predicate matching a few thousand rows, resolving row ids from
host-resident CSR postings (``segment/invindex.py``) and aggregating
those rows with numpy fancy-indexing finishes in well under a
millisecond of host time and never touches the device.

Shape: one *driving* leaf (EQ/IN/RANGE/REGEX, non-negated) resolves
row ids from postings; every other predicate of a root-level AND
evaluates as a *residual* on just those rows (recursive subset masks,
mirroring ``host_fallback._segment_mask`` semantics).  Estimated and
actual match counts above the selectivity threshold bail back to the
device scan — exactly the reference's operator-choice contract.
"""
from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from pinot_tpu.common.request import BrokerRequest, FilterOperator, FilterQueryTree
from pinot_tpu.engine.context import TableContext
from pinot_tpu.engine.plan import cached_match_table
from pinot_tpu.engine.results import IntermediateResult
from pinot_tpu.segment.immutable import ImmutableSegment
from pinot_tpu.segment.invindex import inverted_index

_DRIVING_OPS = (
    FilterOperator.EQUALITY,
    FilterOperator.IN,
    FilterOperator.RANGE,
    FilterOperator.REGEX,
)


def _max_matches(total_docs: int) -> int:
    env = os.environ.get("PINOT_TPU_INDEX_MAX_MATCHES")
    if env:
        return int(env)
    # crossover heuristic: numpy fancy-index aggregation costs ~10 ns/row
    # host-side; the device scan costs ~0.35 ns/row (2.8 B rows/s) plus a
    # fixed dispatch+RTT floor.  The fraction bound (1/64 of the table)
    # keeps the host path an order of magnitude under the scan at any
    # size AND keeps unselective predicates on the device even for small
    # tables — this is a needle-query path, not a general fallback.
    # Constants live in engine/tiercost.py (PINOT_TPU_TIER_COST_*).
    from pinot_tpu.engine.tiercost import postings_max_matches

    return postings_max_matches(total_docs)


def _mv_subset_hits(col, table: np.ndarray, rows: np.ndarray) -> np.ndarray:
    offs = np.asarray(col.mv_offsets)
    starts = offs[rows]
    counts = offs[rows + 1] - starts
    total = int(counts.sum())
    if total == 0:
        return np.zeros(rows.size, dtype=bool)
    reps = np.repeat(np.arange(rows.size), counts)
    base = np.repeat(starts, counts)
    cum = np.concatenate([[0], np.cumsum(counts)[:-1]])
    pos = np.arange(total) - np.repeat(cum, counts)
    hits = table[np.asarray(col.mv_values)[base + pos]]
    any_hit = np.zeros(rows.size, dtype=bool)
    np.logical_or.at(any_hit, reps, hits)
    return any_hit


def _subset_mask(
    seg: ImmutableSegment, tree: FilterQueryTree, rows: np.ndarray
) -> np.ndarray:
    """Evaluate a filter tree over a row-id subset — bool[rows.size].
    Semantics mirror host_fallback._segment_mask exactly."""
    if tree.is_leaf:
        col = seg.column(tree.column)
        d = col.dictionary
        table = cached_match_table(
            tree, d, d.cardinality if d.cardinality else 1,
            cache_key=(seg.segment_name, seg.metadata.crc, tree.column),
        )
        negative = tree.operator in (FilterOperator.NOT, FilterOperator.NOT_IN)
        if col.is_single_value:
            m = table[np.asarray(col.fwd)[rows]]
            return ~m if negative else m
        any_hit = _mv_subset_hits(col, table, rows)
        return ~any_hit if negative else any_hit
    masks = [_subset_mask(seg, c, rows) for c in tree.children]
    out = masks[0]
    for m in masks[1:]:
        out = (out & m) if tree.operator == FilterOperator.AND else (out | m)
    return out


def _decompose(tree: FilterQueryTree):
    """-> (driving candidates, all conjuncts) or None.  The filter must
    be a single leaf or a root-level AND of subtrees; the driving leaf
    is any direct-child positive leaf, the rest evaluate as residuals."""
    if tree.is_leaf:
        return ([tree], [tree]) if tree.operator in _DRIVING_OPS else None
    if tree.operator != FilterOperator.AND:
        return None
    cands = [
        c for c in tree.children if c.is_leaf and c.operator in _DRIVING_OPS
    ]
    return (cands, list(tree.children)) if cands else None


def index_path_decision(
    request: BrokerRequest,
    live: List[ImmutableSegment],
    ctx: TableContext,
    total_docs: int,
):
    """The operator-choice verdict, separated from execution so the
    EXPLAIN plane can report it without serving the query.

    Returns ``(decision, state)``: ``decision`` is a JSON-safe record
    (``taken`` plus the reason/estimates that justify it); ``state`` is
    the resolved ``(best leaf, indexes, residuals, est)`` execution
    handoff, present only when ``taken`` is True."""
    if os.environ.get("PINOT_TPU_INVINDEX") == "0":
        return {"taken": False, "reason": "postings path disabled (PINOT_TPU_INVINDEX=0)"}, None
    tree = request.filter
    if tree is None:
        return {"taken": False, "reason": "no filter: nothing selective to drive postings"}, None
    dec = _decompose(tree)
    if dec is None:
        return {
            "taken": False,
            "reason": "filter shape not postings-drivable (needs a root-level "
            "AND / single positive leaf)",
        }, None
    cands, conjuncts = dec
    live_docs = sum(s.num_docs for s in live)
    limit = _max_matches(live_docs)

    # cheap pre-estimate (uniform assumption: matched dict fraction *
    # rows) picks ONE candidate before any postings build; tables are
    # kept for the confirm/resolve stages (REGEX tables cost O(card)
    # regex evaluations — never compute them twice)
    best = None
    best_frac = None
    best_tables = None
    for leaf in cands:
        frac = 0.0
        ok = True
        tables = []
        for seg in live:
            col = seg.columns.get(leaf.column)
            if col is None or col.dictionary.cardinality <= 0:
                ok = False
                break
            d = col.dictionary
            t = cached_match_table(
                leaf, d, d.cardinality,
                cache_key=(seg.segment_name, seg.metadata.crc, leaf.column),
            )
            tables.append(t)
            frac = max(frac, float(t.sum()) / d.cardinality)
        if ok and (best_frac is None or frac < best_frac):
            best, best_frac, best_tables = leaf, frac, tables
    if best is None or best_frac * live_docs > limit:
        return {
            "taken": False,
            "reason": "estimated matches above the postings/scan crossover",
            "column": None if best is None else best.column,
            "estMatches": None
            if best is None
            else int(best_frac * live_docs),
            "maxMatches": int(limit),
        }, None

    # real postings counts confirm (skew can defeat the uniform guess)
    indexes = []
    est = 0
    for seg, t in zip(live, best_tables):
        idx = inverted_index(seg, best.column)
        if idx is None:
            return {
                "taken": False,
                "reason": f"no inverted index for driving column {best.column!r}",
                "column": best.column,
            }, None
        est += idx.count_for_table(t)
        indexes.append((idx, t))
    if est > limit:
        return {
            "taken": False,
            "reason": "postings count above the postings/scan crossover "
            "(skew defeated the uniform estimate)",
            "column": best.column,
            "estMatches": int(est),
            "maxMatches": int(limit),
        }, None

    residuals = [c for c in conjuncts if c is not best]
    decision = {
        "taken": True,
        "reason": "selective driving leaf answers from host postings in O(matches)",
        "column": best.column,
        "estMatches": int(est),
        "maxMatches": int(limit),
        "residuals": len(residuals),
    }
    return decision, (best, indexes, residuals, est)


def try_index_path(
    request: BrokerRequest,
    live: List[ImmutableSegment],
    ctx: TableContext,
    total_docs: int,
    sel_columns: Optional[List[str]],
) -> Optional[IntermediateResult]:
    """O(matches) host path, or None to take the device scan."""
    decision, state = index_path_decision(request, live, ctx, total_docs)
    if state is None:
        return None
    best, indexes, residuals, est = state

    def matched_rows(si: int, seg: ImmutableSegment) -> np.ndarray:
        idx, t = indexes[si]
        rows = idx.resolve_table(t)
        if rows.size and residuals:
            keep = np.ones(rows.size, dtype=bool)
            for r in residuals:
                keep &= _subset_mask(seg, r, rows)
            rows = rows[keep]
        return rows

    from pinot_tpu.engine.host_fallback import execute_host

    res = execute_host(
        live, ctx, request, total_docs, sel_columns, matched_rows=matched_rows
    )
    # filter work was O(postings), not O(n): report candidate rows like
    # the zone-map path does (num_entries_scanned contract)
    res.num_entries_scanned_in_filter = est * max(1, len(residuals) + 1)
    # cost re-attribution: this is the postings tier, and its bytes are
    # O(matches) — the wrapper's full-column upper bound does not apply
    res.cost.pop("segmentsHost", None)
    res.cost["segmentsPostings"] = len(live)
    res.cost["bytesScanned"] = est * max(1, len(residuals) + 1) * 8
    return res
