"""Segment pruning before planning.

Reference: pinot-core ``query/pruner/`` —
``DataSchemaSegmentPruner`` (drop segments missing referenced columns),
``ValidSegmentPruner`` (drop empty segments), ``TimeSegmentPruner``
(drop segments whose [startTime, endTime] cannot match the query's
time-column predicate).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from pinot_tpu.common.request import BrokerRequest, FilterOperator, FilterQueryTree
from pinot_tpu.segment.immutable import ImmutableSegment


def _time_bounds(
    tree: Optional[FilterQueryTree], time_column: str
) -> Optional[Tuple[float, float]]:
    """Conservative [lo, hi] the time column must intersect, from
    top-level AND / single-leaf predicates only."""
    if tree is None:
        return None
    leaves: List[FilterQueryTree] = []
    if tree.is_leaf:
        leaves = [tree]
    elif tree.operator == FilterOperator.AND:
        leaves = [c for c in tree.children if c.is_leaf]
    lo, hi = float("-inf"), float("inf")
    found = False
    for leaf in leaves:
        if leaf.column != time_column:
            continue
        try:
            if leaf.operator == FilterOperator.EQUALITY:
                v = float(leaf.values[0])
                lo, hi = max(lo, v), min(hi, v)
                found = True
            elif leaf.operator == FilterOperator.RANGE and leaf.range_spec:
                r = leaf.range_spec
                if r.lower not in (None, "*"):
                    lo = max(lo, float(r.lower))
                if r.upper not in (None, "*"):
                    hi = min(hi, float(r.upper))
                found = True
            elif leaf.operator == FilterOperator.IN:
                vs = [float(v) for v in leaf.values]
                lo, hi = max(lo, min(vs)), min(hi, max(vs))
                found = True
        except ValueError:
            continue
    return (lo, hi) if found else None


def _prune_reason(
    seg: ImmutableSegment, request: BrokerRequest, needed: Sequence[str]
) -> Optional[str]:
    """Why this segment is pruned, or None to keep it — the ONE verdict
    prune_segments and the EXPLAIN decision records share."""
    if seg.num_docs == 0:  # ValidSegmentPruner
        return "empty segment (ValidSegmentPruner)"
    missing = [c for c in needed if not seg.has_column(c)]
    if missing:  # DataSchemaSegmentPruner
        return f"missing columns {sorted(missing)} (DataSchemaSegmentPruner)"
    meta = seg.metadata
    if meta.time_column and meta.start_time is not None and meta.end_time is not None:
        bounds = _time_bounds(request.filter, meta.time_column)
        if bounds is not None:
            lo, hi = bounds
            if hi < meta.start_time or lo > meta.end_time:  # TimeSegmentPruner
                return (
                    f"time range [{meta.start_time},{meta.end_time}] outside "
                    f"predicate [{lo},{hi}] (TimeSegmentPruner)"
                )
    return None


def prune_explain(
    segments: Sequence[ImmutableSegment], request: BrokerRequest
) -> List[Tuple[ImmutableSegment, Optional[str]]]:
    """Per-segment prune verdicts in input order: (segment, reason) —
    reason None means the segment survives to planning.  The EXPLAIN
    plane's view of the pruning stage."""
    needed = request.referenced_columns()
    return [(seg, _prune_reason(seg, request, needed)) for seg in segments]


def prune_segments(
    segments: Sequence[ImmutableSegment], request: BrokerRequest
) -> List[ImmutableSegment]:
    needed = request.referenced_columns()
    return [
        seg for seg in segments if _prune_reason(seg, request, needed) is None
    ]
