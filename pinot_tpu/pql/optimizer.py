"""Filter-tree optimizers, mirroring the reference broker's rewrites.

Reference: pinot-transport ``requestHandler/BrokerRequestOptimizer.java``
with ``FlattenNestedPredicatesFilterQueryTreeOptimizer.java`` and
``MultipleOrEqualitiesToInClauseFilterQueryTreeOptimizer.java``.

1. Flatten nested AND(AND(...)) / OR(OR(...)) into a single level.
2. Collapse OR of EQUALITY/IN on the same column into one IN clause
   (single-value IN degenerates back to EQUALITY).
"""
from __future__ import annotations

from typing import List, Optional

from pinot_tpu.common.request import BrokerRequest, FilterOperator, FilterQueryTree
from pinot_tpu.pql.parser import PqlParseError


def flatten(tree: FilterQueryTree) -> FilterQueryTree:
    if tree.is_leaf:
        return tree
    new_children: List[FilterQueryTree] = []
    for child in tree.children:
        c = flatten(child)
        if c.operator == tree.operator and not c.is_leaf:
            new_children.extend(c.children)
        else:
            new_children.append(c)
    if len(new_children) == 1:
        return new_children[0]
    return FilterQueryTree(operator=tree.operator, children=new_children)


def or_equalities_to_in(tree: FilterQueryTree) -> FilterQueryTree:
    if tree.is_leaf:
        return tree
    children = [or_equalities_to_in(c) for c in tree.children]
    if tree.operator != FilterOperator.OR:
        return FilterQueryTree(operator=tree.operator, children=children)

    # Gather EQUALITY/IN leaves per column; keep everything else as-is.
    by_column: dict = {}
    others: List[FilterQueryTree] = []
    for c in children:
        if c.is_leaf and c.operator in (FilterOperator.EQUALITY, FilterOperator.IN) and c.column:
            by_column.setdefault(c.column, [])
            for v in c.values:
                if v not in by_column[c.column]:
                    by_column[c.column].append(v)
        else:
            others.append(c)

    merged: List[FilterQueryTree] = []
    for col, vals in by_column.items():
        if len(vals) == 1:
            merged.append(FilterQueryTree(operator=FilterOperator.EQUALITY, column=col, values=vals))
        else:
            merged.append(FilterQueryTree(operator=FilterOperator.IN, column=col, values=vals))

    out = merged + others
    if len(out) == 1:
        return out[0]
    return FilterQueryTree(operator=FilterOperator.OR, children=out)


class InvalidQueryOptionsError(PqlParseError):
    """Bad per-query options (e.g. malformed ``optimizationFlags``) —
    a client error, distinct from internal ValueErrors so the broker
    can report it as PQL_PARSING without masking engine bugs (ADVICE
    r1: broker.py bare-ValueError catch)."""


class OptimizationFlags:
    """Per-query optimizer toggles from the ``optimizationFlags`` debug
    option (``requestHandler/OptimizationFlags.java``): a comma list of
    names each prefixed ``+`` (enable — disabling all others) or ``-``
    (disable that one); mixing both is an error, as in the reference."""

    def __init__(self, enabled: set, disabled: set) -> None:
        if enabled and disabled:
            raise InvalidQueryOptionsError(
                "cannot exclude and include optimizations at the same time"
            )
        self._enabled = enabled
        self._disabled = disabled

    def is_enabled(self, name: str) -> bool:
        if self._enabled:
            return name in self._enabled
        return name not in self._disabled

    @staticmethod
    def from_debug_options(debug_options) -> Optional["OptimizationFlags"]:
        s = (debug_options or {}).get("optimizationFlags", "")
        if not s:
            return None
        enabled: set = set()
        disabled: set = set()
        for opt in (o.strip() for o in s.split(",")):
            if not opt:
                continue
            if opt[0] == "+":
                enabled.add(opt[1:])
            elif opt[0] == "-":
                disabled.add(opt[1:])
            else:
                raise InvalidQueryOptionsError(
                    f"optimization flag {opt!r} must be prefixed with + or -"
                )
        return OptimizationFlags(enabled, disabled)


def optimize_filter(
    tree: Optional[FilterQueryTree], flags: Optional[OptimizationFlags] = None
) -> Optional[FilterQueryTree]:
    if tree is None:
        return None
    flatten_on = flags is None or flags.is_enabled("flattenNestedPredicates")
    if flatten_on:
        tree = flatten(tree)
    if flags is None or flags.is_enabled("multipleOrEqualitiesToInClause"):
        tree = or_equalities_to_in(tree)
        if flatten_on:
            tree = flatten(tree)
    return tree


def optimize_request(request: BrokerRequest) -> BrokerRequest:
    if request.having is not None:
        # HAVING must name a selected aggregation — silently ignoring
        # an unmatched predicate would return unfiltered groups
        h = request.having
        if not any(
            h.function == a.function and (h.column == a.column or h.column == "*")
            for a in request.aggregations
        ):
            from pinot_tpu.pql.parser import PqlParseError

            raise PqlParseError(
                f"HAVING references {h.function}({h.column}), which is not "
                "in the SELECT aggregation list"
            )
    flags = OptimizationFlags.from_debug_options(request.debug_options)
    request.filter = optimize_filter(request.filter, flags)
    return request
