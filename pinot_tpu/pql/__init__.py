from pinot_tpu.pql.parser import parse_pql, PqlParseError
from pinot_tpu.pql.optimizer import optimize_request

__all__ = ["parse_pql", "PqlParseError", "optimize_request"]
