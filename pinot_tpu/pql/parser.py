"""PQL parser: query text -> BrokerRequest.

Implements the language defined by the reference grammar
(pinot-common ``src/main/antlr4/.../PQL2.g4``) with a hand-written
tokenizer + recursive-descent parser (no ANTLR dependency):

    SELECT [TOP n] (* | col|agg(col) [, ...]) FROM table
      [WHERE predicates] [GROUP BY cols] [HAVING pred]
      [ORDER BY col [ASC|DESC], ...] [TOP n] [LIMIT n[, m]]

Predicates: ``=  <>  !=  <  >  <=  >=``, ``BETWEEN a AND b``,
``[NOT] IN (v, ...)``, ``REGEXP_LIKE(col, 'pattern')``, combined with
AND/OR and parentheses.  AND binds tighter than OR (standard SQL; the
reference's Pql2 compiler flattens the same way via its precedence
handling in ``pql/parsers/pql2/ast/PredicateListAstNode.java``).
"""
from __future__ import annotations

import re
from dataclasses import dataclass
from typing import List, Optional, Tuple

from pinot_tpu.common.request import (
    AGGREGATION_FUNCTIONS,
    AggregationInfo,
    BrokerRequest,
    FilterOperator,
    FilterQueryTree,
    GroupBy,
    HavingSpec,
    JoinSpec,
    RangeSpec,
    Selection,
    SelectionSort,
)


class PqlParseError(ValueError):
    pass


# keywords that terminate a FROM-clause table/alias position — an ident
# here is a clause, not an alias
_CLAUSE_KEYWORDS = frozenset(
    {"WHERE", "GROUP", "ORDER", "HAVING", "TOP", "LIMIT", "JOIN", "INNER",
     "CROSS", "LEFT", "RIGHT", "FULL", "OUTER", "ON", "AS"}
)


_TOKEN_RE = re.compile(
    r"""
      (?P<ws>\s+)
    | (?P<comment>--[^\n]*)
    | (?P<number>[-+]?(\d+\.\d*|\.\d+|\d+)([eE][-+]?\d+)?)
    | (?P<string>'(?:[^']|'')*'|"(?:[^"]|"")*")
    | (?P<ident>[A-Za-z_][A-Za-z0-9_\-]*)
    | (?P<op><>|<=|>=|!=|[=<>(),.;*])
    """,
    re.VERBOSE,
)


@dataclass
class Token:
    kind: str  # 'number' | 'string' | 'ident' | 'op' | 'eof'
    text: str
    pos: int

    @property
    def upper(self) -> str:
        return self.text.upper()


def _tokenize(pql: str) -> List[Token]:
    tokens: List[Token] = []
    pos = 0
    n = len(pql)
    while pos < n:
        m = _TOKEN_RE.match(pql, pos)
        if m is None:
            raise PqlParseError(f"unexpected character {pql[pos]!r} at position {pos}")
        pos = m.end()
        kind = m.lastgroup
        if kind in ("ws", "comment"):
            continue
        text = m.group()
        if kind == "string":
            quote = text[0]
            text = text[1:-1].replace(quote * 2, quote)
        tokens.append(Token(kind=kind, text=text, pos=m.start()))
    tokens.append(Token(kind="eof", text="", pos=n))
    return tokens


class _Parser:
    def __init__(self, pql: str) -> None:
        self.tokens = _tokenize(pql)
        self.i = 0

    # -- token helpers -------------------------------------------------
    def peek(self, offset: int = 0) -> Token:
        return self.tokens[min(self.i + offset, len(self.tokens) - 1)]

    def next(self) -> Token:
        t = self.tokens[self.i]
        if t.kind != "eof":
            self.i += 1
        return t

    def accept_kw(self, *kws: str) -> Optional[Token]:
        t = self.peek()
        if t.kind == "ident" and t.upper in kws:
            return self.next()
        return None

    def expect_kw(self, kw: str) -> Token:
        t = self.accept_kw(kw)
        if t is None:
            raise PqlParseError(f"expected {kw} at position {self.peek().pos}, got {self.peek().text!r}")
        return t

    def accept_op(self, *ops: str) -> Optional[Token]:
        t = self.peek()
        if t.kind == "op" and t.text in ops:
            return self.next()
        return None

    def expect_op(self, op: str) -> Token:
        t = self.accept_op(op)
        if t is None:
            raise PqlParseError(f"expected {op!r} at position {self.peek().pos}, got {self.peek().text!r}")
        return t

    def expect_ident(self) -> Token:
        t = self.peek()
        if t.kind != "ident":
            raise PqlParseError(f"expected identifier at position {t.pos}, got {t.text!r}")
        return self.next()

    # -- grammar -------------------------------------------------------
    def parse(self) -> BrokerRequest:
        # EXPLAIN [ANALYZE] [PLAN FOR] SELECT ... — the introspection
        # prefix (reference later grew ``EXPLAIN PLAN FOR``, see
        # PARITY.md).  EXPLAIN returns the physical plan without
        # executing; EXPLAIN ANALYZE executes and annotates the plan
        # nodes with actuals from the cost vector.
        explain: Optional[str] = None
        if self.accept_kw("EXPLAIN"):
            explain = "analyze" if self.accept_kw("ANALYZE") else "plan"
            if self.accept_kw("PLAN"):
                self.expect_kw("FOR")
        self.expect_kw("SELECT")
        top_n: Optional[int] = None
        if self.accept_kw("TOP"):
            top_n = self._int_literal()

        star, projections = self._output_columns()
        self.expect_kw("FROM")
        table = self._table_name()
        left_alias = self._maybe_alias()
        join = None
        join_aliases: Optional[dict] = None
        if self.peek().kind == "op" and self.peek().text == ",":
            # comma-separated FROM lists are implicit cross joins
            raise PqlParseError(
                "cross joins are not supported: use JOIN ... ON <a.col> = <b.col>"
            )
        if self.accept_kw("CROSS"):
            raise PqlParseError(
                "cross joins are not supported: use JOIN ... ON <a.col> = <b.col>"
            )
        if self.accept_kw("LEFT", "RIGHT", "FULL", "OUTER"):
            raise PqlParseError(
                "only INNER equi-joins are supported (LEFT/RIGHT/FULL/OUTER "
                "joins are not)"
            )
        inner = self.accept_kw("INNER")
        if self.accept_kw("JOIN"):
            join, join_aliases = self._join_clause(table, left_alias)
        elif inner is not None:
            raise PqlParseError("expected JOIN after INNER")

        filter_tree: Optional[FilterQueryTree] = None
        group_by_cols: List[str] = []
        having: Optional[HavingSpec] = None
        sorts: List[SelectionSort] = []
        offset, size = 0, None

        while True:
            if self.accept_kw("WHERE"):
                filter_tree = self._predicate_list()
            elif self.peek().upper == "GROUP":
                self.next()
                self.expect_kw("BY")
                group_by_cols = [self._column_token()]
                while self.accept_op(","):
                    group_by_cols.append(self._column_token())
            elif self.accept_kw("HAVING"):
                having = self._having()
            elif self.peek().upper == "ORDER":
                self.next()
                self.expect_kw("BY")
                sorts = [self._order_by_expr()]
                while self.accept_op(","):
                    sorts.append(self._order_by_expr())
            elif self.accept_kw("TOP"):
                top_n = self._int_literal()
            elif self.accept_kw("LIMIT"):
                a = self._int_literal()
                if self.accept_op(","):
                    # LIMIT offset, size (PQL2.g4 limitClause)
                    offset, size = a, self._int_literal()
                else:
                    size = a
            elif self.accept_op(";"):
                continue
            elif self.peek().kind == "eof":
                break
            else:
                raise PqlParseError(
                    f"unexpected token {self.peek().text!r} at position {self.peek().pos}"
                )

        # Assemble the request.
        aggregations = [p for p in projections if isinstance(p, AggregationInfo)]
        plain_cols = [p for p in projections if isinstance(p, str)]
        if aggregations and plain_cols:
            raise PqlParseError("cannot mix aggregation functions and plain columns in SELECT")

        req = BrokerRequest(table_name=table)
        req.explain = explain
        req.filter = filter_tree
        req.having = having
        req.join = join
        if aggregations:
            req.aggregations = aggregations
            if group_by_cols:
                req.group_by = GroupBy(columns=group_by_cols, top_n=top_n if top_n is not None else 10)
        else:
            if star and join is not None:
                raise PqlParseError(
                    "SELECT * is not supported in join queries: name the "
                    "output columns explicitly (qualified with a side alias)"
                )
            sel_cols = ["*"] if star else plain_cols
            req.selection = Selection(
                columns=sel_cols,
                sorts=sorts,
                offset=offset,
                size=size if size is not None else 10,
            )
        if join is not None:
            _resolve_join_columns(req, join, join_aliases)
        else:
            _reject_qualified_columns(req)
        return req

    def _maybe_alias(self) -> Optional[str]:
        """``[AS] alias`` after a FROM-clause table name, or None."""
        if self.accept_kw("AS"):
            return self.expect_ident().text
        t = self.peek()
        if t.kind == "ident" and t.upper not in _CLAUSE_KEYWORDS:
            return self.next().text
        return None

    def _join_clause(self, left_table: str, left_alias: Optional[str]):
        """``JOIN <table> [AS alias] ON <x.k> = <y.k>`` — returns the
        JoinSpec plus the alias->side map used by column resolution.
        Everything outside a single INNER equi-join between exactly two
        tables is a typed parse error (clear 4xx, never a crash)."""
        right_table = self._table_name()
        right_alias = self._maybe_alias()
        self.expect_kw("ON")
        lref = self._qualified_ref("ON")
        op = self.accept_op("=")
        if op is None:
            bad = self.peek()
            raise PqlParseError(
                "only equi-joins are supported: the ON predicate must be "
                f"<a.col> = <b.col> (got {bad.text!r} at position {bad.pos})"
            )
        rref = self._qualified_ref("ON")
        if self.peek().kind == "ident" and self.peek().upper in ("AND", "OR"):
            raise PqlParseError(
                "compound ON predicates are not supported: exactly one "
                "equality between one column from each side"
            )
        if self.peek().kind == "ident" and self.peek().upper == "JOIN" or (
            self.peek().upper in ("INNER", "CROSS") and self.peek(1).upper == "JOIN"
        ):
            raise PqlParseError("at most two tables can be joined (one JOIN clause)")
        aliases: dict = {}
        for name, side in (
            (left_table, "l"), (left_alias, "l"),
            (right_table, "r"), (right_alias, "r"),
        ):
            if not name:
                continue
            if aliases.get(name, side) != side:
                raise PqlParseError(
                    f"alias {name!r} is ambiguous: it names both join sides"
                )
            aliases[name] = side
        sides = {}
        for qual, col in (lref, rref):
            side = aliases.get(qual)
            if side is None:
                raise PqlParseError(
                    f"unknown table alias {qual!r} in ON clause"
                )
            if side in sides:
                raise PqlParseError(
                    "the ON equality must reference one column from EACH "
                    f"side (both operands resolve to the same table)"
                )
            sides[side] = col
        # reversed ON order (b.k = a.k) normalizes here: sides are
        # keyed by resolution, not operand position
        spec = JoinSpec(
            right_table=right_table,
            left_key=sides["l"],
            right_key=sides["r"],
        )
        return spec, aliases

    def _qualified_ref(self, where: str) -> Tuple[str, str]:
        """``alias.col`` (both idents required) for the ON clause."""
        t = self.expect_ident()
        if not self.accept_op("."):
            raise PqlParseError(
                f"column references in {where} must be qualified as "
                f"<alias>.<column> (got bare {t.text!r} at position {t.pos})"
            )
        return t.text, self.expect_ident().text

    def _output_columns(self) -> Tuple[bool, List[object]]:
        if self.accept_op("*"):
            return True, []
        projections: List[object] = [self._output_column()]
        while self.accept_op(","):
            projections.append(self._output_column())
        return False, projections

    def _column_token(self) -> str:
        """A column reference: ``col`` or ``alias.col`` (the dotted form
        is resolved to a join side after the FROM clause is known)."""
        t = self.expect_ident()
        if self.accept_op("."):
            return t.text + "." + self.expect_ident().text
        return t.text

    def _output_column(self) -> object:
        t = self.expect_ident()
        if self.peek().kind == "op" and self.peek().text == "(":
            # aggregation function call
            func = t.text.lower()
            self.expect_op("(")
            if self.accept_op("*"):
                col = "*"
            else:
                col = self._column_token()
            self.expect_op(")")
            if self.accept_kw("AS"):
                self.next()  # alias ignored (reference keeps function_col naming)
            if func not in AGGREGATION_FUNCTIONS:
                raise PqlParseError(f"unknown aggregation function {func!r}")
            return AggregationInfo(function=func, column=col)
        name = t.text
        if self.accept_op("."):
            name += "." + self.expect_ident().text
        if self.accept_kw("AS"):
            self.next()
        return name

    def _table_name(self) -> str:
        t = self.peek()
        if t.kind == "string":
            return self.next().text
        name = self.expect_ident().text
        if self.accept_op("."):
            name += "." + self.expect_ident().text
        return name

    def _int_literal(self) -> int:
        t = self.next()
        if t.kind != "number":
            raise PqlParseError(f"expected integer at position {t.pos}, got {t.text!r}")
        return int(float(t.text))

    def _literal(self) -> str:
        t = self.next()
        if t.kind not in ("number", "string", "ident"):
            raise PqlParseError(f"expected literal at position {t.pos}, got {t.text!r}")
        return t.text

    # predicates: OR( AND( unit ) ) with parens
    def _predicate_list(self) -> FilterQueryTree:
        node = self._and_list()
        children = [node]
        while self.accept_kw("OR"):
            children.append(self._and_list())
        if len(children) == 1:
            return children[0]
        return FilterQueryTree(operator=FilterOperator.OR, children=children)

    def _and_list(self) -> FilterQueryTree:
        node = self._predicate_unit()
        children = [node]
        while self.accept_kw("AND"):
            children.append(self._predicate_unit())
        if len(children) == 1:
            return children[0]
        return FilterQueryTree(operator=FilterOperator.AND, children=children)

    def _predicate_unit(self) -> FilterQueryTree:
        if self.accept_op("("):
            node = self._predicate_list()
            self.expect_op(")")
            return node

        t = self.expect_ident()
        if t.upper == "REGEXP_LIKE" and self.peek().text == "(":
            self.expect_op("(")
            col = self._column_token()
            self.expect_op(",")
            pattern = self._literal()
            self.expect_op(")")
            return FilterQueryTree(operator=FilterOperator.REGEX, column=col, values=[pattern])

        column = t.text
        if self.accept_op("."):
            column += "." + self.expect_ident().text
        if self.accept_kw("BETWEEN"):
            lo = self._literal()
            self.expect_kw("AND")
            hi = self._literal()
            return FilterQueryTree(
                operator=FilterOperator.RANGE,
                column=column,
                range_spec=RangeSpec(lower=lo, upper=hi, include_lower=True, include_upper=True),
            )
        if self.accept_kw("NOT"):
            self.expect_kw("IN")
            vals = self._in_list()
            return FilterQueryTree(operator=FilterOperator.NOT_IN, column=column, values=vals)
        if self.accept_kw("IN"):
            vals = self._in_list()
            return FilterQueryTree(operator=FilterOperator.IN, column=column, values=vals)

        op = self.accept_op("=", "<>", "!=", "<", ">", "<=", ">=")
        if op is None:
            raise PqlParseError(f"expected predicate operator at position {self.peek().pos}")
        value = self._literal()
        if op.text == "=":
            return FilterQueryTree(operator=FilterOperator.EQUALITY, column=column, values=[value])
        if op.text in ("<>", "!="):
            return FilterQueryTree(operator=FilterOperator.NOT, column=column, values=[value])
        spec = {
            "<": RangeSpec(upper=value, include_upper=False),
            "<=": RangeSpec(upper=value, include_upper=True),
            ">": RangeSpec(lower=value, include_lower=False),
            ">=": RangeSpec(lower=value, include_lower=True),
        }[op.text]
        return FilterQueryTree(operator=FilterOperator.RANGE, column=column, range_spec=spec)

    def _in_list(self) -> List[str]:
        self.expect_op("(")
        vals = [self._literal()]
        while self.accept_op(","):
            vals.append(self._literal())
        self.expect_op(")")
        return vals

    def _having(self) -> HavingSpec:
        func_tok = self.expect_ident()
        self.expect_op("(")
        if self.accept_op("*"):
            col = "*"
        else:
            col = self._column_token()
        self.expect_op(")")
        op = self.accept_op("=", "<>", "!=", "<", ">", "<=", ">=")
        if op is None:
            raise PqlParseError(f"expected comparison in HAVING at position {self.peek().pos}")
        val = float(self._literal())
        return HavingSpec(function=func_tok.text.lower(), column=col, operator=op.text, value=val)

    def _order_by_expr(self) -> SelectionSort:
        col = self._column_token()
        asc = True
        if self.accept_kw("DESC"):
            asc = False
        elif self.accept_kw("ASC"):
            asc = True
        return SelectionSort(column=col, ascending=asc)


def _rewrite_request_columns(req: BrokerRequest, fn) -> None:
    """Apply ``fn(name) -> name`` to every column reference in the
    request (filter leaves, aggregation inputs, group-by, selection,
    sorts, having).  ``"*"`` passes through untouched."""

    def f(name: Optional[str]) -> Optional[str]:
        if name is None or name == "*":
            return name
        return fn(name)

    if req.filter is not None:
        for node in req.filter.walk():
            if node.is_leaf:
                node.column = f(node.column)
    for a in req.aggregations:
        a.column = f(a.column)
    if req.group_by is not None:
        req.group_by.columns = [f(c) for c in req.group_by.columns]
    if req.selection is not None:
        req.selection.columns = [f(c) for c in req.selection.columns]
        for s in req.selection.sorts:
            s.column = f(s.column)
    if req.having is not None:
        req.having.column = f(req.having.column)


def _resolve_join_columns(req: BrokerRequest, join: JoinSpec, aliases: dict) -> None:
    """Resolve every ``alias.col`` reference to its join side: left-side
    columns become bare names, right-side columns the canonical
    ``"<right_table>.<col>"`` form (stable across alias spellings, so
    two phrasings of one semantic query share a plan-shape digest).
    Bare references in a join query are rejected — requiring
    qualification makes side resolution purely syntactic instead of
    depending on schemas the broker may not hold."""

    def resolve(name: str) -> str:
        if "." not in name:
            raise PqlParseError(
                "column references in a join query must be qualified with "
                f"a table alias (got bare {name!r})"
            )
        qual, col = name.split(".", 1)
        side = aliases.get(qual)
        if side is None:
            raise PqlParseError(f"unknown table alias {qual!r}")
        return col if side == "l" else join.right_prefix() + col

    _rewrite_request_columns(req, resolve)


def _reject_qualified_columns(req: BrokerRequest) -> None:
    """Single-table queries have no aliases to resolve against: a
    dotted reference is a typed client error, not a silent column name
    with a dot in it."""

    def check(name: str) -> str:
        if "." in name:
            raise PqlParseError(
                f"qualified column reference {name!r} is only valid in a "
                "join query"
            )
        return name

    _rewrite_request_columns(req, check)


def parse_pql(pql: str) -> BrokerRequest:
    """Parse a PQL query string into a BrokerRequest."""
    return _Parser(pql).parse()
