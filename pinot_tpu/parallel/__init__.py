from pinot_tpu.parallel.multichip import (
    default_mesh,
    make_sharded_table_kernel,
    run_sharded_query,
)

__all__ = ["default_mesh", "make_sharded_table_kernel", "run_sharded_query"]
