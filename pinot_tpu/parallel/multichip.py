"""Multi-chip query execution: shard the segment axis over a device mesh.

The reference scales a query two ways (SURVEY §2.5): segments fan out
across server threads (``MCombineOperator.java:55-64``) and across
servers via broker scatter-gather + reduce
(``BrokerReduceService.java:62``).  On TPU both collapse into ONE SPMD
program: the stacked segment axis is sharded over a 1-D
``jax.sharding.Mesh``; each chip vmaps the single-segment kernel over
its local segments; cross-chip merge is an XLA collective over ICI
(``psum`` for sums/histograms/group-by holders, ``pmin``/``pmax`` for
min/max/HLL registers/presence bitmaps).  Aggregation outputs come back
replicated; selection candidates stay sharded (gathered host-side).

Cross-host/DCN scale-out keeps the broker/server scatter-gather path
(see ``pinot_tpu.broker``) — the mesh covers the chips a single server
process owns (its "slice").
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.6: top-level shard_map
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map  # type: ignore

# replication-check kwarg renamed across jax versions (check_rep ->
# check_vma); detect ONCE instead of guessing, so a trace-time
# TypeError can't masquerade as a poisoned plan and silently heal
# every sharded query onto the host path (the bug that kept the
# serving path single-chip: each mesh launch "failed" at shard_map
# and failed over)
import inspect as _inspect

try:
    _SHARD_MAP_CHECK_KWARG = (
        "check_vma"
        if "check_vma" in _inspect.signature(shard_map).parameters
        else (
            "check_rep"
            if "check_rep" in _inspect.signature(shard_map).parameters
            else None
        )
    )
except (TypeError, ValueError):  # pragma: no cover - exotic wrappers
    _SHARD_MAP_CHECK_KWARG = None

from pinot_tpu.engine.kernel import (
    apply_reduce,
    make_single_segment_kernel,
    output_reducers,
)
from pinot_tpu.engine.plan import StaticPlan

SEGMENT_AXIS = "segments"


def default_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    devs = list(devices) if devices is not None else jax.devices()
    return Mesh(np.asarray(devs), (SEGMENT_AXIS,))


def _collective(op: str, value: Any, axis):
    # ``axis`` may be one name or a tuple of mesh axis names: on a 2-D
    # (hosts, chips) mesh the same psum reduces over ICI within a host
    # and DCN across hosts (multihost.py layering)
    if op.startswith("hll_sort:"):
        # each chip's packed-sort reduce already produced dense
        # registers; the cross-chip merge is an elementwise max
        return jax.lax.pmax(value, axis)
    if op == "sum":
        return jax.lax.psum(value, axis)
    if op == "min":
        return jax.lax.pmin(value, axis)
    if op == "max":
        return jax.lax.pmax(value, axis)
    if op == "sum_pair":
        return (jax.lax.psum(value[0], axis), jax.lax.psum(value[1], axis))
    if op == "minmax_pair":
        return (jax.lax.pmin(value[0], axis), jax.lax.pmax(value[1], axis))
    if op == "distinct_pairs":
        # sort-dedup distinct/histogram merge across chips: each chip's
        # compacted buffer converts run starts -> counts, all chips
        # gather everyone's buffers (CAP-bounded, rides ICI/DCN), and a
        # replicated re-merge sums counts of pairs seen on several chips
        from pinot_tpu.engine.kernel import (
            _PAIR_SENTINEL,
            counts_from_starts,
            merge_pair_buffers,
        )

        slots, gids, starts, n, total = value
        k_buf = slots.shape[0]
        counts = counts_from_starts(starts, n, total)
        iota = jax.lax.iota(jnp.int32, k_buf)
        valid = iota < n
        s_ = jnp.where(valid, slots, _PAIR_SENTINEL)
        g_ = jnp.where(valid, gids, _PAIR_SENTINEL)
        # a chip whose local uniques overflowed its buffer already lost
        # pairs; so can int32 cumsum positions past ~2^30 total
        # occurrences — both force the merged n_unique past the buffer
        # so the executor's overflow check drops to the exact host path
        over_local = (n > k_buf).astype(jnp.int32)
        names = axis if isinstance(axis, tuple) else (axis,)
        stacked = jnp.stack([s_, g_, counts])  # ONE gather per axis
        for ax in names:
            stacked = jnp.concatenate(jax.lax.all_gather(stacked, ax), axis=1)
        grand_total = jax.lax.psum(total.astype(jnp.float32), axis)
        overflow = jax.lax.psum(over_local, axis) + (
            grand_total >= 2.0**30
        ).astype(jnp.int32)
        s2, g2, e2, n_u, tv = merge_pair_buffers(
            stacked[0], stacked[1], stacked[2]
        )
        n_u = jnp.where(overflow > 0, jnp.int32(s2.shape[0] + 1), n_u)
        return (s2, g2, e2, n_u, tv)
    if op == "none":
        return value
    raise ValueError(op)


def _out_specs(reducers: Dict[str, str], shard_spec) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for k, op in reducers.items():
        spec = shard_spec if op == "none" else P()
        if op in ("sum_pair", "minmax_pair"):
            out[k] = (spec, spec)
        elif op == "distinct_pairs":
            out[k] = (spec,) * 5
        else:
            out[k] = spec
    return out


def _make_sharded(plan: StaticPlan, mesh: Mesh, single: Callable, n_extra: int) -> Callable:
    """Shared SPMD wiring for the full-scan and block-skipping kernels:
    vmap the single-segment kernel per chip, merge with collectives over
    every mesh axis.  ``n_extra`` extra positional operands (e.g. the
    block id array) shard over the segment axis like everything else."""
    reducers = output_reducers(plan)
    axes = tuple(mesh.axis_names)  # 1-D (segments) or 2-D (hosts, segments)

    def local_fn(segs: Dict[str, Any], q: Dict[str, Any], *extra) -> Dict[str, Any]:
        outs = jax.vmap(single)(segs, q, *extra)  # this chip's segments
        merged: Dict[str, Any] = {}
        for k, v in outs.items():
            op = reducers[k]
            if op == "none":
                merged[k] = v  # stays sharded over the segment axis
            else:
                merged[k] = _collective(op, apply_reduce(op, v), axes)
        return merged

    shard_spec = P(axes)  # segment axis sharded over every mesh axis

    def sharded(segs, q, *extra):
        in_specs = (
            jax.tree_util.tree_map(lambda _: shard_spec, segs),
            jax.tree_util.tree_map(lambda _: shard_spec, q),
        ) + (shard_spec,) * n_extra
        kwargs = {}
        if _SHARD_MAP_CHECK_KWARG is not None:
            kwargs[_SHARD_MAP_CHECK_KWARG] = False
        fn = shard_map(
            local_fn,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=_out_specs(reducers, shard_spec),
            **kwargs,
        )
        return fn(segs, q, *extra)

    return jax.jit(sharded)


def make_sharded_table_kernel(plan: StaticPlan, mesh: Mesh) -> Callable:
    """Compile the query kernel as an SPMD program over the mesh.

    Takes the same (seg_arrays, query_inputs) pytrees as the
    single-chip table kernel; every leaf's leading axis must equal the
    (padded) segment count and divide evenly by the mesh size.  Works
    over a 1-D ``segments`` mesh (one server's slice, ICI collectives)
    or a 2-D ``(hosts, segments)`` mesh (``multihost.py``): the segment
    axis shards over all mesh axes and the merge collectives name all
    of them, so XLA lowers the reduction hierarchically — ICI inside a
    host, DCN across hosts.
    """
    return _make_sharded(plan, mesh, make_single_segment_kernel(plan), 0)


def make_sharded_block_table_kernel(plan: StaticPlan, mesh: Mesh, block: int) -> Callable:
    """Zone-map block-skipping variant of the sharded kernel: the block
    id array [S, nb_pad] shards over the segment axis with everything
    else, so selective queries stay O(candidate blocks) per chip."""
    from pinot_tpu.engine.kernel import make_single_segment_block_kernel

    return _make_sharded(plan, mesh, make_single_segment_block_kernel(plan, block), 1)


def run_sharded_query(plan: StaticPlan, mesh: Mesh, seg_arrays, q_inputs):
    return make_sharded_table_kernel(plan, mesh)(seg_arrays, q_inputs)
