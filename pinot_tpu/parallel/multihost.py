"""Multi-host (DCN + ICI) execution topology.

The reference scales across machines with Netty/TCP scatter-gather +
Helix (SURVEY §5 "Distributed communication backend").  The TPU-native
layering here is:

  1. Within one server process's chip slice: 1-D ``segments`` mesh,
     collectives over **ICI** (``multichip.py``).
  2. Across hosts of ONE pod slice: jax's distributed runtime — a 2-D
     ``(hosts, chips)`` mesh where the segment axis spans both; XLA
     routes the reductions over ICI within a host and **DCN** across
     hosts.  ``initialize_distributed`` + ``make_multihost_mesh`` set
     this up; the same shard_map kernel runs unchanged because it only
     names the flattened ``segments`` axis.
  3. Across pods / regions: stays the broker scatter-gather path (TCP,
     ``pinot_tpu.broker``) — partial aggregates are small and
     latency-tolerant, which is exactly what the reference's
     DataTable-over-TCP layer is for.

(1) runs on the real chip; (2) is exercised END TO END by
``tests/test_multihost_process.py``: two OS processes bring up
``jax.distributed.initialize`` (CPU backend, gloo cross-process
collectives), build this module's 2-D mesh, and run the production
sharded kernel through a collective that crosses the process boundary.
On a real multi-host slice the identical wiring activates with the TPU
backend.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from pinot_tpu.parallel.multichip import SEGMENT_AXIS

HOST_AXIS = "hosts"


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Bring up jax's distributed runtime (multi-host).  No-op when
    single-process (the common case in this environment)."""
    if num_processes is None or num_processes <= 1:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def make_multihost_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """2-D (hosts, chips-per-host) mesh; reductions cross DCN on the
    host axis and ICI on the chip axis."""
    devs = list(devices) if devices is not None else jax.devices()
    by_process: dict = {}
    for d in devs:
        by_process.setdefault(d.process_index, []).append(d)
    num_hosts = len(by_process)
    per_host = min(len(v) for v in by_process.values())
    grid = np.array(
        [sorted(v, key=lambda d: d.id)[:per_host] for _, v in sorted(by_process.items())]
    )
    return Mesh(grid, (HOST_AXIS, SEGMENT_AXIS))


def simulated_multihost_mesh(num_hosts: int, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """(hosts, chips) mesh carved out of one process's devices — the
    single-process stand-in for ``make_multihost_mesh`` so the 2-D
    sharding + hierarchical collective path is executable on the
    virtual CPU mesh (tests) without a real multi-host slice."""
    devs = list(devices) if devices is not None else jax.devices()
    per_host = len(devs) // num_hosts
    if per_host * num_hosts != len(devs):
        raise ValueError(f"{len(devs)} devices do not split into {num_hosts} hosts")
    grid = np.array(devs[: num_hosts * per_host]).reshape(num_hosts, per_host)
    return Mesh(grid, (HOST_AXIS, SEGMENT_AXIS))


def flatten_to_segment_mesh(mesh: Mesh) -> Mesh:
    """Collapse a (hosts, chips) mesh into the 1-D segments mesh the
    query kernels shard over (XLA still routes per-link appropriately)."""
    return Mesh(mesh.devices.reshape(-1), (SEGMENT_AXIS,))
