"""Schema-evolution default columns.

When a table's schema grows a column, already-sealed segments don't
have it.  The reference patches each old segment at load time by
writing a constant forward index + single-entry dictionary for the new
column (pinot-core ``segment/index/loader/defaultcolumn/
BaseDefaultColumnHandler.java:18``, ``V3DefaultColumnHandler.java:31``,
driven by ``loader/SegmentPreProcessor.java``), so old rows answer with
the field's default null value instead of vanishing from results.

The TPU design needs no on-disk rewrite: a default column is a
cardinality-1 dictionary plus a constant dictId stream, which the
staging layer turns into a trivially compressible device array.  We
synthesize the ``ColumnData`` in memory at segment-add time
(``ServerInstance.set_table_schema`` / ``add_segment``) — the query
engine then sees it as an ordinary sorted column: global-dictionary
build, zone maps, group-by, everything works unchanged.
"""
from __future__ import annotations

import logging
from typing import Optional

import numpy as np

from pinot_tpu.common.schema import FieldSpec, Schema
from pinot_tpu.segment.dictionary import Dictionary
from pinot_tpu.segment.immutable import ColumnData, ColumnMetadata, ImmutableSegment

logger = logging.getLogger(__name__)


def make_default_column(spec: FieldSpec, num_docs: int) -> ColumnData:
    """A constant column: every doc holds ``spec.get_default_null_value()``.

    Single-entry dictionary, so the forward index is all-zeros — the
    engine treats it as a sorted cardinality-1 column (best case for
    zone maps and match tables).  MV columns get one default entry per
    doc, mirroring DefaultColumnStatistics in the reference.
    """
    default = spec.get_default_null_value()
    dictionary = Dictionary(spec.stored_type, [default])
    meta = ColumnMetadata(
        name=spec.name,
        data_type=spec.data_type,
        field_type=spec.field_type,
        single_value=spec.single_value,
        cardinality=1,
        total_docs=num_docs,
        is_sorted=True,
        max_num_multi_values=0 if spec.single_value else 1,
        total_number_of_entries=num_docs,
        min_value=default,
        max_value=default,
    )
    if spec.single_value:
        return ColumnData(
            metadata=meta,
            dictionary=dictionary,
            fwd=np.zeros(num_docs, dtype=np.int32),
        )
    return ColumnData(
        metadata=meta,
        dictionary=dictionary,
        mv_values=np.zeros(num_docs, dtype=np.int32),
        mv_offsets=np.arange(num_docs + 1, dtype=np.int32),
    )


def inject_default_columns(
    segment: ImmutableSegment, schema: Optional[Schema]
) -> int:
    """Add synthesized columns for schema fields the segment lacks.

    Returns the number of columns injected.  The time column is never
    synthesized (a segment without its time column has no time range —
    pruning it is correct, defaulting it would corrupt time filters).
    """
    if schema is None:
        return 0
    injected = 0
    # patch via copy + atomic swap: live queries may be iterating the
    # column dict on another thread (dict insert during iteration raises)
    columns = dict(segment.columns)
    meta_columns = dict(segment.metadata.columns)
    for spec in schema.all_fields():
        if spec.name in columns:
            continue
        if spec.name == schema.time_column_name:
            continue
        col = make_default_column(spec, segment.num_docs)
        columns[spec.name] = col
        # metadata stays consistent with the live column set — the
        # reference's handler updates metadata.properties the same way;
        # converters/persistence iterate metadata.columns
        meta_columns[spec.name] = col.metadata
        injected += 1
    if injected:
        segment.columns = columns
        segment.metadata.columns = meta_columns
    if injected:
        logger.info(
            "injected %d default column(s) into %s", injected, segment.segment_name
        )
    return injected
