"""On-disk segment format: single file + index map (v3-style).

The reference's v3 format stores all indexes in one blob with an index
map (``core/segment/store/SingleFileIndexDirectory.java``); v2 bit-packs
forward indexes (``SegmentVersion.java:23-30``).  This format does both:

    [0:8]    magic  b"PNTPUSEG"
    [8:16]   uint64 little-endian header JSON length H
    [16:16+H] header JSON: segment metadata + index map
              (per-buffer: offset, length, codec, dtype, shape)
    [16+H:]  concatenated buffers

Buffer codecs:
  raw      — dtype bytes as-is
  bitpack  — fixed-bit packed dictIds (see ``bitpack.py``)
  strings  — utf-8, '\\x00'-separated sorted dictionary entries

Everything is mmap-friendly: buffers are loaded with np.frombuffer over
a single read (the PinotDataBuffer analog is the OS page cache + numpy
views; device staging copies straight into HBM).
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Tuple

import numpy as np

from pinot_tpu.common.schema import DataType
from pinot_tpu.segment.bitpack import bits_required, pack_bits, unpack_bits
from pinot_tpu.segment.dictionary import Dictionary
from pinot_tpu.segment.immutable import ColumnData, ImmutableSegment, SegmentMetadata

MAGIC = b"PNTPUSEG"

SEGMENT_FILE_NAME = "columns.pnt"  # analog of v3's columns.psf


class SegmentIntegrityError(RuntimeError):
    """A segment's bytes do not match their metadata CRC claim — a
    corrupt download or bit-rotted disk copy.  The load paths quarantine
    the copy and re-fetch from the controller's durable store instead of
    serving wrong data (SegmentFetcherAndLoader.java:84 semantics)."""


class SegmentStaleError(SegmentIntegrityError):
    """An internally-CONSISTENT copy whose CRC is simply a different
    version than the ideal state asked for (replication lag during a
    segment refresh).  Not corruption: no quarantine, no crcFailures —
    the load is retried on the next transition once the source catches
    up."""


def verify_segment_crc(segment: ImmutableSegment, source: str = "") -> None:
    """Recompute the column-data CRC and compare against the metadata
    claim.

    Only producers that actually computed a data CRC mark the claim
    verifiable (``custom["dataCrc"]``: segment/builder.py and the
    realtime commit conversion).  Synthetic bench segments and consuming
    snapshots reuse the crc field as a cheap cache-identity token —
    those (and crc == 0) pass trivially: there is no byte-level claim to
    hold them to."""
    claimed = segment.metadata.crc
    if not claimed or not segment.metadata.custom.get("dataCrc"):
        return
    actual = segment.compute_crc()
    if actual != claimed:
        where = f" ({source})" if source else ""
        raise SegmentIntegrityError(
            f"segment {segment.segment_name!r}{where}: computed CRC {actual} != "
            f"metadata CRC {claimed} — corrupt copy"
        )


def write_segment(segment: ImmutableSegment, directory: str) -> str:
    """Write a segment directory: one data file (index map inside)."""
    os.makedirs(directory, exist_ok=True)
    buffers: List[bytes] = []
    index_map: Dict[str, Dict[str, Any]] = {}
    offset = 0

    def add(key: str, data: bytes, codec: str, **extra: Any) -> None:
        nonlocal offset
        index_map[key] = {"offset": offset, "length": len(data), "codec": codec, **extra}
        buffers.append(data)
        offset += len(data)

    for name, col in segment.columns.items():
        d = col.dictionary
        if d.is_string:
            blob = "\x00".join(d.values).encode("utf-8")
            add(f"{name}.dict", blob, "strings", count=len(d))
        else:
            arr = np.ascontiguousarray(d.values)
            add(f"{name}.dict", arr.tobytes(), "raw", dtype=str(arr.dtype), count=len(d))

        nbits = bits_required(max(d.cardinality, 1))
        if col.fwd is not None:
            add(
                f"{name}.fwd",
                pack_bits(col.fwd, nbits).tobytes(),
                "bitpack",
                nbits=nbits,
                count=int(col.fwd.size),
            )
        if col.mv_values is not None:
            add(
                f"{name}.mv",
                pack_bits(col.mv_values, nbits).tobytes(),
                "bitpack",
                nbits=nbits,
                count=int(col.mv_values.size),
            )
            off = np.ascontiguousarray(col.mv_offsets, dtype=np.int32)
            add(f"{name}.mvoff", off.tobytes(), "raw", dtype="int32", count=int(off.size))

    # zone maps: per-block dictId min/max per SV column, persisted at
    # build/write time so selective-query pruning (engine/zonemap.py)
    # never pays an O(n) first-query scan (the inverted-index artifact
    # of the reference's segment files, re-derived)
    from pinot_tpu.engine.zonemap import column_zones, zone_block_rows

    zblock = zone_block_rows()
    for name, col in segment.columns.items():
        if col.fwd is None or col.fwd.size <= zblock:
            continue
        z = column_zones(segment, name, zblock)  # single source of truth
        if z is None:
            continue
        zmin, zmax = (a.astype(np.int32) for a in z)
        add(f"{name}.zmin", zmin.tobytes(), "raw", dtype="int32", count=int(zmin.size))
        add(f"{name}.zmax", zmax.tobytes(), "raw", dtype="int32", count=int(zmax.size))

    star_tree = getattr(segment, "star_tree", None)
    star_header = None
    if star_tree is not None:
        add("__startree__.dims", np.ascontiguousarray(star_tree.dims).tobytes(), "raw",
            dtype=str(star_tree.dims.dtype), count=int(star_tree.dims.size))
        add("__startree__.sums", np.ascontiguousarray(star_tree.sums).tobytes(), "raw",
            dtype=str(star_tree.sums.dtype), count=int(star_tree.sums.size))
        add("__startree__.counts", np.ascontiguousarray(star_tree.counts).tobytes(), "raw",
            dtype=str(star_tree.counts.dtype), count=int(star_tree.counts.size))
        for hcol, regs in star_tree.hll_registers.items():
            add(f"__startree__.hll.{hcol}", np.ascontiguousarray(regs).tobytes(), "raw",
                dtype=str(regs.dtype), count=int(regs.size))
        star_header = {
            "splitOrder": star_tree.split_order,
            "metricColumns": star_tree.metric_columns,
            "maxLeafRecords": star_tree.max_leaf_records,
            "numRecords": star_tree.num_records,
            "hllColumns": list(star_tree.hll_columns),
            "root": star_tree.root.to_json(),
        }

    header = {
        "metadata": segment.metadata.to_json(),
        "indexMap": index_map,
        "zoneBlock": zblock,
    }
    if star_header is not None:
        header["starTree"] = star_header
    hdr = json.dumps(header).encode("utf-8")
    path = os.path.join(directory, SEGMENT_FILE_NAME)
    with open(path, "wb") as f:
        f.write(MAGIC)
        f.write(len(hdr).to_bytes(8, "little"))
        f.write(hdr)
        for b in buffers:
            f.write(b)
    return path


def _decode(entry: Dict[str, Any], blob: bytes) -> Any:
    codec = entry["codec"]
    if codec == "raw":
        return np.frombuffer(blob, dtype=np.dtype(entry["dtype"]), count=entry["count"]).copy()
    if codec == "bitpack":
        packed = np.frombuffer(blob, dtype=np.uint8)
        return unpack_bits(packed, entry["nbits"], entry["count"])
    if codec == "strings":
        if entry["count"] == 0:
            return []
        return blob.decode("utf-8").split("\x00")
    raise ValueError(f"unknown codec {codec}")


def read_segment(directory: str) -> ImmutableSegment:
    path = os.path.join(directory, SEGMENT_FILE_NAME) if os.path.isdir(directory) else directory
    with open(path, "rb") as f:
        data = f.read()
    if data[:8] != MAGIC:
        raise ValueError(f"{path}: not a pinot_tpu segment file")
    hlen = int.from_bytes(data[8:16], "little")
    header = json.loads(data[16 : 16 + hlen].decode("utf-8"))
    base = 16 + hlen
    index_map = header["indexMap"]
    metadata = SegmentMetadata.from_json(header["metadata"])

    def load(key: str) -> Any:
        e = index_map[key]
        blob = data[base + e["offset"] : base + e["offset"] + e["length"]]
        return _decode(e, blob)

    columns: Dict[str, ColumnData] = {}
    for name, cmeta in metadata.columns.items():
        dict_values = load(f"{name}.dict")
        dictionary = Dictionary(cmeta.data_type.stored_type, dict_values)
        col = ColumnData(metadata=cmeta, dictionary=dictionary)
        if f"{name}.fwd" in index_map:
            col.fwd = load(f"{name}.fwd")
        if f"{name}.mv" in index_map:
            col.mv_values = load(f"{name}.mv")
            col.mv_offsets = load(f"{name}.mvoff")
        columns[name] = col
    segment = ImmutableSegment(metadata=metadata, columns=columns)

    # preload persisted zone maps into the segment's zone cache
    zblock = header.get("zoneBlock")
    if zblock:
        cache = {}
        for name in metadata.columns:
            if f"{name}.zmin" in index_map:
                cache[(name, int(zblock))] = (
                    load(f"{name}.zmin").astype(np.int64),
                    load(f"{name}.zmax").astype(np.int64),
                )
        if cache:
            object.__setattr__(segment, "_zone_cache", cache)

    st = header.get("starTree")
    if st is not None:
        from pinot_tpu.startree.index import StarTreeIndex, StarTreeNode

        n_rec = st["numRecords"]
        k = len(st["splitOrder"])
        m = len(st["metricColumns"])
        hll_cols = list(st.get("hllColumns", []))
        segment.star_tree = StarTreeIndex(
            split_order=list(st["splitOrder"]),
            metric_columns=list(st["metricColumns"]),
            dims=load("__startree__.dims").reshape(n_rec, k),
            sums=load("__startree__.sums").reshape(n_rec, m),
            counts=load("__startree__.counts"),
            root=StarTreeNode.from_json(st["root"]),
            max_leaf_records=st["maxLeafRecords"],
            hll_columns=hll_cols,
            hll_registers={
                c: load(f"__startree__.hll.{c}").reshape(n_rec, -1) for c in hll_cols
            },
        )
    return segment
