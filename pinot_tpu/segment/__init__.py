from pinot_tpu.segment.immutable import ColumnData, ColumnMetadata, ImmutableSegment, SegmentMetadata
from pinot_tpu.segment.builder import SegmentBuilder, SegmentGeneratorConfig
from pinot_tpu.segment.format import write_segment, read_segment

__all__ = [
    "ColumnData",
    "ColumnMetadata",
    "ImmutableSegment",
    "SegmentMetadata",
    "SegmentBuilder",
    "SegmentGeneratorConfig",
    "write_segment",
    "read_segment",
]
