"""Vectorized fixed-bit packing codecs.

The TPU-native analog of the reference's fixed-bit forward-index
readers/writers (pinot-core ``io/reader/impl/v1/FixedBitSingleValueReader.java``,
``io/writer/impl/``): dictIds are stored with ``ceil(log2(cardinality))``
bits each.  Unlike the Java word-by-word readers, packing/unpacking here
is whole-array vectorized numpy (bit-slicing), used at segment
write/load time; on device the forward index lives unpacked as int32
(HBM trades space for gather speed; the packed form is the *disk* format).
"""
from __future__ import annotations

import numpy as np


def bits_required(cardinality: int) -> int:
    """Minimum bits to store dictIds in [0, cardinality)."""
    if cardinality <= 1:
        return 1
    return int(cardinality - 1).bit_length()


def pack_bits(values: np.ndarray, nbits: int) -> np.ndarray:
    """Pack int array into a uint8 byte stream, little-endian bit order.

    Uses the native C++ codec (``segment/native.py``) when available;
    the numpy bit-slicing below is the always-available fallback."""
    n = np.asarray(values).size
    if n == 0:
        return np.zeros(0, dtype=np.uint8)
    if n >= 4096:
        from pinot_tpu.segment import native

        out = native.pack_bits(np.asarray(values), nbits)
        if out is not None:
            return out
    values = np.asarray(values, dtype=np.uint64)
    # Expand each value into its bits [n, nbits], then pack.
    shifts = np.arange(nbits, dtype=np.uint64)
    bits = ((values[:, None] >> shifts[None, :]) & 1).astype(np.uint8)
    flat = bits.reshape(-1)
    pad = (-flat.size) % 8
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, dtype=np.uint8)])
    return np.packbits(flat.reshape(-1, 8)[:, ::-1], axis=1).reshape(-1)


def unpack_bits(packed: np.ndarray, nbits: int, count: int) -> np.ndarray:
    """Inverse of :func:`pack_bits`; returns int32 array of length count."""
    if count == 0:
        return np.zeros(0, dtype=np.int32)
    if count >= 4096:
        from pinot_tpu.segment import native

        out = native.unpack_bits(np.asarray(packed), nbits, count)
        if out is not None:
            return out
    packed = np.asarray(packed, dtype=np.uint8)
    # undo per-byte bit order, then take the first count*nbits bits
    bits = np.unpackbits(packed).reshape(-1, 8)[:, ::-1].reshape(-1)[: count * nbits]
    bits = bits.reshape(count, nbits).astype(np.uint64)
    shifts = np.arange(nbits, dtype=np.uint64)
    vals = (bits << shifts[None, :]).sum(axis=1)
    return vals.astype(np.int32)
