"""Columnar segment build path: typed arrays in, segment out — no
per-row Python objects.

The row-wise ``SegmentBuilder`` mirrors the reference's two passes over
records (``SegmentIndexCreationDriverImpl.java:71``). This module is the
vectorized equivalent: ``np.unique(return_inverse=True)`` produces the
sorted dictionary and the dictId forward index in one pass, so stats
collection, dictionary build, and fwd-index write collapse into array
ops. Output segments are bit-identical to the row path (same
dictionaries, fwd indexes, metadata, CRC), which the differential tests
assert.

``build_segment_from_csv`` feeds this from the native one-pass CSV
parser (``native/csvread.cpp``) when available, falling back to the
Python csv module otherwise (reference reader layer:
``data/readers/CSVRecordReader.java``).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from pinot_tpu.common.schema import DataType, FieldSpec, Schema
from pinot_tpu.segment import native
from pinot_tpu.segment.builder import (
    SegmentGeneratorConfig,
    build_segment,
    finalize_segment,
)
from pinot_tpu.segment.dictionary import Dictionary
from pinot_tpu.segment.immutable import ColumnData, ColumnMetadata, ImmutableSegment

# SV columns: a typed numpy array (object dtype for strings), length
# num_docs. MV columns: (flat_values, offsets) CSR — offsets[i]:offsets[i+1]
# spans doc i's values.
ColumnInput = Union[np.ndarray, Tuple[np.ndarray, np.ndarray]]


def build_segment_from_columns(
    schema: Schema,
    columns_in: Dict[str, ColumnInput],
    num_docs: int,
    table_name: str,
    segment_name: Optional[str] = None,
    **kwargs: Any,
) -> ImmutableSegment:
    config = SegmentGeneratorConfig(
        table_name=table_name, segment_name=segment_name, **kwargs
    )
    columns: Dict[str, ColumnData] = {}
    for spec in schema.all_fields():
        columns[spec.name] = _build_column(spec, columns_in[spec.name], num_docs)
    return finalize_segment(schema, config, num_docs, columns)


def _build_column(spec: FieldSpec, data: ColumnInput, num_docs: int) -> ColumnData:
    st = spec.stored_type
    if spec.single_value:
        arr = data
        uniq, inv = np.unique(arr, return_inverse=True)
        d = Dictionary(st, uniq.tolist() if st == DataType.STRING else uniq)
        fwd = inv.astype(np.int32)
        is_sorted = bool(num_docs < 2 or np.all(arr[1:] >= arr[:-1]))
        meta = _column_metadata(spec, d, num_docs, is_sorted, 0, num_docs)
        return ColumnData(metadata=meta, dictionary=d, fwd=fwd)

    flat, offsets = data
    uniq, inv = np.unique(flat, return_inverse=True)
    d = Dictionary(st, uniq.tolist() if st == DataType.STRING else uniq)
    mv_values = inv.astype(np.int32)
    lengths = np.diff(offsets)
    max_mv = int(lengths.max()) if len(lengths) else 0
    meta = _column_metadata(spec, d, num_docs, False, max_mv, int(len(flat)))
    return ColumnData(
        metadata=meta,
        dictionary=d,
        mv_values=mv_values,
        mv_offsets=np.asarray(offsets, dtype=np.int32),
    )


def _column_metadata(
    spec: FieldSpec,
    d: Dictionary,
    num_docs: int,
    is_sorted: bool,
    max_mv: int,
    total_entries: int,
) -> ColumnMetadata:
    return ColumnMetadata(
        name=spec.name,
        data_type=spec.data_type,
        field_type=spec.field_type,
        single_value=spec.single_value,
        cardinality=d.cardinality,
        total_docs=num_docs,
        is_sorted=is_sorted,
        max_num_multi_values=max_mv,
        total_number_of_entries=total_entries,
        min_value=d.min_value,
        max_value=d.max_value,
    )


# ---------------------------------------------------------------------------
# CSV -> columnar arrays (native fast path + Python fallback)
# ---------------------------------------------------------------------------

from pinot_tpu.segment.readers import MV_DELIMITER, read_csv


def build_segment_from_csv(
    schema: Schema,
    path: str,
    table_name: str,
    segment_name: Optional[str] = None,
    delimiter: str = ",",
    **kwargs: Any,
) -> ImmutableSegment:
    """CSV file -> segment via the columnar path when possible."""
    cols, num_docs = read_csv_columnar(path, schema, delimiter)
    if cols is not None:
        return build_segment_from_columns(
            schema, cols, num_docs, table_name, segment_name, **kwargs
        )
    rows = read_csv(path, schema, delimiter)
    return build_segment(schema, rows, table_name, segment_name, **kwargs)


def read_csv_columnar(
    path: str, schema: Schema, delimiter: str = ","
) -> Tuple[Optional[Dict[str, ColumnInput]], int]:
    """Parse a CSV into per-column arrays using the native parser.

    Returns ``(None, 0)`` when the fast path does not apply (no native
    lib, quoted cells, unparseable numerics) — caller falls back to the
    row-wise reader, which handles full csv-module semantics.
    """
    import mmap

    if not native.csv_available():
        return None, 0  # don't read the file just to discover there's no lib
    with open(path, "rb") as f:
        try:
            data = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        except ValueError:  # empty file
            return None, 0
    # mmap instead of read(): the scans below and the native parse run
    # against page-cache-backed memory, so peak RSS stays O(columns)
    # instead of 2x the file (ADVICE r1)
    if data.find(b'"') >= 0:
        return None, 0  # quoted CSV: python csv module semantics needed
    i = data.find(b"\r")
    while i != -1:
        # a lone \r is a row separator for python's csv module but cell
        # data for the native parser — keep both paths identical
        if i + 1 >= len(data) or data[i + 1] != 0x0A:
            return None, 0
        i = data.find(b"\r", i + 2)
    nl = data.find(b"\n")
    if nl < 0:
        return None, 0
    header_line = data[:nl].rstrip(b"\r").decode("utf-8")
    # exact header names, like csv.DictReader in the fallback path (a
    # space-padded header mismatches the schema on both paths alike)
    header = header_line.split(delimiter)

    # per-header-column parse type; columns absent from the schema are
    # tokenized but record nothing (type 3)
    types: List[int] = []
    i64_def: List[int] = []
    f64_def: List[float] = []
    specs: List[Optional[FieldSpec]] = []
    for name in header:
        spec = schema.field(name) if schema.has_column(name) else None
        specs.append(spec)
        if spec is None:
            types.append(3)
            i64_def.append(0)
            f64_def.append(0.0)
        elif spec.single_value and spec.stored_type in (
            DataType.INT,
            DataType.LONG,
        ):
            types.append(0)
            i64_def.append(int(spec.get_default_null_value()))
            f64_def.append(0.0)
        elif spec.single_value and spec.stored_type in (
            DataType.FLOAT,
            DataType.DOUBLE,
        ):
            types.append(1)
            i64_def.append(0)
            f64_def.append(float(spec.get_default_null_value()))
        else:
            types.append(2)
            i64_def.append(0)
            f64_def.append(0.0)

    parsed = native.csv_parse(data, nl + 1, delimiter, types, i64_def, f64_def)
    if parsed is None:
        return None, 0
    num_docs, i64_cols, f64_cols, str_offs = parsed

    out: Dict[str, ColumnInput] = {}
    for c, spec in enumerate(specs):
        if spec is None:
            continue
        if types[c] == 0:
            arr = i64_cols[c]
            dtype = spec.stored_type.to_numpy()
            if dtype == np.int32 and arr.size:
                info = np.iinfo(np.int32)
                if arr.min() < info.min or arr.max() > info.max:
                    # same loud failure as the row-wise np.asarray(int32)
                    raise OverflowError(
                        f"value out of INT range in column {spec.name!r}"
                    )
            out[spec.name] = arr.astype(dtype, copy=False)
        elif types[c] == 1:
            arr = f64_cols[c]
            # the row-wise builder maps NaN cells to the default null
            nan = np.isnan(arr)
            if nan.any():
                arr = np.where(nan, float(spec.get_default_null_value()), arr)
            if spec.stored_type == DataType.FLOAT:
                # round-trip through float32 like DataType.convert
                arr = arr.astype(np.float32)
            out[spec.name] = arr.astype(spec.stored_type.to_numpy(), copy=False)
        else:
            out[spec.name] = _materialize_cells(data, str_offs[c], num_docs, spec)

    # schema columns missing from the header get default null values
    for spec in schema.all_fields():
        if spec.name in out:
            continue
        default = spec.get_default_null_value()
        if spec.single_value:
            out[spec.name] = np.full(
                num_docs,
                default,
                dtype=spec.stored_type.to_numpy(),
            )
        else:
            flat = np.full(num_docs, default, dtype=spec.stored_type.to_numpy())
            out[spec.name] = (flat, np.arange(num_docs + 1, dtype=np.int64))
    return out, num_docs


def _materialize_cells(
    body: bytes, offs: np.ndarray, num_docs: int, spec: FieldSpec
) -> ColumnInput:
    """Decode raw (offset,length) cell slices for string / MV columns,
    applying the same empty-cell and MV-split semantics as the row-wise
    reader (MV delimiter ';', CSVRecordReaderConfig default)."""
    starts = offs[0::2]
    lens = offs[1::2]
    default = spec.get_default_null_value()
    if spec.single_value:
        vals = np.empty(num_docs, dtype=object)
        for i in range(num_docs):
            if lens[i] == 0:
                vals[i] = default
            else:
                s = int(starts[i])
                vals[i] = body[s : s + int(lens[i])].decode("utf-8")
        return vals

    st = spec.stored_type
    flat: List[Any] = []
    offsets = np.zeros(num_docs + 1, dtype=np.int64)
    for i in range(num_docs):
        if lens[i] == 0:
            parts: List[Any] = [default]
        else:
            s = int(starts[i])
            cell = body[s : s + int(lens[i])].decode("utf-8")
            parts = [st.convert(p) for p in cell.split(MV_DELIMITER) if p != ""] or [
                default
            ]
        flat.extend(parts)
        offsets[i + 1] = len(flat)
    if st == DataType.STRING:
        return np.asarray(flat, dtype=object), offsets
    return np.asarray(flat, dtype=st.to_numpy()), offsets
