"""Record readers: CSV / JSON-lines / Avro -> rows for the segment
builder.

Reference: pinot-core ``data/readers/`` (Avro/CSV/JSON record readers).
Avro containers decode via the pure-Python codec in
``pinot_tpu.segment.avro`` (re-exported here as ``read_avro``).

Multi-value CSV cells use ';' as the value separator (the reference's
CSVRecordReaderConfig default multi-value delimiter).
"""
from __future__ import annotations

import csv
import json
from typing import Any, Dict, Iterator, List, Optional

from pinot_tpu.common.schema import Schema

Row = Dict[str, Any]

MV_DELIMITER = ";"


def _convert_cell(schema: Schema, name: str, raw: str) -> Any:
    spec = schema.field(name)
    if raw == "" or raw is None:
        return spec.get_default_null_value()
    if spec.single_value:
        return spec.stored_type.convert(raw)
    parts = [p for p in str(raw).split(MV_DELIMITER)]
    return [spec.stored_type.convert(p) for p in parts if p != ""] or [
        spec.get_default_null_value()
    ]


def read_csv(path: str, schema: Schema, delimiter: str = ",") -> List[Row]:
    rows: List[Row] = []
    with open(path, newline="") as f:
        reader = csv.DictReader(f, delimiter=delimiter)
        for rec in reader:
            row: Row = {}
            for spec in schema.all_fields():
                raw = rec.get(spec.name)
                row[spec.name] = (
                    _convert_cell(schema, spec.name, raw)
                    if raw is not None
                    else spec.get_default_null_value()
                )
            rows.append(row)
    return rows


def read_avro(path: str, schema: Schema) -> List[Row]:
    """Avro object container -> rows (AvroRecordReader analog)."""
    from pinot_tpu.segment.avro import read_avro as _read_avro

    return _read_avro(path, schema)


def read_for_path(path: str, schema: Schema) -> List[Row]:
    """Pick the reader by file extension (csv / jsonl / avro[.gz])."""
    lower = path.lower()
    if lower.endswith(".csv"):
        return read_csv(path, schema)
    if lower.endswith((".avro", ".avro.gz")):
        return read_avro(path, schema)
    return read_jsonl(path, schema)


def read_jsonl(path: str, schema: Schema) -> List[Row]:
    rows: List[Row] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            row: Row = {}
            for spec in schema.all_fields():
                v = rec.get(spec.name)
                if v is None:
                    row[spec.name] = (
                        spec.get_default_null_value()
                        if spec.single_value
                        else [spec.get_default_null_value()]
                    )
                elif spec.single_value:
                    row[spec.name] = spec.stored_type.convert(v)
                else:
                    vs = v if isinstance(v, list) else [v]
                    row[spec.name] = [spec.stored_type.convert(x) for x in vs] or [
                        spec.get_default_null_value()
                    ]
            rows.append(row)
    return rows
