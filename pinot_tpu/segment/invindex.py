"""Host-resident inverted index: compressed CSR postings per dictId.

Reference capability: ``BitmapInvertedIndexReader.java:28`` — dictId ->
RoaringBitmap of docIds, read host-side by
``core/operator/filter/BitmapBasedFilterOperator.java:34`` to answer
selective predicates in O(matches) regardless of doc order.

TPU-first placement: the postings stay HOST-resident, not in HBM.
On-chip measurement (MICROBENCH_TPU.json) puts XLA per-element gathers
at ~12.5 ns — fine for thousands of matched rows, poison at per-row
scan scale.  The executor therefore uses postings to resolve matched
row ids on host and aggregates exactly those rows with numpy
fancy-indexing (O(matches)), skipping the device dispatch (and its
round trip) entirely; unselective predicates stay on the device scan
path, which at ~2.8B rows/s outruns any index walk.  This re-cuts the
reference's BitmapBasedFilterOperator (selective) vs
ScanBasedFilterOperator (unselective) split at the TPU's
bandwidth-vs-latency boundary.

Representation: row ids stably argsorted by dictId — the postings for
one dictId are one contiguous slice, and a dictId *range* (the sorted
dictionary makes value ranges dictId ranges) is also one contiguous
slice, so EQ/RANGE resolve to slices and IN to a few of them.

Compression (VERDICT r3 #6): the raw int32 posting stream costs
4 B/row/indexed column (~4 GB per column at 1B rows).  The stream is
chunked into 4096-posting blocks, each stored as whichever of two
container kinds is smaller — the roaring-container idea
(``RoaringBitmap``'s array/run containers) re-cut for this layout:

- **run container**: maximal consecutive-int runs as (start, len)
  pairs.  A clustered column (row order correlates with value order —
  e.g. a date column in time-ordered segments) collapses to a handful
  of runs per block: >100x smaller.
- **packed container**: absolute row ids bitpacked at
  ``ceil(log2(num_docs))`` bits (``segment/bitpack.py``, native codec
  when available).  The worst-case bound for shuffled high-cardinality
  columns: 23 bits instead of 32 at 8M docs/segment.  (Information
  theory caps the shuffled case near log2(num_docs) bits/posting — the
  4x+ wins come from run containers on clustered columns, which is
  exactly where the reference's RoaringBitmaps win too.)

Queries decode only the blocks their slices touch — O(matches) holds.

A process-wide byte budget (``PINOT_TPU_INVINDEX_BUDGET_BYTES``, default
2 GiB) bounds total postings memory: once exceeded, further index
builds are refused and those predicates fall back to the zone-map /
device-scan paths (the reference's behavior when no inverted index is
configured).
"""
from __future__ import annotations

import logging
import os
import threading
from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

from pinot_tpu.segment.bitpack import bits_required, pack_bits, unpack_bits
from pinot_tpu.segment.immutable import ImmutableSegment

logger = logging.getLogger(__name__)

BLOCK = 4096  # postings per compression block

_RUN, _PACKED, _RAW = 0, 1, 2


@dataclass
class _Block:
    kind: int
    # _RUN: starts/lens int32 pairs; _PACKED: uint8 bitstream; _RAW: int32
    a: np.ndarray
    b: Optional[np.ndarray] = None

    @property
    def nbytes(self) -> int:
        return self.a.nbytes + (self.b.nbytes if self.b is not None else 0)


def _encode_block(vals: np.ndarray, width: int) -> _Block:
    """Pick the smaller container for one block of postings."""
    n = vals.size
    breaks = np.nonzero(np.diff(vals) != 1)[0]
    n_runs = breaks.size + 1
    run_bytes = n_runs * 8
    packed_bytes = (n * width + 7) // 8
    if run_bytes <= packed_bytes:
        starts_idx = np.concatenate(([0], breaks + 1))
        ends_idx = np.concatenate((breaks + 1, [n]))
        return _Block(
            _RUN,
            vals[starts_idx].astype(np.int32),
            (ends_idx - starts_idx).astype(np.int32),
        )
    return _Block(_PACKED, pack_bits(vals, width))


def _decode_block(blk: _Block, width: int, count: int) -> np.ndarray:
    if blk.kind == _RUN:
        return np.repeat(blk.a, blk.b) + _run_ramps(blk.b)
    if blk.kind == _PACKED:
        return unpack_bits(blk.a, width, count)
    return blk.a


def _shrink(offsets: np.ndarray) -> np.ndarray:
    """int32 offsets when the stream fits — at card 1M this halves the
    per-dictId overhead (8 MB -> 4 MB), which dominates for
    high-cardinality columns with short posting runs."""
    return offsets.astype(np.int32) if offsets[-1] < 2**31 else offsets


def _run_ramps(lens: np.ndarray) -> np.ndarray:
    """[0..l0-1, 0..l1-1, ...] for run lengths lens (vectorized)."""
    total = int(lens.sum())
    out = np.arange(total, dtype=np.int32)
    starts = np.concatenate(([0], np.cumsum(lens)[:-1]))
    return out - np.repeat(starts.astype(np.int32), lens)


class InvertedIndex:
    """Compressed CSR postings: rows of dictId d live at stream
    positions ``offsets[d]:offsets[d+1]`` (ascending within a run)."""

    def __init__(self, offsets: np.ndarray, rows: np.ndarray, compress: bool = True):
        self.offsets = offsets
        self.n_entries = int(rows.size)
        # width covers the largest row id (num_docs is not passed in;
        # max() is exact and cheaper than carrying metadata through)
        self.width = bits_required(int(rows.max()) + 1 if rows.size else 1)
        if compress and rows.size >= BLOCK:
            self.blocks: Optional[List[_Block]] = [
                _encode_block(rows[i : i + BLOCK], self.width)
                for i in range(0, rows.size, BLOCK)
            ]
            self._raw: Optional[np.ndarray] = None
        else:
            self.blocks = None
            self._raw = np.ascontiguousarray(rows, dtype=np.int32)

    @property
    def rows(self) -> np.ndarray:
        """Full decoded posting stream (tests/debug; queries use
        _decode_range on touched blocks only)."""
        if self._raw is not None:
            return self._raw
        return self._decode_range(0, self.n_entries)

    @property
    def nbytes(self) -> int:
        body = (
            sum(b.nbytes for b in self.blocks)
            if self.blocks is not None
            else self._raw.nbytes
        )
        return body + self.offsets.nbytes

    # -- build ---------------------------------------------------------
    @classmethod
    def build_sv(
        cls, fwd: np.ndarray, cardinality: int, compress: bool = True
    ) -> "InvertedIndex":
        order = np.argsort(fwd, kind="stable")
        counts = np.bincount(fwd, minlength=cardinality)
        offsets = np.zeros(cardinality + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets[1:])
        return cls(_shrink(offsets), order.astype(np.int32), compress)

    @classmethod
    def build_mv(
        cls,
        mv_values: np.ndarray,
        mv_offsets: np.ndarray,
        cardinality: int,
        compress: bool = True,
    ) -> "InvertedIndex":
        doc_ids = np.repeat(
            np.arange(mv_offsets.size - 1, dtype=np.int32), np.diff(mv_offsets)
        )
        order = np.argsort(mv_values, kind="stable")
        counts = np.bincount(mv_values, minlength=cardinality)
        offsets = np.zeros(cardinality + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets[1:])
        return cls(_shrink(offsets), doc_ids[order], compress)

    # -- decode --------------------------------------------------------
    def _decode_range(self, s: int, e: int) -> np.ndarray:
        """Postings stream positions [s, e) — decodes only touched
        blocks, so selective queries stay O(matches)."""
        if self._raw is not None:
            return self._raw[s:e]
        first, last = s // BLOCK, (e - 1) // BLOCK
        parts = []
        for bi in range(first, last + 1):
            lo = bi * BLOCK
            count = min(BLOCK, self.n_entries - lo)
            dec = _decode_block(self.blocks[bi], self.width, count)
            parts.append(dec[max(s - lo, 0) : e - lo])
        return parts[0] if len(parts) == 1 else np.concatenate(parts)

    # -- query side ----------------------------------------------------
    def slices_for_table(self, table: np.ndarray) -> List[Tuple[int, int]]:
        """Contiguous posting slices for a bool[>=card] dictId match
        table (plan.match_table): maximal True runs -> (start, end)
        posting ranges."""
        card = self.offsets.size - 1
        t = np.asarray(table[:card], dtype=bool)
        if not t.any():
            return []
        d = np.diff(t.astype(np.int8))
        starts = list(np.nonzero(d == 1)[0] + 1)
        ends = list(np.nonzero(d == -1)[0] + 1)
        if t[0]:
            starts.insert(0, 0)
        if t[-1]:
            ends.append(card)
        return [
            (int(self.offsets[a]), int(self.offsets[b])) for a, b in zip(starts, ends)
        ]

    def count_for_table(self, table: np.ndarray) -> int:
        return sum(e - s for s, e in self.slices_for_table(table))

    def resolve_table(self, table: np.ndarray) -> np.ndarray:
        """Matched row ids (sorted ascending, deduplicated) for a dictId
        match table.  Dedup matters for MV postings: one posting per
        (doc, value) occurrence, and a doc matching several predicate
        values must count once — the RoaringBitmap OR the reference does
        dedupes inherently."""
        sl = self.slices_for_table(table)
        if not sl:
            return np.zeros(0, dtype=np.int32)
        nonempty = [(s, e) for s, e in sl if e > s]
        if not nonempty:
            return np.zeros(0, dtype=np.int32)
        return np.unique(np.concatenate([self._decode_range(s, e) for s, e in nonempty]))


# ---------------------------------------------------------------- budget
_budget_lock = threading.Lock()
_postings_bytes = 0
# Refusals are epoch-stamped, not permanent: a build refused during a
# budget spike retries once bytes have been RELEASED since (each
# release_postings bumps the epoch).  The cache stores ("refused",
# epoch) tuples.
_release_epoch = 0


def _budget_bytes() -> int:
    try:
        return int(os.environ.get("PINOT_TPU_INVINDEX_BUDGET_BYTES", 2 << 30))
    except ValueError:
        return 2 << 30


def _compress_enabled() -> bool:
    return os.environ.get("PINOT_TPU_INVINDEX_COMPRESS", "1") != "0"


def postings_bytes_in_use() -> int:
    with _budget_lock:
        return _postings_bytes


def inverted_index(seg: ImmutableSegment, column: str) -> Optional[InvertedIndex]:
    """Per-(segment, column) index, cached on the immutable segment
    (the ``SoftReference`` cache of ``BitmapInvertedIndexReader.java:32``
    analog — here the build is one argsort, so lazy build-on-first-use
    replaces persistence).  Builds that would push total postings
    memory past the process budget are refused — the engine then falls
    back to the zone-map / device-scan paths."""
    global _postings_bytes
    col = seg.columns.get(column)
    if col is None:
        return None
    with _budget_lock:
        cache = getattr(seg, "_inv_cache", None)
        if cache is None:
            cache = {}
            object.__setattr__(seg, "_inv_cache", cache)
        idx = cache.get(column)
        if isinstance(idx, tuple):  # ("refused", epoch)
            if idx[1] == _release_epoch:
                return None  # nothing released since: don't retry per query
            cache.pop(column, None)
            idx = None
        if isinstance(idx, InvertedIndex):
            return idx
    card = col.dictionary.cardinality
    if card <= 0:
        return None
    if col.metadata.single_value:
        if col.fwd is None:
            return None
        built = InvertedIndex.build_sv(np.asarray(col.fwd), card, _compress_enabled())
    else:
        built = InvertedIndex.build_mv(
            np.asarray(col.mv_values),
            np.asarray(col.mv_offsets),
            card,
            _compress_enabled(),
        )
    with _budget_lock:
        # re-check under the lock: a concurrent query may have built and
        # ACCOUNTED the same index; double-accounting would permanently
        # inflate the budget and eventually refuse all builds
        existing = cache.get(column)
        if isinstance(existing, InvertedIndex):
            return existing
        if _postings_bytes + built.nbytes > _budget_bytes():
            cache[column] = ("refused", _release_epoch)
            logger.warning(
                "postings budget exhausted (%d + %d > %d bytes): %s.%s "
                "falls back to zone-map/scan paths "
                "(raise PINOT_TPU_INVINDEX_BUDGET_BYTES to index more)",
                _postings_bytes,
                built.nbytes,
                _budget_bytes(),
                seg.segment_name,
                column,
            )
            return None
        _postings_bytes += built.nbytes
        cache[column] = built
    return built


def release_postings(seg: ImmutableSegment) -> None:
    """Return a segment's postings bytes to the budget (segment unload).
    Bumps the release epoch so budget refusals elsewhere re-evaluate."""
    global _postings_bytes, _release_epoch
    cache = getattr(seg, "_inv_cache", None)
    if not cache:
        return
    with _budget_lock:
        freed = sum(
            idx.nbytes for idx in cache.values() if isinstance(idx, InvertedIndex)
        )
        cache.clear()
        _postings_bytes = max(0, _postings_bytes - freed)
        if freed:
            _release_epoch += 1


def warm_inverted_indexes(seg: ImmutableSegment, columns) -> None:
    """Best-effort postings pre-build for configured columns at segment
    load (invertedIndexColumns parity) — shared by both server
    starters.  A configured column that cannot index (typo, no
    dictionary) warns instead of silently no-opping."""
    for col in columns or ():
        try:
            if inverted_index(seg, col) is None:
                logger.warning(
                    "invertedIndexColumns: %r cannot be indexed on segment %s "
                    "(unknown column, no dictionary, or postings budget)",
                    col,
                    seg.segment_name,
                )
        except Exception:
            logger.exception(
                "inverted-index warm failed for %s.%s", seg.segment_name, col
            )
