"""Host-resident inverted index: CSR postings per dictId.

Reference capability: ``BitmapInvertedIndexReader.java:28`` — dictId ->
RoaringBitmap of docIds, read host-side by
``core/operator/filter/BitmapBasedFilterOperator.java:34`` to answer
selective predicates in O(matches) regardless of doc order.

TPU-first placement: the postings stay HOST-resident, not in HBM.
On-chip measurement (MICROBENCH_TPU.json) puts XLA per-element gathers
at ~12.5 ns — fine for thousands of matched rows, poison at per-row
scan scale.  The executor therefore uses postings to resolve matched
row ids on host and aggregates exactly those rows with numpy
fancy-indexing (O(matches)), skipping the device dispatch (and its
round trip) entirely; unselective predicates stay on the device scan
path, which at ~2.8B rows/s outruns any index walk.  This re-cuts the
reference's BitmapBasedFilterOperator (selective) vs
ScanBasedFilterOperator (unselective) split at the TPU's
bandwidth-vs-latency boundary.

Representation: row ids stably argsorted by dictId — the postings for
one dictId are one contiguous slice, and a dictId *range* (the sorted
dictionary makes value ranges dictId ranges) is also one contiguous
slice, so EQ/RANGE resolve to slices and IN to a few of them.  This is
the CSR analog of the reference's sorted-run RoaringBitmap containers.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

from pinot_tpu.segment.immutable import ImmutableSegment


@dataclass
class InvertedIndex:
    """CSR postings: rows of dictId d live at
    ``rows[offsets[d]:offsets[d+1]]`` (ascending within a run)."""

    offsets: np.ndarray  # int64 [card + 1]
    rows: np.ndarray  # int32 [n_entries]

    @classmethod
    def build_sv(cls, fwd: np.ndarray, cardinality: int) -> "InvertedIndex":
        order = np.argsort(fwd, kind="stable")
        counts = np.bincount(fwd, minlength=cardinality)
        offsets = np.zeros(cardinality + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets[1:])
        return cls(offsets=offsets, rows=order.astype(np.int32))

    @classmethod
    def build_mv(
        cls, mv_values: np.ndarray, mv_offsets: np.ndarray, cardinality: int
    ) -> "InvertedIndex":
        doc_ids = np.repeat(
            np.arange(mv_offsets.size - 1, dtype=np.int32), np.diff(mv_offsets)
        )
        order = np.argsort(mv_values, kind="stable")
        counts = np.bincount(mv_values, minlength=cardinality)
        offsets = np.zeros(cardinality + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets[1:])
        return cls(offsets=offsets, rows=doc_ids[order])

    # -- query side ----------------------------------------------------
    def slices_for_table(self, table: np.ndarray) -> List[Tuple[int, int]]:
        """Contiguous posting slices for a bool[>=card] dictId match
        table (plan.match_table): maximal True runs -> (start, end)
        posting ranges."""
        card = self.offsets.size - 1
        t = np.asarray(table[:card], dtype=bool)
        if not t.any():
            return []
        d = np.diff(t.astype(np.int8))
        starts = list(np.nonzero(d == 1)[0] + 1)
        ends = list(np.nonzero(d == -1)[0] + 1)
        if t[0]:
            starts.insert(0, 0)
        if t[-1]:
            ends.append(card)
        return [
            (int(self.offsets[a]), int(self.offsets[b])) for a, b in zip(starts, ends)
        ]

    def count_for_table(self, table: np.ndarray) -> int:
        return sum(e - s for s, e in self.slices_for_table(table))

    def resolve_table(self, table: np.ndarray) -> np.ndarray:
        """Matched row ids (sorted ascending, deduplicated) for a dictId
        match table.  Dedup matters for MV postings: one posting per
        (doc, value) occurrence, and a doc matching several predicate
        values must count once — the RoaringBitmap OR the reference does
        dedupes inherently."""
        sl = self.slices_for_table(table)
        if not sl:
            return np.zeros(0, dtype=np.int32)
        return np.unique(np.concatenate([self.rows[s:e] for s, e in sl]))


def inverted_index(seg: ImmutableSegment, column: str) -> Optional[InvertedIndex]:
    """Per-(segment, column) index, cached on the immutable segment
    (the ``SoftReference`` cache of ``BitmapInvertedIndexReader.java:32``
    analog — here the build is one argsort, so lazy build-on-first-use
    replaces persistence)."""
    col = seg.columns.get(column)
    if col is None:
        return None
    cache = getattr(seg, "_inv_cache", None)
    if cache is None:
        cache = {}
        object.__setattr__(seg, "_inv_cache", cache)
    idx = cache.get(column)
    if idx is None:
        card = col.dictionary.cardinality
        if card <= 0:
            return None
        if col.metadata.single_value:
            if col.fwd is None:
                return None
            idx = InvertedIndex.build_sv(np.asarray(col.fwd), card)
        else:
            idx = InvertedIndex.build_mv(
                np.asarray(col.mv_values), np.asarray(col.mv_offsets), card
            )
        cache[column] = idx
    return idx


def warm_inverted_indexes(seg: ImmutableSegment, columns) -> None:
    """Best-effort postings pre-build for configured columns at segment
    load (invertedIndexColumns parity) — shared by both server
    starters.  A configured column that cannot index (typo, no
    dictionary) warns instead of silently no-opping."""
    import logging

    log = logging.getLogger(__name__)
    for col in columns or ():
        try:
            if inverted_index(seg, col) is None:
                log.warning(
                    "invertedIndexColumns: %r cannot be indexed on segment %s "
                    "(unknown column or no dictionary)",
                    col,
                    seg.segment_name,
                )
        except Exception:
            log.exception(
                "inverted-index warm failed for %s.%s", seg.segment_name, col
            )
