"""Two-pass segment builder.

Mirrors the reference build pipeline
(``SegmentIndexCreationDriverImpl.java:71``):

  pass 1 — scan records, collect per-column stats (cardinality, min/max,
           sortedness, MV lengths) (:229-256);
  then   — build sorted dictionaries per column
           (``SegmentDictionaryCreator.java``);
  pass 2 — write dictId forward indexes (SV: one dictId per doc,
           MV: CSR values+offsets) (``SegmentColumnarIndexCreator``);
  finally — segment metadata (time range, crc, creation time —
           metadata.properties + creation.meta analogs).

Missing fields get the schema's default null value (FieldSpec.java:37-47).
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Sequence

import numpy as np

from pinot_tpu.common.schema import DataType, FieldSpec, Schema
from pinot_tpu.segment.dictionary import Dictionary
from pinot_tpu.segment.immutable import (
    ColumnData,
    ColumnMetadata,
    ImmutableSegment,
    SegmentMetadata,
)

Row = Dict[str, Any]


@dataclass
class SegmentGeneratorConfig:
    """Build-time options (reference: SegmentGeneratorConfig)."""

    table_name: str
    segment_name: Optional[str] = None
    # columns to build a star-tree over; None disables (stage 8)
    startree_config: Optional[object] = None
    # columns to pre-derive HLL companions for (HllConfig analog)
    hll_columns: Sequence[str] = ()
    hll_suffix: str = "_hll"


class _ColumnStats:
    """Pass-1 per-column stats collector
    (reference: creator/impl/stats/ collectors)."""

    def __init__(self, spec: FieldSpec) -> None:
        self.spec = spec
        self.values: List[Any] = []
        self.max_mv = 0
        self.total_entries = 0
        self.prev = None
        self.is_sorted = spec.single_value  # MV columns are never "sorted"

    def collect(self, value: Any) -> None:
        st = self.spec.stored_type
        if self.spec.single_value:
            v = st.convert(value)
            self.values.append(v)
            self.total_entries += 1
            if self.is_sorted and self.prev is not None and v < self.prev:
                self.is_sorted = False
            self.prev = v
        else:
            vs = value if isinstance(value, (list, tuple)) else [value]
            if not vs:
                vs = [self.spec.get_default_null_value()]
            converted = [st.convert(x) for x in vs]
            self.values.extend(converted)
            self.total_entries += len(converted)
            self.max_mv = max(self.max_mv, len(converted))


class SegmentBuilder:
    def __init__(self, schema: Schema, config: SegmentGeneratorConfig) -> None:
        self.schema = schema
        self.config = config

    def build(self, rows: Sequence[Row]) -> ImmutableSegment:
        schema = self.schema
        num_docs = len(rows)

        # ---- pass 1: stats ------------------------------------------
        stats: Dict[str, _ColumnStats] = {
            spec.name: _ColumnStats(spec) for spec in schema.all_fields()
        }
        for row in rows:
            for spec in schema.all_fields():
                value = row.get(spec.name)
                if value is None or (isinstance(value, float) and np.isnan(value)):
                    value = spec.get_default_null_value()
                stats[spec.name].collect(value)

        # ---- dictionaries -------------------------------------------
        dictionaries: Dict[str, Dictionary] = {}
        for spec in schema.all_fields():
            dictionaries[spec.name] = Dictionary.build(
                spec.stored_type, stats[spec.name].values
            )

        # ---- pass 2: forward indexes --------------------------------
        columns: Dict[str, ColumnData] = {}
        for spec in schema.all_fields():
            st = spec.stored_type
            d = dictionaries[spec.name]
            s = stats[spec.name]
            meta = ColumnMetadata(
                name=spec.name,
                data_type=spec.data_type,
                field_type=spec.field_type,
                single_value=spec.single_value,
                cardinality=d.cardinality,
                total_docs=num_docs,
                is_sorted=s.is_sorted,
                max_num_multi_values=s.max_mv,
                total_number_of_entries=s.total_entries,
                min_value=d.min_value,
                max_value=d.max_value,
            )
            if spec.single_value:
                raw = np.asarray(s.values, dtype=st.to_numpy()) if not d.is_string else s.values
                fwd = d.index_array(np.asarray(s.values, dtype=object) if d.is_string else raw)
                columns[spec.name] = ColumnData(metadata=meta, dictionary=d, fwd=fwd)
            else:
                # CSR: s.values is already flattened in row order
                offsets = np.zeros(num_docs + 1, dtype=np.int32)
                flat: List[Any] = []
                pos = 0
                i = 0
                for row in rows:
                    value = row.get(spec.name)
                    if value is None:
                        vs = [spec.get_default_null_value()]
                    else:
                        vs = value if isinstance(value, (list, tuple)) else [value]
                        vs = [st.convert(x) for x in vs] or [spec.get_default_null_value()]
                    flat.extend(vs)
                    pos += len(vs)
                    i += 1
                    offsets[i] = pos
                if d.is_string:
                    mv_values = d.index_array(np.asarray(flat, dtype=object))
                else:
                    mv_values = d.index_array(np.asarray(flat, dtype=st.to_numpy()))
                columns[spec.name] = ColumnData(
                    metadata=meta, dictionary=d, mv_values=mv_values, mv_offsets=offsets
                )

        return finalize_segment(schema, self.config, num_docs, columns)


def finalize_segment(
    schema: Schema,
    config: SegmentGeneratorConfig,
    num_docs: int,
    columns: Dict[str, ColumnData],
) -> ImmutableSegment:
    """Segment metadata + CRC + optional star-tree — shared tail of the
    row-wise and columnar build paths (metadata.properties /
    creation.meta analogs)."""
    seg_name = config.segment_name or f"{config.table_name}_{num_docs}_{int(time.time())}"
    meta = SegmentMetadata(
        segment_name=seg_name,
        table_name=config.table_name,
        num_docs=num_docs,
        columns={c.metadata.name: c.metadata for c in columns.values()},
        time_column=schema.time_column_name,
        time_unit=schema.time_field.time_unit if schema.time_field else "DAYS",
        creation_time_ms=int(time.time() * 1000),
    )
    if schema.time_field is not None and num_docs > 0:
        tcol = columns[schema.time_column_name]
        if not tcol.dictionary.is_string:
            meta.start_time = int(tcol.dictionary.min_value)
            meta.end_time = int(tcol.dictionary.max_value)

    segment = ImmutableSegment(metadata=meta, columns=columns)
    meta.crc = segment.compute_crc()
    meta.custom["dataCrc"] = True  # verifiable claim (format.verify_segment_crc)

    if config.startree_config is not None:
        from pinot_tpu.startree.builder import build_star_tree

        segment = build_star_tree(segment, schema, config.startree_config)
    return segment


def build_segment(
    schema: Schema,
    rows: Sequence[Row],
    table_name: str,
    segment_name: Optional[str] = None,
    **kwargs: Any,
) -> ImmutableSegment:
    cfg = SegmentGeneratorConfig(table_name=table_name, segment_name=segment_name, **kwargs)
    return SegmentBuilder(schema, cfg).build(rows)
