"""Pluggable segment fetchers, dispatched by download-URI scheme.

Reference parity: ``common/segment/fetcher/SegmentFetcherFactory.java``
selects ``HttpSegmentFetcher`` / ``LocalFileSegmentFetcher`` (and the
WebHDFS client, ``common/utils/webhdfs/WebHdfsV1Client.java``) from the
segment's download URI scheme; servers use it in
``SegmentFetcherAndLoader.java:84`` and push jobs use it to hand
segments to the controller.  The *pluggability seam* is the point:
deployments register fetchers for their blob store.

Here the factory maps scheme -> fetcher and both load paths (in-process
server starter and the networked server) resolve ``downloadUri``
through it; ``register`` adds custom schemes at runtime.  The WebHDFS
fetcher speaks the WebHDFS v1 REST protocol (OPEN op) over urllib, so
it works against any WebHDFS-compatible endpoint without Hadoop
libraries.
"""
from __future__ import annotations

import os
import shutil
import urllib.parse
import urllib.request
from typing import Callable, Dict, Optional

from pinot_tpu.utils.retry import ExponentialBackoffRetryPolicy


class SegmentFetcher:
    """Copy the segment file at ``uri`` to ``dest_path`` (a local file
    path; parent directories are the caller's concern)."""

    def fetch(self, uri: str, dest_path: str) -> None:
        raise NotImplementedError


class LocalFileSegmentFetcher(SegmentFetcher):
    """``file://`` URIs and bare paths (LocalFileSegmentFetcher.java)."""

    def fetch(self, uri: str, dest_path: str) -> None:
        parsed = urllib.parse.urlparse(uri)
        src = parsed.path if parsed.scheme == "file" else uri
        if os.path.isdir(src):
            from pinot_tpu.segment.format import SEGMENT_FILE_NAME

            src = os.path.join(src, SEGMENT_FILE_NAME)
        shutil.copyfile(src, dest_path)


def _http_download(
    url: str, dest_path: str, timeout_s: float, policy: ExponentialBackoffRetryPolicy
) -> None:
    """Shared retried GET-to-file for the http-based fetchers.

    The body streams into ``dest_path + ".part"`` and only an attempt
    that passes the length check renames into place — a connection cut
    mid-stream can never leave a truncated file where a later load (or a
    parallel fetch attempt) would pick it up.  Failed attempts clean
    their ``.part`` up before the retry."""

    def _once():
        tmp = dest_path + ".part"
        try:
            with urllib.request.urlopen(url, timeout=timeout_s) as r:
                expected = r.headers.get("Content-Length")
                with open(tmp, "wb") as f:
                    shutil.copyfileobj(r, f)
            if expected is not None:
                size = os.path.getsize(tmp)
                if size != int(expected):
                    raise IOError(
                        f"truncated download from {url}: {size} of "
                        f"{expected} bytes"
                    )
            os.replace(tmp, dest_path)
        except BaseException:
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise

    policy.attempt(_once)


class HttpSegmentFetcher(SegmentFetcher):
    """``http(s)://`` download with full-jitter exponential-backoff
    retries (HttpSegmentFetcher.java + its RetryPolicy; jitter so a
    replica fleet re-downloading after a controller restart does not
    hammer it in lockstep)."""

    def __init__(self, timeout_s: float = 120.0, attempts: int = 3) -> None:
        self.timeout_s = timeout_s
        self.policy = ExponentialBackoffRetryPolicy(attempts, 0.2, jitter=True)

    def fetch(self, uri: str, dest_path: str) -> None:
        _http_download(uri, dest_path, self.timeout_s, self.policy)


class WebHdfsSegmentFetcher(SegmentFetcher):
    """``hdfs://`` via the WebHDFS v1 REST gateway
    (``WebHdfsV1Client.java`` analog: GET ?op=OPEN, follow the datanode
    redirect urllib handles automatically), with the same retry policy
    as the http fetcher."""

    def __init__(self, gateway: str = "", timeout_s: float = 120.0, attempts: int = 3) -> None:
        # gateway e.g. "http://namenode:50070"; empty -> derive from the
        # uri authority (hdfs://host:port/path -> http://host:port)
        self.gateway = gateway.rstrip("/")
        self.timeout_s = timeout_s
        self.policy = ExponentialBackoffRetryPolicy(attempts, 0.2, jitter=True)

    def fetch(self, uri: str, dest_path: str) -> None:
        parsed = urllib.parse.urlparse(uri)
        gateway = self.gateway or f"http://{parsed.netloc}"
        url = f"{gateway}/webhdfs/v1{parsed.path}?op=OPEN"
        _http_download(url, dest_path, self.timeout_s, self.policy)


class SegmentFetcherFactory:
    """scheme -> fetcher registry (SegmentFetcherFactory.java)."""

    def __init__(self) -> None:
        local = LocalFileSegmentFetcher()
        http = HttpSegmentFetcher()
        self._fetchers: Dict[str, SegmentFetcher] = {
            "": local,
            "file": local,
            "http": http,
            "https": http,
            "hdfs": WebHdfsSegmentFetcher(),
        }

    def register(self, scheme: str, fetcher: SegmentFetcher) -> None:
        self._fetchers[scheme] = fetcher

    def for_uri(self, uri: str) -> SegmentFetcher:
        scheme = urllib.parse.urlparse(uri).scheme
        f = self._fetchers.get(scheme)
        if f is None:
            raise ValueError(
                f"no segment fetcher registered for scheme {scheme!r} ({uri})"
            )
        return f

    def fetch(
        self,
        uri: str,
        dest_path: str,
        expected_crc: Optional[int] = None,
        suspect_cb=None,
    ):
        """Fetch ``uri`` to ``dest_path``; with ``expected_crc`` the
        download lands in a side file, is parsed and CRC-verified, and
        only then atomically renamed into place — a corrupt copy raises
        ``SegmentIntegrityError`` (a wrong-version one the softer
        ``SegmentStaleError``) and leaves ``dest_path`` untouched (the
        server's quarantine/re-fetch loop depends on never installing
        bad bytes).  Returns the already-parsed, already-verified
        segment on the verified path (None otherwise) so callers don't
        decode + CRC multi-GB files a second time.

        ``suspect_cb(uri, exc)`` fires when the FETCHED bytes fail
        verification (not on stale versions): the source copy — usually
        the controller's deep store — is the suspect, and the callback
        routes the evidence to the ``DeepStoreScrubber`` so the rotten
        copy gets repaired instead of poisoning every future fetch."""
        os.makedirs(os.path.dirname(dest_path) or ".", exist_ok=True)
        if expected_crc is None:
            self.for_uri(uri).fetch(uri, dest_path)
            return None
        from pinot_tpu.segment.format import (
            SegmentIntegrityError,
            SegmentStaleError,
            read_segment,
            verify_segment_crc,
        )

        tmp = dest_path + ".verify"
        self.for_uri(uri).fetch(uri, tmp)
        try:
            try:
                seg = read_segment(tmp)
            except SegmentIntegrityError:
                raise
            except Exception as e:  # unparseable: corrupt beyond the CRC
                raise SegmentIntegrityError(
                    f"fetched segment from {uri} is unreadable: "
                    f"{type(e).__name__}: {e}"
                ) from e
            verify_segment_crc(seg, source=uri)
            if seg.metadata.crc and seg.metadata.crc != expected_crc:
                # internally consistent (verified above) but a different
                # VERSION than asked for: replication lag, not corruption
                raise SegmentStaleError(
                    f"fetched segment from {uri}: metadata CRC "
                    f"{seg.metadata.crc} != expected {expected_crc} (stale copy)"
                )
        except BaseException as exc:
            try:
                os.remove(tmp)
            except OSError:
                pass
            if (
                suspect_cb is not None
                and isinstance(exc, SegmentIntegrityError)
                and not isinstance(exc, SegmentStaleError)
            ):
                try:
                    suspect_cb(uri, exc)
                except Exception:
                    pass  # reporting is best-effort, never masks the fetch error
            raise
        os.replace(tmp, dest_path)
        return seg


DEFAULT_FACTORY = SegmentFetcherFactory()
