"""Immutable columnar segment — the in-memory (host) representation.

The reference's ``IndexSegmentImpl`` (pinot-core
``segment/index/IndexSegmentImpl.java:41``) holds per-column data
sources (dictionary + forward index + optional inverted index) plus
``SegmentMetadataImpl``.  Here a segment is a plain dataclass of numpy
arrays per column; the device-resident form (jax arrays, padded/stacked)
is produced by ``pinot_tpu.engine.device``.

Forward index layouts:
- single-value: ``fwd`` int32 [num_docs] of dictIds
- multi-value: CSR-style ``mv_values`` int32 [total_values] +
  ``mv_offsets`` int32 [num_docs + 1]  (padded to a dense
  [num_docs, max_mv] matrix only at device staging; the reference's
  FixedBitMultiValueReader stores a similar offset+values layout)
"""
from __future__ import annotations

import time
import zlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from pinot_tpu.common.schema import DataType, FieldSpec, FieldType, Schema
from pinot_tpu.segment.dictionary import Dictionary

SEGMENT_FORMAT_VERSION = "tpu1"  # analog of SegmentVersion v1/v2/v3


@dataclass
class ColumnMetadata:
    """Per-column metadata (reference: ColumnMetadata / metadata.properties)."""

    name: str
    data_type: DataType
    field_type: FieldType
    single_value: bool
    cardinality: int
    total_docs: int
    is_sorted: bool
    has_inverted_index: bool = False
    max_num_multi_values: int = 0
    total_number_of_entries: int = 0  # = num_docs for SV, total MV values for MV
    min_value: Any = None
    max_value: Any = None

    def to_json(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "dataType": self.data_type.value,
            "fieldType": self.field_type.value,
            "singleValue": self.single_value,
            "cardinality": self.cardinality,
            "totalDocs": self.total_docs,
            "isSorted": self.is_sorted,
            "hasInvertedIndex": self.has_inverted_index,
            "maxNumMultiValues": self.max_num_multi_values,
            "totalNumberOfEntries": self.total_number_of_entries,
            "minValue": self.min_value,
            "maxValue": self.max_value,
        }

    @classmethod
    def from_json(cls, d: Dict[str, Any]) -> "ColumnMetadata":
        return cls(
            name=d["name"],
            data_type=DataType(d["dataType"]),
            field_type=FieldType(d["fieldType"]),
            single_value=d["singleValue"],
            cardinality=d["cardinality"],
            total_docs=d["totalDocs"],
            is_sorted=d["isSorted"],
            has_inverted_index=d.get("hasInvertedIndex", False),
            max_num_multi_values=d.get("maxNumMultiValues", 0),
            total_number_of_entries=d.get("totalNumberOfEntries", 0),
            min_value=d.get("minValue"),
            max_value=d.get("maxValue"),
        )


@dataclass
class SegmentMetadata:
    """Segment-level metadata (reference: SegmentMetadataImpl +
    creation.meta: crc + creation time, V1Constants.java:87-96)."""

    segment_name: str
    table_name: str
    num_docs: int
    columns: Dict[str, ColumnMetadata] = field(default_factory=dict)
    time_column: Optional[str] = None
    time_unit: str = "DAYS"
    start_time: Optional[int] = None
    end_time: Optional[int] = None
    crc: int = 0
    creation_time_ms: int = 0
    format_version: str = SEGMENT_FORMAT_VERSION
    custom: Dict[str, Any] = field(default_factory=dict)

    def to_json(self) -> Dict[str, Any]:
        return {
            "segmentName": self.segment_name,
            "tableName": self.table_name,
            "numDocs": self.num_docs,
            "columns": {k: v.to_json() for k, v in self.columns.items()},
            "timeColumn": self.time_column,
            "timeUnit": self.time_unit,
            "startTime": self.start_time,
            "endTime": self.end_time,
            "crc": self.crc,
            "creationTimeMs": self.creation_time_ms,
            "formatVersion": self.format_version,
            "custom": self.custom,
        }

    @classmethod
    def from_json(cls, d: Dict[str, Any]) -> "SegmentMetadata":
        return cls(
            segment_name=d["segmentName"],
            table_name=d["tableName"],
            num_docs=d["numDocs"],
            columns={k: ColumnMetadata.from_json(v) for k, v in d["columns"].items()},
            time_column=d.get("timeColumn"),
            time_unit=d.get("timeUnit", "DAYS"),
            start_time=d.get("startTime"),
            end_time=d.get("endTime"),
            crc=d.get("crc", 0),
            creation_time_ms=d.get("creationTimeMs", 0),
            format_version=d.get("formatVersion", SEGMENT_FORMAT_VERSION),
            custom=d.get("custom", {}),
        )


@dataclass
class ColumnData:
    """One column's index data inside an immutable segment."""

    metadata: ColumnMetadata
    dictionary: Dictionary
    fwd: Optional[np.ndarray] = None  # int32 [num_docs] (SV)
    mv_values: Optional[np.ndarray] = None  # int32 [total_values] (MV)
    mv_offsets: Optional[np.ndarray] = None  # int32 [num_docs + 1] (MV)

    @property
    def is_single_value(self) -> bool:
        return self.metadata.single_value

    def dict_ids_for_doc(self, doc_id: int) -> np.ndarray:
        if self.is_single_value:
            return self.fwd[doc_id : doc_id + 1]
        lo, hi = self.mv_offsets[doc_id], self.mv_offsets[doc_id + 1]
        return self.mv_values[lo:hi]

    def values_for_doc(self, doc_id: int):
        ids = self.dict_ids_for_doc(doc_id)
        vals = [self.dictionary.get(int(i)) for i in ids]
        return vals[0] if self.is_single_value else vals


import itertools

_staging_tokens = itertools.count()


@dataclass
class ImmutableSegment:
    """A sealed columnar segment: metadata + per-column index data."""

    metadata: SegmentMetadata
    columns: Dict[str, ColumnData]
    # process-unique instance identity for the device staging cache
    # (engine/device.py): a RE-LOADED segment (e.g. re-fetched after a
    # corruption quarantine) carries the same name and claimed crc but a
    # fresh token, so it can never alias stale arrays staged from the
    # old copy.  compare=False keeps segment equality by content.
    staging_token: int = field(
        default_factory=lambda: next(_staging_tokens), compare=False, repr=False
    )

    @property
    def segment_name(self) -> str:
        return self.metadata.segment_name

    @property
    def num_docs(self) -> int:
        return self.metadata.num_docs

    def column(self, name: str) -> ColumnData:
        try:
            return self.columns[name]
        except KeyError:
            raise KeyError(
                f"column {name!r} not in segment {self.segment_name!r}"
            ) from None

    def has_column(self, name: str) -> bool:
        return name in self.columns

    def row(self, doc_id: int) -> Dict[str, Any]:
        """Materialize one row (used by the scan path and converters)."""
        return {name: col.values_for_doc(doc_id) for name, col in self.columns.items()}

    def rows(self) -> List[Dict[str, Any]]:
        return [self.row(i) for i in range(self.num_docs)]

    def compute_crc(self) -> int:
        """CRC over column data, for reload-skip checks
        (SegmentFetcherAndLoader.java:84 CRC compare)."""
        crc = 0
        for name in sorted(self.columns):
            col = self.columns[name]
            for arr in (col.fwd, col.mv_values, col.mv_offsets):
                if arr is not None:
                    crc = zlib.crc32(np.ascontiguousarray(arr).tobytes(), crc)
            if col.dictionary.is_string:
                crc = zlib.crc32("\x00".join(col.dictionary.values).encode(), crc)
            else:
                crc = zlib.crc32(np.ascontiguousarray(col.dictionary.values).tobytes(), crc)
        return crc & 0xFFFFFFFF
