"""ctypes bindings for the native codec (native/bitpack.cpp).

Loads ``native/libpinotnative.so`` (building it with make on first use
if a compiler is available); every entry point has a numpy fallback in
``bitpack.py``, so the package works without a toolchain.
"""
from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

import numpy as np

logger = logging.getLogger(__name__)

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libpinotnative.so")

_lib: Optional[ctypes.CDLL] = None
_tried = False
_lock = threading.Lock()


def _stale() -> bool:
    try:
        if not os.path.exists(_LIB_PATH):
            return True
        so_mtime = os.path.getmtime(_LIB_PATH)
        return any(
            f.endswith(".cpp")
            and os.path.getmtime(os.path.join(_NATIVE_DIR, f)) > so_mtime
            for f in os.listdir(_NATIVE_DIR)
        )
    except OSError:  # concurrent clean/checkout: let make sort it out
        return True


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if os.path.exists(os.path.join(_NATIVE_DIR, "Makefile")) and _stale():
            # only spawn make when the .so is missing or older than a
            # source; the Makefile builds atomically (temp + rename) so
            # concurrent processes can't corrupt it
            try:
                subprocess.run(
                    ["make", "-C", _NATIVE_DIR],
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
            except Exception as e:  # no toolchain / build failure -> fallback
                logger.info("native codec build skipped: %s", e)
        if os.path.exists(_LIB_PATH):
            try:
                lib = ctypes.CDLL(_LIB_PATH)
                lib.pinot_pack_bits.argtypes = [
                    ctypes.POINTER(ctypes.c_int32),
                    ctypes.c_int64,
                    ctypes.c_int,
                    ctypes.POINTER(ctypes.c_uint8),
                ]
                lib.pinot_unpack_bits.argtypes = [
                    ctypes.POINTER(ctypes.c_uint8),
                    ctypes.c_int64,
                    ctypes.c_int,
                    ctypes.POINTER(ctypes.c_int32),
                ]
                # a stale prebuilt .so (no toolchain to rebuild) keeps its
                # working symbols; only csv_parse degrades to the fallback
                if hasattr(lib, "pinot_csv_parse"):
                    lib.pinot_csv_parse.argtypes = [
                        ctypes.c_void_p,  # readonly buffers (mmap) pass by address
                        ctypes.c_int64,
                        ctypes.c_int64,
                        ctypes.c_char,
                        ctypes.c_int,
                        ctypes.POINTER(ctypes.c_int8),
                        ctypes.POINTER(ctypes.c_int64),
                        ctypes.POINTER(ctypes.c_double),
                        ctypes.c_int64,
                        ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),
                        ctypes.POINTER(ctypes.POINTER(ctypes.c_double)),
                        ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),
                    ]
                    lib.pinot_csv_parse.restype = ctypes.c_int64
                _lib = lib
            except OSError as e:
                logger.info("native codec load failed: %s", e)
        return _lib


def available() -> bool:
    return _load() is not None


def csv_available() -> bool:
    lib = _load()
    return lib is not None and hasattr(lib, "pinot_csv_parse")


def pack_bits(values: np.ndarray, nbits: int) -> Optional[np.ndarray]:
    lib = _load()
    if lib is None:
        return None
    values = np.ascontiguousarray(values, dtype=np.int32)
    n = values.size
    out = np.zeros((n * nbits + 7) // 8, dtype=np.uint8)
    lib.pinot_pack_bits(
        values.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        n,
        nbits,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    return out


def csv_parse(data, start: int, delimiter: str, types, i64_defaults, f64_defaults):
    """One-pass columnar CSV parse (native/csvread.cpp), starting at
    byte offset ``start`` (past the header) — the buffer is not copied.

    ``types[c]``: 0 -> int64 column, 1 -> float64 column, 2 -> raw
    (offset,length) slices for string/MV cells (offsets absolute into
    ``data``), 3 -> tokenize but record nothing (non-schema columns).
    Returns ``(nrows, i64_cols, f64_cols, str_offs)`` — dicts keyed by
    column index, each value a numpy array trimmed to nrows — or None
    when the native library is unavailable or the data needs the Python
    parser (quoted cells, unparseable numerics, ragged-wide rows,
    non-ASCII delimiter).
    """
    lib = _load()
    if lib is None or not hasattr(lib, "pinot_csv_parse"):
        return None
    try:
        delim = delimiter.encode("ascii")
    except UnicodeEncodeError:
        return None  # python csv handles exotic delimiters
    if len(delim) != 1:
        return None
    ncols = len(types)
    types_arr = np.asarray(types, dtype=np.int8)
    i64_def = np.asarray(i64_defaults, dtype=np.int64)
    f64_def = np.asarray(f64_defaults, dtype=np.float64)
    if isinstance(data, (bytes, bytearray)):
        max_rows = data.count(b"\n", start) + 1
        buf = data
    else:  # mmap: chunked newline count + pass-by-address (readonly)
        view = np.frombuffer(data, dtype=np.uint8)
        # bounded chunks keep the comparison temporary at O(chunk), not
        # O(file) — the point of mmap-ing in the first place
        nl = 0
        for ofs in range(start, view.size, 1 << 24):
            nl += int(np.count_nonzero(view[ofs : ofs + (1 << 24)] == 0x0A))
        max_rows = nl + 1
        buf = ctypes.c_void_p(view.ctypes.data)
    i64_cols = {c: np.empty(max_rows, dtype=np.int64) for c in range(ncols) if types[c] == 0}
    f64_cols = {c: np.empty(max_rows, dtype=np.float64) for c in range(ncols) if types[c] == 1}
    str_offs = {c: np.empty(2 * max_rows, dtype=np.int64) for c in range(ncols) if types[c] == 2}

    PI64 = ctypes.POINTER(ctypes.c_int64)
    PF64 = ctypes.POINTER(ctypes.c_double)
    null_i64 = ctypes.cast(None, PI64)
    null_f64 = ctypes.cast(None, PF64)
    i64_ptrs = (PI64 * ncols)(
        *[i64_cols[c].ctypes.data_as(PI64) if c in i64_cols else null_i64 for c in range(ncols)]
    )
    f64_ptrs = (PF64 * ncols)(
        *[f64_cols[c].ctypes.data_as(PF64) if c in f64_cols else null_f64 for c in range(ncols)]
    )
    off_ptrs = (PI64 * ncols)(
        *[str_offs[c].ctypes.data_as(PI64) if c in str_offs else null_i64 for c in range(ncols)]
    )
    nrows = lib.pinot_csv_parse(
        buf,
        len(data),
        start,
        delim,
        ncols,
        types_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
        i64_def.ctypes.data_as(PI64),
        f64_def.ctypes.data_as(PF64),
        max_rows,
        i64_ptrs,
        f64_ptrs,
        off_ptrs,
    )
    if nrows < 0:
        return None  # fall back to the Python csv module
    return (
        int(nrows),
        {c: a[:nrows] for c, a in i64_cols.items()},
        {c: a[:nrows] for c, a in f64_cols.items()},
        {c: a[: 2 * nrows] for c, a in str_offs.items()},
    )


def unpack_bits(packed: np.ndarray, nbits: int, count: int) -> Optional[np.ndarray]:
    lib = _load()
    if lib is None:
        return None
    packed = np.ascontiguousarray(packed, dtype=np.uint8)
    out = np.empty(count, dtype=np.int32)
    lib.pinot_unpack_bits(
        packed.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        count,
        nbits,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    return out
