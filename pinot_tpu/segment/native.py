"""ctypes bindings for the native codec (native/bitpack.cpp).

Loads ``native/libpinotnative.so`` (building it with make on first use
if a compiler is available); every entry point has a numpy fallback in
``bitpack.py``, so the package works without a toolchain.
"""
from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

import numpy as np

logger = logging.getLogger(__name__)

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libpinotnative.so")

_lib: Optional[ctypes.CDLL] = None
_tried = False
_lock = threading.Lock()


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if not os.path.exists(_LIB_PATH) and os.path.exists(os.path.join(_NATIVE_DIR, "Makefile")):
            try:
                subprocess.run(
                    ["make", "-C", _NATIVE_DIR],
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
            except Exception as e:  # no toolchain / build failure -> fallback
                logger.info("native codec build skipped: %s", e)
        if os.path.exists(_LIB_PATH):
            try:
                lib = ctypes.CDLL(_LIB_PATH)
                lib.pinot_pack_bits.argtypes = [
                    ctypes.POINTER(ctypes.c_int32),
                    ctypes.c_int64,
                    ctypes.c_int,
                    ctypes.POINTER(ctypes.c_uint8),
                ]
                lib.pinot_unpack_bits.argtypes = [
                    ctypes.POINTER(ctypes.c_uint8),
                    ctypes.c_int64,
                    ctypes.c_int,
                    ctypes.POINTER(ctypes.c_int32),
                ]
                _lib = lib
            except OSError as e:
                logger.info("native codec load failed: %s", e)
        return _lib


def available() -> bool:
    return _load() is not None


def pack_bits(values: np.ndarray, nbits: int) -> Optional[np.ndarray]:
    lib = _load()
    if lib is None:
        return None
    values = np.ascontiguousarray(values, dtype=np.int32)
    n = values.size
    out = np.zeros((n * nbits + 7) // 8, dtype=np.uint8)
    lib.pinot_pack_bits(
        values.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        n,
        nbits,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    return out


def unpack_bits(packed: np.ndarray, nbits: int, count: int) -> Optional[np.ndarray]:
    lib = _load()
    if lib is None:
        return None
    packed = np.ascontiguousarray(packed, dtype=np.uint8)
    out = np.empty(count, dtype=np.int32)
    lib.pinot_unpack_bits(
        packed.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        count,
        nbits,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    return out
