"""Pure-Python Avro Object Container File reader/writer.

The reference ingests Avro as its primary interchange format
(``core/data/readers/AvroRecordReader.java:46``, reading a
``DataFileStream<GenericRecord>``; schema mapping via
``AvroUtils``), and its sample/test datasets are Avro containers.  No
Avro library is baked into this image, so this module implements the
container format directly — it needs nothing beyond the stdlib:

  header:  magic "Obj\\x01" | file-metadata map (avro.schema JSON,
           avro.codec) | 16-byte sync marker
  blocks:  long record-count | long byte-size | block data | sync
  codecs:  null, deflate (raw DEFLATE, RFC 1951 — zlib wbits=-15)
  values:  zigzag-varint ints/longs, little-endian IEEE float/double,
           length-prefixed bytes/string, records/arrays/maps/unions/
           enums/fixed per the writer schema

Supports ``.gz``-wrapped containers like the reference reader
(``AvroRecordReader.java:75-78``).
"""
from __future__ import annotations

import gzip
import io
import json
import os
import struct
import zlib
from typing import Any, BinaryIO, Dict, Iterator, List, Optional, Sequence, Tuple

from pinot_tpu.common.schema import DataType, FieldSpec, FieldType, Schema, TimeFieldSpec

MAGIC = b"Obj\x01"

Row = Dict[str, Any]


# ---------------------------------------------------------------------------
# primitive codecs
# ---------------------------------------------------------------------------


def _read_long(buf: io.BytesIO) -> int:
    """Zigzag varint (Avro int and long share the encoding)."""
    shift = 0
    acc = 0
    while True:
        b = buf.read(1)
        if not b:
            raise EOFError("truncated varint")
        byte = b[0]
        acc |= (byte & 0x7F) << shift
        if not byte & 0x80:
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1)


def _write_long(out: io.BytesIO, value: int) -> None:
    acc = (value << 1) ^ (value >> 63)
    acc &= (1 << 64) - 1
    while True:
        byte = acc & 0x7F
        acc >>= 7
        if acc:
            out.write(bytes([byte | 0x80]))
        else:
            out.write(bytes([byte]))
            break


def _read_bytes(buf: io.BytesIO) -> bytes:
    n = _read_long(buf)
    data = buf.read(n)
    if len(data) != n:
        raise EOFError("truncated bytes")
    return data


def _write_bytes(out: io.BytesIO, data: bytes) -> None:
    _write_long(out, len(data))
    out.write(data)


# ---------------------------------------------------------------------------
# schema-driven value codec
# ---------------------------------------------------------------------------


def _resolve(schema: Any, named: Dict[str, Any]) -> Any:
    """Expand a named-type reference to its definition."""
    if isinstance(schema, str) and schema in named:
        return named[schema]
    return schema


def _register_named(schema: Any, named: Dict[str, Any]) -> None:
    if isinstance(schema, dict):
        t = schema.get("type")
        if t in ("record", "enum", "fixed") and "name" in schema:
            named[schema["name"]] = schema
        if t == "record":
            for f in schema.get("fields", []):
                _register_named(f.get("type"), named)
        elif t == "array":
            _register_named(schema.get("items"), named)
        elif t == "map":
            _register_named(schema.get("values"), named)
    elif isinstance(schema, list):
        for s in schema:
            _register_named(s, named)


def _decode(schema: Any, buf: io.BytesIO, named: Dict[str, Any]) -> Any:
    schema = _resolve(schema, named)
    if isinstance(schema, list):  # union: index then value
        idx = _read_long(buf)
        return _decode(schema[idx], buf, named)
    if isinstance(schema, dict):
        t = schema["type"]
        if t == "record":
            return {
                f["name"]: _decode(f["type"], buf, named)
                for f in schema["fields"]
            }
        if t == "array":
            out: List[Any] = []
            while True:
                n = _read_long(buf)
                if n == 0:
                    break
                if n < 0:  # block with byte-size prefix
                    n = -n
                    _read_long(buf)
                for _ in range(n):
                    out.append(_decode(schema["items"], buf, named))
            return out
        if t == "map":
            m: Dict[str, Any] = {}
            while True:
                n = _read_long(buf)
                if n == 0:
                    break
                if n < 0:
                    n = -n
                    _read_long(buf)
                for _ in range(n):
                    key = _read_bytes(buf).decode("utf-8")
                    m[key] = _decode(schema["values"], buf, named)
            return m
        if t == "enum":
            return schema["symbols"][_read_long(buf)]
        if t == "fixed":
            return buf.read(schema["size"])
        schema = t  # {"type": "string"} style wrapper

    if schema == "null":
        return None
    if schema == "boolean":
        b = buf.read(1)
        return b != b"\x00"
    if schema in ("int", "long"):
        return _read_long(buf)
    if schema == "float":
        return struct.unpack("<f", buf.read(4))[0]
    if schema == "double":
        return struct.unpack("<d", buf.read(8))[0]
    if schema == "bytes":
        return _read_bytes(buf)
    if schema == "string":
        return _read_bytes(buf).decode("utf-8")
    raise ValueError(f"unsupported avro type {schema!r}")


def _encode(schema: Any, value: Any, out: io.BytesIO, named: Dict[str, Any]) -> None:
    schema = _resolve(schema, named)
    if isinstance(schema, list):  # union: pick first matching branch
        for idx, branch in enumerate(schema):
            if _matches(branch, value, named):
                _write_long(out, idx)
                _encode(branch, value, out, named)
                return
        raise ValueError(f"value {value!r} matches no union branch {schema!r}")
    if isinstance(schema, dict):
        t = schema["type"]
        if t == "record":
            for f in schema["fields"]:
                _encode(f["type"], value[f["name"]], out, named)
            return
        if t == "array":
            items = list(value)
            if items:
                _write_long(out, len(items))
                for v in items:
                    _encode(schema["items"], v, out, named)
            _write_long(out, 0)
            return
        if t == "map":
            if value:
                _write_long(out, len(value))
                for k, v in value.items():
                    _write_bytes(out, str(k).encode("utf-8"))
                    _encode(schema["values"], v, out, named)
            _write_long(out, 0)
            return
        if t == "enum":
            _write_long(out, schema["symbols"].index(value))
            return
        if t == "fixed":
            out.write(bytes(value))
            return
        schema = t

    if schema == "null":
        return
    if schema == "boolean":
        out.write(b"\x01" if value else b"\x00")
    elif schema in ("int", "long"):
        _write_long(out, int(value))
    elif schema == "float":
        out.write(struct.pack("<f", float(value)))
    elif schema == "double":
        out.write(struct.pack("<d", float(value)))
    elif schema == "bytes":
        _write_bytes(out, bytes(value))
    elif schema == "string":
        _write_bytes(out, str(value).encode("utf-8"))
    else:
        raise ValueError(f"unsupported avro type {schema!r}")


def _matches(schema: Any, value: Any, named: Dict[str, Any]) -> bool:
    schema = _resolve(schema, named)
    name = schema["type"] if isinstance(schema, dict) else schema
    if name == "null":
        return value is None
    if value is None:
        return False
    if name == "boolean":
        return isinstance(value, bool)
    if name in ("int", "long"):
        return isinstance(value, int) and not isinstance(value, bool)
    if name in ("float", "double"):
        return isinstance(value, (int, float)) and not isinstance(value, bool)
    if name in ("string", "enum"):
        return isinstance(value, str)
    if name in ("bytes", "fixed"):
        return isinstance(value, (bytes, bytearray))
    if name == "record":
        return isinstance(value, dict)
    if name == "array":
        return isinstance(value, (list, tuple))
    if name == "map":
        return isinstance(value, dict)
    return False


# ---------------------------------------------------------------------------
# container file
# ---------------------------------------------------------------------------


class AvroContainerReader:
    """Streams records out of an Avro Object Container File."""

    def __init__(self, path: str) -> None:
        self.path = path
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            self._data = f.read()
        buf = io.BytesIO(self._data)
        if buf.read(4) != MAGIC:
            raise ValueError(f"{path}: not an Avro object container file")
        self.metadata: Dict[str, bytes] = {}
        while True:
            n = _read_long(buf)
            if n == 0:
                break
            if n < 0:
                n = -n
                _read_long(buf)
            for _ in range(n):
                key = _read_bytes(buf).decode("utf-8")
                self.metadata[key] = _read_bytes(buf)
        self.sync = buf.read(16)
        self.schema = json.loads(self.metadata["avro.schema"].decode("utf-8"))
        self.codec = self.metadata.get("avro.codec", b"null").decode("utf-8")
        if self.codec not in ("null", "deflate"):
            raise ValueError(f"unsupported avro codec {self.codec!r}")
        self._named: Dict[str, Any] = {}
        _register_named(self.schema, self._named)
        self._body_offset = buf.tell()

    def __iter__(self) -> Iterator[Any]:
        # each iteration walks its own cursor from the first block, so
        # the reader is safely re-iterable
        buf = io.BytesIO(self._data)
        buf.seek(self._body_offset)
        while True:
            head = buf.read(1)
            if not head:
                return
            buf.seek(-1, io.SEEK_CUR)
            count = _read_long(buf)
            size = _read_long(buf)
            block = buf.read(size)
            if len(block) != size:
                raise EOFError("truncated avro block")
            if self.codec == "deflate":
                block = zlib.decompress(block, -15)
            bbuf = io.BytesIO(block)
            for _ in range(count):
                yield _decode(self.schema, bbuf, self._named)
            marker = buf.read(16)
            if marker != self.sync:
                raise ValueError("avro sync marker mismatch")


def write_avro(
    path: str,
    avro_schema: Dict[str, Any],
    records: Sequence[Dict[str, Any]],
    codec: str = "null",
    records_per_block: int = 4096,
) -> None:
    """Write records as an Avro Object Container File."""
    if codec not in ("null", "deflate"):
        raise ValueError(f"unsupported avro codec {codec!r}")
    named: Dict[str, Any] = {}
    _register_named(avro_schema, named)
    sync = os.urandom(16)
    with open(path, "wb") as f:
        f.write(MAGIC)
        head = io.BytesIO()
        meta = {
            "avro.schema": json.dumps(avro_schema).encode("utf-8"),
            "avro.codec": codec.encode("utf-8"),
        }
        _write_long(head, len(meta))
        for k, v in meta.items():
            _write_bytes(head, k.encode("utf-8"))
            _write_bytes(head, v)
        _write_long(head, 0)
        f.write(head.getvalue())
        f.write(sync)
        for start in range(0, len(records), records_per_block):
            chunk = records[start : start + records_per_block]
            body = io.BytesIO()
            for rec in chunk:
                _encode(avro_schema, rec, body, named)
            data = body.getvalue()
            if codec == "deflate":
                compressor = zlib.compressobj(9, zlib.DEFLATED, -15)
                data = compressor.compress(data) + compressor.flush()
            block = io.BytesIO()
            _write_long(block, len(chunk))
            _write_long(block, len(data))
            f.write(block.getvalue())
            f.write(data)
            f.write(sync)


# ---------------------------------------------------------------------------
# pinot-side adapters (AvroRecordReader / AvroUtils analogs)
# ---------------------------------------------------------------------------


def read_avro(path: str, schema: Schema) -> List[Row]:
    """Avro container -> rows typed per the Pinot schema (the
    ``AvroRecordReader`` role: extract schema fields from each
    GenericRecord, null-defaulting and MV flattening)."""
    def conv(spec: FieldSpec, v: Any) -> Any:
        # Avro bytes/fixed arrive as Python bytes; decode before the
        # STRING conversion so the stored value is the content, not repr
        if isinstance(v, (bytes, bytearray)):
            v = bytes(v).decode("utf-8", "replace")
        return spec.stored_type.convert(v)

    rows: List[Row] = []
    for rec in AvroContainerReader(path):
        row: Row = {}
        for spec in schema.all_fields():
            v = rec.get(spec.name)
            if spec.single_value:
                row[spec.name] = (
                    spec.get_default_null_value() if v is None else conv(spec, v)
                )
            else:
                vs = v if isinstance(v, list) else ([] if v is None else [v])
                row[spec.name] = [conv(spec, x) for x in vs if x is not None] or [
                    spec.get_default_null_value()
                ]
        rows.append(row)
    return rows


_AVRO_TO_DATATYPE = {
    "boolean": DataType.STRING,
    "int": DataType.INT,
    "long": DataType.LONG,
    "float": DataType.FLOAT,
    "double": DataType.DOUBLE,
    "string": DataType.STRING,
    "bytes": DataType.STRING,
    "enum": DataType.STRING,
    "fixed": DataType.STRING,
}

_SV_TO_MV = {
    DataType.INT: DataType.INT_ARRAY,
    DataType.LONG: DataType.LONG_ARRAY,
    DataType.FLOAT: DataType.FLOAT_ARRAY,
    DataType.DOUBLE: DataType.DOUBLE_ARRAY,
    DataType.STRING: DataType.STRING_ARRAY,
}


def _field_datatype(ftype: Any, named: Dict[str, Any]) -> Tuple[DataType, bool]:
    """(stored type, single_value) for an Avro field type; unions of
    [null, T] unwrap to T (AvroUtils union handling)."""
    ftype = _resolve(ftype, named)
    if isinstance(ftype, list):
        non_null = [t for t in ftype if t != "null"]
        if not non_null:
            return DataType.STRING, True
        return _field_datatype(non_null[0], named)
    if isinstance(ftype, dict):
        t = ftype["type"]
        if t == "array":
            inner, _sv = _field_datatype(ftype["items"], named)
            return inner, False
        if t in _AVRO_TO_DATATYPE:
            return _AVRO_TO_DATATYPE[t], True
        return DataType.STRING, True
    return _AVRO_TO_DATATYPE.get(ftype, DataType.STRING), True


def avro_to_pinot_schema(
    path: str,
    table_name: Optional[str] = None,
    metrics: Sequence[str] = (),
    time_field: Optional[str] = None,
    time_unit: str = "DAYS",
) -> Schema:
    """Derive a Pinot schema from an Avro container's writer schema —
    the ``AvroUtils.getPinotSchemaFromAvroSchema`` role.  Fields default
    to dimensions; pass ``metrics``/``time_field`` to classify."""
    reader = AvroContainerReader(path)
    avro_schema = reader.schema
    if avro_schema.get("type") != "record":
        raise ValueError("top-level avro schema must be a record")
    named: Dict[str, Any] = {}
    _register_named(avro_schema, named)

    dims: List[FieldSpec] = []
    mets: List[FieldSpec] = []
    tf: Optional[TimeFieldSpec] = None
    for f in avro_schema["fields"]:
        name = f["name"]
        dt, sv = _field_datatype(f["type"], named)
        data_type = dt if sv else _SV_TO_MV.get(dt, DataType.STRING_ARRAY)
        if name == time_field:
            tf = TimeFieldSpec(name, dt, time_unit=time_unit)
        elif name in metrics:
            mets.append(FieldSpec(name, data_type, FieldType.METRIC, single_value=sv))
        else:
            dims.append(FieldSpec(name, data_type, FieldType.DIMENSION, single_value=sv))
    return Schema(
        table_name or avro_schema.get("name", "avroTable"),
        dimensions=dims,
        metrics=mets,
        time_field=tf,
    )


def pinot_to_avro_schema(schema: Schema) -> Dict[str, Any]:
    """Pinot schema -> Avro record schema (segment->Avro converter
    support, pinot-tools segment converters)."""
    type_map = {
        DataType.INT: "int",
        DataType.LONG: "long",
        DataType.FLOAT: "float",
        DataType.DOUBLE: "double",
        DataType.STRING: "string",
    }
    fields = []
    for spec in schema.all_fields():
        st = spec.stored_type
        base = type_map.get(st, "string")
        ftype: Any = base if spec.single_value else {"type": "array", "items": base}
        fields.append({"name": spec.name, "type": ["null", ftype]})
    return {"type": "record", "name": schema.schema_name, "fields": fields}
