"""Per-column sorted value dictionaries.

Mirrors the reference's ``ImmutableDictionaryReader`` family
(pinot-core ``segment/index/readers/ImmutableDictionaryReader.java:25``):
values are stored sorted, ``index_of`` is a binary search, and dictIds
are therefore *order-preserving* — which is what lets range predicates
become dictId-space comparisons on device.

Numeric dictionaries are numpy arrays (stageable into HBM); string
dictionaries stay host-side (only dictIds reach the device, group keys
are materialized back to strings at reduce time, as the reference does
at result build).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List, Optional, Sequence, Union

import numpy as np

from pinot_tpu.common.schema import DataType


class Dictionary:
    """Sorted, deduplicated value dictionary for one column."""

    def __init__(self, stored_type: DataType, values: Union[np.ndarray, List[str]]):
        self.stored_type = stored_type
        self.is_string = stored_type == DataType.STRING
        if self.is_string:
            self.values: Union[np.ndarray, List[str]] = list(values)
            self._np = np.asarray(self.values, dtype=object)
        else:
            self.values = np.asarray(values, dtype=stored_type.to_numpy())
            self._np = self.values

    @classmethod
    def build(cls, stored_type: DataType, raw_values: Sequence[Any]) -> "Dictionary":
        if stored_type == DataType.STRING:
            uniq = sorted(set(str(v) for v in raw_values))
            return cls(stored_type, uniq)
        arr = np.asarray(list(raw_values), dtype=stored_type.to_numpy())
        return cls(stored_type, np.unique(arr))

    def __len__(self) -> int:
        return len(self.values)

    @property
    def cardinality(self) -> int:
        return len(self.values)

    def value_array(self) -> np.ndarray:
        """Values as ONE reusable numpy array (object dtype for strings)
        — the vectorized-gather alternative to per-id ``get`` loops on
        the bulk distinct/partial-building paths."""
        return self._np

    def get(self, dict_id: int) -> Any:
        v = self.values[dict_id]
        if self.is_string:
            return v
        return v.item()

    def index_of(self, value: Any) -> int:
        """dictId of value, or -1 if absent (binary search,
        ImmutableDictionaryReader.java:39-55)."""
        i = self.insertion_index(value)
        if 0 <= i < len(self.values) and self._eq(self.values[i], value):
            return int(i)
        return -1

    def insertion_index(self, value: Any) -> int:
        """Index of the first element >= value (np.searchsorted 'left')."""
        if self.is_string:
            import bisect

            return bisect.bisect_left(self.values, str(value))
        return int(np.searchsorted(self.values, value, side="left"))

    def _eq(self, a: Any, b: Any) -> bool:
        if self.is_string:
            return a == str(b)
        return bool(a == b)

    def index_array(self, raw: np.ndarray) -> np.ndarray:
        """Vectorized index_of for building forward indexes (all values
        must be present)."""
        if self.is_string:
            lookup = {v: i for i, v in enumerate(self.values)}
            return np.fromiter((lookup[v] for v in raw), dtype=np.int32, count=len(raw))
        idx = np.searchsorted(self.values, raw)
        return idx.astype(np.int32)

    @property
    def min_value(self) -> Any:
        return self.get(0) if len(self.values) else None

    @property
    def max_value(self) -> Any:
        return self.get(len(self.values) - 1) if len(self.values) else None

    def numeric_array(self, dtype=np.float64) -> np.ndarray:
        """Dictionary values as a numeric array for device staging."""
        if self.is_string:
            raise TypeError("string dictionary has no numeric array")
        return np.asarray(self.values, dtype=dtype)
