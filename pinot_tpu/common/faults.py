"""Fault-injection transport wrapper for deterministic chaos tests.

Wraps any transport exposing ``request(address, payload, timeout)`` and
injects per-address faults *at the call site*, so the same scenarios run
against ``LocalTransport`` (in-process, deterministic) and
``TcpTransport`` (real sockets) without touching server code — the
ChaosMonkey analog, but seedable and replayable instead of killing OS
processes with signals.

Fault modes per address (composable):

- ``down``        — every request raises ``TransportError`` immediately
                    (dead server / connection refused).
- ``fail_next=n`` — the next ``n`` requests raise ``TransportError``,
                    then the address heals (transient blip).
- ``error_rate``  — each request fails with probability p, drawn from a
                    seeded RNG (flaky link; deterministic per seed).
- ``delay_s``     — sleep before forwarding (slow server / stragglers;
                    the hedged-request trigger).
- ``blackhole``   — sleep out the caller's full timeout budget, then
                    raise (packets dropped: no RST, just silence).

Every call is appended to ``calls`` (address, mode-applied) so tests can
assert exactly which replicas absorbed retries and hedges.
"""
from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from pinot_tpu.transport.tcp import TransportError

Address = Tuple[str, int]


@dataclass
class FaultSpec:
    down: bool = False
    fail_next: int = 0
    error_rate: float = 0.0
    delay_s: float = 0.0
    blackhole: bool = False


@dataclass
class CallRecord:
    address: Address
    outcome: str  # "ok" | "down" | "fail_next" | "error_rate" | "blackhole" | "error"
    latency_s: float = 0.0


class FaultInjectingTransport:
    """Decorator transport: same ``request`` interface as the inner one."""

    def __init__(self, inner, seed: int = 0) -> None:
        self.inner = inner
        self._rng = random.Random(seed)
        self._faults: Dict[Address, FaultSpec] = {}
        self._lock = threading.Lock()
        self.calls: List[CallRecord] = []

    # -- fault programming --------------------------------------------
    def set_fault(self, address: Address, **kwargs: Any) -> FaultSpec:
        """Program faults for one address, e.g. ``set_fault(a, down=True)``
        or ``set_fault(a, delay_s=0.5)``.  Unspecified modes reset."""
        spec = FaultSpec(**kwargs)
        with self._lock:
            self._faults[address] = spec
        return spec

    def clear_fault(self, address: Address) -> None:
        with self._lock:
            self._faults.pop(address, None)

    def clear_all(self) -> None:
        with self._lock:
            self._faults.clear()

    def calls_to(self, address: Address) -> List[CallRecord]:
        with self._lock:
            return [c for c in self.calls if c.address == address]

    # -- transport interface ------------------------------------------
    def request(self, address: Address, payload: bytes, timeout: float = 15.0) -> bytes:
        with self._lock:
            spec = self._faults.get(address)
            if spec is not None:
                if spec.down:
                    self.calls.append(CallRecord(address, "down"))
                    raise TransportError(f"injected: server {address} down")
                if spec.fail_next > 0:
                    spec.fail_next -= 1
                    self.calls.append(CallRecord(address, "fail_next"))
                    raise TransportError(f"injected: transient failure at {address}")
                if spec.error_rate > 0.0 and self._rng.random() < spec.error_rate:
                    self.calls.append(CallRecord(address, "error_rate"))
                    raise TransportError(f"injected: flaky link to {address}")
            delay = spec.delay_s if spec is not None else 0.0
            blackhole = spec.blackhole if spec is not None else False
        if blackhole:
            time.sleep(timeout)
            with self._lock:
                self.calls.append(CallRecord(address, "blackhole", timeout))
            raise TransportError(f"injected: request to {address} blackholed")
        if delay > 0.0:
            time.sleep(delay)
        t0 = time.perf_counter()
        try:
            reply = self.inner.request(address, payload, timeout=timeout)
        except Exception:
            with self._lock:
                self.calls.append(
                    CallRecord(address, "error", time.perf_counter() - t0 + delay)
                )
            raise
        with self._lock:
            self.calls.append(CallRecord(address, "ok", time.perf_counter() - t0 + delay))
        return reply
