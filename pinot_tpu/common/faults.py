"""Fault-injection transport wrapper for deterministic chaos tests.

Wraps any transport exposing ``request(address, payload, timeout)`` and
injects per-address faults *at the call site*, so the same scenarios run
against ``LocalTransport`` (in-process, deterministic) and
``TcpTransport`` (real sockets) without touching server code — the
ChaosMonkey analog, but seedable and replayable instead of killing OS
processes with signals.

Fault modes per address (composable):

- ``down``        — every request raises ``TransportError`` immediately
                    (dead server / connection refused).
- ``fail_next=n`` — the next ``n`` requests raise ``TransportError``,
                    then the address heals (transient blip).
- ``error_rate``  — each request fails with probability p, drawn from a
                    seeded RNG (flaky link; deterministic per seed).
- ``delay_s``     — sleep before forwarding (slow server / stragglers;
                    the hedged-request trigger).
- ``blackhole``   — sleep out the caller's full timeout budget, then
                    raise (packets dropped: no RST, just silence).

Every call is appended to ``calls`` (address, mode-applied) so tests can
assert exactly which replicas absorbed retries and hedges.

``DeviceFaultInjector`` is the same idea one layer down: it hooks the
server's DeviceLane (``engine/dispatch.py``) and injects *device-side*
faults — failed launches (retryable or poison), stalls that wedge the
lane thread (the watchdog trigger), and per-plan-digest poisoning — so
the self-healing path (device retry, watchdog restart, host failover,
poison quarantine) runs deterministically on a CPU test rig.
"""
from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from pinot_tpu.transport.tcp import TransportError

Address = Tuple[str, int]


@dataclass
class FaultSpec:
    down: bool = False
    fail_next: int = 0
    error_rate: float = 0.0
    delay_s: float = 0.0
    blackhole: bool = False


@dataclass
class CallRecord:
    address: Address
    outcome: str  # "ok" | "down" | "fail_next" | "error_rate" | "blackhole" | "error"
    latency_s: float = 0.0


class FaultInjectingTransport:
    """Decorator transport: same ``request`` interface as the inner one."""

    def __init__(self, inner, seed: int = 0) -> None:
        self.inner = inner
        self._rng = random.Random(seed)
        self._faults: Dict[Address, FaultSpec] = {}
        self._lock = threading.Lock()
        self.calls: List[CallRecord] = []

    # -- fault programming --------------------------------------------
    def set_fault(self, address: Address, **kwargs: Any) -> FaultSpec:
        """Program faults for one address, e.g. ``set_fault(a, down=True)``
        or ``set_fault(a, delay_s=0.5)``.  Unspecified modes reset."""
        spec = FaultSpec(**kwargs)
        with self._lock:
            self._faults[address] = spec
        return spec

    def clear_fault(self, address: Address) -> None:
        with self._lock:
            self._faults.pop(address, None)

    def clear_all(self) -> None:
        with self._lock:
            self._faults.clear()

    def calls_to(self, address: Address) -> List[CallRecord]:
        with self._lock:
            return [c for c in self.calls if c.address == address]

    # -- transport interface ------------------------------------------
    def request(self, address: Address, payload: bytes, timeout: float = 15.0) -> bytes:
        with self._lock:
            spec = self._faults.get(address)
            if spec is not None:
                if spec.down:
                    self.calls.append(CallRecord(address, "down"))
                    raise TransportError(f"injected: server {address} down")
                if spec.fail_next > 0:
                    spec.fail_next -= 1
                    self.calls.append(CallRecord(address, "fail_next"))
                    raise TransportError(f"injected: transient failure at {address}")
                if spec.error_rate > 0.0 and self._rng.random() < spec.error_rate:
                    self.calls.append(CallRecord(address, "error_rate"))
                    raise TransportError(f"injected: flaky link to {address}")
            delay = spec.delay_s if spec is not None else 0.0
            blackhole = spec.blackhole if spec is not None else False
        if blackhole:
            time.sleep(timeout)
            with self._lock:
                self.calls.append(CallRecord(address, "blackhole", timeout))
            raise TransportError(f"injected: request to {address} blackholed")
        if delay > 0.0:
            time.sleep(delay)
        t0 = time.perf_counter()
        try:
            reply = self.inner.request(address, payload, timeout=timeout)
        except Exception:
            with self._lock:
                self.calls.append(
                    CallRecord(address, "error", time.perf_counter() - t0 + delay)
                )
            raise
        with self._lock:
            self.calls.append(CallRecord(address, "ok", time.perf_counter() - t0 + delay))
        return reply


# ---------------------------------------------------------------------------
# Device-side fault injection (the lane-supervision chaos hook)
# ---------------------------------------------------------------------------


@dataclass
class LaunchRecord:
    """One lane launch as seen by the injector (digest is the StaticPlan
    digest the executor handed the lane; None for raw key-only
    submits)."""

    digest: Optional[str]
    outcome: str  # "ok" | "fail_next" | "error_rate" | "poison" | "stall"


class DeviceFaultInjector:
    """Seedable device-fault programming for the DeviceLane.

    Modes (composable, mirroring the transport injector):

    - ``fail_next(n, retryable=True)`` — the next ``n`` launches raise a
      typed ``DeviceExecutionError`` (transient blip or hard fault).
    - ``stall_next(n, stall_s)``      — the next ``n`` launches sleep
      ``stall_s`` inside the lane thread before proceeding (the
      watchdog-restart trigger when ``stall_s`` exceeds the lane's
      stall timeout).
    - ``poison_plan(digest)``         — every launch whose StaticPlan
      digest matches raises a non-retryable poison error until
      ``heal()``; the executor's quarantine is expected to stop sending
      the plan to the device at all.
    - ``error_rate``                  — each launch fails (retryable)
      with probability p from a seeded RNG.

    Every launch decision is recorded in ``launches`` so tests can
    assert which plans were poisoned/stalled and read back digests.
    """

    def __init__(self, seed: int = 0) -> None:
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.launches: List[LaunchRecord] = []
        self._fail_next = 0
        self._fail_retryable = True
        self._stall_next = 0
        self._stall_s = 0.0
        self._poisoned: set = set()
        self.error_rate = 0.0

    # -- fault programming --------------------------------------------
    def fail_next(self, n: int, retryable: bool = True) -> None:
        with self._lock:
            self._fail_next = n
            self._fail_retryable = retryable

    def stall_next(self, n: int, stall_s: float) -> None:
        with self._lock:
            self._stall_next = n
            self._stall_s = stall_s

    def poison_plan(self, digest: str) -> None:
        with self._lock:
            self._poisoned.add(digest)

    def heal(self) -> None:
        with self._lock:
            self._fail_next = 0
            self._stall_next = 0
            self._stall_s = 0.0
            self._poisoned.clear()
            self.error_rate = 0.0

    def records_for(self, outcome: str) -> List[LaunchRecord]:
        with self._lock:
            return [r for r in self.launches if r.outcome == outcome]

    # -- lane hook -----------------------------------------------------
    def on_launch(self, digest: Optional[str], key: Any) -> None:
        """Called by the lane thread immediately before a launch; may
        sleep (stall) or raise ``DeviceExecutionError``."""
        from pinot_tpu.engine.dispatch import DeviceExecutionError

        with self._lock:
            if digest is not None and digest in self._poisoned:
                self.launches.append(LaunchRecord(digest, "poison"))
                raise DeviceExecutionError(
                    f"injected: poisoned plan {digest}", retryable=False
                )
            if self._fail_next > 0:
                self._fail_next -= 1
                retryable = self._fail_retryable
                self.launches.append(LaunchRecord(digest, "fail_next"))
                raise DeviceExecutionError(
                    "injected: device launch failure", retryable=retryable
                )
            if self.error_rate > 0.0 and self._rng.random() < self.error_rate:
                self.launches.append(LaunchRecord(digest, "error_rate"))
                raise DeviceExecutionError(
                    "injected: flaky device launch", retryable=True
                )
            stall = 0.0
            if self._stall_next > 0:
                self._stall_next -= 1
                stall = self._stall_s
                self.launches.append(LaunchRecord(digest, "stall"))
            else:
                self.launches.append(LaunchRecord(digest, "ok"))
        if stall > 0.0:
            # sleep OUTSIDE the injector lock, inside the lane thread:
            # this is the wedge the watchdog must detect
            time.sleep(stall)
