"""Fault-injection transport wrapper for deterministic chaos tests.

Wraps any transport exposing ``request(address, payload, timeout)`` and
injects per-address faults *at the call site*, so the same scenarios run
against ``LocalTransport`` (in-process, deterministic) and
``TcpTransport`` (real sockets) without touching server code — the
ChaosMonkey analog, but seedable and replayable instead of killing OS
processes with signals.

Fault modes per address (composable):

- ``down``        — every request raises ``TransportError`` immediately
                    (dead server / connection refused).
- ``fail_next=n`` — the next ``n`` requests raise ``TransportError``,
                    then the address heals (transient blip).
- ``error_rate``  — each request fails with probability p, drawn from a
                    seeded RNG (flaky link; deterministic per seed).
- ``delay_s``     — sleep before forwarding (slow server / stragglers;
                    the hedged-request trigger).
- ``blackhole``   — sleep out the caller's full timeout budget, then
                    raise (packets dropped: no RST, just silence).

Every call is appended to ``calls`` (address, mode-applied) so tests can
assert exactly which replicas absorbed retries and hedges.

``DeviceFaultInjector`` is the same idea one layer down: it hooks the
server's DeviceLane (``engine/dispatch.py``) and injects *device-side*
faults — failed launches (retryable or poison), stalls that wedge the
lane thread (the watchdog trigger), and per-plan-digest poisoning — so
the self-healing path (device retry, watchdog restart, host failover,
poison quarantine) runs deterministically on a CPU test rig.

``NetworkFaultInjector`` is the partition layer: it models the NETWORK
between roles as directed links keyed by instance NAME (``src -> dst``)
rather than one server's address, so a single injector shared by every
role-pair transport (broker<->server scatter, server<->controller
heartbeat/commit/fetch, broker<->controller clusterstate poll) can cut,
delay, duplicate, or one-way-partition any link in the cluster.  The
physical model is per-DIRECTION packet loss: cutting ``a -> b`` loses
a's requests before they reach b (and b's replies to a ride ``b -> a``,
so cutting only that direction delivers a's request — side effects
happen at b! — and then loses the reply, which is exactly the
asymmetric-partition shape that makes lease fencing necessary).
"""
from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from pinot_tpu.transport.tcp import TransportError

Address = Tuple[str, int]


@dataclass
class FaultSpec:
    down: bool = False
    fail_next: int = 0
    error_rate: float = 0.0
    delay_s: float = 0.0
    blackhole: bool = False


@dataclass
class CallRecord:
    address: Address
    outcome: str  # "ok" | "down" | "fail_next" | "error_rate" | "blackhole" | "error"
    latency_s: float = 0.0


class FaultInjectingTransport:
    """Decorator transport: same ``request`` interface as the inner one."""

    def __init__(self, inner, seed: int = 0) -> None:
        self.inner = inner
        self._rng = random.Random(seed)
        self._faults: Dict[Address, FaultSpec] = {}
        self._lock = threading.Lock()
        self.calls: List[CallRecord] = []

    # -- fault programming --------------------------------------------
    def set_fault(self, address: Address, **kwargs: Any) -> FaultSpec:
        """Program faults for one address, e.g. ``set_fault(a, down=True)``
        or ``set_fault(a, delay_s=0.5)``.  Unspecified modes reset."""
        spec = FaultSpec(**kwargs)
        with self._lock:
            self._faults[address] = spec
        return spec

    def clear_fault(self, address: Address) -> None:
        with self._lock:
            self._faults.pop(address, None)

    def clear_all(self) -> None:
        with self._lock:
            self._faults.clear()

    def calls_to(self, address: Address) -> List[CallRecord]:
        with self._lock:
            return [c for c in self.calls if c.address == address]

    # -- transport interface ------------------------------------------
    def request(self, address: Address, payload: bytes, timeout: float = 15.0) -> bytes:
        with self._lock:
            spec = self._faults.get(address)
            if spec is not None:
                if spec.down:
                    self.calls.append(CallRecord(address, "down"))
                    raise TransportError(f"injected: server {address} down")
                if spec.fail_next > 0:
                    spec.fail_next -= 1
                    self.calls.append(CallRecord(address, "fail_next"))
                    raise TransportError(f"injected: transient failure at {address}")
                if spec.error_rate > 0.0 and self._rng.random() < spec.error_rate:
                    self.calls.append(CallRecord(address, "error_rate"))
                    raise TransportError(f"injected: flaky link to {address}")
            delay = spec.delay_s if spec is not None else 0.0
            blackhole = spec.blackhole if spec is not None else False
        if blackhole:
            time.sleep(timeout)
            with self._lock:
                self.calls.append(CallRecord(address, "blackhole", timeout))
            raise TransportError(f"injected: request to {address} blackholed")
        if delay > 0.0:
            time.sleep(delay)
        t0 = time.perf_counter()
        try:
            reply = self.inner.request(address, payload, timeout=timeout)
        except Exception:
            with self._lock:
                self.calls.append(
                    CallRecord(address, "error", time.perf_counter() - t0 + delay)
                )
            raise
        with self._lock:
            self.calls.append(CallRecord(address, "ok", time.perf_counter() - t0 + delay))
        return reply


# ---------------------------------------------------------------------------
# Device-side fault injection (the lane-supervision chaos hook)
# ---------------------------------------------------------------------------


@dataclass
class LaunchRecord:
    """One lane launch as seen by the injector (digest is the StaticPlan
    digest the executor handed the lane; None for raw key-only
    submits)."""

    digest: Optional[str]
    # "ok" | "fail_next" | "alloc_fail" | "error_rate" | "alloc_rate"
    # | "poison" | "stall" | "corrupt"
    outcome: str


class DeviceFaultInjector:
    """Seedable device-fault programming for the DeviceLane.

    Modes (composable, mirroring the transport injector):

    - ``fail_next(n, retryable=True)`` — the next ``n`` launches raise a
      typed ``DeviceExecutionError`` (transient blip or hard fault).
    - ``alloc_fail_next(n)``          — the next ``n`` launches raise a
      RAW RuntimeError with PJRT's RESOURCE_EXHAUSTED wording, so the
      executor's real ``classify_device_error`` path produces the
      ``resource_exhausted`` heal class (demote-then-retry, never
      poison) exactly as a full HBM would — deterministically testable
      without a real device.
    - ``stall_next(n, stall_s)``      — the next ``n`` launches sleep
      ``stall_s`` inside the lane thread before proceeding (the
      watchdog-restart trigger when ``stall_s`` exceeds the lane's
      stall timeout).
    - ``poison_plan(digest)``         — every launch whose StaticPlan
      digest matches raises a non-retryable poison error until
      ``heal()``; the executor's quarantine is expected to stop sending
      the plan to the device at all.
    - ``error_rate``                  — each launch fails (retryable)
      with probability p from a seeded RNG.
    - ``corrupt_results(n, tier=..., digest_substring=..., delta=...)``
      — WRONG-ANSWER injection for the audit plane (utils/audit.py):
      unlike every mode above, a corrupted execution SUCCEEDS — the
      executor consults ``check_corrupt`` after the tier produced its
      result and perturbs one numeric aggregation partial by ``delta``.
      No error is raised, so the self-healing ladder (retry, failover,
      poison) can NEVER catch it; only the shadow differential audit
      can.  The host tier is never corrupted (it is the oracle).
    - ``alloc_error_rate``            — each launch raises the raw
      RESOURCE_EXHAUSTED error with probability p from the same seeded
      RNG (sustained memory pressure, not a one-shot).

    Every launch decision is recorded in ``launches`` so tests can
    assert which plans were poisoned/stalled and read back digests.
    """

    def __init__(self, seed: int = 0) -> None:
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.launches: List[LaunchRecord] = []
        self._fail_next = 0
        self._fail_retryable = True
        self._alloc_fail_next = 0
        self._stall_next = 0
        self._stall_s = 0.0
        self._poisoned: set = set()
        self.error_rate = 0.0
        self.alloc_error_rate = 0.0
        self._corrupt_next = 0
        self._corrupt_tier = ""
        self._corrupt_digest = ""
        self._corrupt_delta = 1.0

    # -- fault programming --------------------------------------------
    def fail_next(self, n: int, retryable: bool = True) -> None:
        with self._lock:
            self._fail_next = n
            self._fail_retryable = retryable

    def alloc_fail_next(self, n: int) -> None:
        with self._lock:
            self._alloc_fail_next = n

    def stall_next(self, n: int, stall_s: float) -> None:
        with self._lock:
            self._stall_next = n
            self._stall_s = stall_s

    def poison_plan(self, digest: str) -> None:
        with self._lock:
            self._poisoned.add(digest)

    def corrupt_results(
        self,
        n: int = 1,
        tier: str = "",
        digest_substring: str = "",
        delta: float = 1.0,
    ) -> None:
        """Arm wrong-answer injection: the next ``n`` executions whose
        serving tier matches ``tier`` (empty = any non-host tier) and
        whose plan-shape digest contains ``digest_substring`` get one
        numeric aggregation partial perturbed by ``delta``."""
        with self._lock:
            self._corrupt_next = n
            self._corrupt_tier = tier
            self._corrupt_digest = digest_substring
            self._corrupt_delta = delta

    @property
    def corruption_armed(self) -> bool:
        """Cheap pre-check so the executor only derives a plan digest
        for the consult when a corruption budget is actually armed."""
        return self._corrupt_next > 0

    def check_corrupt(self, plan_digest: Optional[str], tier: str) -> Optional[float]:
        """Executor consult after a tier produced a result: the delta to
        apply, or None.  Decrements the armed budget on a match."""
        with self._lock:
            if self._corrupt_next <= 0:
                return None
            if tier == "host":
                return None  # the oracle stays correct, always
            if self._corrupt_tier and tier != self._corrupt_tier:
                return None
            if self._corrupt_digest and self._corrupt_digest not in (
                plan_digest or ""
            ):
                return None
            self._corrupt_next -= 1
            self.launches.append(LaunchRecord(plan_digest, "corrupt"))
            return self._corrupt_delta

    def heal(self) -> None:
        with self._lock:
            self._fail_next = 0
            self._alloc_fail_next = 0
            self._stall_next = 0
            self._stall_s = 0.0
            self._poisoned.clear()
            self.error_rate = 0.0
            self.alloc_error_rate = 0.0
            self._corrupt_next = 0
            self._corrupt_tier = ""
            self._corrupt_digest = ""
            self._corrupt_delta = 1.0

    def records_for(self, outcome: str) -> List[LaunchRecord]:
        with self._lock:
            return [r for r in self.launches if r.outcome == outcome]

    # -- lane hook -----------------------------------------------------
    def on_launch(self, digest: Optional[str], key: Any) -> None:
        """Called by the lane thread immediately before a launch; may
        sleep (stall) or raise ``DeviceExecutionError``."""
        from pinot_tpu.engine.dispatch import DeviceExecutionError

        with self._lock:
            if digest is not None and digest in self._poisoned:
                self.launches.append(LaunchRecord(digest, "poison"))
                raise DeviceExecutionError(
                    f"injected: poisoned plan {digest}", retryable=False
                )
            if self._alloc_fail_next > 0:
                self._alloc_fail_next -= 1
                self.launches.append(LaunchRecord(digest, "alloc_fail"))
                # a RAW error, not a pre-typed DeviceExecutionError: the
                # executor must exercise its real classification path
                # (dispatch.classify_device_error -> resource_exhausted)
                raise RuntimeError(
                    "injected: RESOURCE_EXHAUSTED: out of memory while "
                    "allocating device buffer"
                )
            if self._fail_next > 0:
                self._fail_next -= 1
                retryable = self._fail_retryable
                self.launches.append(LaunchRecord(digest, "fail_next"))
                raise DeviceExecutionError(
                    "injected: device launch failure", retryable=retryable
                )
            if (
                self.alloc_error_rate > 0.0
                and self._rng.random() < self.alloc_error_rate
            ):
                self.launches.append(LaunchRecord(digest, "alloc_rate"))
                raise RuntimeError(
                    "injected: RESOURCE_EXHAUSTED: out of memory while "
                    "allocating device buffer"
                )
            if self.error_rate > 0.0 and self._rng.random() < self.error_rate:
                self.launches.append(LaunchRecord(digest, "error_rate"))
                raise DeviceExecutionError(
                    "injected: flaky device launch", retryable=True
                )
            stall = 0.0
            if self._stall_next > 0:
                self._stall_next -= 1
                stall = self._stall_s
                self.launches.append(LaunchRecord(digest, "stall"))
            else:
                self.launches.append(LaunchRecord(digest, "ok"))
        if stall > 0.0:
            # sleep OUTSIDE the injector lock, inside the lane thread:
            # this is the wedge the watchdog must detect
            time.sleep(stall)


def apply_result_corruption(result, delta: float) -> bool:
    """Perturb one numeric field of ``result``'s first aggregation
    partial (scalar list or first group) in place — the wrong-answer the
    armed ``corrupt_results`` mode injects.  Returns True when a field
    was actually perturbed (selection-only results have no numeric
    partial to corrupt and stay untouched)."""
    partials = None
    aggs = getattr(result, "aggregations", None)
    if aggs:
        partials = aggs
    else:
        groups = getattr(result, "groups", None)
        if groups:
            partials = groups[next(iter(groups))]
    if not partials:
        return False
    p = partials[0]
    for attr in ("count", "total", "value", "mn", "mx"):
        v = getattr(p, attr, None)
        if isinstance(v, float):
            setattr(p, attr, v + float(delta))
            return True
    return False


# ---------------------------------------------------------------------------
# Link-level network fault injection (the partition-tolerance chaos hook)
# ---------------------------------------------------------------------------

# the controller's link name: every role-pair link has instance names at
# both ends, and the controller is a singleton role
CONTROLLER_LINK = "controller"


class PartitionedLinkError(TransportError):
    """Injected: the packet (request or reply) died on a cut link."""


@dataclass
class LinkSpec:
    """Quality degradation for one directed link (``src -> dst``).
    A cut link is tracked separately (``NetworkFaultInjector.cut``)."""

    delay_s: float = 0.0
    duplicate: bool = False  # deliver the request twice (at-least-once wire)
    error_rate: float = 0.0  # flaky link: seeded per-call loss probability


@dataclass
class LinkEvent:
    src: str
    dst: str
    # "ok" | "dropped" | "replyDropped" | "delayed" | "duplicated" | "flaky"
    outcome: str


class NetworkFaultInjector:
    """Seedable, name-keyed link-fault programming for EVERY role pair.

    One injector instance is shared by all the transports/HTTP clients
    of a cluster under test; each call site identifies itself with
    ``(src, dst)`` instance names and routes its RPC through ``call``:

    - ``cut(a, b)``                — packets a->b are dropped: a's
      requests to b raise ``PartitionedLinkError`` WITHOUT reaching b.
    - ``cut(b, a)`` (reply path)   — a's requests reach b (side effects
      happen!), but the reply is lost: a still sees a transport error.
      This is the one-way partition that distinguishes a live-but-
      unreachable server from a dead one.
    - ``partition(a, b)``          — both directions (symmetric cut).
    - ``set_link(a, b, ...)``      — delay / duplicate / seeded flaky
      loss on a live link.
    - ``heal(...)``                — clear one link, every link touching
      a node, or everything.

    Every decision is recorded in ``events`` (and optionally marked on a
    per-role metrics registry as ``netfaults.*``) so chaos tests can
    assert exactly which links absorbed the injected weather.
    """

    _EVENT_RING = 4096  # bounded: long harness runs must not grow RAM

    def __init__(self, seed: int = 0, metrics=None) -> None:
        from collections import deque

        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._cuts: set = set()  # directed (src, dst) pairs
        self._links: Dict[Tuple[str, str], LinkSpec] = {}
        self.events = deque(maxlen=self._EVENT_RING)
        # fallback registry; call sites pass their ROLE's registry per
        # call so netfaults.* lands on the role that saw the weather
        self.metrics = metrics

    # -- fault programming --------------------------------------------
    def cut(self, src: str, dst: str) -> None:
        """Drop packets flowing ``src -> dst`` (one direction only)."""
        with self._lock:
            self._cuts.add((src, dst))

    def partition(self, a: str, b: str) -> None:
        """Symmetric partition: no packets flow between ``a`` and ``b``."""
        with self._lock:
            self._cuts.add((a, b))
            self._cuts.add((b, a))

    def set_link(self, src: str, dst: str, **kwargs: Any) -> LinkSpec:
        spec = LinkSpec(**kwargs)
        with self._lock:
            self._links[(src, dst)] = spec
        return spec

    def heal(self, src: Optional[str] = None, dst: Optional[str] = None) -> None:
        """``heal()`` clears everything; ``heal(node)`` clears every cut
        and spec touching ``node``; ``heal(src, dst)`` clears that one
        directed link."""
        with self._lock:
            if src is None:
                self._cuts.clear()
                self._links.clear()
            elif dst is None:
                self._cuts = {c for c in self._cuts if src not in c}
                self._links = {
                    k: v for k, v in self._links.items() if src not in k
                }
            else:
                self._cuts.discard((src, dst))
                self._links.pop((src, dst), None)

    def is_cut(self, src: str, dst: str) -> bool:
        with self._lock:
            return (src, dst) in self._cuts

    def events_for(self, src: str, dst: str) -> List[LinkEvent]:
        with self._lock:
            return [e for e in self.events if e.src == src and e.dst == dst]

    def _record(self, src: str, dst: str, outcome: str, metrics=None) -> None:
        with self._lock:
            self.events.append(LinkEvent(src, dst, outcome))
        m = metrics if metrics is not None else self.metrics
        if m is not None and outcome != "ok":
            m.meter(f"netfaults.{outcome}").mark()

    # -- the one call-site hook ----------------------------------------
    def call(self, src: str, dst: str, fn, metrics=None):
        """Run one RPC (``fn``) over the ``src -> dst`` link.

        May raise ``PartitionedLinkError`` WITHOUT invoking ``fn``
        (request lost), may invoke ``fn`` and then raise (reply lost on
        the cut ``dst -> src`` direction — the asymmetric case), may
        sleep first (delay), may invoke ``fn`` twice and return the
        SECOND reply (duplicate delivery: upstream handlers must be
        idempotent — exactly what the at-least-once message board and
        the epoch/lease commit fences are for).  ``metrics`` is the
        CALLING role's registry for the ``netfaults.*`` attribution."""
        with self._lock:
            request_cut = (src, dst) in self._cuts
            reply_cut = (dst, src) in self._cuts
            spec = self._links.get((src, dst))
            flaky = (
                spec is not None
                and spec.error_rate > 0.0
                and self._rng.random() < spec.error_rate
            )
        if request_cut:
            self._record(src, dst, "dropped", metrics)
            raise PartitionedLinkError(f"injected: link {src}->{dst} is cut")
        if flaky:
            self._record(src, dst, "flaky", metrics)
            raise PartitionedLinkError(f"injected: flaky link {src}->{dst}")
        if spec is not None and spec.delay_s > 0.0:
            self._record(src, dst, "delayed", metrics)
            time.sleep(spec.delay_s)
        if spec is not None and spec.duplicate:
            # duplicate delivery: the first invocation's reply is
            # discarded, as a retransmitted request's would be
            self._record(src, dst, "duplicated", metrics)
            fn()
        reply = fn()
        if reply_cut:
            # the request executed at dst; the caller never learns
            self._record(src, dst, "replyDropped", metrics)
            raise PartitionedLinkError(
                f"injected: reply lost on cut link {dst}->{src}"
            )
        self._record(src, dst, "ok", metrics)
        return reply


def call_on_controller_link(injector, src: str, fn, metrics=None):
    """Shared call-site helper: run one controller-bound RPC through
    ``injector`` as link ``src -> controller`` (plain call when no
    injector is wired).  Used by both networked starters and the
    gateway edge so the link contract lives in one place."""
    if injector is None:
        return fn()
    return injector.call(src, CONTROLLER_LINK, fn, metrics=metrics)


class LinkFaultTransport:
    """Transport decorator consulting a ``NetworkFaultInjector`` per
    request — the broker<->server scatter hook.  ``resolve`` maps a
    transport address to the destination's instance name; the default
    takes ``address[0]``, which IS the name for ``LocalTransport``
    addresses (networked brokers pass a reverse lookup over their
    server-address map)."""

    def __init__(
        self, inner, injector: NetworkFaultInjector, src: str, resolve=None,
        metrics=None,
    ) -> None:
        self.inner = inner
        self.injector = injector
        self.src = src
        self.metrics = metrics  # the owning role's registry (netfaults.*)
        self._resolve = resolve or (lambda address: str(address[0]))

    def request(self, address: Address, payload: bytes, timeout: float = 15.0) -> bytes:
        dst = self._resolve(address)
        return self.injector.call(
            self.src,
            dst,
            lambda: self.inner.request(address, payload, timeout=timeout),
            metrics=self.metrics,
        )
