"""DataTable: the server->broker binary wire format.

The reference ships per-server partial results as a custom versioned
binary ``DataTable`` (pinot-common ``common/utils/DataTable.java:44`` —
layout comment at :325) with special-cased serialization for
aggregation intermediates (``DataTableCustomSerDe.java:49``, which
Java-serializes HLL objects and value lists).

This implementation serializes ``IntermediateResult`` directly:

    [0:8]   magic  b"PTDTBL01"
    [8:16]  uint64 payload length
    payload: tagged binary encoding (below)

Aggregation intermediates are fixed-size numeric state wherever
possible: HLL -> raw 256-byte register array, percentiles -> value/count
histogram arrays, distinct-count -> typed value arrays — all strictly
smaller than the reference's Java-serialized objects, and losslessly
mergeable at the broker.

Value codec tags: N=None i=int(8) f=float(8) s=str T=True F=False
l=list t=tuple — length-prefixed, recursive.
"""
from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from pinot_tpu.engine.results import (
    AggPartial,
    AvgPartial,
    CountPartial,
    DistinctPartial,
    HistogramPartial,
    HllPartial,
    IntermediateResult,
    MaxPartial,
    MinMaxRangePartial,
    MinPartial,
    SumPartial,
)

MAGIC = b"PTDTBL01"


class _Writer:
    def __init__(self) -> None:
        self.parts: List[bytes] = []

    def u8(self, v: int) -> None:
        self.parts.append(struct.pack("<B", v))

    def i64(self, v: int) -> None:
        self.parts.append(struct.pack("<q", int(v)))

    def f64(self, v: float) -> None:
        self.parts.append(struct.pack("<d", float(v)))

    def blob(self, b: bytes) -> None:
        self.i64(len(b))
        self.parts.append(b)

    def string(self, s: str) -> None:
        self.blob(s.encode("utf-8"))

    def value(self, v: Any) -> None:
        """Tagged arbitrary (JSON-ish) value."""
        if v is None:
            self.parts.append(b"N")
        elif isinstance(v, bool):
            self.parts.append(b"T" if v else b"F")
        elif isinstance(v, (int, np.integer)):
            self.parts.append(b"i")
            self.i64(int(v))
        elif isinstance(v, (float, np.floating)):
            self.parts.append(b"f")
            self.f64(float(v))
        elif isinstance(v, str):
            self.parts.append(b"s")
            self.string(v)
        elif isinstance(v, (list, tuple)):
            self.parts.append(b"l")
            self.i64(len(v))
            for x in v:
                self.value(x)
        elif isinstance(v, dict):
            self.parts.append(b"d")
            self.i64(len(v))
            for k, x in v.items():
                self.string(str(k))
                self.value(x)
        elif isinstance(v, np.ndarray):
            # 'a': typed binary array — the join-exchange payloads ship
            # columnar key/value arrays through the same tagged codec
            # (orders of magnitude tighter than per-element 'i' tags)
            self.parts.append(b"a")
            self.array(v)
        else:
            raise TypeError(f"unsupported wire value {type(v)}")

    def array(self, a: np.ndarray) -> None:
        a = np.ascontiguousarray(a)
        self.string(str(a.dtype))
        self.i64(a.size)
        self.parts.append(a.tobytes())

    def getvalue(self) -> bytes:
        return b"".join(self.parts)


class _Reader:
    def __init__(self, data: bytes) -> None:
        self.data = data
        self.pos = 0

    def u8(self) -> int:
        v = struct.unpack_from("<B", self.data, self.pos)[0]
        self.pos += 1
        return v

    def i64(self) -> int:
        v = struct.unpack_from("<q", self.data, self.pos)[0]
        self.pos += 8
        return v

    def f64(self) -> float:
        v = struct.unpack_from("<d", self.data, self.pos)[0]
        self.pos += 8
        return v

    def blob(self) -> bytes:
        n = self.i64()
        b = self.data[self.pos : self.pos + n]
        self.pos += n
        return b

    def string(self) -> str:
        return self.blob().decode("utf-8")

    def value(self) -> Any:
        tag = self.data[self.pos : self.pos + 1]
        self.pos += 1
        if tag == b"N":
            return None
        if tag == b"T":
            return True
        if tag == b"F":
            return False
        if tag == b"i":
            return self.i64()
        if tag == b"f":
            return self.f64()
        if tag == b"s":
            return self.string()
        if tag == b"l":
            n = self.i64()
            return [self.value() for _ in range(n)]
        if tag == b"d":
            n = self.i64()
            return {self.string(): self.value() for _ in range(n)}
        if tag == b"a":
            return self.array()
        raise ValueError(f"bad value tag {tag!r} at {self.pos}")

    def array(self) -> np.ndarray:
        dtype = np.dtype(self.string())
        n = self.i64()
        nbytes = dtype.itemsize * n
        a = np.frombuffer(self.data[self.pos : self.pos + nbytes], dtype=dtype).copy()
        self.pos += nbytes
        return a


# ---------------------------------------------------------------------------
# Partial serde (type tag + state)
# ---------------------------------------------------------------------------

_PARTIAL_TAGS = {
    CountPartial: 1,
    SumPartial: 2,
    MinPartial: 3,
    MaxPartial: 4,
    AvgPartial: 5,
    MinMaxRangePartial: 6,
    DistinctPartial: 7,
    HllPartial: 8,
    HistogramPartial: 9,
}


def _write_partial(w: _Writer, p: AggPartial) -> None:
    tag = _PARTIAL_TAGS[type(p)]
    w.u8(tag)
    if isinstance(p, CountPartial):
        w.f64(p.count)
    elif isinstance(p, SumPartial):
        w.f64(p.total)
    elif isinstance(p, (MinPartial, MaxPartial)):
        w.f64(p.value)
    elif isinstance(p, AvgPartial):
        w.f64(p.total)
        w.f64(p.count)
    elif isinstance(p, MinMaxRangePartial):
        w.f64(p.mn)
        w.f64(p.mx)
    elif isinstance(p, DistinctPartial):
        w.i64(p.finalize())
        for v in p.iter_sorted():
            w.value(v)
    elif isinstance(p, HllPartial):
        w.blob(p.registers.tobytes())
    elif isinstance(p, HistogramPartial):
        w.i64(p.percentile)
        items = sorted(p.counts.items())
        w.array(np.asarray([v for v, _ in items], dtype=np.float64))
        w.array(np.asarray([c for _, c in items], dtype=np.int64))


def _read_partial(r: _Reader) -> AggPartial:
    tag = r.u8()
    if tag == 1:
        return CountPartial(r.f64())
    if tag == 2:
        return SumPartial(r.f64())
    if tag == 3:
        return MinPartial(r.f64())
    if tag == 4:
        return MaxPartial(r.f64())
    if tag == 5:
        return AvgPartial(r.f64(), r.f64())
    if tag == 6:
        return MinMaxRangePartial(r.f64(), r.f64())
    if tag == 7:
        n = r.i64()
        return DistinctPartial({r.value() for _ in range(n)})
    if tag == 8:
        regs = np.frombuffer(r.blob(), dtype=np.uint8).copy()
        return HllPartial(regs)
    if tag == 9:
        p = r.i64()
        vals = r.array()
        counts = r.array()
        return HistogramPartial(
            {float(v): int(c) for v, c in zip(vals, counts)}, percentile=p
        )
    raise ValueError(f"bad partial tag {tag}")


# ---------------------------------------------------------------------------
# IntermediateResult <-> bytes
# ---------------------------------------------------------------------------


def serialize_result(res: IntermediateResult) -> bytes:
    w = _Writer()
    w.i64(res.num_docs_scanned)
    w.i64(res.total_docs)
    w.i64(res.num_segments_queried)
    w.i64(res.num_entries_scanned_in_filter)
    w.i64(res.num_entries_scanned_post_filter)
    w.value(sorted(res.trace.items()) if res.trace else [])
    w.value([[int(c), str(m)] for c, m in res.exceptions])
    w.value([str(s) for s in res.unserved_segments])

    # sections present flags
    w.u8(1 if res.aggregations is not None else 0)
    if res.aggregations is not None:
        w.i64(len(res.aggregations))
        for p in res.aggregations:
            _write_partial(w, p)

    w.u8(1 if res.groups is not None else 0)
    if res.groups is not None:
        w.i64(len(res.groups))
        for key, partials in res.groups.items():
            w.value(list(key))
            w.i64(len(partials))
            for p in partials:
                _write_partial(w, p)

    w.u8(1 if res.selection_rows is not None else 0)
    if res.selection_rows is not None:
        w.value(res.selection_columns or [])
        w.i64(len(res.selection_rows))
        for sort_vals, row in res.selection_rows:
            w.value(sort_vals)
            w.value(row)

    # trailing optional cost vector (engine/results.py COST_KEYS): old
    # readers stop before it, old payloads simply end here — the same
    # mixed-version contract as InstanceRequest.debugOptions.  Keys are
    # written sorted so identical costs serialize byte-identically.
    w.value({k: res.cost[k] for k in sorted(res.cost)})

    # trailing optional backpressure snapshot (scheduler/lane saturation
    # of the answering server — the broker's AIMD admission signal):
    # same mixed-version contract, one more trailing value after cost
    w.value({k: res.backpressure[k] for k in sorted(res.backpressure)})

    # trailing optional plan-tree list (EXPLAIN / EXPLAIN ANALYZE
    # introspection nodes, engine/explain.py): JSON-safe dicts through
    # the tagged codec; empty for every normal query, absent for peers
    # predating the introspection plane
    w.value(list(res.plan_info))

    # trailing optional join-exchange payload (engine/join.py SideRows
    # wire dict — columnar arrays via the 'a' tag): None for every
    # non-join reply, absent for peers predating the join plane
    w.value(getattr(res, "join_payload", None))

    # trailing optional event-time freshness stamp ({"minEventMs": ...},
    # broker/freshness.py): None for offline-only replies, absent for
    # peers predating the audit plane — same mixed-version contract
    w.value(getattr(res, "freshness", None))

    payload = w.getvalue()
    return MAGIC + struct.pack("<Q", len(payload)) + payload


def deserialize_result(data: bytes) -> IntermediateResult:
    if data[:8] != MAGIC:
        raise ValueError("not a DataTable payload")
    (n,) = struct.unpack_from("<Q", data, 8)
    r = _Reader(data[16 : 16 + n])
    res = IntermediateResult()
    res.num_docs_scanned = r.i64()
    res.total_docs = r.i64()
    res.num_segments_queried = r.i64()
    res.num_entries_scanned_in_filter = r.i64()
    res.num_entries_scanned_post_filter = r.i64()
    res.trace = dict(tuple(kv) for kv in r.value())
    res.exceptions = [(int(c), str(m)) for c, m in r.value()]
    res.unserved_segments = [str(s) for s in r.value()]

    if r.u8():
        cnt = r.i64()
        res.aggregations = [_read_partial(r) for _ in range(cnt)]
    if r.u8():
        cnt = r.i64()
        groups: Dict[Tuple[str, ...], List[AggPartial]] = {}
        for _ in range(cnt):
            key = tuple(r.value())
            np_ = r.i64()
            groups[key] = [_read_partial(r) for _ in range(np_)]
        res.groups = groups
    if r.u8():
        cols = r.value()
        res.selection_columns = list(cols) if cols else None
        cnt = r.i64()
        res.selection_rows = [(r.value(), r.value()) for _ in range(cnt)]
    if r.pos < len(r.data):
        # trailing cost vector (absent in payloads from older peers)
        res.cost = {str(k): v for k, v in (r.value() or {}).items()}
    if r.pos < len(r.data):
        # trailing backpressure snapshot (absent from older peers)
        res.backpressure = {str(k): v for k, v in (r.value() or {}).items()}
    if r.pos < len(r.data):
        # trailing EXPLAIN plan-tree list (absent from older peers)
        res.plan_info = [dict(n) for n in (r.value() or [])]
    if r.pos < len(r.data):
        # trailing join-exchange payload (absent from older peers)
        res.join_payload = r.value()
    if r.pos < len(r.data):
        # trailing event-time freshness stamp (absent from older peers)
        res.freshness = r.value()
    return res


# ---------------------------------------------------------------------------
# InstanceRequest (broker -> server)
# ---------------------------------------------------------------------------


def serialize_instance_request(
    request_id,
    pql: str,
    table: str,
    segments: List[str],
    timeout_ms: float,
    trace: bool = False,
    debug_options: Optional[Dict[str, str]] = None,
    join: Optional[Dict[str, Any]] = None,
) -> bytes:
    # request_id is the broker-assigned globally-unique id (a
    # broker-name-prefixed string, e.g. "broker0-3fa9c1-17"); it rides
    # the wire so server-side traces and logs correlate with the
    # broker's response/slow-query log.  Legacy integer ids stringify.
    w = _Writer()
    w.string(str(request_id))
    w.string(pql)
    w.string(table)
    w.value(list(segments))
    w.f64(timeout_ms)
    w.u8(1 if trace else 0)
    # per-query debug options ride to the server so its re-parse applies
    # the same optimizer flags (BrokerRequest.debugOptions thrift field)
    w.value(dict(debug_options or {}))
    # trailing optional join context (broker/joinplan.py): phase + spec
    # + shipped build/exchange payloads (columnar arrays via the 'a'
    # tag).  None for every single-table request; absent for peers
    # predating the join plane.
    w.value(join)
    return w.getvalue()


def deserialize_instance_request(data: bytes) -> Dict[str, Any]:
    r = _Reader(data)
    out = {
        "requestId": r.string(),
        "pql": r.string(),
        "table": r.string(),
        "segments": list(r.value()),
        "timeoutMs": r.f64(),
        "trace": bool(r.u8()),
    }
    # debugOptions is a trailing optional field: payloads from peers
    # predating it simply end here, and must stay readable during
    # mixed-version operation (ADVICE r1)
    if r.pos < len(data):
        out["debugOptions"] = dict(r.value() or {})
    else:
        out["debugOptions"] = {}
    # trailing optional join context (absent from older peers)
    out["join"] = r.value() if r.pos < len(data) else None
    return out
