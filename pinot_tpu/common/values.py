"""Shared value rendering: dictIds / raw values -> result strings.

Group-by keys and selection cells are rendered identically by the scan
oracle and the TPU engine so differential tests compare exactly (the
reference renders via ``Dictionary.getStringValue`` at result build).
"""
from __future__ import annotations

from typing import Any

from pinot_tpu.common.schema import DataType


def render_value(stored_type: DataType, v: Any) -> str:
    if stored_type in (DataType.INT, DataType.LONG):
        return str(int(v))
    if stored_type in (DataType.FLOAT, DataType.DOUBLE):
        return repr(float(v))
    return str(v)
