"""Internal query request model — the BrokerRequest equivalent.

The reference models a parsed query as a Thrift ``BrokerRequest``
(pinot-common ``src/thrift/request.thrift``): querySource, a filter query
tree, aggregationsInfo, groupBy, selections, plus per-query flags
(enableTrace, debugOptions, queryOptions).  Here the same information is
plain dataclasses — there is no cross-language wire concern for the parsed
form; the serialized wire format between broker and server is the
DataTable/JSON layer (see ``common/datatable.py`` and ``transport/``).

Filter trees use the reference's operator vocabulary
(``FilterOperator``: AND, OR, EQUALITY, NOT, RANGE, REGEX, NOT_IN, IN —
request.thrift enum), but ranges are structured (lower/upper/inclusive)
instead of Pinot's encoded "[a\\t\\tb]" strings.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Dict, List, Optional, Tuple


class FilterOperator(str, Enum):
    AND = "AND"
    OR = "OR"
    EQUALITY = "EQUALITY"
    NOT = "NOT"  # not-equal in the reference ("<>")
    RANGE = "RANGE"
    REGEX = "REGEX"
    NOT_IN = "NOT_IN"
    IN = "IN"


# Sentinel for unbounded range ends (reference uses "*").
UNBOUNDED = "*"


@dataclass
class RangeSpec:
    """Structured range predicate: lower/upper bounds with inclusivity.

    ``None`` bound = unbounded (reference encodes as "*",
    pinot-core predicate evaluators parse "[lo\\t\\thi]" strings).
    """

    lower: Optional[str] = None
    upper: Optional[str] = None
    include_lower: bool = True
    include_upper: bool = True

    def to_json(self) -> Dict[str, Any]:
        return {
            "lower": self.lower,
            "upper": self.upper,
            "includeLower": self.include_lower,
            "includeUpper": self.include_upper,
        }


@dataclass
class FilterQueryTree:
    """Filter tree node (reference: FilterQueryTree in pinot-common
    ``common/utils/request/FilterQueryTree.java``).

    Leaves carry (column, operator, values|range); internal nodes are
    AND/OR over children.
    """

    operator: FilterOperator
    column: Optional[str] = None
    values: List[str] = field(default_factory=list)
    range_spec: Optional[RangeSpec] = None
    children: List["FilterQueryTree"] = field(default_factory=list)

    @property
    def is_leaf(self) -> bool:
        return not self.children

    def walk(self):
        yield self
        for c in self.children:
            yield from c.walk()

    def to_json(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"operator": self.operator.value}
        if self.column is not None:
            d["column"] = self.column
        if self.values:
            d["values"] = list(self.values)
        if self.range_spec is not None:
            d["range"] = self.range_spec.to_json()
        if self.children:
            d["children"] = [c.to_json() for c in self.children]
        return d

    def __repr__(self) -> str:  # compact for debugging
        if self.is_leaf:
            if self.operator == FilterOperator.RANGE and self.range_spec is not None:
                r = self.range_spec
                lo = "(" if not r.include_lower else "["
                hi = ")" if not r.include_upper else "]"
                return f"{self.column} RANGE {lo}{r.lower},{r.upper}{hi}"
            return f"{self.column} {self.operator.value} {self.values}"
        inner = f" {self.operator.value} ".join(repr(c) for c in self.children)
        return f"({inner})"


# Aggregation function names supported by the engine — superset naming of
# AggregationFunctionFactory.java:25-58 (count/min/max/sum/avg/minmaxrange/
# distinctcount/distinctcounthll/fasthll/percentileNN/percentileestNN + MV).
SV_AGGREGATION_FUNCTIONS = (
    "count",
    "min",
    "max",
    "sum",
    "avg",
    "minmaxrange",
    "distinctcount",
    "distinctcounthll",
    "fasthll",
    "percentile50",
    "percentile90",
    "percentile95",
    "percentile99",
    "percentileest50",
    "percentileest90",
    "percentileest95",
    "percentileest99",
)
MV_AGGREGATION_FUNCTIONS = tuple(f + "mv" for f in SV_AGGREGATION_FUNCTIONS)
AGGREGATION_FUNCTIONS = SV_AGGREGATION_FUNCTIONS + MV_AGGREGATION_FUNCTIONS


def group_sort_ascending(function: str) -> bool:
    """Group-by results for min (and minMV) sort ascending; every other
    function — including minmaxrange — sorts descending.  Mirrors
    AggregationGroupByOperatorService.java:52,146: the trim comparator
    reverses only when getFunctionName() starts with "min_", which is
    true for min_<col> (the registry maps minmv there too) but NOT for
    minMaxRange_<col>."""
    return function in ("min", "minmv")


@dataclass
class AggregationInfo:
    """One aggregation call, e.g. sum(runs) (request.thrift AggregationInfo)."""

    function: str  # lower-cased, e.g. "sum", "distinctcounthll", "summv"
    column: str  # "*" for count(*)

    def __post_init__(self) -> None:
        self.function = self.function.lower()

    @property
    def is_mv(self) -> bool:
        return self.function.endswith("mv")

    @property
    def base_function(self) -> str:
        return self.function[:-2] if self.is_mv else self.function

    @property
    def display_name(self) -> str:
        """Response column name, reference style: ``sum_runs`` / ``count_star``."""
        col = "star" if self.column == "*" else self.column
        return f"{self.function}_{col}"


@dataclass
class GroupBy:
    columns: List[str] = field(default_factory=list)
    top_n: int = 10  # reference default TOP 10


@dataclass
class SelectionSort:
    column: str
    ascending: bool = True


@dataclass
class Selection:
    columns: List[str] = field(default_factory=list)  # ["*"] = all
    sorts: List[SelectionSort] = field(default_factory=list)
    offset: int = 0
    size: int = 10  # reference default LIMIT 10


@dataclass
class HavingSpec:
    """HAVING predicate over aggregation results (PQL2.g4 havingClause)."""

    function: str
    column: str
    operator: str  # '=', '<>', '<', '>', '<=', '>='
    value: float


@dataclass
class JoinSpec:
    """Two-table INNER equi-join (``FROM a JOIN b ON a.k = b.k``).

    The LEFT table (``BrokerRequest.table_name``) is the probe/fact
    side; the RIGHT table is the build/dimension side.  Column
    references are resolved at parse time: left-side columns are stored
    UNQUALIFIED everywhere in the request (filter tree, aggregations,
    group-by, selection), right-side columns as
    ``"<right_table>.<col>"`` — the raw right TABLE name, not the query
    alias, so two aliases of the same semantic query share a plan
    shape.  ``left_key``/``right_key`` are plain column names on their
    own sides.  The reference (Pinot v0.016) had no join support at
    all — see PARITY.md."""

    right_table: str
    left_key: str
    right_key: str

    def right_prefix(self) -> str:
        return self.right_table + "."

    def is_right_column(self, column: Optional[str]) -> bool:
        return bool(column) and column.startswith(self.right_prefix())

    def strip_right(self, column: str) -> str:
        """``"<right_table>.<col>"`` -> ``"<col>"``."""
        p = self.right_prefix()
        return column[len(p):] if column.startswith(p) else column


@dataclass
class BrokerRequest:
    table_name: str
    filter: Optional[FilterQueryTree] = None
    aggregations: List[AggregationInfo] = field(default_factory=list)
    group_by: Optional[GroupBy] = None
    selection: Optional[Selection] = None
    having: Optional[HavingSpec] = None
    # two-table equi-join (broker-planned; engine/join.py executes) —
    # None for the single-table queries the reference supported
    join: Optional[JoinSpec] = None
    enable_trace: bool = False
    query_options: Dict[str, str] = field(default_factory=dict)
    debug_options: Dict[str, str] = field(default_factory=dict)
    # introspection mode from an EXPLAIN prefix: None (execute),
    # "plan" (return the physical plan, NO execution), or "analyze"
    # (execute AND annotate the plan with actuals).  Rides the wire
    # inside the PQL text itself, so servers re-derive it on re-parse.
    explain: Optional[str] = None

    @property
    def is_aggregation(self) -> bool:
        return bool(self.aggregations)

    @property
    def is_group_by(self) -> bool:
        return self.group_by is not None and bool(self.group_by.columns)

    @property
    def is_selection(self) -> bool:
        return not self.aggregations

    def referenced_columns(self) -> List[str]:
        """All physical columns the query touches (for pruning)."""
        cols: List[str] = []

        def add(c: Optional[str]) -> None:
            if c and c != "*" and c not in cols:
                cols.append(c)

        if self.filter is not None:
            for node in self.filter.walk():
                add(node.column)
        for agg in self.aggregations:
            add(agg.column)
        if self.group_by:
            for c in self.group_by.columns:
                add(c)
        if self.selection:
            for c in self.selection.columns:
                add(c)
            for s in self.selection.sorts:
                add(s.column)
        return cols
