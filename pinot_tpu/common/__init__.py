from pinot_tpu.common.schema import DataType, FieldType, FieldSpec, TimeFieldSpec, Schema
from pinot_tpu.common.request import (
    FilterOperator,
    FilterQueryTree,
    AggregationInfo,
    GroupBy,
    Selection,
    SelectionSort,
    BrokerRequest,
)
from pinot_tpu.common.response import BrokerResponse, AggregationResult, GroupByResult, SelectionResults

__all__ = [
    "DataType",
    "FieldType",
    "FieldSpec",
    "TimeFieldSpec",
    "Schema",
    "FilterOperator",
    "FilterQueryTree",
    "AggregationInfo",
    "GroupBy",
    "Selection",
    "SelectionSort",
    "BrokerRequest",
    "BrokerResponse",
    "AggregationResult",
    "GroupByResult",
    "SelectionResults",
]
