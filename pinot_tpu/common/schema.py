"""Schema / field model.

Semantics mirror the reference data model (pinot-common
``common/data/FieldSpec.java`` and ``common/data/Schema.java``):

- A schema is a set of columns, each a DIMENSION, METRIC, or TIME field
  (``FieldSpec.java:196-200``). METRIC fields are numeric; TIME prunes
  segments, otherwise behaves as a dimension.
- Five storage data types: INT, LONG, FLOAT, DOUBLE, STRING, plus the
  multi-value ``*_ARRAY`` variants (``FieldSpec.java:209-228``).
- Missing input values are replaced by per-type default null values
  (``FieldSpec.java:37-47``): dimensions get min-int / min-long / -inf /
  ``"null"``; metrics get 0 / 0.0 / ``"null"``.

TPU mapping: INT/LONG/FLOAT/DOUBLE columns live on device as dictionary-
encoded int32 forward indexes + numeric dictionary value arrays; STRING
columns keep their dictionaries host-side and only dictIds reach device.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Dict, List, Optional

import numpy as np

_INT_MIN = -(2**31)
_LONG_MIN = -(2**63)


class DataType(str, Enum):
    INT = "INT"
    LONG = "LONG"
    FLOAT = "FLOAT"
    DOUBLE = "DOUBLE"
    STRING = "STRING"
    BOOLEAN = "BOOLEAN"  # stored as STRING (FieldSpec.java:210)
    INT_ARRAY = "INT_ARRAY"
    LONG_ARRAY = "LONG_ARRAY"
    FLOAT_ARRAY = "FLOAT_ARRAY"
    DOUBLE_ARRAY = "DOUBLE_ARRAY"
    STRING_ARRAY = "STRING_ARRAY"

    # These derivations are pure functions of the member, but as plain
    # properties they re-run string/enum machinery on EVERY call — and
    # the ingest path calls them per row-column (~14 calls/row), where
    # they dominated the profile.  Computed once per member below the
    # class body and served from per-member attributes.
    @property
    def is_single_value(self) -> bool:
        return self._is_sv

    @property
    def element_type(self) -> "DataType":
        """The scalar type of this (possibly multi-value) type."""
        return self._elem

    @property
    def is_numeric(self) -> bool:
        return self._is_num

    @property
    def is_integer(self) -> bool:
        return self._is_int

    @property
    def stored_type(self) -> "DataType":
        """BOOLEAN is stored as STRING (FieldSpec.java:210)."""
        return self._stored

    def to_numpy(self) -> np.dtype:
        return {
            DataType.INT: np.dtype(np.int32),
            DataType.LONG: np.dtype(np.int64),
            DataType.FLOAT: np.dtype(np.float32),
            DataType.DOUBLE: np.dtype(np.float64),
            DataType.STRING: np.dtype(object),
        }[self.stored_type]

    def convert(self, value: Any) -> Any:
        """Coerce a raw ingest value to this type's python representation.

        FLOAT round-trips through float32 (the reference stores Java
        ``float``), so predicate literals, stored values, and rendered
        results all agree on the same 32-bit value.
        """
        t = self.stored_type
        if t == DataType.STRING:
            if isinstance(value, bool):
                return "true" if value else "false"
            return str(value)
        if t in (DataType.INT, DataType.LONG):
            try:
                return int(value)
            except ValueError:
                return int(float(value))
        v = float(value)
        if t == DataType.FLOAT:
            return float(np.float32(v))
        return v


for _m in DataType:
    _m._is_sv = not _m.name.endswith("_ARRAY")
    _m._elem = _m if _m._is_sv else DataType(_m.name[: -len("_ARRAY")])
    _m._stored = DataType.STRING if _m._elem == DataType.BOOLEAN else _m._elem
    _m._is_num = _m._elem in (DataType.INT, DataType.LONG, DataType.FLOAT, DataType.DOUBLE)
    _m._is_int = _m._elem in (DataType.INT, DataType.LONG)


class FieldType(str, Enum):
    DIMENSION = "DIMENSION"
    METRIC = "METRIC"
    TIME = "TIME"


# Default null values, FieldSpec.java:37-47.
_DIM_NULL = {
    DataType.INT: _INT_MIN,
    DataType.LONG: _LONG_MIN,
    DataType.FLOAT: float("-inf"),
    DataType.DOUBLE: float("-inf"),
    DataType.STRING: "null",
}
_METRIC_NULL = {
    DataType.INT: 0,
    DataType.LONG: 0,
    DataType.FLOAT: 0.0,
    DataType.DOUBLE: 0.0,
    DataType.STRING: "null",
}


@dataclass
class FieldSpec:
    name: str
    data_type: DataType
    field_type: FieldType = FieldType.DIMENSION
    single_value: bool = True
    default_null_value: Optional[Any] = None
    # Multi-value columns: max entries per row (builder fills this in).
    max_num_multi_values: int = 0

    def __post_init__(self) -> None:
        self.data_type = DataType(self.data_type)
        self.field_type = FieldType(self.field_type)
        if not self.data_type.is_single_value:
            self.single_value = False

    @property
    def stored_type(self) -> DataType:
        return self.data_type.stored_type

    def get_default_null_value(self) -> Any:
        if self.default_null_value is not None:
            return self.stored_type.convert(self.default_null_value)
        table = _METRIC_NULL if self.field_type == FieldType.METRIC else _DIM_NULL
        return table[self.stored_type]

    def to_json(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "name": self.name,
            "dataType": self.data_type.value,
            "fieldType": self.field_type.value,
            "singleValueField": self.single_value,
        }
        if self.default_null_value is not None:
            d["defaultNullValue"] = self.default_null_value
        return d

    @classmethod
    def from_json(cls, d: Dict[str, Any], field_type: Optional[FieldType] = None) -> "FieldSpec":
        ft = field_type or FieldType(d.get("fieldType", "DIMENSION"))
        return cls(
            name=d["name"],
            data_type=DataType(d["dataType"]),
            field_type=ft,
            single_value=d.get("singleValueField", True),
            default_null_value=d.get("defaultNullValue"),
        )


@dataclass
class TimeFieldSpec(FieldSpec):
    """TIME column with a granularity unit (Schema.java timeFieldSpec)."""

    time_unit: str = "DAYS"  # DAYS | HOURS | MINUTES | SECONDS | MILLISECONDS

    def __post_init__(self) -> None:
        super().__post_init__()
        self.field_type = FieldType.TIME

    def to_json(self) -> Dict[str, Any]:
        d = super().to_json()
        d["timeUnit"] = self.time_unit
        return d

    @classmethod
    def from_json(cls, d: Dict[str, Any], field_type: Optional[FieldType] = None) -> "TimeFieldSpec":
        # Accept both the flat form this package writes and the
        # reference's nested TimeGranularitySpec form
        # (``"timeFieldSpec": {"incomingGranularitySpec": {"name", "dataType",
        # "timeType"}}`` — common/data/TimeFieldSpec.java, as in the
        # sample_data/*.schema files), so reference schema JSON loads as-is.
        g = d.get("incomingGranularitySpec")
        if g is not None:
            return cls(
                name=g["name"],
                data_type=DataType(g["dataType"]),
                single_value=g.get("singleValueField", True),
                default_null_value=d.get("defaultNullValue"),
                time_unit=g.get("timeType", d.get("timeUnit", "DAYS")),
            )
        return cls(
            name=d["name"],
            data_type=DataType(d["dataType"]),
            single_value=d.get("singleValueField", True),
            default_null_value=d.get("defaultNullValue"),
            time_unit=d.get("timeUnit", "DAYS"),
        )


_TIME_UNIT_MILLIS = {
    "MILLISECONDS": 1,
    "SECONDS": 1000,
    "MINUTES": 60 * 1000,
    "HOURS": 3600 * 1000,
    "DAYS": 24 * 3600 * 1000,
}


def time_unit_to_millis(unit: str) -> int:
    return _TIME_UNIT_MILLIS[unit.upper()]


@dataclass
class Schema:
    """Column schema: dimensions + metrics + optional time column.

    Mirrors pinot-common ``common/data/Schema.java`` (JSON shape:
    ``{"schemaName": ..., "dimensionFieldSpecs": [...],
    "metricFieldSpecs": [...], "timeFieldSpec": {...}}``).
    """

    schema_name: str
    dimensions: List[FieldSpec] = field(default_factory=list)
    metrics: List[FieldSpec] = field(default_factory=list)
    time_field: Optional[TimeFieldSpec] = None

    def __post_init__(self) -> None:
        self._by_name: Dict[str, FieldSpec] = {}
        for spec in self.all_fields():
            if spec.name in self._by_name:
                raise ValueError(f"duplicate column {spec.name!r} in schema {self.schema_name!r}")
            self._by_name[spec.name] = spec

    def all_fields(self) -> List[FieldSpec]:
        out: List[FieldSpec] = list(self.dimensions) + list(self.metrics)
        if self.time_field is not None:
            out.append(self.time_field)
        return out

    @property
    def column_names(self) -> List[str]:
        return [s.name for s in self.all_fields()]

    def field(self, name: str) -> FieldSpec:
        try:
            return self._by_name[name]
        except KeyError:
            raise KeyError(f"unknown column {name!r} in schema {self.schema_name!r}") from None

    def has_column(self, name: str) -> bool:
        return name in self._by_name

    @property
    def time_column_name(self) -> Optional[str]:
        return self.time_field.name if self.time_field is not None else None

    def to_json(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "schemaName": self.schema_name,
            "dimensionFieldSpecs": [s.to_json() for s in self.dimensions],
            "metricFieldSpecs": [s.to_json() for s in self.metrics],
        }
        if self.time_field is not None:
            d["timeFieldSpec"] = self.time_field.to_json()
        return d

    def to_json_str(self) -> str:
        return json.dumps(self.to_json(), indent=2)

    @classmethod
    def from_json(cls, d: Dict[str, Any]) -> "Schema":
        dims = [FieldSpec.from_json(x, FieldType.DIMENSION) for x in d.get("dimensionFieldSpecs", [])]
        mets = [FieldSpec.from_json(x, FieldType.METRIC) for x in d.get("metricFieldSpecs", [])]
        tf = d.get("timeFieldSpec")
        time_field = TimeFieldSpec.from_json(tf) if tf else None
        return cls(
            schema_name=d.get("schemaName", d.get("name", "unknown")),
            dimensions=dims,
            metrics=mets,
            time_field=time_field,
        )

    @classmethod
    def from_json_str(cls, s: str) -> "Schema":
        return cls.from_json(json.loads(s))
