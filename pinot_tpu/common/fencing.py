"""Fencing primitives for partition tolerance: epochs and leases.

Reference Pinot outsources "who is alive and who may write" to
Helix/ZooKeeper: a participant's authority is its ZK session (expires
when the node is partitioned away), and a controller's authority is its
leadership generation.  This module is the bespoke-controller analog:

- **Controller epoch** — a monotonically increasing incarnation number
  persisted in the property store (``cluster/epoch``).  Every store
  write and every state-changing RPC is fenced on it: a restarted or
  partitioned-away controller still holding an old epoch gets a typed
  ``StaleEpochError`` instead of silently clobbering the live
  controller's state (the ZK leader-generation fence).

- **Serving lease** (``ServingLease``) — the server-side half of the ZK
  session.  Heartbeat replies carry a controller-signed lease
  ``{epoch, durationS}``; a server that cannot renew it within the
  window (``PINOT_TPU_LEASE_S``) loses WRITE authority — no new
  consuming roles, no segment commits — while the read path stays up
  (in-flight and new queries keep serving from local data; routing
  degradation is the broker's business).  A server that never received
  a lease (in-process harness, no gateway) holds implicit authority:
  the fence only arms once a controller has granted a lease.

Both clocks are injectable so chaos tests advance time explicitly.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Optional


def default_lease_s() -> float:
    """Lease duration granted on each heartbeat (seconds)."""
    return float(os.environ.get("PINOT_TPU_LEASE_S", "10"))


class StaleEpochError(Exception):
    """A write carried an epoch older than the cluster's current one:
    the writer is a fenced-off former authority (restarted controller,
    partitioned-away committer) and must not mutate anything."""

    def __init__(self, message: str, stale: Any = None, current: Any = None) -> None:
        super().__init__(message)
        self.stale = stale
        self.current = current


def epoch_int(value: Any) -> int:
    """Parse an epoch from wire/json forms (int, numeric string).
    Unparseable/absent values come back as -1 — always stale, so an
    epoch-less legacy caller can never fence OUT a real epoch holder."""
    try:
        return int(value)
    except (TypeError, ValueError):
        return -1


class ServingLease:
    """The server's view of its controller-granted serving lease.

    States:
    - *unleased* (never granted): ``held()`` is True — implicit local
      authority, the in-process/back-compat mode.
    - *held*: renewed within the window.
    - *expired*: the renewal stopped arriving (partition, controller
      outage); write authority is gone until the next successful renew.
    """

    def __init__(self, clock=None, metrics=None) -> None:
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._granted = False
        self._expires_at = 0.0
        self._epoch = -1
        self._was_held = False
        self.metrics = metrics
        if metrics is not None:
            for m in ("lease.renewals", "lease.expiries"):
                metrics.meter(m)
            metrics.gauge("lease.held").set_fn(lambda: 1 if self.held() else 0)

    def renew(self, lease: Optional[Dict[str, Any]]) -> None:
        """Apply the ``lease`` block of a heartbeat reply
        (``{"epoch": ..., "durationS": ...}``); None is ignored (a
        legacy controller grants nothing — fence stays unarmed)."""
        if not lease:
            return
        duration = float(lease.get("durationS") or default_lease_s())
        with self._lock:
            self._granted = True
            self._epoch = epoch_int(lease.get("epoch"))
            self._expires_at = self._clock() + duration
            self._was_held = True
        if self.metrics is not None:
            self.metrics.meter("lease.renewals").mark()

    def held(self) -> bool:
        with self._lock:
            if not self._granted:
                return True  # unleased: implicit local authority
            held = self._clock() < self._expires_at
            if not held and self._was_held:
                self._was_held = False
                if self.metrics is not None:
                    self.metrics.meter("lease.expiries").mark()
            return held

    def remaining_s(self) -> float:
        with self._lock:
            if not self._granted:
                return float("inf")
            return max(0.0, self._expires_at - self._clock())

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    @property
    def granted(self) -> bool:
        with self._lock:
            return self._granted

    def expire(self) -> None:
        """Force-expire (tests / explicit self-fencing)."""
        with self._lock:
            self._expires_at = 0.0

    def snapshot(self) -> Dict[str, Any]:
        held = self.held()  # outside the lock: held() takes it
        with self._lock:
            return {
                "granted": self._granted,
                "held": held,
                "epoch": self._epoch,
                "remainingS": (
                    None
                    if not self._granted
                    else round(max(0.0, self._expires_at - self._clock()), 3)
                ),
            }
