"""Table configuration.

Mirrors the reference's JSON table config (pinot-common
``common/config/AbstractTableConfig.java:37``): table type
OFFLINE|REALTIME|HYBRID, replication, retention, indexing config
(inverted index columns, star-tree), stream (realtime) config, quotas.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class RetentionConfig:
    retention_time_unit: str = "DAYS"
    retention_time_value: int = 0  # 0 = keep forever

    def to_json(self) -> Dict[str, Any]:
        return {
            "retentionTimeUnit": self.retention_time_unit,
            "retentionTimeValue": self.retention_time_value,
        }


@dataclass
class IndexingConfig:
    inverted_index_columns: List[str] = field(default_factory=list)
    sorted_column: Optional[str] = None
    startree_enabled: bool = False
    startree_dimensions_split_order: List[str] = field(default_factory=list)
    startree_max_leaf_records: int = 10_000
    startree_skip_star_node_for_dims: List[str] = field(default_factory=list)

    def to_json(self) -> Dict[str, Any]:
        return {
            "invertedIndexColumns": list(self.inverted_index_columns),
            "sortedColumn": self.sorted_column,
            "starTreeEnabled": self.startree_enabled,
            "starTreeDimensionsSplitOrder": list(self.startree_dimensions_split_order),
            "starTreeMaxLeafRecords": self.startree_max_leaf_records,
        }


@dataclass
class StreamConfig:
    """Realtime ingestion config (the kafka.* stream properties analog).

    ``stream_type`` selects the provider: ``network`` (the built-in TCP
    stream broker, ``realtime/netstream.py`` — properties: host, port),
    ``file`` (JSONL per partition — properties: paths), ``memory``
    (in-process — properties: partitions), or ``kafka`` (gated; no
    client library in this image)."""

    stream_type: str = "file"  # network | file | memory | kafka (gated)
    topic: str = ""
    decoder: str = "json"
    rows_per_segment: int = 100_000  # segment flush threshold
    consume_seconds: float = 3600.0
    # "lowlevel": one controller-coordinated consumer per stream
    #   partition, committer election + exact offset checkpoints (LLC).
    # "highlevel": one consumer per SERVER in a broker-coordinated
    #   consumer group; partitions rebalance across servers on
    #   membership change; group offsets checkpoint in the stream
    #   broker (HLC, HLRealtimeSegmentDataManager.java:54). Requires a
    #   network stream (consumer groups live in the stream broker).
    consumer_type: str = "lowlevel"
    properties: Dict[str, Any] = field(default_factory=dict)


@dataclass
class SloConfig:
    """Per-table service-level objectives (ISSUE 11): the broker
    evaluates these as multi-window burn rates (utils/slo.py).  Unset
    fields fall back to the env defaults (PINOT_TPU_SLO_*)."""

    latency_ms: Optional[float] = None  # queries must answer under this
    latency_target: Optional[float] = None  # fraction that must (0.99)
    availability_target: Optional[float] = None  # non-failed fraction

    def to_json(self) -> Dict[str, Any]:
        return {
            "latencyMs": self.latency_ms,
            "latencyTarget": self.latency_target,
            "availabilityTarget": self.availability_target,
        }

    @classmethod
    def from_json(cls, d: Dict[str, Any]) -> "SloConfig":
        return cls(
            latency_ms=d.get("latencyMs"),
            latency_target=d.get("latencyTarget"),
            availability_target=d.get("availabilityTarget"),
        )


@dataclass
class PartitionConfig:
    """Declared key partitioning (the reference's segmentPartitionConfig
    analog): segments of the table carry their partition id in the
    segment name (``..._pN``), and the broker's join planner picks the
    COLOCATED strategy when both join sides declare partitioning on
    their join keys with equal partition counts and the covers align."""

    column: Optional[str] = None
    num_partitions: Optional[int] = None

    def to_json(self) -> Dict[str, Any]:
        return {"column": self.column, "numPartitions": self.num_partitions}

    @classmethod
    def from_json(cls, d: Dict[str, Any]) -> "PartitionConfig":
        return cls(column=d.get("column"), num_partitions=d.get("numPartitions"))


@dataclass
class QuotaConfig:
    storage: Optional[str] = None
    # fractional values (< 1.0) are honored: 0.5 = one query per 2s
    max_queries_per_second: Optional[float] = None
    # token-bucket burst capacity (queries); None = max(qps, 1) — a
    # bursty-but-in-budget client can spend saved-up headroom at once
    burst_queries: Optional[float] = None

    _UNITS = {"": 1, "K": 2**10, "M": 2**20, "G": 2**30, "T": 2**40}

    def storage_bytes(self) -> Optional[int]:
        """Parse the human-readable storage quota ("128M", "2.5G", "1024")
        into bytes; None when unset (the QuotaConfig.storage contract of
        ``common/config/QuotaConfig`` in the reference)."""
        if not self.storage:
            return None
        import re

        m = re.fullmatch(r"(\d+(?:\.\d+)?)\s*([kKmMgGtT]?)[bB]?", self.storage.strip())
        if m is None:
            raise ValueError(f"bad storage quota {self.storage!r}")
        return int(float(m.group(1)) * self._UNITS[m.group(2).upper()])

    def to_json(self) -> Dict[str, Any]:
        d = {"storage": self.storage, "maxQueriesPerSecond": self.max_queries_per_second}
        if self.burst_queries is not None:
            d["burstQueries"] = self.burst_queries
        return d


@dataclass
class TableConfig:
    table_name: str
    table_type: str = "OFFLINE"  # OFFLINE | REALTIME
    replication: int = 1
    retention: RetentionConfig = field(default_factory=RetentionConfig)
    indexing: IndexingConfig = field(default_factory=IndexingConfig)
    stream: Optional[StreamConfig] = None
    quota: QuotaConfig = field(default_factory=QuotaConfig)
    slo: Optional[SloConfig] = None
    partitioning: Optional[PartitionConfig] = None
    broker_tenant: str = "DefaultTenant"
    server_tenant: str = "DefaultTenant"

    @property
    def physical_name(self) -> str:
        suffix = "_OFFLINE" if self.table_type == "OFFLINE" else "_REALTIME"
        if self.table_name.endswith(("_OFFLINE", "_REALTIME")):
            return self.table_name
        return self.table_name + suffix

    @property
    def raw_name(self) -> str:
        for sfx in ("_OFFLINE", "_REALTIME"):
            if self.table_name.endswith(sfx):
                return self.table_name[: -len(sfx)]
        return self.table_name

    def to_json(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "tableName": self.table_name,
            "tableType": self.table_type,
            "segmentsConfig": {
                "replication": self.replication,
                **self.retention.to_json(),
            },
            "tableIndexConfig": self.indexing.to_json(),
            "tenants": {"broker": self.broker_tenant, "server": self.server_tenant},
            "quota": self.quota.to_json(),
        }
        if self.slo is not None:
            d["slo"] = self.slo.to_json()
        if self.partitioning is not None:
            d["partitioning"] = self.partitioning.to_json()
        if self.stream is not None:
            d["streamConfigs"] = {
                "streamType": self.stream.stream_type,
                "topic": self.stream.topic,
                "decoder": self.stream.decoder,
                "rowsPerSegment": self.stream.rows_per_segment,
                "consumerType": self.stream.consumer_type,
                "properties": self.stream.properties,
            }
        return d

    @classmethod
    def from_json(cls, d: Dict[str, Any]) -> "TableConfig":
        seg = d.get("segmentsConfig", {})
        idx = d.get("tableIndexConfig", {})
        stream = None
        if "streamConfigs" in d:
            sc = d["streamConfigs"]
            stream = StreamConfig(
                stream_type=sc.get("streamType", "file"),
                topic=sc.get("topic", ""),
                decoder=sc.get("decoder", "json"),
                rows_per_segment=sc.get("rowsPerSegment", 100_000),
                consumer_type=sc.get("consumerType", "lowlevel"),
                properties=sc.get("properties", {}),
            )
        tenants = d.get("tenants", {})
        quota_json = d.get("quota", {})
        return cls(
            table_name=d["tableName"],
            table_type=d.get("tableType", "OFFLINE"),
            replication=seg.get("replication", 1),
            broker_tenant=tenants.get("broker", "DefaultTenant"),
            server_tenant=tenants.get("server", "DefaultTenant"),
            quota=QuotaConfig(
                storage=quota_json.get("storage"),
                max_queries_per_second=quota_json.get("maxQueriesPerSecond"),
                burst_queries=quota_json.get("burstQueries"),
            ),
            retention=RetentionConfig(
                retention_time_unit=seg.get("retentionTimeUnit", "DAYS"),
                retention_time_value=seg.get("retentionTimeValue", 0),
            ),
            indexing=IndexingConfig(
                inverted_index_columns=idx.get("invertedIndexColumns", []),
                sorted_column=idx.get("sortedColumn"),
                startree_enabled=idx.get("starTreeEnabled", False),
                startree_dimensions_split_order=idx.get("starTreeDimensionsSplitOrder", []),
                startree_max_leaf_records=idx.get("starTreeMaxLeafRecords", 10_000),
            ),
            slo=SloConfig.from_json(d["slo"]) if d.get("slo") else None,
            partitioning=(
                PartitionConfig.from_json(d["partitioning"])
                if d.get("partitioning")
                else None
            ),
            stream=stream,
        )
