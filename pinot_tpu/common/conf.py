"""Per-process configuration.

The reference layers config (SURVEY §5): per-process ``.properties``
files via Commons Configuration (``ServerConf.java``,
``ControllerConf.java:28``, ``DefaultHelixBrokerConfig``), with keys
centralized in ``CommonConstants.java:26``; cluster state (table
configs, schemas) lives in ZK as JSON; per-segment metadata.properties;
per-query flags in the request.

Here: typed dataclasses with the same key namespace, loadable from
java-properties-style files or dicts.  Cluster state JSON lives with the
controller (``tableconfig.py`` / ``schema.py``); per-segment metadata in
the segment header; per-query flags on BrokerRequest.
"""
from __future__ import annotations

from dataclasses import dataclass, fields
from typing import Any, Dict, Type, TypeVar

T = TypeVar("T", bound="BaseConf")


def env_float(name: str, default: float = 0.0) -> float:
    """Float from the environment, falling back on absent OR junk
    values (a malformed knob must degrade to the default, not crash
    the role at construction)."""
    import os

    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def parse_properties(text: str) -> Dict[str, str]:
    """Parse java-properties-style ``key=value`` lines (# comments)."""
    out: Dict[str, str] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#") or "=" not in line:
            continue
        key, _, value = line.partition("=")
        out[key.strip()] = value.strip()
    return out


class BaseConf:
    PREFIX = ""

    @classmethod
    def from_dict(cls: Type[T], props: Dict[str, Any]) -> T:
        kwargs: Dict[str, Any] = {}
        for f in fields(cls):  # type: ignore[arg-type]
            key = f"{cls.PREFIX}{f.name.replace('_', '.')}"
            if key in props:
                raw = props[key]
                if f.type in ("int", int):
                    kwargs[f.name] = int(raw)
                elif f.type in ("float", float):
                    kwargs[f.name] = float(raw)
                elif f.type in ("bool", bool):
                    kwargs[f.name] = str(raw).lower() in ("1", "true", "yes")
                else:
                    kwargs[f.name] = raw
        return cls(**kwargs)  # type: ignore[call-arg]

    @classmethod
    def from_properties_file(cls: Type[T], path: str) -> T:
        with open(path) as f:
            return cls.from_dict(parse_properties(f.read()))


@dataclass
class ServerConf(BaseConf):
    """pinot.server.* (ServerConf.java keys)."""

    PREFIX = "pinot.server."

    instance_id: str = "server0"
    netty_port: int = 8098
    query_executor_timeout_ms: int = 15_000  # ServerQueryExecutorV1Impl.java:58
    query_worker_threads: int = 4
    instance_data_dir: str = "/tmp/pinot_tpu/server/index"
    instance_segment_tar_dir: str = "/tmp/pinot_tpu/server/tar"


@dataclass
class BrokerConf(BaseConf):
    """pinot.broker.* (DefaultHelixBrokerConfig keys)."""

    PREFIX = "pinot.broker."

    instance_id: str = "broker0"
    client_query_port: int = 8099
    timeout_ms: int = 15_000
    routing_table_count: int = 10
    max_query_qps: float = 0.0  # 0 = unlimited (QuotaConfig enforcement)
    # -- resilience knobs (scatter-gather retry / hedge / circuit breaker)
    retry_attempts: int = 2  # failover re-issues per segment set beyond the first send
    retry_backoff_ms: float = 25.0  # capped exponential base between re-issues
    retry_backoff_cap_ms: float = 1_000.0
    hedge_delay_ms: float = 0.0  # 0 disables hedged requests
    hedge_latency_percentile: float = 95.0  # observed-latency percentile that arms a hedge
    hedge_min_quota_headroom: float = 0.1  # skip hedging when the table is near its QPS quota
    health_failure_threshold: int = 3  # consecutive failures before the penalty box
    health_penalty_ms: float = 5_000.0  # circuit-open duration before a half-open probe
    # -- adaptive admission (broker/admission.py overload front door)
    admission_table_inflight: int = 32  # per-table in-flight concurrency cap
    admission_window_init: float = 8.0  # AIMD per-server window start
    admission_window_max: float = 64.0  # AIMD window additive-increase ceiling
    admission_pending_high_water: float = 0.8  # backpressure saturation fraction


@dataclass
class ControllerConf(BaseConf):
    """controller.* (ControllerConf.java:28 keys)."""

    PREFIX = "controller."

    host: str = "127.0.0.1"
    port: int = 9000
    data_dir: str = "/tmp/pinot_tpu/controller/data"
    retention_frequency_seconds: int = 3600
    validation_frequency_seconds: int = 300
    status_check_frequency_seconds: int = 300
