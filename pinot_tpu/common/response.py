"""Broker response model.

JSON shape mirrors the reference ``BrokerResponseNative``
(pinot-common ``common/response/broker/BrokerResponseNative.java``):
``aggregationResults`` (plain or group-by), ``selectionResults``,
``exceptions``, and execution stats (``numDocsScanned``, ``totalDocs``,
``timeUsedMs``, ``numServersQueried``, ``numServersResponded``,
``traceInfo``).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


def _fmt_value(v: Any) -> str:
    """Reference renders aggregation values as strings (String.format)."""
    if isinstance(v, bool):
        return str(v).lower()
    if isinstance(v, float):
        # Pinot prints doubles with 5 decimal places in aggregation results
        # (SelectionOperatorUtils / AggregationFunctionUtils formatting).
        return f"{v:.5f}"
    return str(v)


@dataclass
class GroupByResult:
    group: List[str]
    value: Any

    def to_json(self) -> Dict[str, Any]:
        return {"value": _fmt_value(self.value), "group": list(self.group)}


@dataclass
class AggregationResult:
    function: str  # display name, e.g. "sum_runs"
    value: Any = None
    group_by_columns: Optional[List[str]] = None
    group_by_result: Optional[List[GroupByResult]] = None

    def to_json(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"function": self.function}
        if self.group_by_result is not None:
            d["groupByResult"] = [g.to_json() for g in self.group_by_result]
            d["groupByColumns"] = list(self.group_by_columns or [])
        else:
            d["value"] = _fmt_value(self.value)
        return d


@dataclass
class SelectionResults:
    columns: List[str]
    rows: List[List[Any]]

    def to_json(self) -> Dict[str, Any]:
        return {
            "columns": list(self.columns),
            "results": [[_sel_fmt(v) for v in row] for row in self.rows],
        }


def _sel_fmt(v: Any) -> Any:
    if isinstance(v, list):
        return [_sel_fmt(x) for x in v]
    if isinstance(v, float):
        return _fmt_value(v)
    return str(v)


@dataclass
class QueryException:
    error_code: int
    message: str

    def to_json(self) -> Dict[str, Any]:
        return {"errorCode": self.error_code, "message": self.message}


# Error codes, mirroring pinot-common QueryException constants.
class ErrorCode:
    JSON_PARSING = 100
    PQL_PARSING = 150
    QUERY_VALIDATION = 160
    QUERY_EXECUTION = 200
    SERVER_SCHEDULER_DOWN = 210
    SERVER_SHUTTING_DOWN = 220
    # a server answered but could not serve some requested segments
    # (dropped / quarantined pending re-fetch); the broker re-covers
    # them on a replica or degrades honestly via partialResponse
    SERVER_SEGMENT_MISSING = 230
    EXECUTION_TIMEOUT = 250
    BROKER_GATHER = 300
    BROKER_TIMEOUT = 350
    BROKER_RESOURCE_MISSING = 410
    BROKER_INSTANCE_MISSING = 420
    TOO_MANY_REQUESTS = 429
    INTERNAL = 450
    UNKNOWN = 1000


@dataclass
class BrokerResponse:
    aggregation_results: Optional[List[AggregationResult]] = None
    selection_results: Optional[SelectionResults] = None
    exceptions: List[QueryException] = field(default_factory=list)
    num_docs_scanned: int = 0
    num_entries_scanned_in_filter: int = 0
    num_entries_scanned_post_filter: int = 0
    total_docs: int = 0
    num_segments_queried: int = 0
    num_servers_queried: int = 0
    num_servers_responded: int = 0
    # graceful-degradation contract: when retries/failover could not
    # cover every routed segment, partial_response flips true and
    # num_segments_unserved counts what is missing — clients must be
    # able to distinguish a complete answer from a degraded one without
    # parsing exception strings
    partial_response: bool = False
    num_segments_unserved: int = 0
    num_retries: int = 0
    num_hedges: int = 0
    time_used_ms: float = 0.0
    # per-query cost vector (engine/results.py COST_KEYS): bytes
    # touched, device vs host kernel ms, serving-tier segment counts,
    # coalesce/cache hits — merged across scatter-gather so the totals
    # equal the sum of the per-server totals exactly
    cost: Dict[str, float] = field(default_factory=dict)
    trace_info: Dict[str, Any] = field(default_factory=dict)
    # broker-assigned globally-unique id echoed to the client so a
    # response correlates with traces and the slow-query log
    request_id: str = ""
    # workload-introspection plane: the literal-erased plan-shape digest
    # (engine/plandigest.py) on EVERY response, cross-linking a query to
    # /debug/plans and /debug/workload; ``explain`` is populated only
    # for EXPLAIN / EXPLAIN ANALYZE queries (the structured plan tree)
    plan_digest: str = ""
    explain: Optional[Dict[str, Any]] = None
    # event-time freshness of the answer (broker/freshness.py): now −
    # the stalest consumed event-time watermark over the realtime
    # partitions that served this query.  None for offline-only answers
    # — the key is then absent from the JSON, so pure-offline responses
    # stay byte-identical to the pre-audit-plane payloads.  Like
    # timeUsedMs/requestId, every byte-identity differential oracle
    # strips it (it is wall-clock-dependent accounting, not data).
    freshness_ms: Optional[float] = None

    def to_json(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {}
        if self.request_id:
            d["requestId"] = self.request_id
        if self.selection_results is not None:
            d["selectionResults"] = self.selection_results.to_json()
        if self.aggregation_results is not None:
            d["aggregationResults"] = [a.to_json() for a in self.aggregation_results]
        d["exceptions"] = [e.to_json() for e in self.exceptions]
        d["numDocsScanned"] = self.num_docs_scanned
        d["numEntriesScannedInFilter"] = self.num_entries_scanned_in_filter
        d["numEntriesScannedPostFilter"] = self.num_entries_scanned_post_filter
        d["totalDocs"] = self.total_docs
        d["numSegmentsQueried"] = self.num_segments_queried
        d["numServersQueried"] = self.num_servers_queried
        d["numServersResponded"] = self.num_servers_responded
        d["partialResponse"] = self.partial_response
        d["numSegmentsUnserved"] = self.num_segments_unserved
        if self.num_retries:
            d["numRetries"] = self.num_retries
        if self.num_hedges:
            d["numHedges"] = self.num_hedges
        if self.cost:
            d["cost"] = {
                k: (round(v, 3) if isinstance(v, float) else v)
                for k, v in sorted(self.cost.items())
            }
        d["timeUsedMs"] = round(self.time_used_ms, 3)
        if self.freshness_ms is not None:
            d["freshnessMs"] = round(self.freshness_ms, 3)
        if self.plan_digest:
            d["planDigest"] = self.plan_digest
        if self.explain is not None:
            d["explain"] = self.explain
        if self.trace_info:
            d["traceInfo"] = self.trace_info
        return d
