from pinot_tpu.startree.builder import StarTreeBuilderConfig, build_star_tree
from pinot_tpu.startree.index import StarTreeIndex, STAR
from pinot_tpu.startree.operator import is_fit_for_star_tree, execute_star_tree

__all__ = [
    "StarTreeBuilderConfig",
    "build_star_tree",
    "StarTreeIndex",
    "STAR",
    "is_fit_for_star_tree",
    "execute_star_tree",
]
