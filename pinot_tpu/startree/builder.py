"""Star-tree builder.

Algorithm mirrors the reference (``OffHeapStarTreeBuilder.java:96``,
algorithm doc :69-91): records are aggregated by the dimension split
order; each node splits on its level's dimension into per-value
children plus a star child whose records aggregate over that dimension
(deduped by the remaining dimensions); splitting stops at
``max_leaf_records`` or when dimensions run out.  Split order defaults
to descending cardinality (the reference's default heuristic).

Implementation is vectorized numpy throughout: grouping is
lexicographic sort + run detection (``np.unique(axis=0)``), and star
records are generated level-wise by masking the starred column and
re-aggregating — no per-record recursion.

HLL pre-aggregation (``config.hll_columns`` — the HllConfig
derived-column capability): each cube row carries a uint8[256] register
array sketching the configured column's values folded into it; rows
merge with elementwise max, so ``distinctcounthll``/``fasthll`` answer
from the cube too.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from pinot_tpu.common.schema import FieldType, Schema
from pinot_tpu.engine import hll as hll_mod
from pinot_tpu.segment.immutable import ImmutableSegment
from pinot_tpu.startree.index import STAR, StarTreeIndex, StarTreeNode
from pinot_tpu.utils.npgroup import group_max_rows, scatter_max_2d

Regs = Dict[str, np.ndarray]  # column -> uint8 [n, 256]


@dataclass
class StarTreeBuilderConfig:
    """StarTreeBuilderConfig analog (split order, leaf cap, skips,
    HLL columns)."""

    split_order: Optional[List[str]] = None
    max_leaf_records: int = 10_000
    skip_star_for_dims: List[str] = field(default_factory=list)
    hll_columns: List[str] = field(default_factory=list)


def _pack_keys(dims: np.ndarray, radices: Optional[np.ndarray]) -> Optional[np.ndarray]:
    """ONE mixed-radix int64 key per row (STAR=-1 offset in): sorting /
    uniquing the packed key is identical in order and grouping to
    lexicographic row operations, and a scalar int64 argsort is several
    times faster than np.unique(axis=0)'s structured-view sort — the
    dominant cost of large builds.  None when the radix product could
    overflow (callers fall back to the row-wise path)."""
    if radices is None:
        return None
    key = np.zeros(dims.shape[0], dtype=np.int64)
    for j in range(dims.shape[1]):
        key = key * int(radices[j]) + (dims[:, j].astype(np.int64) + 1)
    return key


def _dim_radices(cards: Sequence[int]) -> Optional[np.ndarray]:
    radices = np.asarray([int(c) + 1 for c in cards], dtype=np.int64)
    prod = 1.0
    for r in radices:
        prod *= float(r)
    if prod >= 2.0**62:
        return None
    return radices


def _unique_rows(dims: np.ndarray, radices: Optional[np.ndarray]):
    """(unique rows lexicographically sorted, inverse) — packed-key
    fast path when the radix product fits int64."""
    key = _pack_keys(dims, radices)
    if key is not None:
        _, index, inverse = np.unique(key, return_index=True, return_inverse=True)
        return dims[index], inverse
    return np.unique(dims, axis=0, return_inverse=True)


def _aggregate(
    dims: np.ndarray,
    sums: np.ndarray,
    counts: np.ndarray,
    regs: Optional[Regs],
    radices: Optional[np.ndarray] = None,
):
    """Group rows by all dim columns; sum metrics/counts, max registers.
    Output rows come back lexicographically SORTED (np.unique's order on
    either path) — the invariant split_node's run detection relies on,
    with no separate sort pass."""
    if dims.shape[0] == 0:
        return dims, sums, counts, regs
    uniq, inverse = _unique_rows(dims, radices)
    m = sums.shape[1]
    agg_sums = np.zeros((uniq.shape[0], m), dtype=np.float64)
    for j in range(m):
        agg_sums[:, j] = np.bincount(inverse, weights=sums[:, j], minlength=uniq.shape[0])
    agg_counts = np.bincount(inverse, weights=counts, minlength=uniq.shape[0]).astype(np.int64)
    agg_regs: Optional[Regs] = None
    if regs is not None:
        agg_regs = {
            col: group_max_rows(inverse, uniq.shape[0], r) for col, r in regs.items()
        }
    return uniq.astype(np.int32), agg_sums, agg_counts, agg_regs


class _Accum:
    """Append-only global record arrays."""

    def __init__(self, k: int, m: int, hll_cols: Sequence[str]) -> None:
        self.dims: List[np.ndarray] = []
        self.sums: List[np.ndarray] = []
        self.counts: List[np.ndarray] = []
        self.regs: Dict[str, List[np.ndarray]] = {c: [] for c in hll_cols}
        self.size = 0
        self.k = k
        self.m = m

    def append(self, dims, sums, counts, regs: Optional[Regs]) -> Tuple[int, int]:
        start = self.size
        self.dims.append(dims)
        self.sums.append(sums)
        self.counts.append(counts)
        if regs is not None:
            for c, r in regs.items():
                self.regs[c].append(r)
        self.size += dims.shape[0]
        return start, self.size

    def finalize(self):
        if not self.dims:
            return (
                np.zeros((0, self.k), np.int32),
                np.zeros((0, self.m), np.float64),
                np.zeros(0, np.int64),
                {c: np.zeros((0, hll_mod.M), np.uint8) for c in self.regs},
            )
        return (
            np.concatenate(self.dims),
            np.concatenate(self.sums),
            np.concatenate(self.counts),
            {c: np.concatenate(blocks) for c, blocks in self.regs.items()},
        )


def build_star_tree(
    segment: ImmutableSegment,
    schema: Schema,
    config: Optional[StarTreeBuilderConfig] = None,
) -> ImmutableSegment:
    """Attach a star-tree index to the segment (in place; returned for
    chaining).  Only single-value dimension/time columns participate;
    metrics must be numeric (reference: metrics are summed into
    MetricBuffers)."""
    config = config or StarTreeBuilderConfig()

    dim_cols = [
        s.name
        for s in schema.all_fields()
        if s.field_type in (FieldType.DIMENSION, FieldType.TIME) and s.single_value
    ]
    metric_cols = [
        s.name for s in schema.all_fields() if s.field_type == FieldType.METRIC and s.single_value
    ]

    split_order = list(config.split_order) if config.split_order else None
    if split_order is None:
        # default: descending cardinality (reference heuristic)
        split_order = sorted(
            dim_cols,
            key=lambda c: -segment.column(c).metadata.cardinality,
        )
    # HLL columns must not be split dims (they're the counted column)
    split_order = [c for c in split_order if c not in config.hll_columns]
    k, m = len(split_order), len(metric_cols)

    # base records: raw docs in dictId space
    n = segment.num_docs
    dims = (
        np.stack([segment.column(c).fwd for c in split_order], axis=1).astype(np.int32)
        if k
        else np.zeros((n, 0), np.int32)
    )
    sums = (
        np.stack(
            [
                np.asarray(segment.column(c).dictionary.values, dtype=np.float64)[
                    segment.column(c).fwd
                ]
                for c in metric_cols
            ],
            axis=1,
        )
        if m
        else np.zeros((n, 0), np.float64)
    )
    counts = np.ones(n, dtype=np.int64)

    radices = _dim_radices([segment.column(c).metadata.cardinality for c in split_order])

    # aggregate raw docs by all split dims; fold HLL registers in the
    # same pass via per-dictId (bucket, rho) tables
    if n:
        uniq, inverse = _unique_rows(dims, radices)
    else:
        uniq, inverse = np.zeros((0, k), np.int32), np.zeros(0, np.int64)
    agg_sums = np.zeros((uniq.shape[0], m), dtype=np.float64)
    for j in range(m):
        agg_sums[:, j] = np.bincount(inverse, weights=sums[:, j], minlength=uniq.shape[0])
    agg_counts = np.bincount(inverse, weights=counts, minlength=uniq.shape[0]).astype(np.int64)

    regs: Optional[Regs] = None
    if config.hll_columns:
        regs = {}
        for hcol in config.hll_columns:
            d = segment.column(hcol).dictionary
            # ONE shared per-dictId (bucket, rho) table build, cached on
            # the dictionary (hll.dictionary_tables) — the same tables
            # the staging/planner paths use, so repeated builds and
            # queries over this segment never re-hash the dictionary
            bucket, rho = hll_mod.dictionary_tables(d)
            fwd = segment.column(hcol).fwd
            regs[hcol] = scatter_max_2d(
                inverse, uniq.shape[0], bucket[fwd].astype(np.int64), rho[fwd], hll_mod.M
            )

    # rows are already lexicographically sorted (np.unique order)
    dims, sums, counts = uniq.astype(np.int32), agg_sums, agg_counts

    acc = _Accum(k, m, config.hll_columns)
    skip = set(config.skip_star_for_dims)

    def split_node(dims_b, sums_b, counts_b, regs_b, level: int, gstart: int) -> StarTreeNode:
        """Node over rows [gstart, gstart+len) of the flat table.
        Children reference subranges of the SAME block (records are
        stored once); only star children append new aggregated blocks."""
        node = StarTreeNode(level=level, start=gstart, end=gstart + dims_b.shape[0])
        if level >= k or dims_b.shape[0] <= config.max_leaf_records:
            return node
        col = dims_b[:, level]
        boundaries = np.flatnonzero(np.diff(col)) + 1
        run_starts = np.concatenate([[0], boundaries])
        run_ends = np.concatenate([boundaries, [col.size]])
        for rs, re_ in zip(run_starts, run_ends):
            rregs = {c: r[rs:re_] for c, r in regs_b.items()} if regs_b is not None else None
            node.children[int(col[rs])] = split_node(
                dims_b[rs:re_], sums_b[rs:re_], counts_b[rs:re_], rregs, level + 1, gstart + int(rs)
            )
        if split_order[level] not in skip:
            star_dims = dims_b.copy()
            star_dims[:, level] = STAR
            sd, ss, sc, sr = _aggregate(star_dims, sums_b, counts_b, regs_b, radices)
            sstart, _ = acc.append(sd, ss, sc, sr)
            node.star_child = split_node(sd, ss, sc, sr, level + 1, sstart)
        return node

    base_start, _ = acc.append(dims, sums, counts, regs)
    root = split_node(dims, sums, counts, regs, 0, base_start)

    flat_dims, flat_sums, flat_counts, flat_regs = acc.finalize()
    segment.star_tree = StarTreeIndex(
        split_order=split_order,
        metric_columns=metric_cols,
        dims=flat_dims,
        sums=flat_sums,
        counts=flat_counts,
        root=root,
        max_leaf_records=config.max_leaf_records,
        hll_columns=list(config.hll_columns),
        hll_registers=flat_regs if config.hll_columns else {},
    )
    segment.metadata.custom["starTree"] = {
        "splitOrder": split_order,
        "maxLeafRecords": config.max_leaf_records,
        "numRecords": int(flat_dims.shape[0]),
        "hllColumns": list(config.hll_columns),
    }
    return segment
