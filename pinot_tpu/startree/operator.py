"""Star-tree query execution.

Reference: eligibility gate ``RequestUtils.isFitForStarTreeIndex``
(used at ``FilterPlanNode.java:66-69``) + traversal operator
``StarTreeIndexOperator.java:53``.

Eligible queries — aggregation (optionally group-by) where every
function is count/sum/avg over metrics, the filter is a conjunction of
EQ/IN/RANGE predicates on split-order dimensions (cube rows live in
sorted-dictId space, so a range is a contiguous dictId interval —
``StarTreeIndexOperator.java:53`` handles the same mixed shapes), and
group-by columns are split-order dimensions — are answered from the
pre-aggregated cube:
host traversal picks [start, end) ranges (star rows wherever a
dimension is unconstrained), residual predicates and the aggregation
itself run vectorized over those rows.  ``numDocsScanned`` reports
pre-agg rows visited — the reference's headline star-tree effect
(3 docs scanned instead of 6M, BASELINE.md).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from pinot_tpu.common.request import BrokerRequest, FilterOperator, FilterQueryTree
from pinot_tpu.common.values import render_value
from pinot_tpu.engine.results import (
    AvgPartial,
    CountPartial,
    HllPartial,
    IntermediateResult,
    SumPartial,
)
from pinot_tpu.segment.immutable import ImmutableSegment
from pinot_tpu.startree.index import STAR, StarTreeIndex, StarTreeNode

_FIT_AGGS = ("count", "sum", "avg")


class _Constraint:
    """Predicate constraint on one dimension in local dictId space:
    either an explicit id set (EQ/IN) or a half-open interval (RANGE —
    kept as an interval so a wide range on a high-cardinality split
    dimension costs two compares, not an O(card) materialized set)."""

    __slots__ = ("ids", "lo", "hi")

    def __init__(self, ids: Optional[Set[int]] = None, lo: int = 0, hi: int = 0):
        self.ids = ids
        self.lo = lo
        self.hi = hi

    def intersect(self, other: "_Constraint") -> "_Constraint":
        if self.ids is not None and other.ids is not None:
            return _Constraint(ids=self.ids & other.ids)
        if self.ids is None and other.ids is None:
            return _Constraint(lo=max(self.lo, other.lo), hi=min(self.hi, other.hi))
        ids = self.ids if self.ids is not None else other.ids
        iv = other if self.ids is not None else self
        return _Constraint(ids={i for i in ids if iv.lo <= i < iv.hi})

    def contains(self, dict_id: int) -> bool:
        if self.ids is not None:
            return dict_id in self.ids
        return self.lo <= dict_id < self.hi

    def matching_children(self, children: Dict[int, "StarTreeNode"]):
        if self.ids is not None and len(self.ids) < len(children):
            return (children[i] for i in self.ids if i in children)
        return (c for i, c in children.items() if self.contains(i))

    def mask(self, vals: np.ndarray) -> np.ndarray:
        if self.ids is not None:
            if not self.ids:
                return np.zeros(vals.size, bool)
            return np.isin(vals, np.asarray(sorted(self.ids), dtype=np.int64))
        return (vals >= self.lo) & (vals < self.hi)


def _conjunctive_eq_leaves(tree: Optional[FilterQueryTree]) -> Optional[List[FilterQueryTree]]:
    """Flatten an AND-only tree of EQ/IN/RANGE leaves; None otherwise."""
    if tree is None:
        return []
    if tree.is_leaf:
        if tree.operator in (
            FilterOperator.EQUALITY,
            FilterOperator.IN,
            FilterOperator.RANGE,
        ):
            return [tree]
        return None
    if tree.operator != FilterOperator.AND:
        return None
    out: List[FilterQueryTree] = []
    for c in tree.children:
        sub = _conjunctive_eq_leaves(c)
        if sub is None:
            return None
        out.extend(sub)
    return out


def is_fit_for_star_tree(request: BrokerRequest, segment: ImmutableSegment) -> bool:
    tree: Optional[StarTreeIndex] = getattr(segment, "star_tree", None)
    if tree is None or not request.is_aggregation:
        return False
    for agg in request.aggregations:
        if agg.is_mv:
            return False
        base = agg.base_function
        if base in ("distinctcounthll", "fasthll"):
            if agg.column not in tree.hll_columns:
                return False
        elif base not in _FIT_AGGS:
            return False
        elif agg.column != "*" and agg.column not in tree.metric_columns:
            return False
    leaves = _conjunctive_eq_leaves(request.filter)
    if leaves is None:
        return False
    split = set(tree.split_order)
    for leaf in leaves:
        if leaf.column not in split:
            return False
    if request.is_group_by:
        for col in request.group_by.columns:
            if col not in split:
                return False
    return True


def _traverse(
    node: StarTreeNode,
    split_order: List[str],
    constraints: Dict[str, "_Constraint"],
    group_dims: Set[str],
) -> List[Tuple[int, int]]:
    if node.is_leaf:
        return [(node.start, node.end)]
    dim = split_order[node.level]
    ranges: List[Tuple[int, int]] = []
    if dim in constraints:
        for child in constraints[dim].matching_children(node.children):
            ranges.extend(_traverse(child, split_order, constraints, group_dims))
    elif dim in group_dims:
        for child in node.children.values():
            ranges.extend(_traverse(child, split_order, constraints, group_dims))
    elif node.star_child is not None:
        ranges.extend(_traverse(node.star_child, split_order, constraints, group_dims))
    else:
        for child in node.children.values():
            ranges.extend(_traverse(child, split_order, constraints, group_dims))
    return ranges


def execute_star_tree(segment: ImmutableSegment, request: BrokerRequest) -> IntermediateResult:
    tree: StarTreeIndex = segment.star_tree
    split = tree.split_order

    # predicate constraints in local dictId space; RANGE leaves stay
    # contiguous dictId intervals (dictionaries are sorted)
    constraints: Dict[str, _Constraint] = {}
    for leaf in _conjunctive_eq_leaves(request.filter) or []:
        d = segment.column(leaf.column).dictionary
        if leaf.operator == FilterOperator.RANGE:
            from pinot_tpu.engine.plan import leaf_interval

            lo, hi = leaf_interval(leaf, d)
            c = _Constraint(lo=lo, hi=hi)
        else:
            ids = {d.index_of(d.stored_type.convert(v)) for v in leaf.values}
            ids.discard(-1)
            c = _Constraint(ids=ids)
        prev = constraints.get(leaf.column)
        constraints[leaf.column] = c if prev is None else prev.intersect(c)

    group_cols = list(request.group_by.columns) if request.is_group_by else []
    ranges = _traverse(tree.root, split, constraints, set(group_cols))

    if ranges:
        rows = np.concatenate([np.arange(s, e) for s, e in ranges])
    else:
        rows = np.zeros(0, dtype=np.int64)

    # residual predicate masks (idempotent over already-descended dims)
    mask = np.ones(rows.size, dtype=bool)
    level_of = {c: i for i, c in enumerate(split)}
    for col, c in constraints.items():
        vals = tree.dims[rows, level_of[col]]
        mask &= c.mask(vals)
    rows = rows[mask]

    counts = tree.counts[rows]
    res = IntermediateResult(
        num_docs_scanned=int(rows.size),
        total_docs=segment.num_docs,
        num_segments_queried=1,
    )
    # cost vector: cube rows touched (dims + counts), star-tree tier
    res.add_cost(
        segmentsStarTree=1,
        bytesScanned=int(rows.size)
        * (tree.dims.shape[1] * tree.dims.itemsize + tree.counts.itemsize),
    )

    def scalar_partial(agg, sel=slice(None)):
        base = agg.base_function
        if base == "count":
            return CountPartial(float(counts[sel].sum()))
        if base in ("distinctcounthll", "fasthll"):
            regs = tree.hll_registers[agg.column][rows[sel]]
            merged = regs.max(axis=0) if regs.shape[0] else np.zeros(regs.shape[1], np.uint8)
            return HllPartial(merged)
        mi = tree.metric_columns.index(agg.column)
        s = float(tree.sums[rows[sel], mi].sum())
        if base == "sum":
            return SumPartial(s)
        return AvgPartial(s, float(counts[sel].sum()))

    if not request.is_group_by:
        res.aggregations = [scalar_partial(a) for a in request.aggregations]
        return res

    # group-by: keys from the dims matrix (real values — traversal never
    # stars group-by dims), rendered via the segment dictionaries.
    # States build VECTORIZED over the inverse index — a per-group
    # boolean mask would re-scan all pre-agg rows per group (O(R x G),
    # ~0.4 ms/group in Python at cube scale)
    glevels = [level_of[c] for c in group_cols]
    gdicts = [segment.column(c).dictionary for c in group_cols]
    key_matrix = tree.dims[rows][:, glevels] if rows.size else np.zeros((0, len(glevels)), np.int32)
    groups: Dict[Tuple[str, ...], list] = {}
    if rows.size:
        uniq, inverse = np.unique(key_matrix, axis=0, return_inverse=True)
        G = uniq.shape[0]
        cnt_g = np.bincount(inverse, weights=counts, minlength=G)
        order = boundaries = None  # lazily built for register merges
        agg_states = []
        for a in request.aggregations:
            base = a.base_function
            if base == "count":
                agg_states.append(("count",))
            elif base in ("distinctcounthll", "fasthll"):
                if order is None:
                    order = np.argsort(inverse, kind="stable")
                    rows_sorted = rows[order]
                    boundaries = np.searchsorted(inverse[order], np.arange(G))
                # one gather in sorted order + reduceat (ufunc.at runs an
                # element-wise Python-speed loop)
                regs_g = np.maximum.reduceat(
                    tree.hll_registers[a.column][rows_sorted], boundaries, axis=0
                )
                agg_states.append(("hll", regs_g))
            else:
                mi = tree.metric_columns.index(a.column)
                sums_g = np.bincount(
                    inverse, weights=tree.sums[rows, mi], minlength=G
                )
                agg_states.append(("sum" if base == "sum" else "avg", sums_g))
        for gi in range(G):
            key = tuple(
                render_value(gdicts[j].stored_type, gdicts[j].get(int(uniq[gi, j])))
                for j in range(len(group_cols))
            )
            parts = []
            for st in agg_states:
                if st[0] == "count":
                    parts.append(CountPartial(float(cnt_g[gi])))
                elif st[0] == "hll":
                    parts.append(HllPartial(st[1][gi]))
                elif st[0] == "sum":
                    parts.append(SumPartial(float(st[1][gi])))
                else:
                    parts.append(AvgPartial(float(st[1][gi]), float(cnt_g[gi])))
            groups[key] = parts
    res.groups = groups
    return res
