"""Star-tree index structures.

The reference serializes a node tree over materialized aggregate
records (``StarTreeSerDe.java``, ``StarTreeIndexNode``).  The TPU-first
representation is a **flat pre-aggregated cube table**:

  dims    int32 [n_agg, k]   dictIds per split-order dimension,
                             STAR (-1) where a row aggregates over a dim
  sums    f64   [n_agg, m]   per-metric sums
  counts  i64   [n_agg]      raw docs folded into the row

plus a small host-side node tree whose leaves are [start, end) ranges
into that table.  Query-time traversal (host, O(tree)) picks ranges;
the aggregation over them is an ordinary vectorized scan — so the
"index" is just a smaller table for the same engine, which is exactly
what a TPU wants.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

STAR = -1  # dictId sentinel: this row aggregates over the dimension


@dataclass
class StarTreeNode:
    level: int  # dimension index this node's children split on
    start: int
    end: int
    children: Dict[int, "StarTreeNode"] = field(default_factory=dict)  # dictId -> node
    star_child: Optional["StarTreeNode"] = None

    @property
    def is_leaf(self) -> bool:
        return not self.children and self.star_child is None

    def to_json(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"level": int(self.level), "start": int(self.start), "end": int(self.end)}
        if self.children:
            d["children"] = {str(k): v.to_json() for k, v in self.children.items()}
        if self.star_child is not None:
            d["star"] = self.star_child.to_json()
        return d

    @classmethod
    def from_json(cls, d: Dict[str, Any]) -> "StarTreeNode":
        node = cls(level=d["level"], start=d["start"], end=d["end"])
        for k, v in d.get("children", {}).items():
            node.children[int(k)] = cls.from_json(v)
        if "star" in d:
            node.star_child = cls.from_json(d["star"])
        return node


@dataclass
class StarTreeIndex:
    split_order: List[str]  # dimension column names, split order
    metric_columns: List[str]
    dims: np.ndarray  # int32 [n_agg, k]
    sums: np.ndarray  # float64 [n_agg, m]
    counts: np.ndarray  # int64 [n_agg]
    root: StarTreeNode
    max_leaf_records: int
    # HLL pre-aggregation (the derived-HLL-column capability,
    # HllConfig/HllUtil analogs): per configured column, uint8 register
    # arrays [n_agg, 256] merged with elementwise max.
    hll_columns: List[str] = field(default_factory=list)
    hll_registers: Dict[str, np.ndarray] = field(default_factory=dict)

    @property
    def num_records(self) -> int:
        return int(self.dims.shape[0])
