"""Kafka binary wire protocol (v0) — client, StreamProvider, and a
protocol-compat server shim.

The reference consumes real Kafka through
``core/realtime/impl/kafka/SimpleConsumerWrapper.java`` (LLC: Metadata
to find partition leaders, ListOffsets for earliest/latest, Fetch by
exact offset) and the high-level consumer for HLC.  No Kafka client
library ships in this image, so this module implements the wire
protocol itself — the v0 request/response encodings every Kafka broker
since 0.8 answers:

  Metadata    (api_key 3, v0): topics -> brokers + partition leaders
  ListOffsets (api_key 2, v0): (topic, partition, time -1|-2) -> offsets
  Fetch       (api_key 1, v0): (topic, partition, offset) -> MessageSet

MessageSet v0 is a raw byte stream of [offset int64 | size int32 |
crc int32 | magic int8 | attrs int8 | key bytes | value bytes]; a
truncated trailing message (the broker cuts at max_bytes) is dropped,
as the protocol requires.

``KafkaStreamProvider`` adapts the client to the offset-addressed
``StreamProvider`` interface the LLC/HLC machinery consumes (rows are
JSON message values, the ``KafkaJSONMessageDecoder`` analog).

``KafkaProtocolShim`` serves the SAME wire protocol over an in-process
``StreamBrokerServer``'s topic logs, so the client integration-tests
against real sockets without a Kafka deployment — the
``FileBasedStreamProviderImpl.java`` test-fake pattern, upgraded to
wire compatibility.  Pointing ``KafkaStreamProvider`` at a real Kafka
0.8+ broker is the same code path.
"""
from __future__ import annotations

import gzip
import io
import json
import socket
import struct
import threading
import zlib
from typing import Any, Dict, List, Optional, Tuple

from pinot_tpu.realtime.stream import Row, StreamProvider

API_FETCH = 1
API_LIST_OFFSETS = 2
API_METADATA = 3

EARLIEST = -2
LATEST = -1

ERR_NONE = 0
ERR_UNKNOWN_TOPIC = 3
ERR_OFFSET_OUT_OF_RANGE = 1
# shim-specific (outside the v0 error range): the addressed partition
# stores verbatim columnar blocks, which the row-oriented Kafka wire
# protocol cannot serve.  Mirrors the netstream broker's typed
# '{"error": "columnar partition"}' rejection — without it a populated
# columnar partition silently reported high-watermark 0 and consumers
# idled forever believing the partition empty.
ERR_COLUMNAR_PARTITION = 87


class ColumnarPartitionError(IOError):
    """A Kafka-protocol fetch/offsets request addressed a columnar-mode
    partition; consume it via the netstream fetchc transport instead."""


# -- primitive encoders ------------------------------------------------


def _i8(v: int) -> bytes:
    return struct.pack(">b", v)


def _i16(v: int) -> bytes:
    return struct.pack(">h", v)


def _i32(v: int) -> bytes:
    return struct.pack(">i", v)


def _i64(v: int) -> bytes:
    return struct.pack(">q", v)


def _string(s: Optional[str]) -> bytes:
    if s is None:
        return _i16(-1)
    b = s.encode()
    return _i16(len(b)) + b


def _bytes(b: Optional[bytes]) -> bytes:
    if b is None:
        return _i32(-1)
    return _i32(len(b)) + b


class _Reader:
    def __init__(self, data: bytes) -> None:
        self._io = io.BytesIO(data)

    def _take(self, n: int) -> bytes:
        b = self._io.read(n)
        if len(b) != n:
            raise EOFError("short read")
        return b

    def i8(self) -> int:
        return struct.unpack(">b", self._take(1))[0]

    def i16(self) -> int:
        return struct.unpack(">h", self._take(2))[0]

    def i32(self) -> int:
        return struct.unpack(">i", self._take(4))[0]

    def i64(self) -> int:
        return struct.unpack(">q", self._take(8))[0]

    def string(self) -> Optional[str]:
        n = self.i16()
        return None if n < 0 else self._take(n).decode()

    def bytes(self) -> Optional[bytes]:
        n = self.i32()
        return None if n < 0 else self._take(n)

    def remaining(self) -> bytes:
        return self._io.read()


# -- MessageSet v0 -----------------------------------------------------


def encode_message(
    offset: int, value: bytes, key: Optional[bytes] = None, codec: int = 0
) -> bytes:
    body = _i8(0) + _i8(codec & 0x07) + _bytes(key) + _bytes(value)  # magic 0
    msg = _i32(_signed_crc(body)) + body
    return _i64(offset) + _i32(len(msg)) + msg


_CODEC_IDS = {"gzip": 1, "snappy": 2, "lz4": 3}


def compress_message_set(data: bytes, codec_name: str) -> bytes:
    """Compress an inner MessageSet with the named codec, producing the
    bytes a producer puts in the wrapper message's value."""
    if codec_name == "gzip":
        return gzip.compress(data)
    if codec_name == "snappy":
        from pinot_tpu.utils.snappy import compress as snappy_compress

        return snappy_compress(data)
    if codec_name == "lz4":
        from pinot_tpu.utils.lz4 import compress_frame

        return compress_frame(data)
    raise ValueError(f"unknown codec {codec_name!r}")


def _signed_crc(b: bytes) -> int:
    c = zlib.crc32(b) & 0xFFFFFFFF
    return c - (1 << 32) if c >= (1 << 31) else c


def decode_message_set(data: bytes) -> List[Tuple[int, Optional[bytes], bytes]]:
    """-> [(offset, key, value)]; silently drops a truncated tail (the
    broker cuts MessageSets at max_bytes mid-message by design)."""
    out: List[Tuple[int, Optional[bytes], bytes]] = []
    pos = 0
    n = len(data)
    while pos + 12 <= n:
        offset, size = struct.unpack(">qi", data[pos : pos + 12])
        if pos + 12 + size > n:
            break  # truncated tail
        r = _Reader(data[pos + 12 : pos + 12 + size])
        crc = r.i32()
        body = data[pos + 16 : pos + 12 + size]
        if _signed_crc(body) != crc:
            raise ValueError(f"message CRC mismatch at offset {offset}")
        r.i8()  # magic
        attrs = r.i8()
        key = r.bytes()
        value = r.bytes()
        codec = attrs & 0x07
        if codec == 0:
            out.append((offset, key, value if value is not None else b""))
        elif codec == 1:  # gzip wrapper: value is an inner MessageSet
            out.extend(decode_message_set(gzip.decompress(value or b"")))
        elif codec == 2:  # snappy (incl. xerial framing): pure-Python
            from pinot_tpu.utils.snappy import decompress as snappy_decompress

            out.extend(decode_message_set(snappy_decompress(value or b"")))
        elif codec == 3:  # lz4 frame (incl. KAFKA-3160 header tolerance)
            from pinot_tpu.utils.lz4 import decompress as lz4_decompress

            out.extend(decode_message_set(lz4_decompress(value or b"")))
        else:
            # fail loudly instead of handing compressed bytes to the
            # row decoder
            raise ValueError(
                f"unsupported message compression codec {codec} at offset "
                f"{offset} (gzip=1, snappy=2, lz4=3 are supported)"
            )
        pos += 12 + size
    return out


# -- client ------------------------------------------------------------


class KafkaWireClient:
    """Blocking single-connection Kafka v0 client (the
    ``SimpleConsumerWrapper.java`` analog)."""

    def __init__(self, host: str, port: int, client_id: str = "pinot-tpu", timeout: float = 30.0) -> None:
        self.host, self.port = host, port
        self.client_id = client_id
        self.timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._corr = 0
        self._lock = threading.Lock()

    def _connect(self) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection((self.host, self.port), timeout=self.timeout)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = s
        return self._sock

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def _roundtrip(self, api_key: int, body: bytes) -> _Reader:
        with self._lock:
            self._corr += 1
            corr = self._corr
            header = _i16(api_key) + _i16(0) + _i32(corr) + _string(self.client_id)
            payload = header + body
            try:
                s = self._connect()
                s.sendall(_i32(len(payload)) + payload)
                resp = self._read_frame(s)
            except (OSError, EOFError):
                # one reconnect ride-through (broker restart / idle reap)
                self.close()
                s = self._connect()
                s.sendall(_i32(len(payload)) + payload)
                resp = self._read_frame(s)
        r = _Reader(resp)
        got = r.i32()
        if got != corr:
            raise ValueError(f"correlation mismatch: sent {corr} got {got}")
        return r

    @staticmethod
    def _read_frame(s: socket.socket) -> bytes:
        hdr = b""
        while len(hdr) < 4:
            chunk = s.recv(4 - len(hdr))
            if not chunk:
                raise EOFError("connection closed")
            hdr += chunk
        (n,) = struct.unpack(">i", hdr)
        buf = b""
        while len(buf) < n:
            chunk = s.recv(min(65536, n - len(buf)))
            if not chunk:
                raise EOFError("connection closed mid-frame")
            buf += chunk
        return buf

    # -- api calls -----------------------------------------------------
    def metadata(self, topics: Optional[List[str]] = None) -> Dict[str, Any]:
        ts = topics or []
        body = _i32(len(ts)) + b"".join(_string(t) for t in ts)
        r = self._roundtrip(API_METADATA, body)
        brokers = []
        for _ in range(r.i32()):
            node = r.i32()
            host = r.string()
            port = r.i32()
            brokers.append({"nodeId": node, "host": host, "port": port})
        topics_out = {}
        for _ in range(r.i32()):
            terr = r.i16()
            name = r.string()
            parts = {}
            for _ in range(r.i32()):
                perr = r.i16()
                pid = r.i32()
                leader = r.i32()
                replicas = [r.i32() for _ in range(r.i32())]
                isr = [r.i32() for _ in range(r.i32())]
                parts[pid] = {
                    "error": perr,
                    "leader": leader,
                    "replicas": replicas,
                    "isr": isr,
                }
            topics_out[name] = {"error": terr, "partitions": parts}
        return {"brokers": brokers, "topics": topics_out}

    def list_offsets(self, topic: str, partition: int, time: int = LATEST) -> List[int]:
        body = (
            _i32(-1)  # replica_id
            + _i32(1)
            + _string(topic)
            + _i32(1)
            + _i32(partition)
            + _i64(time)
            + _i32(1)  # max_num_offsets
        )
        r = self._roundtrip(API_LIST_OFFSETS, body)
        offsets: List[int] = []
        for _ in range(r.i32()):
            r.string()  # topic
            for _ in range(r.i32()):
                r.i32()  # partition
                err = r.i16()
                got = [r.i64() for _ in range(r.i32())]
                if err == ERR_COLUMNAR_PARTITION:
                    raise ColumnarPartitionError(
                        f"columnar partition {topic}/{partition}: not servable "
                        "over the row-oriented Kafka protocol (use fetchc)"
                    )
                if err != ERR_NONE:
                    raise IOError(f"ListOffsets error {err} for {topic}/{partition}")
                offsets.extend(got)
        return offsets

    MAX_FETCH_BYTES = 64 << 20  # growth cap for a single oversized message

    def fetch(
        self, topic: str, partition: int, offset: int, max_bytes: int = 1 << 20
    ) -> List[Tuple[int, Optional[bytes], bytes]]:
        while True:
            msgs, raw_len, decoded_any = self._fetch_once(
                topic, partition, offset, max_bytes
            )
            if msgs or raw_len == 0:
                return msgs
            # bytes came back but nothing usable decoded.  Two cases,
            # both cured by growing max_bytes (the reference
            # SimpleConsumer loop does the same): a single message
            # larger than max_bytes (truncated by the broker), or a
            # stored compressed wrapper wholly below the requested
            # offset with the NEXT wrapper cut off (decoded_any) —
            # growing lets that next wrapper fit.
            if max_bytes >= self.MAX_FETCH_BYTES:
                why = (
                    "below-offset wrapper region"
                    if decoded_any
                    else "message"
                )
                raise IOError(
                    f"{why} at {topic}/{partition}@{offset} exceeds "
                    f"{self.MAX_FETCH_BYTES} bytes"
                )
            max_bytes = min(max_bytes * 2, self.MAX_FETCH_BYTES)

    def _fetch_once(
        self, topic: str, partition: int, offset: int, max_bytes: int
    ) -> Tuple[List[Tuple[int, Optional[bytes], bytes]], int, bool]:
        body = (
            _i32(-1)  # replica_id
            + _i32(100)  # max_wait_ms
            + _i32(0)  # min_bytes
            + _i32(1)
            + _string(topic)
            + _i32(1)
            + _i32(partition)
            + _i64(offset)
            + _i32(max_bytes)
        )
        r = self._roundtrip(API_FETCH, body)
        msgs: List[Tuple[int, Optional[bytes], bytes]] = []
        raw_len = 0
        decoded_any = False
        for _ in range(r.i32()):
            r.string()  # topic
            for _ in range(r.i32()):
                r.i32()  # partition
                err = r.i16()
                r.i64()  # high watermark
                size = r.i32()
                data = r._take(size)
                if err == ERR_OFFSET_OUT_OF_RANGE:
                    raise IndexError(f"offset {offset} out of range for {topic}/{partition}")
                if err == ERR_COLUMNAR_PARTITION:
                    raise ColumnarPartitionError(
                        f"columnar partition {topic}/{partition}: not servable "
                        "over the row-oriented Kafka protocol (use fetchc)"
                    )
                if err != ERR_NONE:
                    raise IOError(f"Fetch error {err} for {topic}/{partition}")
                raw_len += len(data)
                # a REAL broker serves stored compressed wrappers whose
                # inner set may start BEFORE the requested offset (the
                # wrapper is the log unit); skip the below-offset inner
                # messages or they would re-ingest as duplicates
                decoded = decode_message_set(data)
                decoded_any = decoded_any or bool(decoded)
                msgs.extend(m for m in decoded if m[0] >= offset)
        return msgs, raw_len, decoded_any


class KafkaStreamProvider(StreamProvider):
    """LLC-shaped provider over the wire client: JSON message values
    decode to rows (``KafkaJSONMessageDecoder`` analog)."""

    def __init__(self, host: str, port: int, topic: str) -> None:
        self.host, self.port, self.topic = host, int(port), topic
        self.client = KafkaWireClient(host, int(port))

    def describe(self) -> Dict[str, Any]:
        return {"type": "kafka", "host": self.host, "port": self.port, "topic": self.topic}

    def partition_count(self) -> int:
        meta = self.client.metadata([self.topic])
        t = meta["topics"].get(self.topic)
        if t is None or t["error"] != ERR_NONE:
            raise IOError(f"topic {self.topic!r} metadata error: {t}")
        return len(t["partitions"])

    def fetch(self, partition: int, offset: int, max_rows: int) -> Tuple[List[Row], int]:
        # size the request to the row budget (adaptive avg message size)
        # instead of always pulling 1MB and discarding past max_rows —
        # otherwise the same tail bytes cross the socket every step
        est = getattr(self, "_avg_msg_bytes", 512)
        max_bytes = max(16384, min(1 << 20, max_rows * est * 2))
        msgs = self.client.fetch(self.topic, partition, offset, max_bytes=max_bytes)
        rows: List[Row] = []
        nxt = offset
        total_b = 0
        for moff, _key, value in msgs[:max_rows]:
            rows.append(json.loads(value.decode()))
            total_b += len(value) + 26  # + v0 header/crc overhead
            nxt = moff + 1
        if rows:
            self._avg_msg_bytes = max(64, total_b // len(rows))
        return rows, nxt

    def latest_offset(self, partition: int) -> int:
        offs = self.client.list_offsets(self.topic, partition, LATEST)
        return offs[0] if offs else 0


# -- protocol-compat server shim --------------------------------------


class KafkaProtocolShim:
    """Kafka v0 wire protocol served over a ``StreamBrokerServer``'s
    topic logs: the integration seam that lets the wire client run
    against real sockets without a Kafka deployment."""

    def __init__(
        self,
        stream_broker,
        host: str = "127.0.0.1",
        port: int = 0,
        compression: Optional[str] = None,
    ) -> None:
        from pinot_tpu.realtime.kafka_group import GroupCoordinator

        if compression is not None and compression not in _CODEC_IDS:
            raise ValueError(f"unknown compression {compression!r}")
        self.compression = compression  # fetch batches ship compressed
        self.broker = stream_broker
        self.coordinator = GroupCoordinator()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(16)
        self.address = self._srv.getsockname()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)

    def start(self) -> "KafkaProtocolShim":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,), daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        try:
            while not self._stop.is_set():
                try:
                    frame = KafkaWireClient._read_frame(conn)
                except (EOFError, OSError):
                    return
                r = _Reader(frame)
                api_key = r.i16()
                r.i16()  # api_version (v0 assumed)
                corr = r.i32()
                r.string()  # client_id
                if api_key == API_METADATA:
                    body = self._metadata(r)
                elif api_key == API_LIST_OFFSETS:
                    body = self._list_offsets(r)
                elif api_key == API_FETCH:
                    body = self._fetch(r)
                else:
                    body = self._group_api(api_key, r)
                    if body is None:
                        return  # unsupported api: drop the connection
                payload = _i32(corr) + body
                conn.sendall(_i32(len(payload)) + payload)
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _group_api(self, api_key: int, r: _Reader) -> Optional[bytes]:
        """Consumer-group coordinator APIs (kafka_group.py)."""
        from pinot_tpu.realtime import kafka_group as kg

        c = self.coordinator
        if api_key == kg.API_FIND_COORDINATOR:
            return c.find_coordinator(r, self.address)
        if api_key == kg.API_JOIN_GROUP:
            return c.join_group(r)
        if api_key == kg.API_SYNC_GROUP:
            return c.sync_group(r)
        if api_key == kg.API_HEARTBEAT:
            return c.heartbeat(r)
        if api_key == kg.API_LEAVE_GROUP:
            return c.leave_group(r)
        if api_key == kg.API_OFFSET_COMMIT:
            return c.offset_commit(r)
        if api_key == kg.API_OFFSET_FETCH:
            return c.offset_fetch(r)
        return None

    # topic access over the stream broker's internal state
    def _topic(self, name: str):
        return self.broker._topics.get(name)

    def _metadata(self, r: _Reader) -> bytes:
        want = [r.string() for _ in range(r.i32())]
        with self.broker._lock:
            names = list(self.broker._topics) if not want else [w for w in want]
        host, port = self.address
        out = _i32(1) + _i32(0) + _string(host) + _i32(port)  # one broker, node 0
        body = _i32(len(names))
        for name in names:
            t = self._topic(name)
            if t is None:
                body += _i16(ERR_UNKNOWN_TOPIC) + _string(name) + _i32(0)
                continue
            nparts = len(t.raw)
            body += _i16(ERR_NONE) + _string(name) + _i32(nparts)
            for p in range(nparts):
                body += (
                    _i16(ERR_NONE) + _i32(p) + _i32(0) + _i32(1) + _i32(0) + _i32(1) + _i32(0)
                )
        return out + body

    def _list_offsets(self, r: _Reader) -> bytes:
        r.i32()  # replica_id
        body = b""
        ntopics = r.i32()
        body += _i32(ntopics)
        for _ in range(ntopics):
            name = r.string()
            nparts = r.i32()
            body += _string(name) + _i32(nparts)
            t = self._topic(name)
            for _ in range(nparts):
                pid = r.i32()
                time = r.i64()
                r.i32()  # max_num_offsets
                if t is None or pid >= len(t.raw):
                    body += _i32(pid) + _i16(ERR_UNKNOWN_TOPIC) + _i32(0)
                    continue
                if t.columnar is not None and t.columnar.counts[pid]:
                    body += _i32(pid) + _i16(ERR_COLUMNAR_PARTITION) + _i32(0)
                    continue
                off = 0 if time == EARLIEST else len(t.raw[pid])
                body += _i32(pid) + _i16(ERR_NONE) + _i32(1) + _i64(off)
        return body

    def _fetch(self, r: _Reader) -> bytes:
        r.i32()  # replica_id
        r.i32()  # max_wait
        r.i32()  # min_bytes
        ntopics = r.i32()
        body = _i32(ntopics)
        for _ in range(ntopics):
            name = r.string()
            nparts = r.i32()
            body += _string(name) + _i32(nparts)
            t = self._topic(name)
            for _ in range(nparts):
                pid = r.i32()
                offset = r.i64()
                max_bytes = r.i32()
                if t is None or pid >= len(t.raw):
                    body += _i32(pid) + _i16(ERR_UNKNOWN_TOPIC) + _i64(0) + _i32(0)
                    continue
                if t.columnar is not None and t.columnar.counts[pid]:
                    # typed rejection, not a silent empty reply: the
                    # partition HAS data, just not row-protocol data
                    body += _i32(pid) + _i16(ERR_COLUMNAR_PARTITION) + _i64(0) + _i32(0)
                    continue
                log = t.raw[pid]  # stored serialized bytes, verbatim
                hw = len(log)
                if offset > hw:
                    body += _i32(pid) + _i16(ERR_OFFSET_OUT_OF_RANGE) + _i64(hw) + _i32(0)
                    continue
                parts = []  # complete encodings, shared by both paths
                size = 0
                tail = b""  # truncated partial message (raw path only)
                o = offset
                while o < hw:
                    m = encode_message(o, log[o])
                    if size + len(m) > max_bytes:
                        # real-broker behavior: cut the MessageSet at
                        # max_bytes, leaving a truncated partial message
                        # the client must drop (and grow+retry when it
                        # was the FIRST message)
                        tail = m[: max(0, max_bytes - size)]
                        break
                    parts.append(m)
                    size += len(m)
                    o += 1
                msgs = b"".join(parts) + tail
                if self.compression is not None and o > offset:
                    # producer-style wrapper: inner set compressed, the
                    # wrapper carries the LAST inner offset (the 0.8/0.9
                    # convention) and the codec bits in attrs; like the
                    # raw path, an over-budget wrapper is CUT at
                    # max_bytes (the stored-compressed-log behavior) so
                    # the client's grow+retry handling still engages.
                    # (A real broker's stored wrapper may also START
                    # below the requested offset — the client filters
                    # below-offset inner messages, _fetch_once.)
                    # An incompressible payload can make the wrapper
                    # exceed max_bytes even though the raw set fit — at
                    # the client's MAX_FETCH_BYTES ceiling that would
                    # turn a servable batch into a permanent truncation
                    # (ADVICE r3).  Re-pack with fewer messages until
                    # the wrapper fits; only a single message that still
                    # doesn't fit gets cut (the grow+retry case).
                    while True:
                        wrapper = encode_message(
                            offset + len(parts) - 1,
                            compress_message_set(b"".join(parts), self.compression),
                            codec=_CODEC_IDS[self.compression],
                        )
                        if len(wrapper) <= max_bytes or len(parts) <= 1:
                            break
                        parts.pop()
                    msgs = wrapper[:max_bytes]
                body += _i32(pid) + _i16(ERR_NONE) + _i64(hw) + _i32(len(msgs)) + msgs
        return body
