"""Ingest consumer pool: bounded worker threads driving N per-partition
realtime consumers.

Before r15 every realtime consumer owned a dedicated thread
(``server/network_starter.py RemoteConsumer._run``) or was stepped
manually by the harness (``realtime/llc.py
RealtimeSegmentDataManager.consume_step``).  At fleet breadth — 100+
tables, each with one consumer per stream partition — a
thread-per-consumer server melts into scheduler thrash, and the
in-process harness had no background ingest at all.

The pool is the LLC analog of the reference's shared realtime consumer
executor (``RealtimeSegmentDataManager`` instances multiplexed over a
bounded segment-build/consume thread budget): consumers register a
cooperative ``step()`` — one bounded unit of fetch+index (+ completion
protocol) work that NEVER blocks on a wait — and ``PINOT_TPU_INGEST_CONSUMERS``
worker threads (default 4) drive the ready consumer with the earliest
eligible time.  ``step()`` returns:

- ``0.0`` — made progress, immediately eligible again;
- ``t > 0`` — idle/held (backpressure pause, stream empty, completion
  HOLD, controller freeze): eligible again in ``t`` seconds.  The pool
  sleeps on a condition variable, so a held consumer costs nothing;
- ``None`` — finished (committed/discarded/stopped): deregistered.

Independence properties the elastic-fleet plane leans on:

- each partition's consumer checks the server's backpressure governor
  inside its own step, so one held partition never blocks the others
  sharing its worker;
- N partitions crossing their row thresholds run N completion
  protocols concurrently — safe by construction because every
  ``segmentConsumed``/``segmentCommit`` carries the caller's lease
  epoch through the PR 9 fences (the FSM is per-segment and the
  property-store writes are epoch-checked);
- a consumer raising out of ``step()`` is parked with a backoff rather
  than killing the worker (one poisoned consumer must not stall the
  other partitions' ingest).

Per-(table, partition) lag/pause gauges stay continuous across segment
rollover and pool resize: the series is named by (table, partition),
not by consumer, and a successor re-registers the same name (the
``clear_fn`` equality guard in ``utils/metrics.py`` makes the
predecessor's detach a no-op once the successor owns the series —
regression-tested in ``tests/test_elastic_fleet.py``).
"""
from __future__ import annotations

import logging
import os
import threading
import time
import weakref
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

# error backoff for a consumer whose step() raised: long enough not to
# spin a broken consumer, short enough that a transient (stream hiccup
# racing a commit) self-heals quickly
_ERROR_PARK_S = 1.0


def default_pool_workers() -> int:
    """``PINOT_TPU_INGEST_CONSUMERS``: worker threads per pool (per
    server process).  More workers = more partitions consuming truly
    concurrently, up to the host's cores."""
    try:
        return max(1, int(os.environ.get("PINOT_TPU_INGEST_CONSUMERS", "4")))
    except ValueError:
        return 4


# every pool registers here so the conftest thread-leak guard can
# assert a stopped pool's workers actually exited (mirrors
# engine.dispatch._all_lanes / controller.managers._all_managers)
_all_pools: "weakref.WeakSet[IngestConsumerPool]" = weakref.WeakSet()


def leaked_pool_threads(grace_s: float = 2.0) -> List[threading.Thread]:
    """Worker threads still alive on STOPPED pools (running pools are
    exempt — they are still ingesting).  Covers workers retired by a
    shrink too, not only the current generation."""
    suspects: List[threading.Thread] = []
    for pool in list(_all_pools):
        if pool._stop.is_set():
            suspects.extend(
                t for t in pool._threads + pool._retired if t.is_alive()
            )
    deadline = time.monotonic() + grace_s
    leaked = []
    for t in suspects:
        t.join(timeout=max(0.0, deadline - time.monotonic()))
        if t.is_alive():
            leaked.append(t)
    return leaked


class _Entry:
    __slots__ = ("consumer", "eligible_at", "running")

    def __init__(self, consumer: Any, eligible_at: float) -> None:
        self.consumer = consumer
        self.eligible_at = eligible_at
        self.running = False


class IngestConsumerPool:
    """Bounded worker threads multiplexing cooperative consumers."""

    def __init__(
        self,
        workers: Optional[int] = None,
        metrics=None,
        name: str = "ingest",
    ) -> None:
        self.workers = workers if workers is not None else default_pool_workers()
        self.metrics = metrics
        self.name = name
        self._cv = threading.Condition()
        self._entries: Dict[Any, _Entry] = {}  # key -> entry
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        # workers superseded by a shrink: they exit at their next
        # wakeup, but stay tracked until then so stop() joins them and
        # the leak guard can see one wedged mid-step
        self._retired: List[threading.Thread] = []
        self._generation = 0  # bumped on resize; old workers drain out
        self.steps = 0
        self.errors = 0
        if metrics is not None:
            metrics.meter("ingest.pool.steps")
            metrics.meter("ingest.pool.errors")
            metrics.gauge("ingest.pool.workers").set_fn(lambda: self.workers)
            metrics.gauge("ingest.pool.consumers").set_fn(
                lambda: len(self._entries)
            )
        _all_pools.add(self)

    # -- registration --------------------------------------------------
    def add(self, consumer: Any, key: Optional[Any] = None) -> None:
        """Register a consumer (``key`` defaults to the consumer object
        itself).  Idempotent per key — a redelivered CONSUMING
        transition must not double-drive one consumer."""
        key = consumer if key is None else key
        with self._cv:
            if self._stop.is_set():
                raise RuntimeError("pool is stopped")
            if key in self._entries:
                return
            self._entries[key] = _Entry(consumer, time.monotonic())
            self._ensure_workers_locked()
            self._cv.notify_all()

    def remove(self, key: Any) -> None:
        with self._cv:
            self._entries.pop(key, None)

    def kick(self) -> None:
        """Make every consumer immediately eligible (e.g. backpressure
        cleared, controller reachable again) instead of sleeping out
        its current delay."""
        now = time.monotonic()
        with self._cv:
            for e in self._entries.values():
                e.eligible_at = min(e.eligible_at, now)
            self._cv.notify_all()

    def resize(self, workers: int) -> None:
        """Live worker-count change.  Growing starts threads; shrinking
        retires surplus workers at their next wakeup (consumers and
        their gauges are untouched — the series stay continuous)."""
        workers = max(1, int(workers))
        with self._cv:
            if workers == self.workers:
                return
            if workers < self.workers:
                # workers check their generation on wakeup and exit;
                # until then they stay tracked in _retired
                self._generation += 1
                self.workers = workers
                self._retired.extend(
                    t for t in self._threads if t.is_alive()
                )
                self._threads = []
                self._cv.notify_all()
            else:
                self.workers = workers
            self._ensure_workers_locked()

    def _ensure_workers_locked(self) -> None:
        if self._stop.is_set() or not self._entries:
            return
        alive = [t for t in self._threads if t.is_alive()]
        self._threads = alive
        self._retired = [t for t in self._retired if t.is_alive()]
        gen = self._generation
        while len(self._threads) < self.workers:
            idx = len(self._threads)
            t = threading.Thread(
                target=self._worker,
                args=(gen, idx),
                name=f"{self.name}-pool-{idx}",
                daemon=True,
            )
            self._threads.append(t)
            t.start()

    def stop(self) -> None:
        with self._cv:
            self._stop.set()
            self._entries.clear()
            self._cv.notify_all()
        for t in self._threads + self._retired:
            if t is not threading.current_thread():
                t.join(timeout=2)

    # -- the worker loop ----------------------------------------------
    def _claim_locked(self, now: float):
        """The not-running entry with the earliest eligible time, or
        (None, soonest-wakeup) when nothing is ready."""
        best_key = None
        best = None
        soonest: Optional[float] = None
        for key, e in self._entries.items():
            if e.running:
                continue
            if e.eligible_at <= now:
                if best is None or e.eligible_at < best.eligible_at:
                    best_key, best = key, e
            elif soonest is None or e.eligible_at < soonest:
                soonest = e.eligible_at
        return best_key, best, soonest

    def _worker(self, gen: int, idx: int) -> None:
        while True:
            with self._cv:
                while True:
                    if self._stop.is_set():
                        return
                    if gen != self._generation or idx >= self.workers:
                        return  # retired by resize
                    now = time.monotonic()
                    key, entry, soonest = self._claim_locked(now)
                    if entry is not None:
                        entry.running = True
                        break
                    timeout = None if soonest is None else max(0.0, soonest - now)
                    self._cv.wait(timeout=timeout if timeout != 0 else 0.01)
            delay: Optional[float]
            try:
                delay = entry.consumer.step()
            except Exception:
                logger.exception(
                    "consumer step failed in pool %s; parking %.1fs",
                    self.name, _ERROR_PARK_S,
                )
                self.errors += 1
                if self.metrics is not None:
                    self.metrics.meter("ingest.pool.errors").mark()
                delay = _ERROR_PARK_S
            self.steps += 1
            if self.metrics is not None:
                self.metrics.meter("ingest.pool.steps").mark()
            with self._cv:
                if delay is None:
                    self._entries.pop(key, None)
                else:
                    cur = self._entries.get(key)
                    if cur is entry:
                        entry.eligible_at = time.monotonic() + delay
                        entry.running = False
                self._cv.notify_all()

    # -- observability -------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        with self._cv:
            return {
                "workers": self.workers,
                "consumers": len(self._entries),
                "steps": self.steps,
                "errors": self.errors,
                "running": sum(1 for e in self._entries.values() if e.running),
            }
