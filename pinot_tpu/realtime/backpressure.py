"""Ingest backpressure: watermark-governed pause/resume for realtime
consumers.

Before r7 the LLC consumers (in-process
``realtime/llc.py RealtimeSegmentDataManager`` and the networked
``server/network_starter.py RemoteConsumer``) consumed as fast as the
stream served: under a simultaneous query flood the server's HBM
staging ledger and mutable-segment host arrays could only grow — the
one resource pool with NO shed path.  The reference throttles realtime
ingestion against server resource semaphores
(``RealtimeSegmentDataManager`` consumption throttling); here the
governor watches the two measured pools from PR 6:

- **HBM staged bytes** (``engine/device.py LEDGER.total_bytes``): the
  device-side footprint queries create by staging segments;
- **mutable-segment bytes** (``MutableSegment.approx_bytes`` summed
  over every consuming segment on the instance): the host-side
  footprint ingest itself creates.

Hysteresis latch: consumption PAUSES when either pool crosses its high
watermark and RESUMES only once BOTH are back under their low
watermarks — no flapping at the boundary.  Consumers poll
``consume_allowed()`` before every fetch (bounded batches, so one
decision covers at most ``max_batch_rows`` rows of exposure); while
paused the stream offset simply stops advancing — lag grows, is
visible on the ``ingest.lag.*`` gauges, and drains back to 0 after
resume (at-least-once delivery is untouched: nothing consumed is
dropped, nothing unconsumed is skipped).

Observability: ``ingest.paused`` gauge (1 while the governor holds
consumption), per-consumer ``ingest.paused.<table>.p<n>`` gauges,
``ingest.pauses``/``ingest.resumes`` meters, and a bounded event ring
(pause/resume + reason + watermark readings) served inside
``ServerInstance.status()["ingest"]``.

Watermarks default OFF (0 = unlimited) and come from the environment:
``PINOT_TPU_INGEST_HBM_HIGH_BYTES`` / ``..._LOW_BYTES`` (low defaults
to 80% of high) and ``PINOT_TPU_INGEST_MUTABLE_HIGH_BYTES`` /
``..._LOW_BYTES``.

Tier pressure (r18): when a residency HBM cap is configured
(``PINOT_TPU_HBM_CAP_BYTES``, engine/residency.py) the governor also
watches ``RESIDENCY.pressure()`` — hot bytes as a fraction of the cap —
pausing at ``PINOT_TPU_INGEST_RESIDENCY_HIGH_FRAC`` (default 0.95) and
resuming below ``..._LOW_FRAC`` (default 0.8).  Ingest learns memory
pressure BEFORE allocation failures do: a working set pushing the hot
tier against its cap throttles new rows instead of racing queries for
the last HBM bytes.
"""
from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Callable, Dict, Optional

from pinot_tpu.common.conf import env_float as _env_bytes

logger = logging.getLogger(__name__)


class IngestBackpressure:
    """One governor per server instance, shared by all its consumers."""

    def __init__(
        self,
        metrics=None,
        hbm_high_bytes: Optional[float] = None,
        hbm_low_bytes: Optional[float] = None,
        mutable_high_bytes: Optional[float] = None,
        mutable_low_bytes: Optional[float] = None,
        hbm_bytes_fn: Optional[Callable[[], float]] = None,
        mutable_bytes_fn: Optional[Callable[[], float]] = None,
        poll_interval_s: float = 0.2,
        max_batch_rows: Optional[int] = None,
        event_capacity: int = 64,
    ) -> None:
        self.hbm_high = float(
            hbm_high_bytes
            if hbm_high_bytes is not None
            else _env_bytes("PINOT_TPU_INGEST_HBM_HIGH_BYTES")
        )
        self.hbm_low = float(
            hbm_low_bytes
            if hbm_low_bytes is not None
            else _env_bytes("PINOT_TPU_INGEST_HBM_LOW_BYTES", 0.8 * self.hbm_high)
        )
        self.mutable_high = float(
            mutable_high_bytes
            if mutable_high_bytes is not None
            else _env_bytes("PINOT_TPU_INGEST_MUTABLE_HIGH_BYTES")
        )
        self.mutable_low = float(
            mutable_low_bytes
            if mutable_low_bytes is not None
            else _env_bytes(
                "PINOT_TPU_INGEST_MUTABLE_LOW_BYTES", 0.8 * self.mutable_high
            )
        )
        if hbm_bytes_fn is None:
            from pinot_tpu.engine.device import LEDGER

            hbm_bytes_fn = LEDGER.total_bytes
        self._hbm_bytes = hbm_bytes_fn
        self._mutable_bytes = mutable_bytes_fn or (lambda: 0.0)
        # tier-pressure pool (engine/residency.py): fractions of the
        # configured HBM cap; inert (pressure reads 0.0) while no cap
        # is set, so default behavior is unchanged
        self.residency_high_frac = float(
            _env_bytes("PINOT_TPU_INGEST_RESIDENCY_HIGH_FRAC", 0.95)
        )
        self.residency_low_frac = float(
            _env_bytes(
                "PINOT_TPU_INGEST_RESIDENCY_LOW_FRAC",
                0.8 if self.residency_high_frac > 0 else 0.0,
            )
        )

        def _residency_pressure() -> float:
            from pinot_tpu.engine.residency import RESIDENCY

            return RESIDENCY.pressure()

        self._residency_pressure = _residency_pressure
        # one decision per poll interval: watermark reads (ledger lock,
        # data-manager walk) stay off the per-batch hot path
        self.poll_interval_s = poll_interval_s
        self.max_batch_rows = int(
            max_batch_rows
            if max_batch_rows is not None
            else _env_bytes("PINOT_TPU_INGEST_BATCH_ROWS", 4096)
        )
        self.metrics = metrics
        self._lock = threading.Lock()
        self._paused = False
        self._reason = ""
        self._last_poll = 0.0
        self._pauses = 0
        self._resumes = 0
        self._events: deque = deque(maxlen=event_capacity)
        if metrics is not None:
            metrics.meter("ingest.pauses")
            metrics.meter("ingest.resumes")
            metrics.gauge("ingest.paused").set_fn(lambda: 1 if self._paused else 0)

    @property
    def enabled(self) -> bool:
        return (
            self.hbm_high > 0
            or self.mutable_high > 0
            or self._residency_enabled()
        )

    def _residency_enabled(self) -> bool:
        """Tier-pressure pool is live only while a residency HBM cap is
        configured (knob read fresh — chaos scenarios flip it mid-run)."""
        if self.residency_high_frac <= 0:
            return False
        from pinot_tpu.engine.residency import hbm_cap_bytes

        try:
            return hbm_cap_bytes() > 0
        except Exception:
            return False

    @property
    def paused(self) -> bool:
        return self._paused

    @property
    def reason(self) -> str:
        return self._reason

    # -- the consumer-facing check ------------------------------------
    def consume_allowed(self, force_poll: bool = False) -> bool:
        """True when consumers may fetch the next batch.  Re-evaluates
        the watermarks at most every ``poll_interval_s`` (TTL) unless
        ``force_poll``."""
        if not self.enabled:
            return True
        with self._lock:
            now = time.monotonic()
            if not force_poll and now - self._last_poll < self.poll_interval_s:
                return not self._paused
            self._last_poll = now
            hbm = self._read(self._hbm_bytes)
            mutable = self._read(self._mutable_bytes)
            res_on = self._residency_enabled()
            pressure = self._read(self._residency_pressure) if res_on else 0.0
            if not self._paused:
                reason = None
                if self.hbm_high > 0 and hbm >= self.hbm_high:
                    reason = (
                        f"hbm {int(hbm)}B >= high watermark {int(self.hbm_high)}B"
                    )
                elif self.mutable_high > 0 and mutable >= self.mutable_high:
                    reason = (
                        f"mutable {int(mutable)}B >= high watermark "
                        f"{int(self.mutable_high)}B"
                    )
                elif res_on and pressure >= self.residency_high_frac:
                    reason = (
                        f"residency pressure {pressure:.2f} >= "
                        f"{self.residency_high_frac:.2f} of HBM cap"
                    )
                if reason is not None:
                    self._paused = True
                    self._reason = reason
                    self._pauses += 1
                    self._event("pause", reason, hbm, mutable)
                    if self.metrics is not None:
                        self.metrics.meter("ingest.pauses").mark()
                    logger.warning("ingest paused: %s", reason)
            else:
                hbm_ok = self.hbm_high <= 0 or hbm <= self.hbm_low
                mutable_ok = (
                    self.mutable_high <= 0 or mutable <= self.mutable_low
                )
                residency_ok = (
                    not res_on or pressure <= self.residency_low_frac
                )
                if hbm_ok and mutable_ok and residency_ok:
                    self._paused = False
                    self._reason = ""
                    self._resumes += 1
                    self._event("resume", "below low watermarks", hbm, mutable)
                    if self.metrics is not None:
                        self.metrics.meter("ingest.resumes").mark()
                    logger.info("ingest resumed (below low watermarks)")
            return not self._paused

    @staticmethod
    def _read(fn: Callable[[], float]) -> float:
        try:
            return float(fn() or 0)
        except Exception:
            # a broken probe must fail OPEN (ingest keeps running): a
            # stuck-paused server would silently fall behind its stream
            return 0.0

    def _event(self, kind: str, reason: str, hbm: float, mutable: float) -> None:
        self._events.append(
            {
                "event": kind,
                "reason": reason,
                "hbmBytes": int(hbm),
                "mutableBytes": int(mutable),
                "tMs": time.time() * 1000.0,
            }
        )

    def clamp_batch(self, rows: int) -> int:
        """Bound one fetch's in-flight exposure (rows per batch)."""
        return min(rows, self.max_batch_rows) if self.max_batch_rows > 0 else rows

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {
                "enabled": self.enabled,
                "paused": self._paused,
                "reason": self._reason,
                "pauses": self._pauses,
                "resumes": self._resumes,
                "watermarks": {
                    "hbmHighBytes": self.hbm_high,
                    "hbmLowBytes": self.hbm_low,
                    "mutableHighBytes": self.mutable_high,
                    "mutableLowBytes": self.mutable_low,
                    "residencyHighFrac": self.residency_high_frac,
                    "residencyLowFrac": self.residency_low_frac,
                },
                "residencyPressure": round(
                    self._read(self._residency_pressure), 4
                ),
                "maxBatchRows": self.max_batch_rows,
                "events": list(self._events),
            }


def instance_mutable_bytes(server) -> float:
    """Sum ``approx_bytes`` over every consuming (mutable) segment the
    instance currently hosts — the governor's host-memory input."""
    from pinot_tpu.realtime.mutable import MutableSegment

    total = 0.0
    dm = getattr(server, "data_manager", None)
    if dm is None:
        return total
    for table in dm.table_names():
        tdm = dm.table(table)
        if tdm is None:
            continue
        acquired = tdm.acquire_segments()
        try:
            for sdm in acquired:
                seg = sdm.segment
                if isinstance(seg, MutableSegment):
                    total += seg.approx_bytes()
        finally:
            tdm.release_segments(acquired)
    return total
