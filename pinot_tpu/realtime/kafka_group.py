"""Kafka consumer-group wire protocol (0.9+ group coordinator APIs).

The reference's HLC rode Kafka 0.8's ZooKeeper-based high-level
consumer; modern Kafka moved group coordination into the broker behind
these APIs, which this module implements from spec — both sides:

  FindCoordinator (10, v0)   group -> coordinator broker
  JoinGroup       (11, v0)   member admission, generation bump,
                             leader election, member list to the leader
  SyncGroup       (14, v0)   leader distributes assignments
  Heartbeat       (12, v0)   liveness; REBALANCE_IN_PROGRESS on change
  LeaveGroup      (13, v0)   eager departure
  OffsetCommit    (8,  v0)   durable group offsets
  OffsetFetch     (9,  v0)   committed group offsets

plus the embedded "consumer" protocol payloads (Subscription /
Assignment encodings) and range assignment computed CLIENT-side by the
group leader, exactly as the real protocol does.

``KafkaGroupConsumer`` exposes the same surface as the native
``netstream.HLConsumer`` (join / poll / commit / reset_to_committed /
on_revoke), so the HLC ingestion machinery can ride either transport.
``GroupCoordinator`` adds these APIs to ``KafkaProtocolShim`` for
integration tests over real sockets: full join barrier, sync
distribution, heartbeat expiry, and rebalance-in-progress signalling
with condition variables — the broker-side state machine
(Stable -> PreparingRebalance -> AwaitingSync -> Stable).
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from pinot_tpu.realtime.kafka import (
    KafkaWireClient,
    _Reader,
    _bytes,
    _i16,
    _i32,
    _i64,
    _string,
)
from pinot_tpu.realtime.stream import Row

API_OFFSET_COMMIT = 8
API_OFFSET_FETCH = 9
API_FIND_COORDINATOR = 10
API_JOIN_GROUP = 11
API_HEARTBEAT = 12
API_LEAVE_GROUP = 13
API_SYNC_GROUP = 14

ERR_NONE = 0
ERR_NOT_COORDINATOR = 16
ERR_ILLEGAL_GENERATION = 22
ERR_UNKNOWN_MEMBER = 25
ERR_REBALANCE_IN_PROGRESS = 27

PROTOCOL_TYPE = "consumer"
ASSIGN_STRATEGY = "range"


# -- embedded consumer-protocol payloads -------------------------------


def encode_subscription(topics: List[str]) -> bytes:
    return (
        _i16(0)
        + _i32(len(topics))
        + b"".join(_string(t) for t in topics)
        + _bytes(b"")
    )


def decode_subscription(data: bytes) -> List[str]:
    r = _Reader(data)
    r.i16()  # version
    return [r.string() for _ in range(r.i32())]


def encode_assignment(parts_by_topic: Dict[str, List[int]]) -> bytes:
    body = _i16(0) + _i32(len(parts_by_topic))
    for t, ps in sorted(parts_by_topic.items()):
        body += _string(t) + _i32(len(ps)) + b"".join(_i32(p) for p in ps)
    return body + _bytes(b"")


def decode_assignment(data: bytes) -> Dict[str, List[int]]:
    if not data:
        return {}
    r = _Reader(data)
    r.i16()  # version
    out: Dict[str, List[int]] = {}
    for _ in range(r.i32()):
        t = r.string()
        out[t] = [r.i32() for _ in range(r.i32())]
    return out


def range_assign(
    members: List[Tuple[str, List[str]]], partitions: Dict[str, int]
) -> Dict[str, Dict[str, List[int]]]:
    """The client-side 'range' strategy the leader runs: per topic,
    contiguous partition spans to subscribed members in member order."""
    out: Dict[str, Dict[str, List[int]]] = {m: {} for m, _ in members}
    topics = sorted({t for _, subs in members for t in subs})
    for topic in topics:
        subs = sorted(m for m, s in members if topic in s)
        n = partitions.get(topic, 0)
        if not subs or n == 0:
            continue
        per, extra = divmod(n, len(subs))
        start = 0
        for i, m in enumerate(subs):
            take = per + (1 if i < extra else 0)
            if take:
                out[m][topic] = list(range(start, start + take))
            start += take
    return out


# -- client ------------------------------------------------------------


class KafkaGroupConsumer:
    """HLConsumer-compatible consumer over the Kafka group protocol."""

    def __init__(
        self,
        host: str,
        port: int,
        topic: str,
        group: str,
        consumer_id: str = "",
        session_timeout: float = 10.0,
    ) -> None:
        self.topic = topic
        self.group = group
        self.session_timeout = session_timeout
        self.client = KafkaWireClient(host, port, client_id=consumer_id or "pinot-tpu")
        self.on_revoke = None
        self.member_id = ""
        self.generation = -1
        self.assignment: List[int] = []
        self.positions: Dict[int, int] = {}

    # -- raw api calls -------------------------------------------------
    def _find_coordinator(self) -> None:
        r = self.client._roundtrip(API_FIND_COORDINATOR, _string(self.group))
        err = r.i16()
        r.i32()  # node
        r.string()  # host
        r.i32()  # port
        if err != ERR_NONE:
            raise IOError(f"FindCoordinator error {err}")
        # single-broker deployments (the shim, quickstarts): the
        # coordinator is the connected broker, no re-dial needed

    def _join_group(self):
        body = (
            _string(self.group)
            + _i32(int(self.session_timeout * 1000))
            + _string(self.member_id)
            + _string(PROTOCOL_TYPE)
            + _i32(1)
            + _string(ASSIGN_STRATEGY)
            + _bytes(encode_subscription([self.topic]))
        )
        r = self.client._roundtrip(API_JOIN_GROUP, body)
        err = r.i16()
        generation = r.i32()
        r.string()  # protocol
        leader = r.string()
        member_id = r.string()
        members = []
        for _ in range(r.i32()):
            mid = r.string()
            meta = r.bytes() or b""
            members.append((mid, decode_subscription(meta)))
        if err == ERR_UNKNOWN_MEMBER:
            self.member_id = ""
            raise _Rejoin()
        if err != ERR_NONE:
            raise IOError(f"JoinGroup error {err}")
        self.member_id = member_id
        self.generation = generation
        return leader, members

    def _sync_group(self, assignments: Dict[str, bytes]) -> Dict[str, List[int]]:
        body = (
            _string(self.group)
            + _i32(self.generation)
            + _string(self.member_id)
            + _i32(len(assignments))
        )
        for mid, a in assignments.items():
            body += _string(mid) + _bytes(a)
        r = self.client._roundtrip(API_SYNC_GROUP, body)
        err = r.i16()
        blob = r.bytes() or b""
        if err in (ERR_REBALANCE_IN_PROGRESS, ERR_ILLEGAL_GENERATION, ERR_UNKNOWN_MEMBER):
            raise _Rejoin()
        if err != ERR_NONE:
            raise IOError(f"SyncGroup error {err}")
        return decode_assignment(blob)

    def _heartbeat(self) -> int:
        body = _string(self.group) + _i32(self.generation) + _string(self.member_id)
        r = self.client._roundtrip(API_HEARTBEAT, body)
        return r.i16()

    # -- HLConsumer surface --------------------------------------------
    def join(self) -> List[int]:
        self._find_coordinator()
        while True:
            try:
                leader, members = self._join_group()
                if leader == self.member_id:
                    parts = {self.topic: self._partition_count()}
                    plan = range_assign(members, parts)
                    blobs = {m: encode_assignment(a) for m, a in plan.items()}
                else:
                    blobs = {}
                mine = self._sync_group(blobs)
                break
            except _Rejoin:
                time.sleep(0.05)
        new_assignment = sorted(mine.get(self.topic, []))
        # fetch committed offsets BEFORE adopting the assignment: if
        # this call fails mid-join, the old assignment/positions stand
        # and the retry re-floors — never a new partition at offset 0
        committed = self.committed_offsets()
        self.assignment = new_assignment
        # kept partitions resume from the local (possibly further)
        # position — their rows are already in the local segment
        self.positions = {
            p: max(committed.get(p, 0), self.positions.get(p, 0))
            for p in self.assignment
        }
        return self.assignment

    def _partition_count(self) -> int:
        meta = self.client.metadata([self.topic])
        return len(meta["topics"][self.topic]["partitions"])

    def poll(self, max_rows_per_partition: int = 500) -> List[Tuple[int, Row]]:
        import json

        err = self._heartbeat()
        if err in (ERR_REBALANCE_IN_PROGRESS, ERR_ILLEGAL_GENERATION, ERR_UNKNOWN_MEMBER):
            try:
                if self.on_revoke is not None:
                    self.on_revoke()
                else:
                    self.commit()
            except Exception:
                import logging

                logging.getLogger(__name__).exception(
                    "on_revoke failed for %s/%s", self.group, self.member_id
                )
            if err == ERR_UNKNOWN_MEMBER:
                self.member_id = ""
            self.join()
        out: List[Tuple[int, Row]] = []
        for p in self.assignment:
            msgs = self.client.fetch(self.topic, p, self.positions.get(p, 0))
            for moff, _k, value in msgs[:max_rows_per_partition]:
                out.append((p, json.loads(value.decode())))
                self.positions[p] = moff + 1
        return out

    def commit(self) -> bool:
        body = (
            _string(self.group)
            + _i32(1)
            + _string(self.topic)
            + _i32(len(self.assignment))
        )
        for p in self.assignment:
            body += _i32(p) + _i64(self.positions.get(p, 0)) + _string("")
        r = self.client._roundtrip(API_OFFSET_COMMIT, body)
        ok = True
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                r.i32()
                if r.i16() != ERR_NONE:
                    ok = False
        return ok

    def committed_offsets(self) -> Dict[int, int]:
        nparts = self._partition_count()
        body = (
            _string(self.group)
            + _i32(1)
            + _string(self.topic)
            + _i32(nparts)
            + b"".join(_i32(p) for p in range(nparts))
        )
        r = self.client._roundtrip(API_OFFSET_FETCH, body)
        out: Dict[int, int] = {}
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                p = r.i32()
                off = r.i64()
                r.string()  # metadata
                err = r.i16()
                if err == ERR_NONE and off >= 0:
                    out[p] = off
        return out

    def reset_to_committed(self) -> None:
        committed = self.committed_offsets()
        self.positions = {p: committed.get(p, 0) for p in self.assignment}

    def describe_group(self) -> Dict[str, Any]:
        return {"memberId": self.member_id, "generation": self.generation}

    def close(self) -> None:
        try:
            if self.member_id:
                body = _string(self.group) + _string(self.member_id)
                r = self.client._roundtrip(API_LEAVE_GROUP, body)
                r.i16()
        except Exception:
            pass
        self.client.close()


class _Rejoin(Exception):
    pass


# -- coordinator (shim side) -------------------------------------------


class _GroupState:
    EMPTY = "Empty"
    PREPARING = "PreparingRebalance"
    AWAITING_SYNC = "AwaitingSync"
    STABLE = "Stable"

    def __init__(self) -> None:
        self.state = self.EMPTY
        self.generation = 0
        self.members: Dict[str, bytes] = {}  # member_id -> subscription
        self.joined: Dict[str, bytes] = {}  # members of the forming generation
        self.leader: Optional[str] = None
        self.assignments: Dict[str, bytes] = {}
        self.last_seen: Dict[str, float] = {}
        self.session_timeout = 10.0
        self.offsets: Dict[Tuple[str, int], int] = {}
        self.cond = threading.Condition()
        self._next_member = 0


class GroupCoordinator:
    """Broker-side group state machine for the shim: join barrier,
    leader-distributed sync, heartbeat expiry, rebalance signalling."""

    REBALANCE_TIMEOUT_S = 5.0

    def __init__(self) -> None:
        self._groups: Dict[str, _GroupState] = {}
        self._lock = threading.Lock()

    def _group(self, name: str) -> _GroupState:
        with self._lock:
            g = self._groups.get(name)
            if g is None:
                g = _GroupState()
                self._groups[name] = g
            return g

    def _expire(self, g: _GroupState) -> None:
        now = time.monotonic()
        dead = [
            m for m, t in g.last_seen.items() if now - t > g.session_timeout
        ]
        for m in dead:
            g.members.pop(m, None)
            g.joined.pop(m, None)
            g.last_seen.pop(m, None)
        if dead and g.state in (_GroupState.STABLE, _GroupState.AWAITING_SYNC):
            g.state = _GroupState.PREPARING
            g.joined = {}
            g.cond.notify_all()

    # -- API handlers (called from the shim's dispatch) ----------------
    def find_coordinator(self, r: _Reader, address) -> bytes:
        r.string()  # group
        host, port = address
        return _i16(ERR_NONE) + _i32(0) + _string(host) + _i32(port)

    def join_group(self, r: _Reader) -> bytes:
        group = r.string()
        session_ms = r.i32()
        member_id = r.string() or ""
        r.string()  # protocol type
        nproto = r.i32()
        proto_name, sub = "", b""
        for i in range(nproto):
            name = r.string()
            meta = r.bytes() or b""
            if i == 0:
                proto_name, sub = name, meta
        g = self._group(group)
        with g.cond:
            g.session_timeout = max(1.0, session_ms / 1000.0)
            self._expire(g)
            if not member_id:
                g._next_member += 1
                member_id = f"member-{g._next_member}"
            elif member_id not in g.members and g.state != _GroupState.EMPTY:
                if member_id not in g.joined:
                    return (
                        _i16(ERR_UNKNOWN_MEMBER)
                        + _i32(-1)
                        + _string("")
                        + _string("")
                        + _string("")
                        + _i32(0)
                    )
            newly = member_id not in g.members
            g.members[member_id] = sub
            g.last_seen[member_id] = time.monotonic()
            if g.state in (_GroupState.EMPTY, _GroupState.STABLE, _GroupState.AWAITING_SYNC) or newly:
                if g.state != _GroupState.PREPARING:
                    g.state = _GroupState.PREPARING
                    g.joined = {}
                    g.cond.notify_all()
            g.joined[member_id] = sub
            # join barrier: wait until every known member has rejoined
            # (or stragglers expire / the rebalance times out)
            deadline = time.monotonic() + self.REBALANCE_TIMEOUT_S
            while (
                g.state == _GroupState.PREPARING
                and set(g.joined) != set(g.members)
                and time.monotonic() < deadline
            ):
                g.cond.wait(timeout=0.1)
                self._expire(g)
                g.last_seen[member_id] = time.monotonic()
            if g.state == _GroupState.PREPARING:
                # everyone (still alive) joined, or we timed out:
                # drop stragglers and form the new generation
                g.members = dict(g.joined)
                g.generation += 1
                g.leader = sorted(g.members)[0] if g.members else None
                g.assignments = {}
                g.state = _GroupState.AWAITING_SYNC
                g.cond.notify_all()
            body = (
                _i16(ERR_NONE)
                + _i32(g.generation)
                + _string(ASSIGN_STRATEGY)
                + _string(g.leader or "")
                + _string(member_id)
            )
            if member_id == g.leader:
                body += _i32(len(g.members))
                for mid, meta in sorted(g.members.items()):
                    body += _string(mid) + _bytes(meta)
            else:
                body += _i32(0)
            return body

    def sync_group(self, r: _Reader) -> bytes:
        group = r.string()
        generation = r.i32()
        member_id = r.string()
        n = r.i32()
        provided: Dict[str, bytes] = {}
        for _ in range(n):
            mid = r.string()
            provided[mid] = r.bytes() or b""
        g = self._group(group)
        with g.cond:
            if member_id not in g.members:
                return _i16(ERR_UNKNOWN_MEMBER) + _bytes(b"")
            if generation != g.generation or g.state == _GroupState.PREPARING:
                return _i16(ERR_REBALANCE_IN_PROGRESS) + _bytes(b"")
            g.last_seen[member_id] = time.monotonic()
            if member_id == g.leader and provided:
                g.assignments = provided
                g.state = _GroupState.STABLE
                g.cond.notify_all()
            deadline = time.monotonic() + self.REBALANCE_TIMEOUT_S
            while (
                g.state == _GroupState.AWAITING_SYNC
                and generation == g.generation
                and time.monotonic() < deadline
            ):
                g.cond.wait(timeout=0.1)
                g.last_seen[member_id] = time.monotonic()
            if generation != g.generation or g.state == _GroupState.PREPARING:
                return _i16(ERR_REBALANCE_IN_PROGRESS) + _bytes(b"")
            if g.state != _GroupState.STABLE:
                return _i16(ERR_REBALANCE_IN_PROGRESS) + _bytes(b"")
            return _i16(ERR_NONE) + _bytes(g.assignments.get(member_id, b""))

    def heartbeat(self, r: _Reader) -> bytes:
        group = r.string()
        generation = r.i32()
        member_id = r.string()
        g = self._group(group)
        with g.cond:
            self._expire(g)
            if member_id not in g.members:
                return _i16(ERR_UNKNOWN_MEMBER)
            g.last_seen[member_id] = time.monotonic()
            if g.state != _GroupState.STABLE:
                return _i16(ERR_REBALANCE_IN_PROGRESS)
            if generation != g.generation:
                return _i16(ERR_ILLEGAL_GENERATION)
            return _i16(ERR_NONE)

    def leave_group(self, r: _Reader) -> bytes:
        group = r.string()
        member_id = r.string()
        g = self._group(group)
        with g.cond:
            if member_id in g.members:
                was_preparing = g.state == _GroupState.PREPARING
                g.members.pop(member_id, None)
                g.joined.pop(member_id, None)
                g.last_seen.pop(member_id, None)
                if not g.members:
                    g.state = _GroupState.EMPTY
                    g.joined = {}
                else:
                    # members already waiting in the join barrier keep
                    # their registrations — wiping g.joined would stall
                    # them to the rebalance timeout and form an empty
                    # generation
                    if not was_preparing:
                        g.joined = {}
                    g.state = _GroupState.PREPARING
                g.cond.notify_all()
        return _i16(ERR_NONE)

    def offset_commit(self, r: _Reader) -> bytes:
        group = r.string()
        g = self._group(group)
        out = b""
        ntopics = r.i32()
        out += _i32(ntopics)
        with g.cond:
            for _ in range(ntopics):
                topic = r.string()
                nparts = r.i32()
                out += _string(topic) + _i32(nparts)
                for _ in range(nparts):
                    p = r.i32()
                    off = r.i64()
                    r.string()  # metadata
                    g.offsets[(topic, p)] = off
                    out += _i32(p) + _i16(ERR_NONE)
        return out

    def offset_fetch(self, r: _Reader) -> bytes:
        group = r.string()
        g = self._group(group)
        out = b""
        ntopics = r.i32()
        out += _i32(ntopics)
        with g.cond:
            for _ in range(ntopics):
                topic = r.string()
                nparts = r.i32()
                out += _string(topic) + _i32(nparts)
                for _ in range(nparts):
                    p = r.i32()
                    off = g.offsets.get((topic, p), -1)
                    out += _i32(p) + _i64(off) + _string("") + _i16(ERR_NONE)
        return out
