"""Mutable (consuming) segment: append rows, query at a row watermark.

The reference's ``RealtimeSegmentImpl.java:62`` keeps mutable
dictionaries (arrival-order ids), growable forward indexes and realtime
inverted indexes, and serves queries in place; at commit a converter
produces an immutable columnar segment
(``realtime/converter/RealtimeSegmentConverter.java``).

TPU-first adaptation (SURVEY §7 hard part 4 — mutability vs immutable
device arrays): ingestion appends into host-side growable numpy arrays
with arrival-order dictIds; queries snapshot the segment at the current
row watermark by converting to a sorted-dictionary ``ImmutableSegment``
(vectorized O(n) remap), cached until the watermark moves.  The
snapshot then goes through the normal device staging path, so the query
kernels never special-case realtime — consistency comes from the
watermark, not locks.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from pinot_tpu.common.schema import DataType, FieldSpec, Schema
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.segment.dictionary import Dictionary
from pinot_tpu.segment.immutable import (
    ColumnData,
    ColumnMetadata,
    ImmutableSegment,
    SegmentMetadata,
)

Row = Dict[str, Any]


class _MutableColumn:
    """Arrival-order dictionary + growable dictId arrays
    (core/realtime/impl/dictionary + fwdindex analogs)."""

    def __init__(self, spec: FieldSpec) -> None:
        self.spec = spec
        self.value_to_id: Dict[Any, int] = {}
        self.id_to_value: List[Any] = []
        self.single = spec.single_value
        if self.single:
            self.ids = np.zeros(1024, dtype=np.int32)
        else:
            self.flat_ids: List[int] = []
            self.offsets: List[int] = [0]
        self.max_mv = 0
        # numeric SV columns keep a SORTED (values, arrival ids) index
        # so whole batches dictionary-encode with searchsorted — no
        # per-value (or per-unique) Python in the steady state
        self._sorted_vals: Optional[np.ndarray] = None
        self._sorted_ids: Optional[np.ndarray] = None
        # capacity-doubled backing for the append-at-end dictionary
        # growth path (monotone columns): _sorted_vals/_sorted_ids are
        # VIEWS of these while appending, so tail growth is amortized
        # O(new) instead of an O(dict) copy per ingest block
        self._cap_vals: Optional[np.ndarray] = None
        self._cap_ids: Optional[np.ndarray] = None
        self._v2i_stale = False  # value_to_id rebuilt on demand (_id_of)

    def _id_of(self, value: Any) -> int:
        if self._v2i_stale:
            self.value_to_id = {v: i for i, v in enumerate(self.id_to_value)}
            self._v2i_stale = False
        i = self.value_to_id.get(value)
        if i is None:
            i = len(self.id_to_value)
            self.value_to_id[value] = i
            self.id_to_value.append(value)
            self._sorted_vals = None  # scalar path invalidates the index
        return i

    def encode_array(self, arr: np.ndarray) -> np.ndarray:
        """Vectorized dictionary encode of a numeric SV batch: one
        np.unique + searchsorted against the sorted known-values index;
        Python work only to record NEVER-SEEN uniques (amortizes to
        zero once the dictionary saturates).  The value_to_id hash map
        is left stale (rebuilt on demand by the scalar path) — at
        north-star cardinality its per-unique inserts were a third of
        the whole ingest cost.  The r4 path paid one dict lookup per
        unique per batch and measured ~580K rows/s; this path measures
        ~1M rows/s single-core at 64K batches."""
        if arr.size > 1 and bool((arr[1:] >= arr[:-1]).all()):
            # sorted-block fast path (monotone time/offset-like
            # columns, and blocks that happen to arrive ordered): the
            # uniques are the change points — no argsort, no gather
            flags = np.empty(arr.size, dtype=bool)
            flags[0] = True
            np.not_equal(arr[1:], arr[:-1], out=flags[1:])
            uniq = arr[flags]
            inverse = np.cumsum(flags) - 1
        else:
            uniq, inverse = np.unique(arr, return_inverse=True)
        if self._sorted_vals is None or self._sorted_vals.dtype != arr.dtype:
            known = np.asarray(self.id_to_value, dtype=arr.dtype)
            order = np.argsort(known, kind="stable")
            self._sorted_vals = known[order]
            self._sorted_ids = order.astype(np.int32)
            self._cap_vals = self._cap_ids = None
        pos = np.searchsorted(self._sorted_vals, uniq)
        if self._sorted_vals.size:
            pc = np.minimum(pos, self._sorted_vals.size - 1)
            hit = self._sorted_vals[pc] == uniq
        else:
            hit = np.zeros(uniq.size, dtype=bool)
        new_vals = uniq[~hit]
        if new_vals.size:
            base = len(self.id_to_value)
            self.id_to_value.extend(new_vals.tolist())
            self._v2i_stale = True
            new_ids = np.arange(base, base + new_vals.size, dtype=np.int32)
            n_old = self._sorted_vals.size
            if n_old == 0 or new_vals[0] > self._sorted_vals[-1]:
                # append-at-end growth (monotone columns: every new
                # value sorts after the whole dictionary): write into
                # the capacity-doubled backing — amortized O(new),
                # where np.insert would copy the full dictionary per
                # ingest block
                need = n_old + new_vals.size
                if (
                    self._cap_vals is None
                    or self._cap_vals.size < need
                    or self._cap_vals.dtype != arr.dtype
                    or self._sorted_vals.base is not self._cap_vals
                ):
                    cap = max(need * 2, 1024)
                    grown_v = np.empty(cap, dtype=arr.dtype)
                    grown_v[:n_old] = self._sorted_vals
                    grown_i = np.empty(cap, dtype=np.int32)
                    grown_i[:n_old] = self._sorted_ids
                    self._cap_vals, self._cap_ids = grown_v, grown_i
                self._cap_vals[n_old:need] = new_vals
                self._cap_ids[n_old:need] = new_ids
                self._sorted_vals = self._cap_vals[:need]
                self._sorted_ids = self._cap_ids[:need]
            else:
                ins = np.searchsorted(self._sorted_vals, new_vals)
                self._sorted_vals = np.insert(self._sorted_vals, ins, new_vals)
                self._sorted_ids = np.insert(self._sorted_ids, ins, new_ids)
                self._cap_vals = self._cap_ids = None
            pos = np.searchsorted(self._sorted_vals, uniq)
        lut = self._sorted_ids[pos]
        return lut[inverse].astype(np.int32)

    # Batch ingestion is two-phase so a dirty value mid-batch (convert
    # raises on producer garbage) can never leave columns misaligned:
    # encode_batch only touches the dictionary (unreferenced entries are
    # harmless), commit_batch cannot raise.
    def encode_batch(self, rows, name: str):
        """-> int32[m] dictIds (SV) or per-row id lists (MV); raises on
        unconvertible values BEFORE any row arrays mutate."""
        st = self.spec.stored_type
        conv = st.convert
        id_of = self._id_of
        default_id = None
        if self.single:
            vals = [row.get(name) for row in rows]
            if st.is_numeric and None not in vals:
                # Vectorized fast path (the ingest hot loop): one numpy
                # conversion + unique, then one id_of per UNIQUE value.
                # np.asarray enforces the same semantics as convert()
                # (int truncation, float32 rounding for FLOAT) and
                # raises on junk BEFORE any dictionary mutation; mixed/
                # stringy payloads fall back to the per-value loop.
                try:
                    arr = np.asarray(vals, dtype=st.to_numpy())
                except (TypeError, ValueError, OverflowError):
                    arr = None
                if arr is not None and arr.ndim != 1:
                    # nested-list values build a 2-D array that would
                    # pass encode and blow up in commit_batch AFTER
                    # other columns committed — the per-value loop
                    # raises in the safe encode phase instead
                    arr = None
                if arr is not None and arr.dtype.kind == "f" and np.isnan(arr).any():
                    # np.unique collapses NaNs to one dictId while the
                    # fallback's dict keying gives each NaN its own —
                    # keep one (the historical) behavior regardless of
                    # which path a batch happens to take
                    arr = None
                if arr is not None:
                    return self.encode_array(arr)
            elif all(type(v) is str for v in vals):
                # STRING columns from JSON payloads arrive as str:
                # convert() would be an identity per value — skip it
                out = np.empty(len(vals), dtype=np.int32)
                for j, v in enumerate(vals):
                    out[j] = id_of(v)
                return out
            out = np.empty(len(rows), dtype=np.int32)
            for j, v in enumerate(vals):
                if v is None:
                    if default_id is None:
                        default_id = id_of(conv(self.spec.get_default_null_value()))
                    out[j] = default_id
                else:
                    out[j] = id_of(conv(v))
            return out
        outs = []
        default_ids = None
        for row in rows:
            v = row.get(name)
            vs = v if isinstance(v, (list, tuple)) else [v] if v is not None else []
            if not vs:
                if default_ids is None:
                    default_ids = [id_of(conv(self.spec.get_default_null_value()))]
                outs.append(default_ids)
            else:
                outs.append([id_of(conv(x)) for x in vs])
        return outs

    def commit_batch(self, encoded, start: int) -> None:
        if self.single:
            need = start + encoded.shape[0]
            while self.ids.size < need:
                self.ids = np.concatenate(
                    [self.ids, np.zeros(self.ids.size, dtype=np.int32)]
                )
            self.ids[start:need] = encoded
            return
        for id_list in encoded:
            self.flat_ids.extend(id_list)
            self.offsets.append(len(self.flat_ids))
            self.max_mv = max(self.max_mv, len(id_list))


class MutableSegment:
    def __init__(self, schema: Schema, segment_name: str, table_name: str) -> None:
        self.schema = schema
        self.segment_name = segment_name
        self.table_name = table_name
        self._columns = {spec.name: _MutableColumn(spec) for spec in schema.all_fields()}
        self._num_docs = 0
        self._lock = threading.Lock()
        self._snapshot: Optional[ImmutableSegment] = None
        self._snapshot_watermark = -1
        self.start_offset: int = 0
        self.end_offset: int = 0

    @property
    def num_docs(self) -> int:
        return self._num_docs

    def approx_bytes(self) -> int:
        """Rough host-memory footprint of the consuming state (growable
        dictId arrays + dictionaries + encode indexes) — the ingest
        backpressure watermark input.  Conservative rather than exact:
        the cached query snapshot (rebuilt per watermark) is not
        counted, so set watermarks with ~2x headroom."""
        with self._lock:
            total = 0
            for mc in self._columns.values():
                if mc.single:
                    total += mc.ids.nbytes
                else:
                    total += 4 * len(mc.flat_ids) + 8 * len(mc.offsets)
                total += 64 * len(mc.id_to_value)  # dict entries (rough)
                if mc._sorted_vals is not None:
                    total += mc._sorted_vals.nbytes + mc._sorted_ids.nbytes
            return total

    def index(self, row: Row) -> None:
        """Append one row (RealtimeSegmentImpl.index :185); visible to
        queries at the next snapshot."""
        self.index_batch((row,))

    def index_batch(self, rows) -> None:
        """Append many rows under ONE lock with per-column tight loops —
        the stream consumers fetch in batches, and batching the encode
        side makes ingestion ~3x faster than per-row calls (the hot
        loop of the 1-row reference path, ``RealtimeSegmentImpl.index``,
        amortized).  Encode-then-commit: a dirty value anywhere in the
        batch raises before ANY column's row arrays change."""
        if not rows:
            return
        with self._lock:
            start = self._num_docs
            specs = self.schema.all_fields()
            encoded = [
                self._columns[spec.name].encode_batch(rows, spec.name)
                for spec in specs
            ]
            for spec, enc in zip(specs, encoded):
                self._columns[spec.name].commit_batch(enc, start)
            self._num_docs = start + len(rows)

    def index_columns(self, cols: Dict[str, np.ndarray]) -> int:
        """Columnar append — the high-throughput ingest path: one
        numpy array per column, vectorized dictionary encode per column
        (``_MutableColumn.encode_array``), no per-row dicts anywhere.
        All schema columns must be single-value and present; numeric
        columns must be NaN-free (callers fall back to ``index_batch``
        rows otherwise).  Returns the number of rows appended."""
        specs = self.schema.all_fields()
        n = -1
        for spec in specs:
            if not spec.single_value:
                raise ValueError(f"columnar ingest requires SV columns: {spec.name}")
            arr = cols.get(spec.name)
            if arr is None:
                raise ValueError(f"columnar batch missing column {spec.name}")
            if n < 0:
                n = len(arr)
            elif len(arr) != n:
                raise ValueError("columnar batch length mismatch")
        if n <= 0:
            return 0
        with self._lock:
            start = self._num_docs
            encoded = []
            for spec in specs:
                st = spec.stored_type
                mc = self._columns[spec.name]
                if st.is_numeric:
                    arr = np.asarray(cols[spec.name], dtype=st.to_numpy())
                    if arr.dtype.kind == "f" and np.isnan(arr).any():
                        raise ValueError(f"NaN in columnar batch: {spec.name}")
                    encoded.append(mc.encode_array(arr))
                else:
                    # STRING: per-unique id_of (vectorized unique first)
                    vals = np.asarray(cols[spec.name], dtype=object)
                    uniq, inverse = np.unique(vals, return_inverse=True)
                    lut = np.empty(uniq.size, dtype=np.int32)
                    for ui in range(uniq.size):
                        lut[ui] = mc._id_of(uniq[ui])
                    encoded.append(lut[inverse].astype(np.int32))
            for spec, enc in zip(specs, encoded):
                self._columns[spec.name].commit_batch(enc, start)
            self._num_docs = start + n
        return n

    # ------------------------------------------------------------------
    def snapshot(self) -> ImmutableSegment:
        """Immutable view at the current watermark; cached until more
        rows arrive (chunk-watermark consistency)."""
        with self._lock:
            n = self._num_docs
            if self._snapshot is not None and self._snapshot_watermark == n:
                return self._snapshot
            snap = self._convert(n)
            self._snapshot = snap
            self._snapshot_watermark = n
            return snap

    def _convert(self, n: int) -> ImmutableSegment:
        columns: Dict[str, ColumnData] = {}
        for spec in self.schema.all_fields():
            mc = self._columns[spec.name]
            st = spec.stored_type
            if st == DataType.STRING:
                order = np.argsort(np.asarray(mc.id_to_value, dtype=object)) if mc.id_to_value else np.zeros(0, np.int64)
                sorted_vals = [mc.id_to_value[i] for i in order]
                d = Dictionary(st, sorted_vals)
            else:
                arr = np.asarray(mc.id_to_value, dtype=st.to_numpy()) if mc.id_to_value else np.zeros(0, st.to_numpy())
                order = np.argsort(arr, kind="stable")
                d = Dictionary(st, arr[order])
            # remap arrival-order ids -> sorted dictIds
            remap = np.empty(max(len(mc.id_to_value), 1), dtype=np.int32)
            remap[order] = np.arange(order.size, dtype=np.int32)

            fwd = remap[mc.ids[:n]] if spec.single_value else None
            meta = ColumnMetadata(
                name=spec.name,
                data_type=spec.data_type,
                field_type=spec.field_type,
                single_value=spec.single_value,
                cardinality=d.cardinality,
                total_docs=n,
                # time-ordered streams produce sorted time columns: the
                # committed segment records it so the docrange fast
                # path (engine/plan.py) applies to realtime data too
                is_sorted=bool(
                    spec.single_value
                    and (fwd is None or fwd.size == 0 or np.all(fwd[1:] >= fwd[:-1]))
                ),
                max_num_multi_values=mc.max_mv,
                total_number_of_entries=n if spec.single_value else len(mc.flat_ids),
                min_value=d.min_value if len(d) else None,
                max_value=d.max_value if len(d) else None,
            )
            if spec.single_value:
                columns[spec.name] = ColumnData(metadata=meta, dictionary=d, fwd=fwd)
            else:
                offsets = np.asarray(mc.offsets[: n + 1], dtype=np.int32)
                flat = np.asarray(mc.flat_ids[: offsets[-1]], dtype=np.int32)
                columns[spec.name] = ColumnData(
                    metadata=meta,
                    dictionary=d,
                    mv_values=remap[flat] if flat.size else flat,
                    mv_offsets=offsets,
                )

        smeta = SegmentMetadata(
            segment_name=self.segment_name,
            table_name=self.table_name,
            num_docs=n,
            columns={c.metadata.name: c.metadata for c in columns.values()},
            time_column=self.schema.time_column_name,
            time_unit=self.schema.time_field.time_unit if self.schema.time_field else "DAYS",
            creation_time_ms=int(time.time() * 1000),
            custom={"realtime": True, "startOffset": self.start_offset, "endOffset": self.end_offset},
        )
        tcol = self.schema.time_column_name
        if tcol and n > 0 and not columns[tcol].dictionary.is_string:
            smeta.start_time = int(columns[tcol].dictionary.min_value)
            smeta.end_time = int(columns[tcol].dictionary.max_value)
        seg = ImmutableSegment(metadata=smeta, columns=columns)
        # watermark-scoped identity so staging/context caches key correctly
        smeta.crc = (hash((self.segment_name, n)) & 0x7FFFFFFF) or 1
        return seg

    def to_committed_segment(self, final_name: Optional[str] = None) -> ImmutableSegment:
        """Final conversion at commit (RealtimeSegmentConverter analog):
        a full CRC'd immutable segment ready for the store."""
        snap = self.snapshot()
        if final_name and final_name != self.segment_name:
            snap.metadata.segment_name = final_name
        snap.metadata.custom.update(
            {"startOffset": self.start_offset, "endOffset": self.end_offset}
        )
        snap.metadata.crc = snap.compute_crc()
        snap.metadata.custom["dataCrc"] = True  # verifiable (format.verify_segment_crc)
        return snap
