"""Mutable (consuming) segment: append rows, query at a row watermark.

The reference's ``RealtimeSegmentImpl.java:62`` keeps mutable
dictionaries (arrival-order ids), growable forward indexes and realtime
inverted indexes, and serves queries in place; at commit a converter
produces an immutable columnar segment
(``realtime/converter/RealtimeSegmentConverter.java``).

TPU-first adaptation (SURVEY §7 hard part 4 — mutability vs immutable
device arrays): ingestion appends into host-side growable numpy arrays
with arrival-order dictIds; queries snapshot the segment at the current
row watermark by converting to a sorted-dictionary ``ImmutableSegment``
(vectorized O(n) remap), cached until the watermark moves.  The
snapshot then goes through the normal device staging path, so the query
kernels never special-case realtime — consistency comes from the
watermark, not locks.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from pinot_tpu.common.schema import DataType, FieldSpec, Schema
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.segment.dictionary import Dictionary
from pinot_tpu.segment.immutable import (
    ColumnData,
    ColumnMetadata,
    ImmutableSegment,
    SegmentMetadata,
)

Row = Dict[str, Any]


class _MutableColumn:
    """Arrival-order dictionary + growable dictId arrays
    (core/realtime/impl/dictionary + fwdindex analogs)."""

    def __init__(self, spec: FieldSpec) -> None:
        self.spec = spec
        self.value_to_id: Dict[Any, int] = {}
        self.id_to_value: List[Any] = []
        self.single = spec.single_value
        if self.single:
            self.ids = np.zeros(1024, dtype=np.int32)
        else:
            self.flat_ids: List[int] = []
            self.offsets: List[int] = [0]
        self.max_mv = 0

    def _id_of(self, value: Any) -> int:
        i = self.value_to_id.get(value)
        if i is None:
            i = len(self.id_to_value)
            self.value_to_id[value] = i
            self.id_to_value.append(value)
        return i

    def append(self, value: Any, row_idx: int) -> None:
        st = self.spec.stored_type
        if self.single:
            if row_idx >= self.ids.size:
                self.ids = np.concatenate([self.ids, np.zeros(self.ids.size, dtype=np.int32)])
            self.ids[row_idx] = self._id_of(st.convert(value))
        else:
            vs = value if isinstance(value, (list, tuple)) else [value]
            vs = [st.convert(x) for x in vs] or [self.spec.get_default_null_value()]
            for v in vs:
                self.flat_ids.append(self._id_of(v))
            self.offsets.append(len(self.flat_ids))
            self.max_mv = max(self.max_mv, len(vs))


class MutableSegment:
    def __init__(self, schema: Schema, segment_name: str, table_name: str) -> None:
        self.schema = schema
        self.segment_name = segment_name
        self.table_name = table_name
        self._columns = {spec.name: _MutableColumn(spec) for spec in schema.all_fields()}
        self._num_docs = 0
        self._lock = threading.Lock()
        self._snapshot: Optional[ImmutableSegment] = None
        self._snapshot_watermark = -1
        self.start_offset: int = 0
        self.end_offset: int = 0

    @property
    def num_docs(self) -> int:
        return self._num_docs

    def index(self, row: Row) -> None:
        """Append one row (RealtimeSegmentImpl.index :185); visible to
        queries at the next snapshot."""
        with self._lock:
            idx = self._num_docs
            for spec in self.schema.all_fields():
                v = row.get(spec.name)
                if v is None:
                    v = (
                        spec.get_default_null_value()
                        if spec.single_value
                        else [spec.get_default_null_value()]
                    )
                self._columns[spec.name].append(v, idx)
            self._num_docs = idx + 1

    # ------------------------------------------------------------------
    def snapshot(self) -> ImmutableSegment:
        """Immutable view at the current watermark; cached until more
        rows arrive (chunk-watermark consistency)."""
        with self._lock:
            n = self._num_docs
            if self._snapshot is not None and self._snapshot_watermark == n:
                return self._snapshot
            snap = self._convert(n)
            self._snapshot = snap
            self._snapshot_watermark = n
            return snap

    def _convert(self, n: int) -> ImmutableSegment:
        columns: Dict[str, ColumnData] = {}
        for spec in self.schema.all_fields():
            mc = self._columns[spec.name]
            st = spec.stored_type
            if st == DataType.STRING:
                order = np.argsort(np.asarray(mc.id_to_value, dtype=object)) if mc.id_to_value else np.zeros(0, np.int64)
                sorted_vals = [mc.id_to_value[i] for i in order]
                d = Dictionary(st, sorted_vals)
            else:
                arr = np.asarray(mc.id_to_value, dtype=st.to_numpy()) if mc.id_to_value else np.zeros(0, st.to_numpy())
                order = np.argsort(arr, kind="stable")
                d = Dictionary(st, arr[order])
            # remap arrival-order ids -> sorted dictIds
            remap = np.empty(max(len(mc.id_to_value), 1), dtype=np.int32)
            remap[order] = np.arange(order.size, dtype=np.int32)

            fwd = remap[mc.ids[:n]] if spec.single_value else None
            meta = ColumnMetadata(
                name=spec.name,
                data_type=spec.data_type,
                field_type=spec.field_type,
                single_value=spec.single_value,
                cardinality=d.cardinality,
                total_docs=n,
                # time-ordered streams produce sorted time columns: the
                # committed segment records it so the docrange fast
                # path (engine/plan.py) applies to realtime data too
                is_sorted=bool(
                    spec.single_value
                    and (fwd is None or fwd.size == 0 or np.all(fwd[1:] >= fwd[:-1]))
                ),
                max_num_multi_values=mc.max_mv,
                total_number_of_entries=n if spec.single_value else len(mc.flat_ids),
                min_value=d.min_value if len(d) else None,
                max_value=d.max_value if len(d) else None,
            )
            if spec.single_value:
                columns[spec.name] = ColumnData(metadata=meta, dictionary=d, fwd=fwd)
            else:
                offsets = np.asarray(mc.offsets[: n + 1], dtype=np.int32)
                flat = np.asarray(mc.flat_ids[: offsets[-1]], dtype=np.int32)
                columns[spec.name] = ColumnData(
                    metadata=meta,
                    dictionary=d,
                    mv_values=remap[flat] if flat.size else flat,
                    mv_offsets=offsets,
                )

        smeta = SegmentMetadata(
            segment_name=self.segment_name,
            table_name=self.table_name,
            num_docs=n,
            columns={c.metadata.name: c.metadata for c in columns.values()},
            time_column=self.schema.time_column_name,
            time_unit=self.schema.time_field.time_unit if self.schema.time_field else "DAYS",
            creation_time_ms=int(time.time() * 1000),
            custom={"realtime": True, "startOffset": self.start_offset, "endOffset": self.end_offset},
        )
        tcol = self.schema.time_column_name
        if tcol and n > 0 and not columns[tcol].dictionary.is_string:
            smeta.start_time = int(columns[tcol].dictionary.min_value)
            smeta.end_time = int(columns[tcol].dictionary.max_value)
        seg = ImmutableSegment(metadata=smeta, columns=columns)
        # watermark-scoped identity so staging/context caches key correctly
        smeta.crc = (hash((self.segment_name, n)) & 0x7FFFFFFF) or 1
        return seg

    def to_committed_segment(self, final_name: Optional[str] = None) -> ImmutableSegment:
        """Final conversion at commit (RealtimeSegmentConverter analog):
        a full CRC'd immutable segment ready for the store."""
        snap = self.snapshot()
        if final_name and final_name != self.segment_name:
            snap.metadata.segment_name = final_name
        snap.metadata.custom.update(
            {"startOffset": self.start_offset, "endOffset": self.end_offset}
        )
        snap.metadata.crc = snap.compute_crc()
        return snap
