from pinot_tpu.realtime.mutable import MutableSegment
from pinot_tpu.realtime.stream import (
    FileBasedStreamProvider,
    MemoryStreamProvider,
    StreamProvider,
)

__all__ = [
    "MutableSegment",
    "StreamProvider",
    "FileBasedStreamProvider",
    "MemoryStreamProvider",
]
